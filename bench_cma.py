#!/usr/bin/env python
"""CMA-ES benchmark: (μ/μ_w, λ) strategy at N=100, λ=4096 on sphere and
ackley (BASELINE config 3).  Prints ONE JSON line like bench.py.

The whole ask-eval-tell generation — candidate sampling (a (λ, N)·(N, N)
matmul on the MXU), fitness, ranking, evolution-path/covariance updates and
the per-generation ``jnp.linalg.eigh`` of C (the reference's numpy hot spot,
/root/reference/deap/cma.py:164) — runs as one ``lax.scan`` over
generations via ``ea_generate_update``'s functional strategy protocol.

Timing honesty kit is identical to bench.py (round-1 verdict): the timed
value is forced host-side from data-dependent output, both NGEN and 2·NGEN
runs are timed, the ratio must be ~2, and the reported figure is the
marginal cost ``(t(2N) - t(N)) / NGEN``.

``vs_baseline`` divides by the stock-DEAP ``cma.Strategy`` +
``eaGenerateUpdate`` measurement on the same config
(BASELINE.json measured.cmaes_sphere_n100_lambda4096_gens_per_sec_serial,
5.59 gens/s on this build host's CPU).

Env overrides: BENCH_DIM (default 100), BENCH_LAMBDA (4096), BENCH_NGEN
(300 timed generations — cheap gens need many to beat dispatch overhead),
BENCH_PRNG (rbg | threefry).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

DIM = int(os.environ.get("BENCH_DIM", 100))
LAMBDA = int(os.environ.get("BENCH_LAMBDA", 4096))
NGEN = int(os.environ.get("BENCH_NGEN", 300))   # generations are ~0.4 ms:
# at NGEN=30 fixed dispatch overhead dominates and the linearity gate
# rejects the measurement (observed ratio 1.05); 300 passes cleanly


def run_tpu(fn_name: str):
    import numpy as np
    import jax

    if os.environ.get("BENCH_PRNG", "rbg") == "rbg":
        jax.config.update("jax_default_prng_impl", "rbg")

    import jax.numpy as jnp
    from jax import lax
    from deap_tpu import base, benchmarks, cma

    evaluate = getattr(benchmarks, fn_name)
    strategy = cma.Strategy(centroid=[5.0] * DIM, sigma=5.0, lambda_=LAMBDA)

    tb = base.Toolbox()
    tb.register("evaluate", evaluate)
    tb.register("generate", strategy.generate)
    tb.register("update", strategy.update)

    def generation(carry, _):
        key, state = carry
        key, k_gen = jax.random.split(key)
        genome = tb.generate(state, k_gen)
        pop = base.Population(genome, base.Fitness.empty(LAMBDA, (-1.0,)))
        from deap_tpu.algorithms import evaluate_population
        pop, _ = evaluate_population(tb, pop)
        state = tb.update(state, pop)
        return (key, state), jnp.min(pop.fitness.values[:, 0])

    def make_run(ngen):
        @jax.jit
        def run(key, state):
            return lax.scan(generation, (key, state), None, length=ngen)
        return run

    key = jax.random.PRNGKey(0)
    state0 = strategy.init()

    def timed(ngen):
        run = make_run(ngen)
        _, best = run(key, state0)          # warmup: compile + run once
        np.asarray(best[-1:])
        t0 = time.perf_counter()
        _, best = run(key, state0)
        best_host = np.asarray(best)        # device->host forces completion
        return time.perf_counter() - t0, float(best_host[-1])

    t1, _ = timed(NGEN)
    t2, best = timed(2 * NGEN)
    ratio = t2 / t1
    marginal = (t2 - t1) / NGEN
    return 1.0 / marginal, ratio, best, jax.devices()[0].platform


def measured_baseline():
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BASELINE.json")
    try:
        with open(path) as f:
            measured = json.load(f).get("measured", {})
        if (DIM, LAMBDA) != (100, 4096):
            return None           # baseline was measured at exactly this config
        return measured["cmaes_sphere_n100_lambda4096_gens_per_sec_serial"]
    except (OSError, KeyError, ValueError):
        return None


def main():
    sphere_gps, ratio_s, best_s, platform = run_tpu("sphere")
    ackley_gps, ratio_a, best_a, _ = run_tpu("ackley")
    linear_ok = (1.5 <= ratio_s <= 2.7) and (1.5 <= ratio_a <= 2.7)
    baseline = measured_baseline()
    vs = (sphere_gps / baseline) if (baseline and linear_ok) else -1.0
    print(json.dumps({
        "metric": f"cmaes_sphere_n{DIM}_lambda{LAMBDA}_gens_per_sec",
        "value": round(sphere_gps, 3) if linear_ok else -1,
        "unit": "generations/sec",
        "vs_baseline": round(vs, 1),
        "extra": {
            "platform": platform,
            "timing_linearity": {
                "sphere_t2N_over_tN": round(ratio_s, 3),
                "ackley_t2N_over_tN": round(ratio_a, 3),
                "ok": linear_ok,
            },
            "ackley_gens_per_sec": round(ackley_gps, 3) if linear_ok else -1,
            "best_sphere_end": best_s,
            "best_ackley_end": best_a,
            "fitness_evals_per_sec":
                round(sphere_gps * LAMBDA, 1) if linear_ok else -1,
            "stock_deap_baseline_gens_per_sec": baseline,
            "prng": os.environ.get("BENCH_PRNG", "rbg"),
        },
    }))


if __name__ == "__main__":
    main()
