#!/usr/bin/env python
"""OneMax GA benchmark (BASELINE config 1): 100-bit individuals, pop=300,
the reference README's canonical example at its exact shape.  Prints ONE
JSON line like bench.py.

At this size the device is idle almost all the time — the point of the
config is the *small-population* regime where the reference is most
competitive (stock DEAP measured 91.6 gens/s here, its best ratio by
far).  The whole run is still one ``lax.scan``, so the marginal
per-generation cost is dominated by kernel launch latency, not work —
which is exactly what the number should show.

Timing honesty kit identical to bench.py: marginal (t(2N)-t(N))/N with a
linearity self-check forced through host-materialised, data-dependent
output.

Env overrides: BENCH_POP (300), BENCH_BITS (100), BENCH_NGEN (20000 —
generations are ~20 µs, so the linearity gate needs many of them),
BENCH_PRNG (rbg | threefry).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

POP = int(os.environ.get("BENCH_POP", 300))
BITS = int(os.environ.get("BENCH_BITS", 100))
NGEN = int(os.environ.get("BENCH_NGEN", 20000))   # gens are ~20 µs: the
# linearity gate needs enough of them to dominate dispatch overhead


def run_tpu():
    import numpy as np
    import jax

    if os.environ.get("BENCH_PRNG", "rbg") == "rbg":
        jax.config.update("jax_default_prng_impl", "rbg")

    import jax.numpy as jnp
    from jax import lax
    from deap_tpu import base
    from deap_tpu.algorithms import vary_genome, evaluate_population
    from deap_tpu.ops import crossover, mutation, selection

    tb = base.Toolbox()
    tb.register("evaluate", lambda g: (jnp.sum(g),))
    tb.register("mate", crossover.cx_two_point)
    tb.register("mutate", mutation.mut_flip_bit, indpb=0.05)
    tb.register("select", selection.sel_tournament, tournsize=3)

    def generation(carry, _):
        key, pop = carry
        key, k_sel, k_var = jax.random.split(key, 3)
        idx = tb.select(k_sel, pop.fitness, POP)
        genome = pop.genome[idx]
        genome, _ = vary_genome(k_var, genome, tb, 0.5, 0.2)
        off = base.Population(genome, base.Fitness.empty(POP, (1.0,)))
        off, _ = evaluate_population(tb, off)
        return (key, off), jnp.max(off.fitness.values[:, 0])

    def make_run(ngen):
        @jax.jit
        def run(key, pop):
            return lax.scan(generation, (key, pop), None, length=ngen)
        return run

    key = jax.random.PRNGKey(0)
    genome = jax.random.bernoulli(key, 0.5, (POP, BITS)).astype(jnp.float32)
    pop = base.Population(genome, base.Fitness.empty(POP, (1.0,)))
    pop, _ = evaluate_population(tb, pop)

    def timed(ngen):
        run = make_run(ngen)
        _, best = run(key, pop)
        np.asarray(best[-1:])
        t0 = time.perf_counter()
        _, best = run(key, pop)
        best_host = np.asarray(best)
        return time.perf_counter() - t0, float(best_host.max())

    t1, _ = timed(NGEN)
    t2, best = timed(2 * NGEN)
    ratio = t2 / t1
    marginal = (t2 - t1) / NGEN
    return 1.0 / marginal, ratio, best, jax.devices()[0].platform


def measured_baseline():
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BASELINE.json")
    try:
        with open(path) as f:
            measured = json.load(f).get("measured", {})
        if (POP, BITS) != (300, 100):
            return None
        return measured["onemax_pop300_gens_per_sec_serial"]
    except (OSError, KeyError, ValueError):
        return None


def main():
    gens_per_sec, ratio, best, platform = run_tpu()
    linear_ok = 1.5 <= ratio <= 2.7
    baseline = measured_baseline()
    vs = (gens_per_sec / baseline) if (baseline and linear_ok) else -1.0
    print(json.dumps({
        "metric": f"onemax_ga_pop{POP}_bits{BITS}_gens_per_sec",
        "value": round(gens_per_sec, 1) if linear_ok else -1,
        "unit": "generations/sec",
        "vs_baseline": round(vs, 1),
        "extra": {
            "platform": platform,
            "timing_linearity": {"t2N_over_tN": round(ratio, 3),
                                 "ok": linear_ok},
            "best_fitness_seen": best,
            "stock_deap_baseline_gens_per_sec": baseline,
            "prng": os.environ.get("BENCH_PRNG", "rbg"),
        },
    }))


if __name__ == "__main__":
    main()
