"""Live per-session migration — move ONE hot session between
instances, mid-run, bitwise-exactly.

The protocol composes three surfaces the fleet already has:

1. **quiesce + export** (``POST /v1/admin/migrate`` on the source) —
   the source flips the session's ``migrating`` flag under the
   dispatcher's queue lock, waits for its in-flight batch and queued
   requests to drain at a *dispatch boundary*, snapshots it in the
   versioned drain wire form, and forgets it.  Every other session on
   the source keeps serving untouched; new work for the migrating
   session is typed-rejected (``ServiceDraining``) and the router's
   forwarder retries it onto the new home once the route commits.
2. **restore** (``POST /v1/admin/restore`` on the target) — the same
   adoption path failover uses; the snapshot carries the raw PRNG key,
   genome, fitness values and bucket rows, so the continuation
   trajectory on the target is bitwise-equal to the trajectory the
   session would have produced had it never moved (slot-packing
   guarantees a slot's result depends only on that slot).
3. **route commit** — :meth:`FleetRouter.reroute_session` rewrites the
   routing table atomically and wakes blocked forwarders; the source is
   left with a *single-session* 307 redirect so clients pointed
   directly at it follow the move without a router in the path.

Failure containment: if the target rejects or dies mid-restore, the
snapshot is restored **back onto the source** and the routing table is
never touched — the migration aborts to exactly the pre-call state
(modulo the quiesce pause the session observed).
"""

from __future__ import annotations

from typing import Optional

from ...observability.sinks import emit_text
from ..buckets import genome_signature
from ..dispatcher import ServeError
from ..router.backend import Backend, BackendDown

__all__ = ["migrate_session", "MigrationError"]


class MigrationError(ServeError):
    """A migration that could not complete; the session's state is
    back on the source (rolled back) unless chained context says
    otherwise."""


def migrate_session(router, name: str, *,
                    target: Optional[Backend] = None,
                    timeout: float = 30.0,
                    prewarm: bool = False) -> dict:
    """Live-migrate session ``name`` to ``target`` (bucket-affinity
    chosen when None).  Returns a summary dict; raises
    :class:`MigrationError` after rolling the session back onto its
    source on any target-side failure.

    ``prewarm`` runs a ``rebucket(warm=("step",))`` on the target after
    the route commits, so the migrated session's next step hits an
    already-compiled program instead of paying its compile inline.
    """
    source = router.route_of(name)
    if target is not None and target.name == source.name:
        raise ValueError(f"session {name!r} is already on {target.name}")
    clock = router.tracer.clock
    t0 = clock()
    # -- quiesce + export (the downtime window opens here) --------------------
    snap = source.migrate(name, timeout=timeout)
    try:
        if target is None:
            target = router.pick_migration_target(
                snap, exclude=(source.name,))
            if target is None:
                raise MigrationError(
                    f"no healthy backend can adopt {name!r} "
                    f"(toolbox {snap.get('toolbox')!r})")
        resp = target.restore({name: snap})
        if name not in (resp.get("restored") or ()):
            raise MigrationError(
                f"target {target.name} skipped {name!r}: "
                f"{(resp.get('skipped') or {}).get(name)}")
    except BaseException as e:
        # roll back: the source exported (and forgot) the session, so
        # put the snapshot straight back — route never moved, nothing
        # to rewrite.  A rollback failure is the one state-losing shape
        # and is surfaced chained for the operator.
        router.metrics.inc("autoscale_migration_failures")
        try:
            source.restore({name: snap})
        except (BackendDown, ServeError, OSError) as rb:
            raise MigrationError(
                f"migration of {name!r} failed ({e}) AND rollback onto "
                f"{source.name} failed ({rb}); session lost") from e
        emit_text(f"[autoscale] migration of {name!r} to "
                  f"{'?' if target is None else target.name} failed "
                  f"({e}); rolled back onto {source.name}", router.sinks)
        if isinstance(e, MigrationError):
            raise
        raise MigrationError(
            f"migration of {name!r} failed; rolled back: {e}") from e
    # -- route commit (downtime window closes at the notify) ------------------
    n = int(snap.get("n", 1))
    router.reroute_session(name, target, n, genome_signature(snap["genome"]))
    seconds = clock() - t0
    # single-session redirect on the source: direct clients follow the
    # move via 307 without re-pointing every other session (best effort
    # — the source may be mid-teardown on the scale-in path)
    try:
        source.set_redirect(target.url, session=name)
    except (BackendDown, ServeError, OSError):
        pass
    if prewarm:
        try:
            target.rebucket(warm=("step",))
        except (BackendDown, ServeError, OSError):
            pass
    router.metrics.inc("autoscale_migrations")
    router.metrics.set_gauge("autoscale_migration_downtime_s", seconds)
    emit_text(f"[autoscale] migrated {name!r} {source.name} -> "
              f"{target.name} in {seconds:.3f}s", router.sinks)
    return {"session": name, "source": source.name,
            "target": target.name, "seconds": seconds}
