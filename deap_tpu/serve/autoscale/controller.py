"""The autoscaler control loop — elastic capacity for one fleet.

:class:`Autoscaler` closes the loop between the fleet's existing
telemetry and its existing elasticity primitives:

* **signals in** — each tick samples every live instance's
  ``/v1/metrics`` (dispatcher ``queue_depth``, bucket ``pad_waste``,
  overload/deadline shed counters) and ``/v1/profile`` (the roofline
  ``phase_split`` compute fraction) into one
  :class:`~deap_tpu.serve.autoscale.policy.FleetSignals` record;
* **decisions** — the pure
  :class:`~deap_tpu.serve.autoscale.policy.AutoscalePolicy` classifies
  the sample; the controller supplies the temporal smoothing
  (consecutive-tick streaks, post-event cooldown) so one noisy sample
  never flaps the fleet;
* **actuation out** — scale-out spawns an instance through the
  injected :class:`InstanceProvider`, **pre-warms** it with the
  fleet-merged bucket grid the router's placement layer already tracks
  (``rebucket(sizes=...)`` — so the first session migrated or placed
  onto it lands in a bucket compiled before its traffic arrives, zero
  unplanned steady-state recompiles) and only then routes to it;
  scale-in reuses PR 9's drain→restore failover to move every session
  off the victim bitwise, then forgets and disposes it.

The loop is an Event-wait (``stop.wait(interval)``) — the
``no-blocking-sleep`` lint holds for this subpackage; stopping
interrupts immediately.  Tests drive :meth:`tick` directly with
``start()`` never called and an injected clock: the controller is then
fully deterministic.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Callable, Optional

from ... import sanitize
from ...observability.sinks import emit_text
from ..dispatcher import ServeError
from ..router.backend import Backend, BackendDown
from .policy import AutoscalePolicy, FleetSignals

__all__ = ["Autoscaler", "InstanceProvider", "CallbackProvider"]


class InstanceProvider:
    """Where instances come from and go to.  The autoscaler never
    constructs servers itself — deployments inject a provider that
    spawns a real process/container and returns a
    :class:`~deap_tpu.serve.router.backend.Backend` handle; tests
    inject in-process NetServers."""

    def spawn(self) -> Backend:
        raise NotImplementedError

    def dispose(self, backend: Backend) -> None:
        raise NotImplementedError


class CallbackProvider(InstanceProvider):
    """Adapter: two callables as a provider."""

    def __init__(self, spawn: Callable[[], Backend],
                 dispose: Callable[[Backend], None]):
        self._spawn = spawn
        self._dispose = dispose

    def spawn(self) -> Backend:
        return self._spawn()

    def dispose(self, backend: Backend) -> None:
        self._dispose(backend)


class Autoscaler:
    """Scale a :class:`~deap_tpu.serve.router.core.FleetRouter`'s
    backend set between ``policy.min_instances`` and
    ``policy.max_instances`` (see module docstring)."""

    #: lock-guarded shared state (``lock-discipline`` lint): streak and
    #: cooldown bookkeeping plus the last sample/decision, written by
    #: the loop thread and read by ``describe()`` on handler threads
    _GUARDED_BY = {"_lock": ("_streak_out", "_streak_in", "_last_event_t",
                             "_last_signals", "_last_decision",
                             "_shed_seen")}

    def __init__(self, router, provider: InstanceProvider, *,
                 policy: Optional[AutoscalePolicy] = None,
                 fabric=None, clock=None, verbose: bool = False):
        import time
        self.router = router
        self.provider = provider
        self.policy = policy if policy is not None else AutoscalePolicy()
        self.fabric = fabric
        self.verbose = bool(verbose)
        self._clock = clock if clock is not None else time.monotonic
        self._lock = sanitize.lock()
        self._stop = sanitize.event()
        self._thread: Optional[threading.Thread] = None
        self._streak_out = 0
        self._streak_in = 0
        self._last_event_t = float("-inf")
        self._last_signals: Optional[FleetSignals] = None
        self._last_decision = "hold"
        self._shed_seen = 0
        router.attach_autoscaler(self)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "Autoscaler":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="deap-tpu-autoscaler", daemon=True)
            self._thread.start()
        if self.fabric is not None:
            self.fabric.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        if self.fabric is not None:
            self.fabric.stop()

    def _run(self) -> None:
        while not self._stop.wait(self.policy.interval_s):
            try:
                self.tick()
            except Exception as e:  # noqa: BLE001 — loop must survive
                self.router.metrics.inc("autoscale_errors")
                emit_text(f"[autoscale] tick failed: {e!r}",
                          self.router.sinks)

    # -- signals -------------------------------------------------------------

    def sample(self) -> FleetSignals:
        """One fleet-wide sample.  Shed counters are cumulative on the
        instances; this converts them to a since-last-tick delta (the
        one impure part of sampling — each call advances the
        watermark)."""
        backends = self.router.healthy()
        qd: list = []
        pw: list = []
        busy: list = []
        shed_total = 0
        for b in backends:
            try:
                rec = b.metrics()
            except (BackendDown, ServeError, OSError, ValueError):
                continue
            g = rec.get("gauges") or {}
            c = rec.get("counters") or {}
            qd.append(float(g.get("queue_depth", 0.0) or 0.0))
            pw.append(float(g.get("pad_waste", 0.0) or 0.0))
            for k in ("rejected", "deadline_shed", "brownout_sheds"):
                shed_total += int(c.get(k, 0) or 0)
            try:
                prof = b.profile()
            except (BackendDown, ServeError, OSError, ValueError):
                prof = None
            for p in ((prof or {}).get("programs") or {}).values():
                frac = (p.get("phase_split") or {}).get("compute_frac")
                if frac is not None:
                    busy.append(float(frac))
        sessions = int(self.router.stats().gauges.get(
            "router_sessions_routed", 0))
        with self._lock:
            delta = max(0, shed_total - self._shed_seen)
            self._shed_seen = shed_total
        return FleetSignals(
            instances=len(backends),
            queue_depth=sum(qd) / len(qd) if qd else 0.0,
            pad_waste=sum(pw) / len(pw) if pw else 0.0,
            sessions=sessions,
            shed_delta=delta,
            device_busy_frac=max(busy) if busy else 0.0)

    # -- the control loop body -----------------------------------------------

    def tick(self) -> dict:
        """One sample → classify → (maybe) act round.  Serialized by
        construction: either the started loop thread calls this, or a
        test driver does — never both."""
        signals = self.sample()
        decision = self.policy.classify(signals)
        now = self._clock()
        act = None
        with self._lock:
            self._last_signals = signals
            self._last_decision = decision
            if decision == "out":
                self._streak_out += 1
                self._streak_in = 0
            elif decision == "in":
                self._streak_in += 1
                self._streak_out = 0
            else:
                self._streak_out = 0
                self._streak_in = 0
            cooling = (now - self._last_event_t) < self.policy.cooldown_s
            if not cooling:
                if decision == "out" \
                        and self._streak_out >= self.policy.out_streak:
                    act = "out"
                    self._streak_out = 0
                elif decision == "in" \
                        and self._streak_in >= self.policy.in_streak:
                    act = "in"
                    self._streak_in = 0
        self.router.metrics.set_gauge("autoscale_last_decision_queue_depth",
                                      signals.queue_depth)
        self.router.metrics.set_gauge("autoscale_instances",
                                      signals.instances)
        if act == "out":
            self.scale_out()
        elif act == "in":
            self.scale_in()
        return {"signals": signals.as_dict(), "decision": decision,
                "acted": act}

    # -- actuation -----------------------------------------------------------

    def scale_out(self) -> Backend:
        """Spawn, predictively pre-warm, then route: the instance joins
        the fleet already carrying the fleet-merged bucket grid, so
        nothing placed onto it recompiles in steady state."""
        backend = self.provider.spawn()
        grid = self.router.live_fleet_rows()
        if grid:
            try:
                backend.rebucket(sizes=list(grid), warm=())
                self.router.metrics.inc("autoscale_prewarms")
            except (BackendDown, ServeError, OSError) as e:
                # a cold instance still serves (it just compiles on
                # first traffic) — pre-warm failure must not strand the
                # spawned capacity outside the fleet
                emit_text(f"[autoscale] pre-warm of {backend.name} "
                          f"failed ({e}); joining cold",
                          self.router.sinks)
        self.router.add_backend(backend)
        self.router.metrics.inc("autoscale_scale_out_events")
        self._note_event()
        self.router.metrics.set_gauge("autoscale_instances",
                                      len(self.router.healthy()))
        emit_text(f"[autoscale] scaled out: {backend.name}",
                  self.router.sinks)
        return backend

    def scale_in(self) -> Optional[str]:
        """Drain the least-loaded instance through the failover path
        (sessions move bitwise to the survivors), then forget and
        dispose it.  None when no instance can be removed."""
        victim = self._pick_victim()
        if victim is None:
            return None
        self.router.failover(victim, reason="scale-in")
        self.router.remove_backend(victim.name)
        if self.fabric is not None:
            self.fabric.forget_backend(victim.name)
        self.provider.dispose(victim)
        self.router.metrics.inc("autoscale_scale_in_events")
        self._note_event()
        self.router.metrics.set_gauge("autoscale_instances",
                                      len(self.router.healthy()))
        emit_text(f"[autoscale] scaled in: {victim.name}",
                  self.router.sinks)
        return victim.name

    def _pick_victim(self) -> Optional[Backend]:
        healthy = self.router.healthy()
        if len(healthy) <= self.policy.min_instances:
            return None
        topo = self.router.topology()["backends"]
        load = {b.name: topo.get(b.name, {}).get("sessions", 0)
                for b in healthy}
        return min(healthy, key=lambda b: (load[b.name], b.name))

    def _note_event(self) -> None:
        with self._lock:
            self._last_event_t = self._clock()

    # -- introspection -------------------------------------------------------

    def describe(self) -> dict:
        """The ``autoscale`` section of
        :meth:`FleetRouter.topology` — policy, streaks, cooldown and
        the last sample."""
        now = self._clock()
        with self._lock:
            remaining = self.policy.cooldown_s - (now - self._last_event_t)
            return {
                "policy": dataclasses.asdict(self.policy),
                "running": self._thread is not None,
                "decision": self._last_decision,
                "streak_out": self._streak_out,
                "streak_in": self._streak_in,
                "cooldown_remaining_s": round(max(0.0, remaining), 3),
                "signals": (None if self._last_signals is None
                            else self._last_signals.as_dict()),
            }
