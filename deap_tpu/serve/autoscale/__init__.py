"""Elastic fleet: autoscaler, live session migration, cache fabric.

The router tier (:mod:`deap_tpu.serve.router`) made a *static* set of
instances one fault-tolerant fleet; this package makes the set
**elastic** — capacity follows load, sessions follow capacity, and
cache hits follow sessions:

* :mod:`~deap_tpu.serve.autoscale.policy` —
  :class:`FleetSignals` / :class:`AutoscalePolicy`: the pure decision
  model (thresholds, min/max bounds) with hysteresis and cooldown kept
  in the controller;
* :mod:`~deap_tpu.serve.autoscale.controller` —
  :class:`Autoscaler`: the Event-wait control loop sampling fleet
  telemetry (queue depth, pad waste, sheds, roofline ``phase_split``)
  and actuating spawn/drain through an injected
  :class:`InstanceProvider`.  Scale-out instances are **predictively
  pre-warmed** with the fleet-merged bucket grid before any traffic
  routes to them;
* :mod:`~deap_tpu.serve.autoscale.migrate` —
  :func:`migrate_session`: live per-session migration — quiesce one
  session at a dispatch boundary, snapshot, restore on a
  bucket-affine target, atomically rewrite the route, leave a
  single-session 307 redirect.  The migrated trajectory is
  bitwise-equal to the one an undisturbed session would produce;
* :mod:`~deap_tpu.serve.autoscale.fabric` —
  :class:`CacheFabric`: bounded digest-exchange gossip sharing
  content-addressed :class:`~deap_tpu.serve.cache.FitnessCache` hits
  across instances over the ordinary DTF1 wire.

Everything here composes wire surfaces the fleet already exposes
(drain/restore, ``/v1/metrics``, ``/v1/profile``, ``/v1/admin/*``) —
the package adds no new protocol, only the control loops above it.
"""

from .controller import (Autoscaler, CallbackProvider,  # noqa: F401
                         InstanceProvider)
from .fabric import CacheFabric  # noqa: F401
from .migrate import MigrationError, migrate_session  # noqa: F401
from .policy import AutoscalePolicy, FleetSignals  # noqa: F401

__all__ = [
    "Autoscaler", "InstanceProvider", "CallbackProvider",
    "AutoscalePolicy", "FleetSignals",
    "migrate_session", "MigrationError",
    "CacheFabric",
]
