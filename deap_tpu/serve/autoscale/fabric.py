"""Cross-instance fitness-cache fabric — bounded digest exchange.

Every instance's :class:`~deap_tpu.serve.cache.FitnessCache` journals
its *local* inserts under a portable content address
(``"toolbox|signature"`` namespace string + blake2b row digest — see
``FitnessCache.export_since``).  The fabric is a router-side gossip
pump: each round it pulls the journal tail from every live instance
(``POST /v1/admin/cache/export``, cursor round-tripped so a busy
instance streams its backlog across rounds) and pushes the gathered
entries to every *other* instance (``POST /v1/admin/cache/import``).

Duplicate evaluations of an identical genome row on *different*
instances then hit cache fleet-wide: imports land in a bounded
side-table the receiving cache consults on a local miss
(``cache_fabric_hits``), never evicting local entries and never
re-journaled (no gossip echo).

Everything rides the ordinary DTF1 wire — digests are raw bytes in the
frame header's ``__bytes__`` envelope, fitness rows are plain float
lists — so the fabric inherits TLS, compression negotiation and the
typed error envelopes for free.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from ... import sanitize
from ...observability.sinks import emit_text
from ..dispatcher import ServeError
from ..router.backend import BackendDown

__all__ = ["CacheFabric"]


class CacheFabric:
    """Periodic cache-journal exchange across a
    :class:`~deap_tpu.serve.router.core.FleetRouter`'s live backends.

    ``start()`` runs rounds on ``interval_s`` (Event-wait loop — the
    stop signal interrupts immediately, there is no polling sleep);
    tests and single-threaded drivers call :meth:`sync_now` directly
    and never start the thread.
    """

    #: lock-guarded shared state (``lock-discipline`` lint): per-backend
    #: journal cursors, read/written by the pump thread and sync_now
    #: callers
    _GUARDED_BY = {"_lock": ("_cursors",)}

    def __init__(self, router, *, interval_s: float = 1.0,
                 limit: int = 256, verbose: bool = False):
        self.router = router
        self.interval_s = float(interval_s)
        self.limit = int(limit)
        self.verbose = bool(verbose)
        self._lock = sanitize.lock()
        self._stop = sanitize.event()
        self._thread: Optional[threading.Thread] = None
        self._cursors: Dict[str, int] = {}

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "CacheFabric":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="deap-tpu-cache-fabric", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.sync_now()
            except Exception as e:  # noqa: BLE001 — pump must survive
                self.router.metrics.inc("autoscale_errors")
                emit_text(f"[autoscale] cache-fabric round failed: {e!r}",
                          self.router.sinks)

    # -- one exchange round --------------------------------------------------

    def sync_now(self) -> dict:
        """One full exchange round: pull every live backend's journal
        tail, push the union to every other backend.  Returns
        ``{"exported": n, "admitted": n}``."""
        backends = self.router.healthy()
        gathered: List[Tuple[str, List[dict]]] = []
        for b in backends:
            with self._lock:
                since = self._cursors.get(b.name, 0)
            try:
                out = b.cache_export(since, limit=self.limit)
            except (BackendDown, ServeError, OSError):
                continue
            seq = int(out.get("seq", since))
            if seq < since:
                # the instance restarted (fresh journal, lower seq) or a
                # new instance reused the name: rewind so its backlog is
                # picked up from the top next round instead of never
                seq = 0
            with self._lock:
                self._cursors[b.name] = seq
            entries = out.get("entries") or []
            if entries:
                gathered.append((b.name, entries))
        admitted = 0
        for src, entries in gathered:
            for b in backends:
                if b.name == src:
                    continue
                try:
                    admitted += int(b.cache_import(entries))
                except (BackendDown, ServeError, OSError):
                    continue
        self.router.metrics.inc("cache_fabric_syncs")
        exported = sum(len(e) for _, e in gathered)
        if self.verbose and exported:
            emit_text(f"[autoscale] cache fabric: {exported} entries "
                      f"from {len(gathered)} instances, {admitted} "
                      f"admissions", self.router.sinks)
        return {"exported": exported, "admitted": admitted}

    def forget_backend(self, name: str) -> None:
        """Drop the cursor of a scaled-in instance (its name may be
        reused by a future spawn with a fresh journal)."""
        with self._lock:
            self._cursors.pop(name, None)
