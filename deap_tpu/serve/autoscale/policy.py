"""Autoscaling decision model — pure, clock-free, trivially testable.

The :class:`Autoscaler` loop samples the fleet into one
:class:`FleetSignals` record per tick and asks the frozen
:class:`AutoscalePolicy` to classify it.  ``classify`` is a *pure
pressure classifier*: it looks at one instantaneous sample and says
whether the fleet is under pressure (``"out"``), idle enough to shrink
(``"in"``) or neither (``"hold"``).  All the *temporal* smoothing —
consecutive-tick streaks (hysteresis) and the post-event cooldown — is
the controller's job, so this module needs no clock and a unit test
needs no threads.

Signals, per the fleet's existing observability surface:

* ``queue_depth`` — mean dispatcher backlog across live instances
  (the primary load signal; one saturated dispatcher queue is the
  first externally-visible symptom of an undersized fleet);
* ``pad_waste`` — mean padded-row waste fraction (bucket pressure:
  high waste with high load means the grid is mis-sized, which
  rebucketing fixes better than scaling — so waste *dampens* scale-out
  rather than driving it);
* ``shed_delta`` — overload/deadline sheds observed since the previous
  tick (any shed is pressure, whatever the queue average says);
* ``device_busy_frac`` — the roofline model's compute fraction from the
  profiler's ``phase_split`` (an instance can be compute-bound with a
  short queue when steps are long);
* ``sessions`` / ``instances`` — fleet shape, for the min/max bounds.
"""

from __future__ import annotations

import dataclasses

__all__ = ["FleetSignals", "AutoscalePolicy"]


@dataclasses.dataclass(frozen=True)
class FleetSignals:
    """One tick's fleet-wide sample (means across live instances)."""

    instances: int
    queue_depth: float = 0.0
    pad_waste: float = 0.0
    sessions: int = 0
    shed_delta: int = 0
    device_busy_frac: float = 0.0

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class AutoscalePolicy:
    """Thresholds and bounds for the elastic fleet.

    ``out_streak`` / ``in_streak`` are the hysteresis widths: the
    controller must see that many *consecutive* ticks classified the
    same way before acting, and scale-in deliberately needs a longer
    streak than scale-out (adding capacity late sheds traffic; removing
    it early causes a migrate-back flap).  ``cooldown_s`` suppresses
    any further scaling event — in either direction — after one fires,
    so a migration-induced queue blip never triggers a second event.
    """

    min_instances: int = 1
    max_instances: int = 4
    queue_high: float = 8.0
    queue_low: float = 1.0
    busy_high: float = 0.85
    out_streak: int = 2
    in_streak: int = 3
    cooldown_s: float = 30.0
    interval_s: float = 1.0

    def __post_init__(self):
        if self.min_instances < 1:
            raise ValueError("min_instances must be >= 1")
        if self.max_instances < self.min_instances:
            raise ValueError("max_instances < min_instances")
        if self.queue_low > self.queue_high:
            raise ValueError("queue_low > queue_high")
        if self.out_streak < 1 or self.in_streak < 1:
            raise ValueError("streaks must be >= 1")

    def classify(self, s: FleetSignals) -> str:
        """``"out"`` / ``"in"`` / ``"hold"`` for one sample.  Bounds
        dominate: a fleet outside ``[min, max]`` always moves toward
        the band regardless of load."""
        if s.instances < self.min_instances:
            return "out"
        if s.instances > self.max_instances:
            return "in"
        pressure = (s.queue_depth >= self.queue_high
                    or s.shed_delta > 0
                    or s.device_busy_frac >= self.busy_high)
        if pressure and s.instances < self.max_instances:
            return "out"
        idle = (s.queue_depth <= self.queue_low
                and s.shed_delta == 0
                and s.device_busy_frac < self.busy_high)
        if idle and s.instances > self.min_instances:
            return "in"
        return "hold"
