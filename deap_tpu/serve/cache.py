"""Two-tier content-addressed fitness cache.

Tier 1 (device, within a batch): :func:`rep_indices` — lexicographic
sort + adjacent-unique over the raw genome bits maps every row of an
evaluation microbatch to the batch index of its group leader; gathering
evaluated values through that map makes identical genomes return
**bitwise-identical** fitness inside one dispatch even for a
non-deterministic evaluator, and the unique count feeds the ``dedup_rows``
counter.

Tier 2 (host, across batches/sessions): :class:`FitnessCache` — an LRU of
``blake2b(genome row bytes)`` → fitness values, namespaced by evaluator
identity (two sessions sharing an evaluator share entries; different
objectives never collide).  Hits are spliced over the device results, so a
genome evaluated once returns the same bits forever after, from any
session.  **Non-finite values are never inserted** — a quarantined (NaN)
evaluation must be re-attempted, not immortalized (pinned by
``tests/test_serve.py``).
"""

from __future__ import annotations

import collections
import hashlib
from typing import Dict, List, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from .. import sanitize

__all__ = ["FitnessCache", "row_digests", "rep_indices", "flatten_rows"]


def flatten_rows(genome) -> jax.Array:
    """Concatenate a genome pytree into one ``(rows, flat_dim)`` array
    (the content view both cache tiers hash/compare)."""
    leaves = jax.tree_util.tree_leaves(genome)
    return jnp.concatenate(
        [jnp.asarray(l).reshape(l.shape[0], -1) for l in leaves], axis=1)


def row_digests(rows: np.ndarray) -> List[bytes]:
    """Content digest per row: blake2b over the raw row bytes, salted with
    dtype + row shape so equal bytes of different types never collide."""
    rows = np.ascontiguousarray(rows)
    salt = f"{rows.dtype.str}:{rows.shape[1:]}".encode()
    return [hashlib.blake2b(salt + r.tobytes(), digest_size=16).digest()
            for r in rows]


def _bit_view(flat: jax.Array) -> jax.Array:
    """Exact-equality integer view of the rows (floats compared by bit
    pattern, so sort/unique grouping never hits NaN != NaN semantics)."""
    if flat.dtype == jnp.float32:
        return jax.lax.bitcast_convert_type(flat, jnp.uint32)
    if flat.dtype == jnp.float16 or flat.dtype == jnp.bfloat16:
        return jax.lax.bitcast_convert_type(flat, jnp.uint16)
    if jnp.issubdtype(flat.dtype, jnp.integer) or flat.dtype == jnp.bool_:
        return flat
    raise TypeError(f"no exact bit view for dtype {flat.dtype}")


def rep_indices(flat: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Device-side within-batch dedup: for ``(rows, flat_dim)`` genome
    content, return ``(rep, n_unique)`` where ``rep[i]`` is the batch index
    of the first row whose content equals row ``i`` (its group *leader*),
    and ``n_unique`` counts distinct rows.  Pure array ops (one variadic
    lexsort + a cumulative max), safe under jit.

    ``values[rep]`` then assigns every duplicate its leader's evaluated
    value — bitwise equality of identical genomes by construction."""
    b = _bit_view(flat)
    rows, d = b.shape
    order = jnp.lexsort([b[:, j] for j in range(d - 1, -1, -1)])
    sg = b[order]
    first = jnp.concatenate([jnp.ones((1,), bool),
                             jnp.any(sg[1:] != sg[:-1], axis=1)])
    # index (in sorted space) of each row's group leader, then back to
    # batch space on both sides of the mapping
    leader_sorted = jax.lax.cummax(jnp.where(first, jnp.arange(rows), 0))
    rep = jnp.zeros((rows,), jnp.int32).at[order].set(
        order[leader_sorted].astype(jnp.int32))
    return rep, jnp.sum(first.astype(jnp.int32))


class FitnessCache:
    """Host LRU of genome-content digests → fitness values.

    ``capacity`` bounds the entry count (least-recently-used eviction,
    counted in ``cache_evictions``).  Keys are ``(namespace, digest)`` —
    the service namespaces by evaluator identity + genome signature +
    objective count, so only sessions that share an evaluator share
    entries.  Values are defensive copies of ``(nobj,)`` float arrays.
    Thread-safe (the dispatcher thread writes; stats readers poll)."""

    #: lock-guarded shared state (``lock-discipline`` lint pass): the
    #: LRU map is written by the dispatcher thread and read by any
    #: client/stats thread — every mutation must hold ``self._lock``
    _GUARDED_BY = {"_lock": ("_entries",)}

    def __init__(self, capacity: int = 4096, metrics=None):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = int(capacity)
        self._metrics = metrics
        self._lock = sanitize.lock()
        self._entries: "collections.OrderedDict[tuple, np.ndarray]" = \
            collections.OrderedDict()

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def _inc(self, name: str, v: int = 1) -> None:
        if self._metrics is not None and v:
            self._metrics.inc(name, v)

    def lookup(self, namespace, digests: List[bytes]
               ) -> List[Optional[np.ndarray]]:
        """Per-digest hit values (``None`` on miss); hits are refreshed to
        most-recently-used and counted."""
        out: List[Optional[np.ndarray]] = []
        hits = misses = 0
        with self._lock:
            for d in digests:
                k = (namespace, d)
                v = self._entries.get(k)
                if v is None:
                    misses += 1
                    out.append(None)
                else:
                    hits += 1
                    self._entries.move_to_end(k)
                    out.append(v)
        self._inc("cache_hits", hits)
        self._inc("cache_misses", misses)
        return out

    def insert(self, namespace, digests: List[bytes],
               values: np.ndarray) -> int:
        """Insert ``digest[i] -> values[i]`` for every FINITE row; NaN/Inf
        rows are skipped (and counted as ``cache_nan_skipped``) — a
        quarantined evaluation is never content-addressable.  Returns the
        number of rows inserted."""
        values = np.asarray(values)
        inserted = skipped = evicted = 0
        with self._lock:
            for d, v in zip(digests, values):
                if not np.all(np.isfinite(v)):
                    skipped += 1
                    continue
                k = (namespace, d)
                if k in self._entries:
                    self._entries.move_to_end(k)
                    continue
                self._entries[k] = np.array(v, copy=True)
                inserted += 1
                while len(self._entries) > self.capacity:
                    self._entries.popitem(last=False)
                    evicted += 1
        self._inc("cache_nan_skipped", skipped)
        self._inc("cache_evictions", evicted)
        return inserted

    def contains(self, namespace, digest: bytes) -> bool:
        with self._lock:
            return (namespace, digest) in self._entries

    def purge_namespace(self, evaluator_id: int) -> int:
        """Drop every entry whose namespace belongs to ``evaluator_id``
        (the leading element of the service's ``(evaluator_id, sig, nobj)``
        namespace tuples).  The service calls this when an evaluator's pin
        refcount hits zero: ``id()`` values recycle, so a later evaluator
        allocated at the same address must never inherit the dead one's
        cached fitness.  Returns the number of entries purged (also counted
        as ``cache_purged``)."""
        with self._lock:
            stale = [k for k in self._entries
                     if isinstance(k[0], tuple) and k[0]
                     and k[0][0] == evaluator_id]
            for k in stale:
                del self._entries[k]
        self._inc("cache_purged", len(stale))
        return len(stale)

    def hit_rate(self) -> float:
        """Lifetime hit fraction (0.0 when nothing was looked up)."""
        if self._metrics is None:
            return 0.0
        h = self._metrics.counter("cache_hits")
        m = self._metrics.counter("cache_misses")
        return h / (h + m) if h + m else 0.0
