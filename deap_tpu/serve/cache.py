"""Two-tier content-addressed fitness cache.

Tier 1 (device, within a batch): :func:`rep_indices` — lexicographic
sort + adjacent-unique over the raw genome bits maps every row of an
evaluation microbatch to the batch index of its group leader; gathering
evaluated values through that map makes identical genomes return
**bitwise-identical** fitness inside one dispatch even for a
non-deterministic evaluator, and the unique count feeds the ``dedup_rows``
counter.

Tier 2 (host, across batches/sessions): :class:`FitnessCache` — an LRU of
``blake2b(genome row bytes)`` → fitness values, namespaced by evaluator
identity (two sessions sharing an evaluator share entries; different
objectives never collide).  Hits are spliced over the device results, so a
genome evaluated once returns the same bits forever after, from any
session.  **Non-finite values are never inserted** — a quarantined (NaN)
evaluation must be re-attempted, not immortalized (pinned by
``tests/test_serve.py``).
"""

from __future__ import annotations

import collections
import hashlib
from typing import Dict, List, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from .. import sanitize

__all__ = ["FitnessCache", "row_digests", "rep_indices", "flatten_rows"]


def flatten_rows(genome) -> jax.Array:
    """Concatenate a genome pytree into one ``(rows, flat_dim)`` array
    (the content view both cache tiers hash/compare)."""
    leaves = jax.tree_util.tree_leaves(genome)
    return jnp.concatenate(
        [jnp.asarray(l).reshape(l.shape[0], -1) for l in leaves], axis=1)


def row_digests(rows: np.ndarray) -> List[bytes]:
    """Content digest per row: blake2b over the raw row bytes, salted with
    dtype + row shape so equal bytes of different types never collide."""
    rows = np.ascontiguousarray(rows)
    salt = f"{rows.dtype.str}:{rows.shape[1:]}".encode()
    return [hashlib.blake2b(salt + r.tobytes(), digest_size=16).digest()
            for r in rows]


def _bit_view(flat: jax.Array) -> jax.Array:
    """Exact-equality integer view of the rows (floats compared by bit
    pattern, so sort/unique grouping never hits NaN != NaN semantics)."""
    if flat.dtype == jnp.float32:
        return jax.lax.bitcast_convert_type(flat, jnp.uint32)
    if flat.dtype == jnp.float16 or flat.dtype == jnp.bfloat16:
        return jax.lax.bitcast_convert_type(flat, jnp.uint16)
    if jnp.issubdtype(flat.dtype, jnp.integer) or flat.dtype == jnp.bool_:
        return flat
    raise TypeError(f"no exact bit view for dtype {flat.dtype}")


def rep_indices(flat: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Device-side within-batch dedup: for ``(rows, flat_dim)`` genome
    content, return ``(rep, n_unique)`` where ``rep[i]`` is the batch index
    of the first row whose content equals row ``i`` (its group *leader*),
    and ``n_unique`` counts distinct rows.  Pure array ops (one variadic
    lexsort + a cumulative max), safe under jit.

    ``values[rep]`` then assigns every duplicate its leader's evaluated
    value — bitwise equality of identical genomes by construction."""
    b = _bit_view(flat)
    rows, d = b.shape
    order = jnp.lexsort([b[:, j] for j in range(d - 1, -1, -1)])
    sg = b[order]
    first = jnp.concatenate([jnp.ones((1,), bool),
                             jnp.any(sg[1:] != sg[:-1], axis=1)])
    # index (in sorted space) of each row's group leader, then back to
    # batch space on both sides of the mapping
    leader_sorted = jax.lax.cummax(jnp.where(first, jnp.arange(rows), 0))
    rep = jnp.zeros((rows,), jnp.int32).at[order].set(
        order[leader_sorted].astype(jnp.int32))
    return rep, jnp.sum(first.astype(jnp.int32))


class FitnessCache:
    """Host LRU of genome-content digests → fitness values.

    ``capacity`` bounds the entry count (least-recently-used eviction,
    counted in ``cache_evictions``).  Keys are ``(namespace, digest)`` —
    the service namespaces by evaluator identity + genome signature +
    objective count, so only sessions that share an evaluator share
    entries.  Values are defensive copies of ``(nobj,)`` float arrays.
    Thread-safe (the dispatcher thread writes; stats readers poll)."""

    #: lock-guarded shared state (``lock-discipline`` lint pass): the
    #: LRU maps, alias table and insert journal are written by the
    #: dispatcher thread and read by any client/stats/fabric thread —
    #: every mutation must hold ``self._lock``
    _GUARDED_BY = {"_lock": ("_entries", "_aliases", "_journal",
                             "_journal_seq", "_fabric")}

    def __init__(self, capacity: int = 4096, metrics=None, *,
                 journal_capacity: int = 1024):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = int(capacity)
        self._metrics = metrics
        self._lock = sanitize.lock()
        self._entries: "collections.OrderedDict[tuple, np.ndarray]" = \
            collections.OrderedDict()
        #: evaluator id → stable cross-instance name (the toolbox
        #: registry name) — the translation that makes a namespace
        #: portable on the cache-fabric wire.  ``id()`` values are
        #: process-local AND the ``sig`` element of a namespace holds a
        #: PyTreeDef (not wire-serializable), so exported entries are
        #: re-keyed ``(name|str(sig), nobj, digest)``.
        self._aliases: Dict[int, str] = {}
        #: bounded journal of LOCAL inserts ``(seq, namespace, digest,
        #: values)`` — the fabric's digest-exchange source.  Imported
        #: entries are never journaled, so two instances exchanging
        #: digests can never echo each other's rows back and forth.
        self._journal: "collections.deque[tuple]" = collections.deque(
            maxlen=int(journal_capacity))
        self._journal_seq = 0
        #: imported cross-instance entries, LRU-bounded separately from
        #: the main table and keyed by PORTABLE namespace — a fabric row
        #: is a hint from another instance, never allowed to evict
        #: locally computed fitness
        self._fabric: "collections.OrderedDict[tuple, np.ndarray]" = \
            collections.OrderedDict()

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def _inc(self, name: str, v: int = 1) -> None:
        if self._metrics is not None and v:
            self._metrics.inc(name, v)

    def lookup(self, namespace, digests: List[bytes]
               ) -> List[Optional[np.ndarray]]:
        """Per-digest hit values (``None`` on miss); hits are refreshed to
        most-recently-used and counted.  A miss falls through to the
        fabric table of imported cross-instance entries (when the
        namespace has a portable alias): a genome evaluated on another
        instance of the fleet is a hit here too (``cache_fabric_hits``),
        and the consumed hint is promoted into the main table."""
        out: List[Optional[np.ndarray]] = []
        hits = misses = fabric_hits = evicted = 0
        with self._lock:
            portable = self._portable_locked(namespace)
            for d in digests:
                k = (namespace, d)
                v = self._entries.get(k)
                if v is not None:
                    hits += 1
                    self._entries.move_to_end(k)
                    out.append(v)
                    continue
                fv = None if portable is None else \
                    self._fabric.get(portable + (d,))
                if fv is None:
                    misses += 1
                    out.append(None)
                    continue
                # promotion goes through _entries directly, NOT
                # insert(): a consumed fabric hint must never enter the
                # local journal (re-exporting it would echo rows around
                # the fleet forever)
                fabric_hits += 1
                hits += 1
                self._entries[k] = fv
                while len(self._entries) > self.capacity:
                    self._entries.popitem(last=False)
                    evicted += 1
                out.append(fv)
        self._inc("cache_hits", hits)
        self._inc("cache_misses", misses)
        self._inc("cache_fabric_hits", fabric_hits)
        self._inc("cache_evictions", evicted)
        return out

    def insert(self, namespace, digests: List[bytes],
               values: np.ndarray) -> int:
        """Insert ``digest[i] -> values[i]`` for every FINITE row; NaN/Inf
        rows are skipped (and counted as ``cache_nan_skipped``) — a
        quarantined evaluation is never content-addressable.  Returns the
        number of rows inserted."""
        values = np.asarray(values)
        inserted = skipped = evicted = 0
        with self._lock:
            for d, v in zip(digests, values):
                if not np.all(np.isfinite(v)):
                    skipped += 1
                    continue
                k = (namespace, d)
                if k in self._entries:
                    self._entries.move_to_end(k)
                    continue
                row = np.array(v, copy=True)
                self._entries[k] = row
                self._journal_seq += 1
                self._journal.append((self._journal_seq, namespace, d, row))
                inserted += 1
                while len(self._entries) > self.capacity:
                    self._entries.popitem(last=False)
                    evicted += 1
        self._inc("cache_nan_skipped", skipped)
        self._inc("cache_evictions", evicted)
        return inserted

    def contains(self, namespace, digest: bytes) -> bool:
        with self._lock:
            return (namespace, digest) in self._entries

    # -- cross-instance fabric ------------------------------------------------

    def _portable_locked(self, namespace) -> Optional[tuple]:
        """Wire-stable rendering of a local ``(evaluator_id, sig, nobj)``
        namespace: ``("<alias>|<str(sig)>", nobj)``, or ``None`` when the
        evaluator has no registered alias (unaliased namespaces stay
        instance-local — nothing anonymous ever crosses the wire)."""
        if not (isinstance(namespace, tuple) and len(namespace) == 3):
            return None
        eid, sig, nobj = namespace
        alias = self._aliases.get(eid)
        if alias is None:
            return None
        return (f"{alias}|{sig}", int(nobj))

    def register_namespace_alias(self, evaluator_id: int,
                                 name: str) -> None:
        """Bind ``evaluator_id`` to a stable cross-instance ``name`` (the
        toolbox registry name the fleet agrees on).  Only aliased
        namespaces participate in the fabric exchange; the alias dies
        with the namespace at :meth:`purge_namespace` (``id()`` recycling
        must not resurrect it for an unrelated evaluator)."""
        with self._lock:
            self._aliases[int(evaluator_id)] = str(name)

    @property
    def journal_seq(self) -> int:
        """Sequence number of the newest local insert (export cursor)."""
        with self._lock:
            return self._journal_seq

    def export_since(self, seq: int, limit: int = 256
                     ) -> Tuple[List[dict], int]:
        """Local inserts journaled after cursor ``seq``, re-keyed to
        their portable namespaces, newest cursor second.  Bounded by
        ``limit`` (the fabric round-trips the cursor, so a busy instance
        streams its backlog across exchanges instead of one giant
        frame).  Unaliased inserts are skipped but still advance the
        cursor — they can never become exportable retroactively."""
        out: List[dict] = []
        last = int(seq)
        with self._lock:
            for s, ns, d, v in self._journal:
                if s <= seq:
                    continue
                if len(out) >= max(1, int(limit)):
                    break
                last = s
                portable = self._portable_locked(ns)
                if portable is None:
                    continue
                out.append({"ns": portable[0], "nobj": portable[1],
                            "digest": d, "values": [float(x) for x in v]})
        self._inc("cache_fabric_exports", len(out))
        return out, last

    def import_entries(self, entries: List[dict]) -> int:
        """Admit another instance's exported entries into the fabric
        table (LRU-bounded at ``capacity``, separate from the main
        table).  Non-finite rows are dropped exactly like local inserts
        — a quarantined evaluation must never become content-addressable
        by riding in over the wire.  Returns rows admitted."""
        admitted = 0
        with self._lock:
            for e in entries:
                values = np.asarray(e["values"], np.float32)
                if values.ndim != 1 or not np.all(np.isfinite(values)):
                    continue
                k = (str(e["ns"]), int(e["nobj"]), bytes(e["digest"]))
                if k in self._fabric:
                    self._fabric.move_to_end(k)
                    continue
                self._fabric[k] = values
                admitted += 1
                while len(self._fabric) > self.capacity:
                    self._fabric.popitem(last=False)
        self._inc("cache_fabric_imports", admitted)
        return admitted

    def purge_namespace(self, evaluator_id: int) -> int:
        """Drop every entry whose namespace belongs to ``evaluator_id``
        (the leading element of the service's ``(evaluator_id, sig, nobj)``
        namespace tuples).  The service calls this when an evaluator's pin
        refcount hits zero: ``id()`` values recycle, so a later evaluator
        allocated at the same address must never inherit the dead one's
        cached fitness.  Returns the number of entries purged (also counted
        as ``cache_purged``)."""
        with self._lock:
            stale = [k for k in self._entries
                     if isinstance(k[0], tuple) and k[0]
                     and k[0][0] == evaluator_id]
            for k in stale:
                del self._entries[k]
            # the portable alias dies with the namespace: a recycled id
            # must not export a successor evaluator's rows under the
            # dead one's fleet-wide name
            self._aliases.pop(evaluator_id, None)
            self._journal = collections.deque(
                (e for e in self._journal if not (
                    isinstance(e[1], tuple) and e[1]
                    and e[1][0] == evaluator_id)),
                maxlen=self._journal.maxlen)
        self._inc("cache_purged", len(stale))
        return len(stale)

    def hit_rate(self) -> float:
        """Lifetime hit fraction (0.0 when nothing was looked up)."""
        if self._metrics is None:
            return 0.0
        h = self._metrics.counter("cache_hits")
        m = self._metrics.counter("cache_misses")
        return h / (h + m) if h + m else 0.0
