"""Serving layer: many concurrent evolution runs multiplexed onto one
device mesh as an async ask/tell service.

The reference frames ``toolbox.map`` as the entire distribution boundary
(doc/tutorials/basic/part4.rst); this package is the *other* half a
production deployment needs — the multi-tenant control plane in front of
the compiled evolution step:

* :mod:`~deap_tpu.serve.service` — :class:`EvolutionService` /
  :class:`Session`: the concurrent ask/tell/step/evaluate API;
* :mod:`~deap_tpu.serve.dispatcher` — bounded request queue with
  backpressure, per-request deadlines, cancellation, retry-wrapped
  microbatch dispatch;
* :mod:`~deap_tpu.serve.buckets` — pad-and-bucket shape selection, so
  XLA compiles one program per bucket and never recompiles in steady
  state;
* :mod:`~deap_tpu.serve.cache` — two-tier content-addressed fitness
  cache (device sort/unique dedup within a batch + host LRU across
  sessions);
* :mod:`~deap_tpu.serve.metrics` — host counters/gauges/latency
  quantiles, snapshotting into the observability sink layer;
* :mod:`~deap_tpu.serve.cli` — the ``deap-tpu-serve`` console entry
  (``--listen`` network mode, demo fleet with a live stats view);
* :mod:`~deap_tpu.serve.net` — the network frontend (imported explicitly,
  not re-exported here): stdlib HTTP server, binary JSON+tensor wire
  protocol, ``RemoteService``/``RemoteSession`` client, and the
  drain/restore surface behind cross-instance failover.
"""

from .buckets import (BucketPolicy, BucketKey, BucketOverflow,  # noqa: F401
                      genome_signature, pad_rows, unpad_rows,
                      pad_population, ShapeHistogram, derive_sizes)
from .cache import FitnessCache, row_digests, rep_indices  # noqa: F401
from .dispatcher import (BatchDispatcher, Request, ServeFuture,  # noqa: F401
                         ServeError, ServiceClosed, ServiceOverloaded,
                         DeadlineExceeded, RequestCancelled,
                         ServiceDraining, SessionUnknown)
from .metrics import (ServeMetrics, SERVE_COUNTERS, SERVE_GAUGES,  # noqa: F401
                      NET_COUNTERS, TENANT_COUNTERS, prometheus_text)
from .rebucket import RebucketPolicy, pad_waste_of  # noqa: F401
from .service import EvolutionService, Session  # noqa: F401

__all__ = [
    "EvolutionService", "Session",
    "BucketPolicy", "BucketKey", "BucketOverflow", "genome_signature",
    "pad_rows", "unpad_rows", "pad_population",
    "ShapeHistogram", "derive_sizes",
    "FitnessCache", "row_digests", "rep_indices",
    "BatchDispatcher", "Request", "ServeFuture",
    "ServeError", "ServiceClosed", "ServiceOverloaded", "DeadlineExceeded",
    "RequestCancelled", "ServiceDraining", "SessionUnknown",
    "ServeMetrics", "SERVE_COUNTERS", "SERVE_GAUGES", "NET_COUNTERS",
    "TENANT_COUNTERS", "prometheus_text",
    "RebucketPolicy", "pad_waste_of",
]
