"""``deap-tpu-serve`` — serve over the network, or demo a session fleet.

The serving sibling of ``deap-tpu-selftest`` / ``deap-tpu-trace``: stand up
an :class:`~deap_tpu.serve.service.EvolutionService` ON THE TARGET BACKEND
and either expose it over HTTP (``--listen``) or drive a mixed-shape fleet
of synthetic GA sessions through it with a live stats view — then print
one JSON summary line.

    deap-tpu-serve                                   # in-process demo fleet
    deap-tpu-serve --listen 0.0.0.0:8077             # network frontend
    deap-tpu-serve --listen 0.0.0.0:8077 --shard-threshold 65536
    deap-tpu-serve --sessions 8 --pops 100,256 --dims 16,32 --ngen 50
    deap-tpu-serve --compile-cache /tmp/xla_cache    # persistent compiles
    deap-tpu-serve --smoke                           # tiny CI smoke run

``--listen`` serves the demo toolbox registry (``demo`` — Rastrigin GA)
through :class:`deap_tpu.serve.net.NetServer` until interrupted; point
:class:`deap_tpu.serve.net.RemoteService` (or curl) at it.  ``--smoke``
exercises the full loopback network path — client → HTTP → service — and
reads its JSON report back over the ``/v1/metrics`` endpoint, so a smoke
pass certifies the wire stack, not just the in-process API.

Exit status is non-zero when any session fails or goes non-finite — a
smoke gate, not a benchmark (throughput numbers live in
``tools/bench_serve.py``).
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _build_toolbox():
    from .. import base
    from ..benchmarks import rastrigin
    from ..ops import crossover, mutation, selection
    from ..resilience import Quarantine

    tb = base.Toolbox()
    tb.register("evaluate", rastrigin)
    tb.register("mate", crossover.cx_two_point)
    tb.register("mutate", mutation.mut_gaussian, mu=0.0, sigma=0.3,
                indpb=0.1)
    tb.register("select", selection.sel_tournament, tournsize=3)
    tb.quarantine = Quarantine("penalize")
    return tb


def _open_fleet(svc, tb, sessions, pops, dims, seed):
    import jax
    import jax.numpy as jnp
    from .. import base

    fleet = []
    for i in range(sessions):
        n, d = pops[i % len(pops)], dims[i % len(dims)]
        key = jax.random.PRNGKey(seed + i)
        genome = jax.random.uniform(key, (n, d), jnp.float32, -5.12, 5.12)
        pop = base.Population(genome=genome,
                              fitness=base.Fitness.empty(n, (-1.0,)))
        fleet.append(svc.open_session(key, pop, tb, cxpb=0.7, mutpb=0.3,
                                      name=f"demo-{i}"))
    return fleet


def _per_kind_quantiles(gauges) -> dict:
    """``{kind: (p50_ms, p99_ms)}`` parsed back out of the
    ``latency_<kind>_p*_ms`` gauges ServeMetrics already reports (the
    pooled ``latency_p*_ms`` keys are excluded)."""
    kinds = {}
    for key in gauges:
        if key.startswith("latency_") and key.endswith("_p50_ms"):
            kind = key[len("latency_"):-len("_p50_ms")]
            if kind:
                kinds[kind] = (gauges[key],
                               gauges.get(f"latency_{kind}_p99_ms", 0.0))
    return kinds


def _stat_line(rec, per_kind: bool = False) -> str:
    c, g = rec.counters, rec.gauges
    line = ("[serve] "
            f"batches={rec.gen} queue={g['queue_depth']:.0f} "
            f"slot_occ={g['slot_occupancy']:.2f} "
            f"compiles={c['compiles']} steps={c['steps']} "
            f"cache_hit={c['cache_hits']}/{c['cache_hits'] + c['cache_misses']} "
            f"p50={g.get('latency_p50_ms', 0.0):.1f}ms "
            f"p99={g.get('latency_p99_ms', 0.0):.1f}ms")
    if per_kind:
        for kind, (p50, p99) in sorted(_per_kind_quantiles(g).items()):
            line += f" {kind}[p50={p50:.1f}ms p99={p99:.1f}ms]"
    return line


def _run_listen(args) -> int:
    """``--listen host:port`` — expose the service over HTTP until
    interrupted."""
    import threading

    from .service import EvolutionService
    from .net import NetServer

    host, _, port = args.listen.rpartition(":")
    if not host:
        host, port = args.listen, "8077"
    tb = _build_toolbox()
    svc = EvolutionService(max_batch=args.max_batch,
                           shard_threshold=args.shard_threshold)
    with NetServer(svc, {"demo": tb}, host=host, port=int(port),
                   verbose=True) as srv:
        print(f"[serve] listening on {srv.url} "
              f"(toolboxes: demo; ctrl-c to stop)")
        try:
            threading.Event().wait()          # serve until interrupted
        except KeyboardInterrupt:
            print("[serve] shutting down")
    svc.close()
    return 0


def _run_smoke_net(args) -> int:
    """``--smoke`` — drive a tiny fleet over the LOOPBACK NETWORK PATH
    (client → HTTP → service) and report from the /v1/metrics endpoint."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from .. import base
    from .service import EvolutionService
    from .net import NetServer, RemoteService

    pops = [int(p) for p in args.pops.split(",")]
    dims = [int(d) for d in args.dims.split(",")]
    tb = _build_toolbox()
    t0 = time.perf_counter()
    failures = 0
    with EvolutionService(max_batch=args.max_batch) as svc, \
            NetServer(svc, {"demo": tb}) as srv, \
            RemoteService(srv.url, timeout=300) as cli:
        fleet = []
        for i in range(args.sessions):
            n, d = pops[i % len(pops)], dims[i % len(dims)]
            key = jax.random.PRNGKey(args.seed + i)
            genome = jax.random.uniform(key, (n, d), jnp.float32,
                                        -5.12, 5.12)
            pop = base.Population(genome=genome,
                                  fitness=base.Fitness.empty(n, (-1.0,)))
            fleet.append(cli.open_session(key, pop, "demo", cxpb=0.7,
                                          mutpb=0.3, name=f"demo-{i}"))
        futures = [(s, s.step(args.ngen)) for s in fleet]
        for s, fs in futures:
            for f in fs:
                exc = f.exception(timeout=300)
                if exc is not None:
                    failures += 1
                    print(f"[serve] {s.name} step failed: {exc!r}",
                          file=sys.stderr)
        wall = time.perf_counter() - t0
        bests = []
        for s in fleet:
            p = s.population()
            bests.append(float(np.asarray(p.fitness.values[:, 0]).min()))
        # the JSON report travels over the metrics endpoint — the smoke
        # certifies the wire stack end to end
        rec = cli.stats()
        report = {
            "mode": "net-smoke", "url": srv.url,
            "sessions": args.sessions, "ngen": args.ngen,
            "pops": pops, "dims": dims, "wall_s": wall,
            "gens_per_sec": args.sessions * args.ngen / wall,
            "counters": rec.counters, "gauges": rec.gauges,
            "best_fitness": bests, "failures": failures,
        }
    print(json.dumps(report))
    if failures or not all(np.isfinite(bests)):
        print("FAILED: session failures or non-finite results",
              file=sys.stderr)
        return 1
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="deap-tpu-serve",
        description="serve an EvolutionService over HTTP (--listen) or "
                    "drive a mixed-shape session fleet with a live stats "
                    "view")
    ap.add_argument("--listen", metavar="HOST:PORT", default=None,
                    help="serve over HTTP instead of running the demo "
                         "fleet (deap_tpu.serve.net.NetServer)")
    ap.add_argument("--shard-threshold", type=int, default=None,
                    help="pop-shard sessions at/above this population "
                         "size over the device mesh")
    ap.add_argument("--sessions", type=int, default=6)
    ap.add_argument("--pops", default="100,180",
                    help="comma-separated session population sizes")
    ap.add_argument("--dims", default="16,32",
                    help="comma-separated genome dims")
    ap.add_argument("--ngen", type=int, default=30)
    ap.add_argument("--max-batch", type=int, default=4)
    ap.add_argument("--stats-every", type=int, default=10,
                    help="emit a live stats line every N dispatched batches")
    ap.add_argument("--per-kind", action="store_true",
                    help="append per-request-kind latency quantiles "
                         "(step/ask/tell/evaluate) to every stats line "
                         "instead of only the pooled p50/p99")
    ap.add_argument("--compile-cache", metavar="DIR", default=None,
                    help="persist XLA compilations under DIR "
                         "(deap_tpu.utils.compilecache)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--smoke", action="store_true",
                    help="tiny fixed configuration for CI smoke tests, "
                         "driven over the loopback network path")
    args = ap.parse_args(argv)
    if args.smoke:
        args.sessions, args.pops, args.dims = 2, "12", "6"
        args.ngen, args.stats_every = 3, 2

    if args.compile_cache:
        from ..utils.compilecache import enable_compile_cache
        enable_compile_cache(args.compile_cache)

    if args.listen:
        return _run_listen(args)
    if args.smoke:
        return _run_smoke_net(args)

    import numpy as np
    from ..observability.sinks import StdoutSink
    from .service import EvolutionService

    pops = [int(p) for p in args.pops.split(",")]
    dims = [int(d) for d in args.dims.split(",")]
    tb = _build_toolbox()
    sink = StdoutSink()

    t0 = time.perf_counter()
    failures = 0
    with EvolutionService(max_batch=args.max_batch,
                          shard_threshold=args.shard_threshold) as svc:
        fleet = _open_fleet(svc, tb, args.sessions, pops, dims, args.seed)
        futures = {s.name: s.step(args.ngen) for s in fleet}
        last_line = 0
        outstanding = {n: list(fs) for n, fs in futures.items()}
        while outstanding:
            for name in list(outstanding):
                fs = outstanding[name]
                while fs and fs[0].done():
                    exc = fs.pop(0).exception()
                    if exc is not None:
                        failures += 1
                        print(f"[serve] {name} step failed: {exc!r}",
                              file=sys.stderr)
                if not fs:
                    del outstanding[name]
            rec = svc.stats()
            if args.stats_every and rec.gen - last_line >= args.stats_every:
                sink.write_text(_stat_line(rec, per_kind=args.per_kind))
                last_line = rec.gen
            if outstanding:
                next(iter(outstanding.values()))[0].exception(timeout=60)
        wall = time.perf_counter() - t0

        bests = []
        for s in fleet:
            p = s.population()
            bests.append(float(np.asarray(p.fitness.values[:, 0]).min()))
        rec = svc.stats()
        report = {
            "sessions": args.sessions, "ngen": args.ngen,
            "pops": pops, "dims": dims, "wall_s": wall,
            "gens_per_sec": args.sessions * args.ngen / wall,
            "counters": rec.counters, "gauges": rec.gauges,
            "best_fitness": bests, "failures": failures,
        }
    print(json.dumps(report))
    if failures or not all(np.isfinite(bests)):
        print("FAILED: session failures or non-finite results",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
