""":class:`EvolutionService` — many concurrent EC runs multiplexed onto one
device (mesh) as an async ask/tell service.

Each :class:`Session` is an independent evolution run: its padded state
lives on device between requests, and every request kind is executed by a
compiled program whose shapes come from the service's
:class:`~deap_tpu.serve.buckets.BucketPolicy`:

* ``step``    — one full :func:`~deap_tpu.algorithms.ea_step` generation
  (select → vary → evaluate on device); sessions sharing a toolbox and a
  bucket are **slot-packed**: up to ``max_batch`` sessions advance under
  one ``vmap`` dispatch, and a slot's result depends only on that slot, so
  multiplexed results are bitwise identical to the same session served
  alone (pinned by ``tests/test_serve.py``);
* ``ask`` / ``tell`` — the generate/update split for clients that evaluate
  externally: ``ask`` returns the varied offspring genomes, ``tell`` feeds
  fitness values back (``toolbox.quarantine`` applied to fresh rows);
* ``evaluate`` — fitness for an ad-hoc genome batch, **row-packed** across
  sessions into one padded bucket, deduplicated on device
  (:func:`~deap_tpu.serve.cache.rep_indices`) and served through the host
  :class:`~deap_tpu.serve.cache.FitnessCache` (content-addressed, never
  caches non-finite values).

Programs are compiled **ahead-of-time** (``jit().lower().compile()``) once
per ``(kind, bucket, toolbox)`` and re-dispatched from the cache — a shape
that would recompile raises instead of silently thrashing, and the
``compiles*`` counters in :class:`~deap_tpu.serve.metrics.ServeMetrics`
are therefore exact.  Backpressure, deadlines, cancellation and retry
semantics live in :class:`~deap_tpu.serve.dispatcher.BatchDispatcher`.

::

    svc = EvolutionService(max_batch=4)
    s1 = svc.open_session(key1, pop1, toolbox, cxpb=0.6, mutpb=0.3)
    s2 = svc.open_session(key2, pop2, toolbox)
    futs = [s.step(10) for s in (s1, s2)]          # pipelined + microbatched
    for f in futs[0]: f.result()
    print(svc.stats())
    svc.close()
"""

from __future__ import annotations

import contextlib
import copy
import dataclasses
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as _P

from .. import sanitize
from ..base import Population, Fitness
from ..algorithms import ea_step, ea_ask, ea_tell, _norm_eval
from ..observability import events as _events
from ..observability import fleettrace
from ..observability.fleettrace import FleetTracer
from ..observability.profiling import ProgramProfiler
from ..observability.sinks import emit_text
from .buckets import (BucketPolicy, BucketKey, ShapeHistogram, pad_rows,
                      unpad_rows, pad_population, genome_signature)
from .cache import FitnessCache, flatten_rows, row_digests, rep_indices
from .dispatcher import (BatchDispatcher, Request, ServeFuture, ServeError,
                         ServiceClosed, ServiceDraining, SessionUnknown)
from .metrics import ServeMetrics

__all__ = ["EvolutionService", "Session", "build_slot_program"]


def _stack(trees):
    """Stack a list of identically-shaped pytrees on a new leading axis."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *trees)


def _slot(tree, i: int):
    return jax.tree_util.tree_map(lambda x: x[i], tree)


def _host(tree):
    return jax.tree_util.tree_map(lambda x: np.asarray(x), tree)


def _as_raw_key(key) -> jax.Array:
    """Canonical uint32 key form, so session keys and slot templates always
    stack to one dtype (typed keys are unwrapped; the raw data drives the
    same threefry stream)."""
    key = jnp.asarray(key) if not isinstance(key, jax.Array) else key
    if jax.dtypes.issubdtype(key.dtype, jax.dtypes.prng_key):
        return jax.random.key_data(key)
    return key.astype(jnp.uint32)


def build_slot_program(kind: str, toolbox, weights: tuple,
                       vmapped: bool = True):
    """Request-kind program over one session state dict (the operand
    pytree ``EvolutionService._make_state`` builds: ``key``/``genome``/
    ``values``/``valid``/``live_n``/``cxpb``/``mutpb``).  ``vmapped``
    (default) wraps it over the slot axis for microbatching;
    ``vmapped=False`` is the pop-sharded form — the same per-session
    computation dispatched alone so GSPMD partitions its pop axis over
    the mesh instead of a slot axis over sessions.

    Module-level (not a service method) so the program-contract analyzer
    (:mod:`deap_tpu.analysis`) lowers the *same* executables the service
    dispatches — an inventory copy of this builder would silently drift.
    Note the trajectory knobs (``cxpb``/``mutpb``) and the key ride in
    the state as **operands**: baking either as a Python constant would
    fork one compile per distinct value across sessions, which the
    analyzer's recompile-hazard variant diff pins."""
    maybe_vmap = jax.vmap if vmapped else (lambda f: f)

    def as_population(state):
        return Population(state["genome"],
                          Fitness(values=state["values"],
                                  valid=state["valid"], weights=weights))

    def live_of(state):
        return jnp.arange(state["valid"].shape[0]) < state["live_n"]

    def pack(state, pop):
        return {**state, "genome": pop.genome,
                "values": pop.fitness.values, "valid": pop.fitness.valid}

    if kind == "step":
        def one(state):
            key, pop, nevals = ea_step(
                state["key"], as_population(state), toolbox,
                state["cxpb"], state["mutpb"], live=live_of(state))
            return {**pack(state, pop), "key": key}, nevals
        return maybe_vmap(one)
    if kind == "init":
        def one(state):
            pop, nevals = ea_tell(toolbox, as_population(state),
                                  live=live_of(state))
            return pack(state, pop), nevals
        return maybe_vmap(one)
    if kind == "ask":
        def one(state):
            key, off = ea_ask(state["key"], as_population(state),
                              toolbox, state["cxpb"], state["mutpb"],
                              live=live_of(state))
            return ({**state, "key": key}, off.genome,
                    off.fitness.values, off.fitness.valid)
        return maybe_vmap(one)
    if kind == "tell":
        def one(state, pending, values):
            pg, pv, pvalid = pending
            pop, nevals = ea_tell(
                toolbox, Population(pg, Fitness(pv, pvalid, weights)),
                values, live=live_of(state))
            return pack(state, pop), nevals
        return maybe_vmap(one)
    raise ValueError(f"unknown slot program kind {kind!r}")


class Session:
    """One live evolution run inside an :class:`EvolutionService`.

    All methods are thread-safe and **asynchronous**: they enqueue a
    request and return a :class:`~deap_tpu.serve.dispatcher.ServeFuture`
    (``step(n)`` returns a list of ``n`` chained futures).  State advances
    strictly in submission order; the service packs compatible requests
    from *different* sessions into shared device batches."""

    def __init__(self, service: "EvolutionService", name: str, toolbox,
                 bucket: BucketKey, state: Dict[str, jax.Array],
                 gen: int = 0, phase: str = "idle", pending=None,
                 sharded: bool = False, streamed: bool = False,
                 priority: int = 1):
        self._service = service
        self.name = name
        self.toolbox = toolbox
        self.bucket = bucket
        #: load-shedding class every request of this session carries
        #: (higher = more important; the fleet router stamps it from the
        #: owning tenant's quota) — under sustained queue pressure the
        #: dispatcher sheds lower-priority admissions first
        self.priority = int(priority)
        self._pop_n: Optional[int] = None   # cached live count (immutable)
        self._state = state          # swapped atomically by the dispatcher
        self._pending = pending      # offspring awaiting tell (phase=asked)
        self.gen = int(gen)
        self.phase = phase           # idle | asked
        self.closed = False
        #: live-migration quiesce flag: flipped ONLY under the
        #: dispatcher's queue lock (``set_session_migrating``), checked
        #: there at submit — while up, this session's submissions are
        #: rejected (``ServiceDraining``) and its pending work can only
        #: shrink; every other session keeps flowing
        self.migrating = False
        #: population placed pop-axis-sharded over the service mesh and
        #: stepped by a dedicated whole-mesh program (no slot-packing)
        self.sharded = bool(sharded)
        #: generation dispatched through the out-of-core streamed engine
        #: (:mod:`deap_tpu.bigpop`): host-driven sliced pipeline, no
        #: compiled slot program, capacity-1 dispatch like sharded
        self.streamed = bool(streamed)
        #: objects pinned on this session's behalf (toolbox, evaluators) —
        #: captured at open/adopt time, released exactly once at close, so
        #: re-registering toolbox attributes mid-run can never skew the
        #: service's refcounts
        self._pins: List[Any] = []
        # guards the phase check-and-transition (concurrent ask()/step()
        # from two client threads must not both pass the guard); NEVER
        # held across a submit — the dispatcher takes its own lock first
        # on some failure paths, and the reverse order would deadlock
        self._phase_lock = sanitize.lock()

    def _rollback_ask(self) -> None:
        """Failure hook of an ask() that never executed (deadline miss,
        cancellation, batch fault): the session returns to 'idle' so the
        client can re-ask or step instead of being wedged."""
        with self._phase_lock:
            if self.phase == "asked" and self._pending is None:
                self.phase = "idle"

    # -- introspection -------------------------------------------------------

    @property
    def pop_size(self) -> int:
        # a session's live count never changes; cache the host read so
        # per-batch policy ticks don't sync a device scalar per session
        if self._pop_n is None:
            self._pop_n = int(np.asarray(self._state["live_n"]))
        return self._pop_n

    @property
    def weights(self) -> tuple:
        return self.bucket.weights

    def population(self) -> Population:
        """Current (unpadded, host-materialized) population."""
        st = self._state
        n = int(np.asarray(st["live_n"]))
        return Population(
            genome=unpad_rows(st["genome"], n),
            fitness=Fitness(values=st["values"][:n], valid=st["valid"][:n],
                            weights=self.bucket.weights))

    # -- request API ---------------------------------------------------------

    def step(self, n: int = 1, deadline: Optional[float] = None,
             block: bool = False) -> List[ServeFuture]:
        """Advance ``n`` generations.  Returns the list of ``n``
        per-generation futures (each resolves to ``{"gen", "nevals"}``) —
        always a list, so call sites never branch on ``n``.  ``deadline``
        is seconds from now; a generation not dispatched by then fails
        (later ones still run on the state reached so far)."""
        with self._phase_lock:
            if self.phase != "idle":
                raise ServeError(f"session {self.name!r} has an "
                                 "outstanding ask(); tell() first")
        return self._service._submit_pipeline(self, "step", int(n),
                                              deadline, block)

    def ask(self, deadline: Optional[float] = None) -> ServeFuture:
        """Produce the next offspring batch (selection + variation, no
        evaluation).  Resolves to the host genome rows awaiting external
        evaluation; the session then expects :meth:`tell`.  An ask that
        fails before executing (deadline, cancellation, fault) rolls the
        session back to 'idle'."""
        with self._phase_lock:
            if self.phase != "idle":
                raise ServeError(f"session {self.name!r} already asked")
            self.phase = "asked"
        try:
            return self._service._submit(self, "ask", {}, deadline,
                                         on_failure=self._rollback_ask)
        except BaseException:
            self._rollback_ask()
            raise

    def tell(self, values, deadline: Optional[float] = None) -> ServeFuture:
        """Complete an :meth:`ask` with externally computed objective
        ``values`` (``(pop, nobj)`` or ``(pop,)``, one row per live
        individual); quarantine applies to the freshly assigned rows.
        Resolves to ``{"gen", "nevals"}``."""
        with self._phase_lock:
            if self.phase != "asked":
                raise ServeError(f"session {self.name!r} has no "
                                 "outstanding ask()")
        values = np.asarray(values)
        if values.shape[0] != self.pop_size:
            raise ValueError(
                f"tell() got {values.shape[0]} fitness rows for a "
                f"population of {self.pop_size}: every live individual "
                "needs a value (zero-filling the gap would silently "
                "assign fitness 0.0)")
        return self._service._submit(self, "tell", {"values": values},
                                     deadline)

    def evaluate(self, genomes, deadline: Optional[float] = None
                 ) -> ServeFuture:
        """Fitness for an ad-hoc genome batch (same structure as the
        session's genomes, any row count within the bucket policy), served
        through the content-addressed cache.  Resolves to a host
        ``(rows, nobj)`` array."""
        return self._service._submit_evaluate(self, genomes, deadline)

    def close(self) -> None:
        """Detach from the service; queued requests fail at dispatch."""
        self.closed = True
        self._service._forget(self)


class EvolutionService:
    """Multi-tenant ask/tell evaluation service (see module docstring).

    Parameters
    ----------
    policy:
        Row :class:`~deap_tpu.serve.buckets.BucketPolicy` (default: powers
        of two from 8).
    max_batch:
        Slot count of step/ask/tell microbatches — up to this many
        sessions advance per dispatch.  Part of the compiled shape, so all
        comparisons across services require equal ``max_batch``.
    max_pending / batch_window:
        Queue bound (backpressure) and optional linger seconds to fill a
        partial batch.
    brownout_watermark / brownout_grace_s:
        Priority load shedding (off by default): once the queue has sat
        at or above ``watermark * max_pending`` for ``grace`` seconds,
        admissions whose session priority is below the highest queued
        priority are shed with typed
        :class:`~deap_tpu.serve.dispatcher.ServiceBrownout` — see
        :class:`~deap_tpu.serve.dispatcher.BatchDispatcher`.
    cache_capacity / dedup_max_flat_dim:
        Host fitness-cache entries; flat genome width beyond which the
        device sort/unique dedup is skipped (a variadic lexsort keys per
        column).
    eval_retries / retry_backoff:
        Transient-fault retry budget around every device dispatch
        (:func:`deap_tpu.resilience.with_retries`).
    shard_threshold / mesh:
        Pop-sharded sessions: a session whose population reaches
        ``shard_threshold`` rows is placed with its pop axis sharded over
        ``mesh`` (default: :func:`deap_tpu.parallel.default_mesh` over all
        visible devices) and stepped by a dedicated whole-mesh program —
        no slot-packing, and an NSGA-II ``select`` is transparently routed
        through :func:`deap_tpu.parallel.sel_nsga2_sharded` (bitwise
        index-identical to the single-device peel).  ``None`` (default)
        disables sharded placement.
    sinks / stats_every:
        Observability: emit a stats :class:`MetricRecord` to ``sinks``
        every N batches (0 = never); compile events also go to the
        in-trace event tap when one is open.
    tracer:
        :class:`~deap_tpu.observability.fleettrace.FleetTracer` recording
        the request span trees (queue wait / pad-bucket / cache lookup /
        device execute phases).  Default: a fresh enabled tracer on the
        service clock; pass ``FleetTracer(enabled=False)`` to opt out —
        the compiled programs and trajectories are identical either way
        (tracing is pure host bookkeeping, pinned by test).
    profiler:
        :class:`~deap_tpu.observability.profiling.ProgramProfiler`
        recording per-compiled-program device-phase profiles: XLA
        cost/memory analyses at AOT time (beside the ``compiles*``
        counters — same event, same program key) and min-of-k measured
        execute walls at the exact ``device_execute`` span bounds.
        Default: a fresh enabled profiler on the service clock; pass
        ``ProgramProfiler(enabled=False)`` to opt out — pure host
        bookkeeping, bitwise-identical trajectories either way (pinned
        by test; overhead committed in ``BENCH_PROFILE.json``).  Read
        it back via :meth:`stats` (``meta["programs"]`` + ``profile_*``
        gauges) or the network frontend's ``GET /v1/profile``.
    rebucket_policy:
        Optional :class:`~deap_tpu.serve.rebucket.RebucketPolicy` —
        evaluated after every dispatched batch; fires
        :meth:`rebucket` automatically on histogram drift + pad waste
        (see :meth:`set_rebucket_policy`).
    fault_hook:
        Test seam: called as ``fault_hook(kind, requests)`` before every
        batch execution (raise to inject an evaluation fault).
    """

    #: lock-guarded shared state, enforced statically by the
    #: ``lock-discipline`` lint pass: the session table, the pin
    #: refcounts, and the admission name reservations are written from
    #: any client thread and read by the dispatch worker — writes only
    #: under ``with self._lock:`` (or in ``*_locked`` helpers).  NOT
    #: registered: ``_programs``/``_templates``/``_sharded_tbs`` (worker-
    #: thread-owned in steady state, locked only where client paths
    #: touch them) and ``_draining`` (opportunistic flag; the
    #: authoritative gate is the dispatcher's, under ITS queue lock).
    _GUARDED_BY = {"_lock": ("_sessions", "_refs", "_refcounts",
                             "_reserved", "_names")}

    def __init__(self, *, policy: Optional[BucketPolicy] = None,
                 max_batch: int = 4, max_pending: int = 256,
                 batch_window: float = 0.0,
                 brownout_watermark: Optional[float] = None,
                 brownout_grace_s: float = 0.0,
                 cache_capacity: int = 4096,
                 dedup_max_flat_dim: int = 512, eval_retries: int = 2,
                 retry_backoff: float = 0.05, sinks: Sequence = (),
                 stats_every: int = 0, verbose: bool = False,
                 shard_threshold: Optional[int] = None, mesh=None,
                 tracer: Optional[FleetTracer] = None,
                 profiler: Optional[ProgramProfiler] = None,
                 rebucket_policy=None,
                 fault_hook=None, clock=time.monotonic):
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        self.policy = policy if policy is not None else BucketPolicy()
        self.max_batch = int(max_batch)
        self.dedup_max_flat_dim = int(dedup_max_flat_dim)
        self.sinks = list(sinks)
        self.stats_every = int(stats_every)
        self.verbose = bool(verbose)
        self.shard_threshold = (None if shard_threshold is None
                                else int(shard_threshold))
        self._mesh = mesh
        self.metrics = ServeMetrics()
        self.cache = FitnessCache(cache_capacity, metrics=self.metrics)
        self.shapes = ShapeHistogram()
        self.tracer = (tracer if tracer is not None
                       else FleetTracer(clock=clock))
        self.profiler = (profiler if profiler is not None
                         else ProgramProfiler(clock=clock))
        self._rebucket_policy = None
        self._fault_hook = fault_hook
        self._clock = clock
        self._programs: Dict[tuple, Any] = {}
        self._templates: Dict[tuple, Dict[str, jax.Array]] = {}
        # id() pins keep toolboxes/evaluators alive (program keys use
        # id(), which must not be recycled) — refcounted per session so a
        # long-lived service releases dead tenants' objects AND their
        # compiled programs instead of leaking them forever
        self._refs: Dict[int, Any] = {}
        self._refcounts: Dict[int, int] = {}
        self._sharded_tbs: Dict[int, Any] = {}   # id(toolbox) -> shadow
        self._sessions: Dict[str, Session] = {}
        self._reserved: set = set()   # names mid-admission (see _admit)
        self._names = 0
        self._lock = sanitize.lock()
        self._closed = False
        self._draining = False
        self._dispatcher = BatchDispatcher(
            self._execute, max_pending=max_pending,
            batch_window=batch_window,
            brownout_watermark=brownout_watermark,
            brownout_grace_s=brownout_grace_s, metrics=self.metrics,
            retries=eval_retries, backoff=retry_backoff, clock=clock,
            tracer=self.tracer, after_batch=self._after_batch)
        if rebucket_policy is not None:
            self.set_rebucket_policy(rebucket_policy)

    # -- lifecycle -----------------------------------------------------------

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def close(self) -> None:
        self._closed = True
        self._dispatcher.close()

    @contextlib.contextmanager
    def quiesce(self):
        """Pause dispatch (in-flight batch completes) — session states are
        stable inside the context.  Queued requests resume after."""
        self._dispatcher.pause()
        try:
            yield
        finally:
            self._dispatcher.resume()

    def stats(self, *, programs: bool = True):
        """Current :class:`~deap_tpu.observability.sinks.MetricRecord` —
        counters (requests/compiles/cache/...) + gauges (queue depth,
        occupancy, pad waste, latency p50/p90/p99); per-tenant SLO
        counters ride in ``meta["tenants"]`` and (with the profiler
        enabled) the per-program device-phase table in
        ``meta["programs"]``.  ``programs=False`` skips building the
        program table — the streaming metrics endpoint emits one record
        per dispatched batch, and rebuilding + re-serializing every
        program's phase split per batch is per-scrape work its
        consumers (``deap-tpu-top`` aggregates counters/gauges) never
        read; the one-shot ``/v1/metrics`` GET and ``/v1/profile``
        remain the full views."""
        from .rebucket import pad_waste_of
        # one locked copy for both gauges: the stats scraper runs on its
        # own thread while handler threads admit/close sessions (a bare
        # len(self._sessions) here was the first race the runtime
        # sanitizer caught)
        live = self.sessions()
        self.metrics.set_gauge("sessions", len(live))
        self.metrics.set_gauge(
            "sharded_sessions",
            sum(1 for s in live.values() if s.sharded))
        self.metrics.set_gauge(
            "sessions_streamed",
            sum(1 for s in live.values() if s.streamed))
        self.metrics.set_gauge("pad_waste", pad_waste_of(self))
        # always written: after a live `profiler.enabled = False` the
        # gauges must read zero, not freeze at the last enabled-state
        # values (a dashboard would conclude profiling is live + current)
        agg = (self.profiler.aggregates() if self.profiler.enabled
               else {"programs": 0.0, "flops_total": 0.0,
                     "bytes_accessed_total": 0.0, "peak_bytes_max": 0.0})
        self.metrics.set_gauge("profile_programs", agg["programs"])
        self.metrics.set_gauge("profile_flops_total", agg["flops_total"])
        self.metrics.set_gauge("profile_bytes_accessed_total",
                               agg["bytes_accessed_total"])
        self.metrics.set_gauge("profile_peak_bytes_max",
                               agg["peak_bytes_max"])
        rec = self.metrics.snapshot(self._dispatcher.batches)
        if programs and self.profiler.enabled:
            table = self.profiler.profiles()
            if table:
                rec.meta["programs"] = table
        return rec

    def set_rebucket_policy(self, policy) -> None:
        """Install (or, with ``None``, remove) the auto-rebucket policy.
        The policy's drift baseline anchors to the current shape
        histogram; from then on :meth:`RebucketPolicy.tick` runs on the
        dispatch worker after every batch and may fire
        :meth:`rebucket` at that quiesce point."""
        if policy is not None:
            policy.observe_baseline(self)
        self._rebucket_policy = policy

    def _after_batch(self) -> None:
        """Dispatcher worker hook (post-batch, not busy, no locks held):
        evaluate the auto-rebucket policy.  Policy failures are counted
        and reported, never propagated — the dispatch worker must
        survive a control-loop bug."""
        policy = self._rebucket_policy
        if policy is None:
            return
        try:
            info = policy.tick(self)
        except Exception as e:  # noqa: BLE001 — contained by design
            self.metrics.inc("rebucket_policy_errors")
            if self.verbose:
                emit_text(f"[serve] rebucket policy error: {e!r}",
                          self.sinks)
            return
        if info is not None and self.verbose:
            emit_text(f"[serve] auto-rebucket fired: sizes={info['sizes']} "
                      f"moved={info['moved']} compiles={info['compiles']}",
                      self.sinks)

    @property
    def draining(self) -> bool:
        return self._draining

    def wait_for_activity(self, seen: int,
                          timeout: Optional[float] = None) -> int:
        """Block until the dispatched-batch count exceeds ``seen`` (or
        ``timeout``); returns the current count.  Condition-based — the
        streaming metrics endpoint tails service activity through this."""
        return self._dispatcher.wait_for_batches(seen, timeout=timeout)

    def drain(self, timeout: Optional[float] = 60.0) -> Dict[str, dict]:
        """Failover step 1 of 2: stop admitting work, flush the queue, and
        return the final host snapshot of every live session (the payload
        :meth:`restore_sessions` / :meth:`adopt_sessions` consumes on the
        replacement instance).

        After ``drain()`` every further submission raises
        :class:`~deap_tpu.serve.dispatcher.ServiceDraining`; the already
        queued requests execute to completion first, so the snapshot sits
        at a request boundary every client observed.  If the queue fails
        to flush within ``timeout`` the drain RAISES (still draining —
        retry with a larger timeout) rather than snapshotting state that
        queued requests would advance past.  The service stays up for
        metrics/introspection until :meth:`close`."""
        self._draining = True
        # the dispatcher-level flag is the authoritative gate: it flips
        # under the queue lock, so a submit racing this drain either
        # lands BEFORE it (and flushes below) or is rejected — never
        # between the flush and the snapshot
        self._dispatcher.set_draining(True)
        if not self._dispatcher.drain(timeout=timeout):
            raise ServeError(
                f"drain timed out after {timeout}s with "
                f"{self._dispatcher.queue_depth} requests still pending — "
                "the service remains draining; retry with a larger "
                "timeout (snapshotting now would lose queued progress)")
        snaps = self.snapshot_sessions()
        with self._lock:
            sessions = list(self._sessions.values())
        for s in sessions:
            s.closed = True
        # postmortem flight record: the last spans before this instance
        # went away, through the ordinary sink stack (no sinks, no write)
        self.tracer.dump("drain", self.sinks, force=True)
        return snaps

    def mesh(self):
        """The service's population-sharding mesh (created on first use
        when sharding is enabled and none was passed)."""
        if self._mesh is None:
            from ..parallel.mapper import default_mesh
            self._mesh = default_mesh()
        return self._mesh

    # -- sessions ------------------------------------------------------------

    def open_session(self, key, population: Population, toolbox, *,
                     cxpb: float = 0.5, mutpb: float = 0.2,
                     name: Optional[str] = None, evaluate_initial: bool = True,
                     priority: int = 1,
                     timeout: Optional[float] = 60.0) -> Session:
        """Register a run and (synchronously, by default) evaluate its
        initial population through the service.  ``population`` is the
        UNPADDED initial population; the service pads it to its bucket
        (and, at or above ``shard_threshold`` rows, shards it over the
        mesh).  ``priority`` is the session's load-shedding class (see
        :class:`Session`)."""
        session = self._admit(key, population, toolbox, cxpb=cxpb,
                              mutpb=mutpb, name=name, priority=priority)
        if evaluate_initial:
            self._submit(session, "init", {}).result(timeout=timeout)
        return session

    def _admit(self, key, population: Population, toolbox, *, cxpb: float,
               mutpb: float, name: Optional[str], gen: int = 0,
               phase: str = "idle", pending_host=None,
               priority: int = 1) -> Session:
        """Shared admission path of :meth:`open_session` and
        :meth:`adopt_sessions`: bucket (+ shard placement), state build,
        registration, pinning, shape observation."""
        if self._closed:
            raise ServiceClosed("service is closed")
        if self._draining:
            raise ServiceDraining("service is draining for failover")
        bucket = self.policy.bucket_for(population)
        # registry-typed admission: unknown engine strings and invalid
        # engine/mesh combos reject HERE, before any device state builds
        from ..engines import resolve_engine
        streamed = resolve_engine(toolbox) == "streamed"
        sharded = (not streamed
                   and self.shard_threshold is not None
                   and population.size >= self.shard_threshold)
        if sharded:
            bucket = dataclasses.replace(
                bucket, rows=self._shard_rows(bucket.rows))
        with self._lock:
            if name is None:
                name = f"session-{self._names}"
            self._names += 1
            # reserve the name NOW: the device-state build below runs
            # outside the lock, and two concurrent opens of the same name
            # (an HTTP create retried after a timeout) must not both pass
            # the check and silently shadow each other's registration
            if name in self._sessions or name in self._reserved:
                raise ValueError(f"session name {name!r} already open")
            self._reserved.add(name)
        try:
            self.shapes.observe(population.size)
            state = self._make_state(key, population, bucket, cxpb, mutpb)
            pending = None
            if pending_host is not None:
                pending = (pad_rows(jax.tree_util.tree_map(
                               jnp.asarray, pending_host["genome"]),
                               bucket.rows),
                           pad_rows(jnp.asarray(pending_host["values"]),
                                    bucket.rows),
                           pad_rows(jnp.asarray(pending_host["valid"]),
                                    bucket.rows))
            if sharded:
                state = self._place_sharded(state, bucket.rows)
                if pending is not None:
                    pending = self._place_sharded(pending, bucket.rows)
            session = Session(self, name, toolbox, bucket, state, gen=gen,
                              phase=phase, pending=pending, sharded=sharded,
                              streamed=streamed, priority=priority)
            session._pins = [toolbox]
            evaluate = getattr(toolbox, "evaluate", None)
            if evaluate is not None:
                session._pins.append(evaluate)
            with self._lock:
                self._sessions[name] = session
                self._pin_locked(session)
        finally:
            with self._lock:
                self._reserved.discard(name)
        return session

    def sessions(self) -> Dict[str, Session]:
        with self._lock:
            return dict(self._sessions)

    def _pin_locked(self, session: Session) -> None:
        for obj in session._pins:
            oid = id(obj)
            self._refs[oid] = obj
            self._refcounts[oid] = self._refcounts.get(oid, 0) + 1

    def _pin_extra(self, session: Session, obj) -> None:
        """Refcounted late pin (an evaluator registered on the toolbox
        after the session opened): joins the session's pin set so close
        releases it exactly once — an unrefcounted pin here would let one
        session's close drop an evaluator its siblings still dispatch
        (the ``_refs.setdefault`` lifecycle bug)."""
        with self._lock:
            if any(p is obj for p in session._pins):
                return
            session._pins.append(obj)
            oid = id(obj)
            self._refs[oid] = obj
            self._refcounts[oid] = self._refcounts.get(oid, 0) + 1

    def _forget(self, session: Session) -> None:
        """Drop a closed session and, when its toolbox/evaluator pins hit
        refcount zero, release the pinned objects plus every compiled
        program, slot template, sharded-toolbox shadow AND fitness-cache
        namespace keyed on them.  The cache purge is load-bearing, not
        tidiness: entries are namespaced by ``id(evaluator)``, and a later
        evaluator allocated at the recycled address would otherwise be
        served the dead evaluator's fitness bit-for-bit."""
        with self._lock:
            if self._sessions.pop(session.name, None) is None:
                return          # already forgotten: don't double-release
            released = []
            for obj in session._pins:
                oid = id(obj)
                left = self._refcounts.get(oid, 0) - 1
                if left > 0:
                    self._refcounts[oid] = left
                    continue
                self._refcounts.pop(oid, None)
                self._refs.pop(oid, None)
                self._sharded_tbs.pop(oid, None)
                self._programs = {k: v for k, v in self._programs.items()
                                  if oid not in k[1][:2]}
                self._templates = {k: v for k, v in self._templates.items()
                                   if k[0] != oid}
                released.append(oid)
        for oid in released:
            self.cache.purge_namespace(oid)

    def _make_state(self, key, population: Population, bucket: BucketKey,
                    cxpb: float, mutpb: float) -> Dict[str, jax.Array]:
        padded = pad_population(population, bucket.rows)
        return {"key": _as_raw_key(key),
                "genome": padded.genome,
                "values": jnp.asarray(padded.fitness.values, jnp.float32),
                "valid": padded.fitness.valid,
                "live_n": jnp.asarray(population.size, jnp.int32),
                "cxpb": jnp.asarray(cxpb, jnp.float32),
                "mutpb": jnp.asarray(mutpb, jnp.float32)}

    # -- pop-sharded placement ----------------------------------------------

    def _shard_rows(self, rows: int) -> int:
        """Bucket rows rounded up to a mesh multiple (a pop-axis
        NamedSharding needs a divisible leading axis)."""
        d = int(self.mesh().devices.size)
        return -(-rows // d) * d

    def _place_sharded(self, tree, rows: int):
        """Canonical device placement of a sharded session's arrays: every
        leaf with a ``rows``-long leading axis is sharded over the mesh's
        pop axis, everything else is replicated.  Idempotent — re-placing
        program outputs is a no-op view — so dispatch args always match
        the shardings the program was AOT-lowered with."""
        mesh = self.mesh()
        axis = mesh.axis_names[0]
        row_sh = NamedSharding(mesh, _P(axis))
        rep_sh = NamedSharding(mesh, _P())

        def put(x):
            x = jnp.asarray(x)
            sh = row_sh if (x.ndim and x.shape[0] == rows) else rep_sh
            return jax.device_put(x, sh)
        return jax.tree_util.tree_map(put, tree)

    def _sharded_toolbox(self, toolbox):
        """The toolbox a sharded session's programs trace: identical to
        the tenant's, except

        * an NSGA-II ``select`` is swapped for
          :func:`deap_tpu.parallel.sel_nsga2_sharded` on the service mesh
          (bitwise index-identical to the single-device path, pinned by
          tests; a tenant-declared ``nd="grid"`` carries over as
          ``ranks="grid"``),
        * a default ``hypervolume`` slot (the host/device router of
          :func:`deap_tpu.ops.hypervolume.hypervolume`) is swapped for
          the mesh-partitioned
          :func:`deap_tpu.ops.hypervolume.hypervolume_sharded`, and
        * a declared ``generation_engine = "megakernel"`` with the
          flagship tournament select is promoted to
          ``"megakernel_sharded"`` targeting the service mesh, so the
          session's step/ask programs trace the mesh-sharded fused
          generation (:mod:`deap_tpu.ops.generation_sharded`) instead of
          replicating the single-device kernel under GSPMD —

        so big-mesh tenants get the distributed paths without touching
        their toolbox."""
        oid = id(toolbox)
        shadow = self._sharded_tbs.get(oid)
        if shadow is None:
            shadow = toolbox
            sel = getattr(toolbox, "select", None)
            from ..engines import resolve_engine
            from ..ops.emo import sel_nsga2
            from ..ops.hypervolume import (
                hypervolume as _hypervolume_default, hypervolume_sharded)
            from ..ops.selection import sel_tournament
            from ..parallel.emo_sharded import sel_nsga2_sharded
            if getattr(sel, "func", sel) is sel_nsga2:
                shadow = copy.copy(toolbox)
                kw = {k: v for k, v in getattr(sel, "keywords", {}).items()
                      if k in ("front_chunk",)}
                if getattr(sel, "keywords", {}).get("nd") == "grid":
                    kw["ranks"] = "grid"
                shadow.register("select", sel_nsga2_sharded,
                                mesh=self.mesh(), **kw)
            hv = getattr(toolbox, "hypervolume", None)
            if getattr(hv, "func", hv) is _hypervolume_default:
                if shadow is toolbox:
                    shadow = copy.copy(toolbox)
                shadow.register("hypervolume", hypervolume_sharded,
                                mesh=self.mesh())
            if (resolve_engine(toolbox) == "megakernel"
                    and getattr(sel, "func", sel) is sel_tournament):
                if shadow is toolbox:
                    shadow = copy.copy(toolbox)
                shadow.generation_engine = "megakernel_sharded"
                shadow.generation_mesh = self.mesh()
            self._sharded_tbs[oid] = shadow
        return shadow

    def _template_state(self, session: Session) -> Dict[str, jax.Array]:
        """The deterministic empty-slot filler of this session's bucket:
        zero rows, zero live count — stepped alongside real slots, its
        results are discarded and (live_n == 0) it assigns nothing."""
        pkey = (id(session.toolbox), session.bucket)
        tmpl = self._templates.get(pkey)
        if tmpl is None:
            zeros = jax.tree_util.tree_map(jnp.zeros_like,
                                           session._state["genome"])
            tmpl = {"key": jnp.zeros((2,), jnp.uint32),
                    "genome": zeros,
                    "values": jnp.zeros_like(session._state["values"]),
                    "valid": jnp.zeros_like(session._state["valid"]),
                    "live_n": jnp.asarray(0, jnp.int32),
                    "cxpb": jnp.asarray(0.0, jnp.float32),
                    "mutpb": jnp.asarray(0.0, jnp.float32)}
            self._templates[pkey] = tmpl
        return tmpl

    # -- request submission --------------------------------------------------

    def _deadline_at(self, deadline: Optional[float]) -> Optional[float]:
        return None if deadline is None else self._clock() + float(deadline)

    def _trace_ctx(self):
        """Per-request trace context: a child of the thread's current
        context (the HTTP handler installs the adopted wire context
        there) or a fresh root for in-process callers; ``None`` with
        tracing off."""
        if not self.tracer.enabled:
            return None
        return self.tracer.context(fleettrace.current())

    def _build_request(self, session: Session, kind: str, payload: dict,
                       deadline: Optional[float] = None,
                       on_failure=None) -> Request:
        if self._draining:
            raise ServiceDraining("service is draining for failover")
        if session.closed:
            raise ServiceClosed(f"session {session.name!r} is closed")
        if session.streamed:
            # a streamed session's generation runs the host-driven sliced
            # pipeline — nothing to co-batch, dispatch one at a time
            program_key: tuple = ("streamed", id(session.toolbox),
                                  session.bucket)
            capacity = 1
        elif session.sharded:
            # a sharded session owns the whole mesh for its dispatch: its
            # program is not vmapped over slots, so it never co-batches
            program_key = ("sharded", id(session.toolbox), session.bucket)
            capacity = 1
        else:
            program_key = (id(session.toolbox), session.bucket)
            capacity = self.max_batch
        req = Request(kind=kind, program_key=program_key,
                      payload=payload, session=session, weight=1,
                      capacity=capacity,
                      deadline=self._deadline_at(deadline),
                      trace=self._trace_ctx(),
                      priority=session.priority)
        if on_failure is not None:
            req.future._on_failure = on_failure
        return req

    def _submit(self, session: Session, kind: str, payload: dict,
                deadline: Optional[float] = None, block: bool = False,
                on_failure=None) -> ServeFuture:
        req = self._build_request(session, kind, payload, deadline,
                                  on_failure)
        return self._dispatcher.submit(req, block=block)

    def _submit_pipeline(self, session: Session, kind: str, n: int,
                         deadline: Optional[float] = None,
                         block: bool = False) -> List[ServeFuture]:
        """Queue ``n`` identical requests ATOMICALLY (all or none) —
        ``step(n)`` must never race a drain into queueing a prefix that
        executes while the call reports failure; see
        :meth:`BatchDispatcher.submit_many`."""
        reqs = [self._build_request(session, kind, {}, deadline)
                for _ in range(int(n))]
        return self._dispatcher.submit_many(reqs, block=block)

    def _submit_evaluate(self, session: Session, genomes,
                         deadline: Optional[float] = None) -> ServeFuture:
        if self._draining:
            raise ServiceDraining("service is draining for failover")
        if session.closed:
            raise ServiceClosed(f"session {session.name!r} is closed")
        genomes = jax.tree_util.tree_map(jnp.asarray, genomes)
        sig = genome_signature(genomes)
        n = jax.tree_util.tree_leaves(genomes)[0].shape[0]
        rows = self.policy.rows_for(n)
        self.shapes.observe(n)
        evaluate = session.toolbox.evaluate
        # normally pinned at open_session; this covers an evaluator
        # registered on the toolbox after the session opened — refcounted
        # into the session's pin set, NOT a bare setdefault, so closing
        # one session cannot drop an evaluator a sibling still uses
        self._pin_extra(session, evaluate)
        nobj = session.bucket.nobj
        req = Request(kind="evaluate",
                      program_key=(id(evaluate), sig, rows, nobj),
                      payload={"genome": genomes, "n": n},
                      session=session, weight=n, capacity=rows,
                      deadline=self._deadline_at(deadline),
                      trace=self._trace_ctx(),
                      priority=session.priority)
        return self._dispatcher.submit(req)

    # -- compiled-program cache ----------------------------------------------

    def _program(self, kind: str, program_key: tuple, build, args):
        """AOT-compile on first use; every later dispatch reuses the
        executable, so the ``compiles`` counters count real XLA
        compilations exactly (a shape drift raises instead of silently
        recompiling)."""
        key = (kind, program_key)
        compiled = self._programs.get(key)
        if compiled is None:
            t0 = self._clock()
            compiled = jax.jit(build()).lower(*args).compile()
            self._programs[key] = compiled
            self.metrics.inc("compiles")
            self.metrics.inc(f"compiles_{kind}")
            if self.profiler.enabled:
                # same event as the compiles* counters, so profile
                # records and compile counters always join; the one-time
                # cost/memory analyses run here, off the steady path
                self.profiler.observe_compile(kind, program_key, compiled,
                                              self._clock() - t0)
            if _events.active():     # in-trace telemetry tap, if one is open
                _events.emit("serve_compiles", 1)
            if self.verbose:
                emit_text(f"[serve] compiled {kind} program "
                          f"#{self.metrics.counter('compiles')}", self.sinks)
        return compiled

    # -- program builders (one per request kind) -----------------------------

    def _build_slot_program(self, kind: str, toolbox, weights: tuple,
                            vmapped: bool = True):
        return build_slot_program(kind, toolbox, weights, vmapped=vmapped)

    def _build_evaluate_program(self, evaluate, flat_dim: int):
        dedup = flat_dim <= self.dedup_max_flat_dim

        def prog(genome):
            values = jax.vmap(_norm_eval(evaluate))(genome)
            if dedup:
                rep, _ = rep_indices(flatten_rows(genome))
                values = values[rep]
            return values
        return prog

    # -- executors (dispatcher worker thread) --------------------------------

    def _execute(self, kind: str, program_key: tuple,
                 requests: List[Request]) -> list:
        if self._fault_hook is not None:
            self._fault_hook(kind, requests)
        if kind == "evaluate":
            # a stale (pre-rebucket) rows value still pads/executes
            # correctly — it just uses the old evaluate program
            return self._exec_evaluate(program_key, requests)
        healed = self._heal_stale_keys(program_key, requests)
        if healed is not None:
            return healed
        if program_key and program_key[0] == "streamed":
            return self._exec_streamed(kind, program_key, requests)
        if program_key and program_key[0] == "sharded":
            return self._exec_sharded(kind, program_key, requests)
        return self._exec_slots(kind, program_key, requests)

    def _current_key(self, session: Session) -> tuple:
        if session.streamed:
            return ("streamed", id(session.toolbox), session.bucket)
        if session.sharded:
            return ("sharded", id(session.toolbox), session.bucket)
        return (id(session.toolbox), session.bucket)

    def _heal_stale_keys(self, program_key: tuple,
                         requests: List[Request]) -> Optional[list]:
        """A submit that raced a rebucket can enqueue with a program key
        read from the PRE-refit bucket (remap_pending rewrites only
        already-queued requests).  Session state/buckets are
        authoritative at execution time: when they disagree with the
        batch's key, regroup by each session's current identity and
        dispatch the subgroups through the normal paths.  Returns None
        when the batch identity is already current (the common case)."""
        groups: Dict[tuple, List[Request]] = {}
        for r in requests:
            groups.setdefault(self._current_key(r.session), []).append(r)
        if len(groups) == 1 and next(iter(groups)) == program_key:
            return None
        out: Dict[int, Any] = {}
        for cur, reqs in groups.items():
            kind = reqs[0].kind
            if cur[0] == "streamed":
                # streamed dispatch is strictly one request at a time
                sub = [self._exec_streamed(kind, cur, [r])[0] for r in reqs]
            elif cur[0] == "sharded":
                # sharded dispatch is strictly one request at a time
                sub = [self._exec_sharded(kind, cur, [r])[0] for r in reqs]
            else:
                sub = self._exec_slots(kind, cur, reqs)
            for r, res in zip(reqs, sub):
                out[id(r)] = res
        return [out[id(r)] for r in requests]

    def _exec_sharded(self, kind: str, program_key: tuple,
                      requests: List[Request]) -> list:
        """Dispatch one pop-sharded session's request: the un-vmapped
        program form over mesh-sharded state (capacity 1, so ``requests``
        is always a single request).  Inputs are re-placed through
        :meth:`_place_sharded` every dispatch — idempotent for program
        outputs, and it canonicalizes host-built args (restored pendings,
        tell values) to the shardings the program was lowered with."""
        [req] = requests
        s = req.session
        rows = s.bucket.rows
        toolbox = self._sharded_toolbox(s.toolbox)
        weights = s.bucket.weights
        build = lambda: self._build_slot_program(  # noqa: E731
            kind, toolbox, weights, vmapped=False)
        t_pad0 = self._clock()
        state = self._place_sharded(s._state, rows)
        if kind == "tell":
            if s._pending is None:
                raise ServeError(
                    f"session {s.name!r} has no pending offspring (its "
                    "ask() may have failed) — re-ask before telling")
            vals = self._pad_values(req.payload["values"], rows,
                                    s.bucket.nobj)
            args = (state, self._place_sharded(s._pending, rows),
                    self._place_sharded(vals, rows))
        else:
            args = (state,)
        t_pad1 = self._clock()
        compiled = self._program(kind, program_key, build, args)
        t_dev0 = self._clock()
        out = compiled(*args)

        if kind == "ask":
            new_state, off_g, off_v, off_valid = out
            s._state = new_state
            s._pending = (off_g, off_v, off_valid)
            results = [_host(unpad_rows(off_g, s.pop_size))]
        else:
            new_state, nevals = out
            s._state = new_state
            if kind == "step":
                s.gen += 1
                self.metrics.inc("steps")
                self.metrics.inc("steps_sharded")
                self.metrics.inc_tenant(s.name, "steps")
            elif kind == "tell":
                with s._phase_lock:
                    s._pending = None
                    s.phase = "idle"
                s.gen += 1
            results = [{"gen": s.gen, "nevals": int(np.asarray(nevals))}]
        t_dev1 = self._clock()
        prof_attrs = self.profiler.observe_execute(kind, program_key,
                                                   t_dev1 - t_dev0)
        if req.trace is not None and self.tracer.enabled:
            self.tracer.phase("pad_bucket", req.trace, t_pad0, t_pad1,
                              attrs={"rows": rows, "sharded": True})
            self.tracer.phase("device_execute", req.trace, t_dev0, t_dev1,
                              attrs={"kind": kind, **(prof_attrs or {})})
        self._maybe_emit_stats()
        return results

    def _exec_streamed(self, kind: str, program_key: tuple,
                       requests: List[Request]) -> list:
        """Dispatch one streamed (out-of-core) session's request through
        the host-driven sliced pipeline (:mod:`deap_tpu.bigpop`).  There
        is no compiled slot program — the engine's own plan/slice
        programs keep device genome residency O(slice) — so the
        ``compiles*`` counters never move here; ``steps_streamed``
        counts the generations instead.  Capacity 1: ``requests`` is
        always a single request, like the sharded path."""
        from ..algorithms import ea_tell
        from ..bigpop.engine import (StreamedEngine, streamed_ea_ask,
                                     streamed_ea_step)
        from ..bigpop.host import HostPopulation
        [req] = requests
        s = req.session
        state = s._state
        weights = s.bucket.weights
        rows = s.bucket.rows
        live = np.arange(rows) < int(np.asarray(state["live_n"]))
        pop = Population(state["genome"],
                         Fitness(values=state["values"],
                                 valid=state["valid"], weights=weights))
        t_dev0 = self._clock()
        if kind == "step":
            key, out, nevals = streamed_ea_step(
                state["key"], pop, s.toolbox, state["cxpb"],
                state["mutpb"], live=live)
            s._state = {**state, "key": _as_raw_key(key),
                        "genome": out.genome,
                        "values": out.fitness.values,
                        "valid": out.fitness.valid}
            s.gen += 1
            self.metrics.inc("steps")
            self.metrics.inc("steps_streamed")
            self.metrics.inc_tenant(s.name, "steps")
            results = [{"gen": s.gen, "nevals": int(np.asarray(nevals))}]
        elif kind == "init":
            host = HostPopulation.from_population(pop, s.toolbox)
            eng = StreamedEngine(s.toolbox, host)
            nevals = eng.evaluate_initial(live_n=int(live.sum()))
            out = host.to_population()
            s._state = {**state, "values": out.fitness.values,
                        "valid": out.fitness.valid}
            results = [{"gen": s.gen, "nevals": int(nevals)}]
        elif kind == "ask":
            key, off = streamed_ea_ask(
                state["key"], pop, s.toolbox, state["cxpb"],
                state["mutpb"], live=live)
            s._state = {**state, "key": _as_raw_key(key)}
            s._pending = (off.genome, off.fitness.values, off.fitness.valid)
            results = [_host(unpad_rows(off.genome, s.pop_size))]
        elif kind == "tell":
            if s._pending is None:
                raise ServeError(
                    f"session {s.name!r} has no pending offspring (its "
                    "ask() may have failed) — re-ask before telling")
            pg, pv, pvalid = s._pending
            vals = self._pad_values(req.payload["values"], rows,
                                    s.bucket.nobj)
            # with externally computed values the tell half is O(pop)-small
            # fitness math — no genome-sized compute, resident ea_tell is
            # exact here
            out, nevals = ea_tell(
                s.toolbox, Population(pg, Fitness(pv, pvalid, weights)),
                vals, live=jnp.asarray(live))
            s._state = {**state, "genome": out.genome,
                        "values": out.fitness.values,
                        "valid": out.fitness.valid}
            with s._phase_lock:
                s._pending = None
                s.phase = "idle"
            s.gen += 1
            results = [{"gen": s.gen, "nevals": int(np.asarray(nevals))}]
        else:
            raise ServeError(f"unknown streamed request kind {kind!r}")
        t_dev1 = self._clock()
        prof_attrs = self.profiler.observe_execute(kind, program_key,
                                                   t_dev1 - t_dev0)
        if req.trace is not None and self.tracer.enabled:
            self.tracer.phase("device_execute", req.trace, t_dev0, t_dev1,
                              attrs={"kind": kind, "streamed": True,
                                     **(prof_attrs or {})})
        self._maybe_emit_stats()
        return results

    def _exec_slots(self, kind: str, program_key: tuple,
                    requests: List[Request]) -> list:
        sessions = [r.session for r in requests]
        t_pad0 = self._clock()
        tmpl = self._template_state(sessions[0])
        states = [s._state for s in sessions]
        states += [tmpl] * (self.max_batch - len(states))
        stacked = _stack(states)
        toolbox = sessions[0].toolbox
        weights = sessions[0].bucket.weights
        build = lambda: self._build_slot_program(kind, toolbox, weights)  # noqa: E731

        if kind == "tell":
            for s in sessions:
                if s._pending is None:
                    raise ServeError(
                        f"session {s.name!r} has no pending offspring (its "
                        "ask() may have failed) — re-ask before telling")
            pend = [s._pending for s in sessions]
            pend += [self._empty_pending(tmpl)] * \
                (self.max_batch - len(sessions))
            rows, nobj = sessions[0].bucket.rows, sessions[0].bucket.nobj
            vals = [self._pad_values(r.payload["values"], rows, nobj)
                    for r in requests]
            vals += [jnp.zeros((rows, nobj), jnp.float32)] * \
                (self.max_batch - len(requests))
            args = (stacked, _stack(pend), jnp.stack(vals))
        else:
            args = (stacked,)
        t_pad1 = self._clock()

        compiled = self._program(kind, program_key, build, args)
        t_dev0 = self._clock()
        out = compiled(*args)

        self.metrics.set_gauge("slot_occupancy",
                               len(requests) / self.max_batch)
        results = []
        if kind == "ask":
            new_states, off_g, off_v, off_valid = out
            for i, (r, s) in enumerate(zip(requests, sessions)):
                s._state = _slot(new_states, i)
                s._pending = (_slot(off_g, i), off_v[i], off_valid[i])
                n = s.pop_size
                results.append(_host(unpad_rows(_slot(off_g, i), n)))
        else:
            new_states, nevals = out
            nevals = np.asarray(nevals)
            for i, (r, s) in enumerate(zip(requests, sessions)):
                s._state = _slot(new_states, i)
                if kind == "step":
                    s.gen += 1
                    self.metrics.inc("steps")
                    self.metrics.inc_tenant(s.name, "steps")
                elif kind == "tell":
                    with s._phase_lock:
                        s._pending = None
                        s.phase = "idle"
                    s.gen += 1
                results.append({"gen": s.gen, "nevals": int(nevals[i])})
        t_dev1 = self._clock()
        prof_attrs = self.profiler.observe_execute(kind, program_key,
                                                   t_dev1 - t_dev0)
        if self.tracer.enabled:
            # the microbatch's phases are shared work: each traced
            # request gets the same bounds under its own span
            for r in requests:
                if r.trace is not None:
                    self.tracer.phase(
                        "pad_bucket", r.trace, t_pad0, t_pad1,
                        attrs={"rows": sessions[0].bucket.rows,
                               "slots": len(requests)})
                    self.tracer.phase("device_execute", r.trace,
                                      t_dev0, t_dev1,
                                      attrs={"kind": kind,
                                             **(prof_attrs or {})})
        self._maybe_emit_stats()
        return results

    @staticmethod
    def _empty_pending(tmpl):
        return (tmpl["genome"], tmpl["values"], tmpl["valid"])

    @staticmethod
    def _pad_values(values, rows: int, nobj: int) -> jax.Array:
        values = jnp.asarray(values, jnp.float32)
        if values.ndim == 1:
            values = values[:, None]
        return pad_rows(values, rows)

    def _exec_evaluate(self, program_key: tuple,
                       requests: List[Request]) -> list:
        evaluate_id, sig, rows, nobj = program_key
        with self._lock:
            # the ref is pinned by the requests' sessions, but the dict
            # itself is shared with open/close on the API threads
            evaluate = self._refs[evaluate_id]
        genomes = [r.payload["genome"] for r in requests]
        counts = [r.payload["n"] for r in requests]
        total = sum(counts)
        t_pad0 = self._clock()
        merged = jax.tree_util.tree_map(
            lambda *xs: jnp.concatenate(xs, axis=0), *genomes)
        padded = pad_rows(merged, rows)
        t_pad1 = self._clock()

        flat = np.asarray(flatten_rows(merged))
        digests = row_digests(flat)
        namespace = (evaluate_id, sig, nobj)
        hits = self.cache.lookup(namespace, digests)
        t_cache = self._clock()
        self.metrics.inc("dedup_rows", total - len(set(digests)))
        self.metrics.set_gauge("row_occupancy", total / rows)
        # per-tenant cache attribution: each request owns a contiguous
        # row range of the merged batch
        off = 0
        for r, n in zip(requests, counts):
            k = sum(1 for h in hits[off:off + n] if h is not None)
            self.metrics.inc_tenant(r.tenant, "cache_hits", k)
            self.metrics.inc_tenant(r.tenant, "cache_misses", n - k)
            off += n

        t_dev0 = t_dev1 = None
        if all(h is not None for h in hits):
            values = np.stack(hits).astype(np.float32)
        else:
            flat_dim = flat.shape[1]
            build = lambda: self._build_evaluate_program(  # noqa: E731
                evaluate, flat_dim)
            compiled = self._program("evaluate", program_key, build,
                                     (padded,))
            t_dev0 = self._clock()
            # np.array (not asarray): device outputs view as read-only, and
            # cached rows are spliced over this buffer below
            values = np.array(compiled(padded))[:total]
            if values.ndim == 1:
                values = values[:, None]
            miss = [i for i, h in enumerate(hits) if h is None]
            self.cache.insert(namespace, [digests[i] for i in miss],
                              values[miss])
            for i, h in enumerate(hits):
                if h is not None:
                    values[i] = h
            t_dev1 = self._clock()
        self.metrics.inc("evaluations", total)
        prof_attrs = None
        if t_dev0 is not None:
            prof_attrs = self.profiler.observe_execute(
                "evaluate", program_key, t_dev1 - t_dev0)
        if self.tracer.enabled:
            for r in requests:
                if r.trace is None:
                    continue
                self.tracer.phase("pad_bucket", r.trace, t_pad0, t_pad1,
                                  attrs={"rows": rows, "live": total})
                self.tracer.phase("cache_lookup", r.trace, t_pad1, t_cache,
                                  attrs={"rows": total})
                if t_dev0 is not None:
                    self.tracer.phase("device_execute", r.trace,
                                      t_dev0, t_dev1,
                                      attrs={"kind": "evaluate",
                                             **(prof_attrs or {})})

        results, off = [], 0
        for n in counts:
            results.append(np.array(values[off:off + n]))
            off += n
        self._maybe_emit_stats()
        return results

    def _maybe_emit_stats(self) -> None:
        if (self.stats_every and self.sinks
                and self._dispatcher.batches % self.stats_every == 0):
            self.metrics.emit(self.sinks, self._dispatcher.batches)

    # -- checkpoint / restore ------------------------------------------------

    def snapshot_sessions(self) -> Dict[str, dict]:
        """Host-side snapshot of every live session (unpadded state +
        run metadata) — the payload
        :func:`deap_tpu.resilience.save_session_states` persists."""
        out: Dict[str, dict] = {}
        with self.quiesce():
            for name, s in self.sessions().items():
                out[name] = self._snapshot_one(s)
        return out

    @staticmethod
    def _snapshot_one(s: Session) -> dict:
        """One session's host snapshot (the versioned wire/checkpoint
        form).  The caller must hold the session at a dispatch boundary
        — either the global :meth:`quiesce` or the single-session
        migration quiesce (``migrating`` flag + ``wait_session_idle``)."""
        st = s._state
        n = int(np.asarray(st["live_n"]))
        snap = {"gen": s.gen, "phase": s.phase, "n": n,
                "priority": s.priority,
                "weights": s.bucket.weights,
                "rows": s.bucket.rows,
                "key": np.asarray(st["key"]),
                "genome": _host(unpad_rows(st["genome"], n)),
                "values": np.asarray(st["values"][:n]),
                "valid": np.asarray(st["valid"][:n]),
                "cxpb": float(np.asarray(st["cxpb"])),
                "mutpb": float(np.asarray(st["mutpb"]))}
        if s._pending is not None:
            pg, pv, pvalid = s._pending
            snap["pending"] = {"genome": _host(unpad_rows(pg, n)),
                               "values": np.asarray(pv[:n]),
                               "valid": np.asarray(pvalid[:n])}
        return snap

    def export_session(self, name: str, *,
                       timeout: Optional[float] = 30.0) -> dict:
        """Live-migration step 1 of 2: quiesce exactly ONE session at a
        dispatch boundary, snapshot it, and detach it from this instance
        — without draining, pausing, or otherwise disturbing its
        neighbors.

        The session's ``migrating`` flag flips under the dispatcher's
        queue lock, so every later submission for it is rejected with
        :class:`~deap_tpu.serve.dispatcher.ServiceDraining` (the same
        provably-not-executed contract a drain gives: the caller re-sends
        to wherever the route now points).  Already-queued requests
        execute to completion first — the snapshot sits at a request
        boundary every client of this session observed, so adopting it
        elsewhere continues the trajectory bit-for-bit when bucket
        policies match.  Raises on timeout with the flag rolled back
        (the session keeps serving here)."""
        with self._lock:
            s = self._sessions.get(name)
        if s is None:
            raise SessionUnknown(f"no session named {name!r}")
        self._dispatcher.set_session_migrating(s, True)
        try:
            if not self._dispatcher.wait_session_idle(s, timeout=timeout):
                raise ServeError(
                    f"session {name!r} did not reach a dispatch boundary "
                    f"within {timeout}s — migration aborted, the session "
                    "keeps serving on this instance")
            snap = self._snapshot_one(s)
        except BaseException:
            self._dispatcher.set_session_migrating(s, False)
            raise
        s.closed = True
        self._forget(s)
        return snap

    def checkpoint(self, path, **io_kwargs) -> None:
        """Persist every live session through the resilient checkpoint
        tier (see :func:`deap_tpu.resilience.save_session_states`)."""
        from ..resilience.runner import save_session_states
        save_session_states(path, self.snapshot_sessions(), **io_kwargs)

    def restore_sessions(self, path, toolboxes: Dict[str, Any],
                         **io_kwargs) -> Dict[str, Session]:
        """Re-open the sessions checkpointed at ``path``.  ``toolboxes``
        maps session name → toolbox (functions are not persisted); only
        named sessions are restored.  Bucketing re-applies the CURRENT
        policy, so restore works across policy changes."""
        from ..resilience.runner import load_session_states
        return self.adopt_sessions(load_session_states(path, **io_kwargs),
                                   toolboxes)

    def adopt_sessions(self, snaps: Dict[str, dict],
                       toolboxes: Dict[str, Any]) -> Dict[str, Session]:
        """Re-open sessions from an in-memory snapshot dict (the
        :meth:`snapshot_sessions` / :meth:`drain` payload) — the transport-
        agnostic half of :meth:`restore_sessions`, and what the network
        frontend's cross-instance failover feeds after moving the snapshot
        over the wire.  Bucketing re-applies the CURRENT policy; when a
        snapshot records the bucket ``rows`` it was padded to and this
        instance buckets differently, a warning is emitted — the live-row
        trajectory is a function of the session's bucket, so bitwise
        continuation needs matching policies."""
        out: Dict[str, Session] = {}
        for name, toolbox in toolboxes.items():
            snap = snaps[name]
            pop = Population(
                genome=jax.tree_util.tree_map(jnp.asarray, snap["genome"]),
                fitness=Fitness(values=jnp.asarray(snap["values"]),
                                valid=jnp.asarray(snap["valid"]),
                                weights=tuple(snap["weights"])))
            pending_host = snap.get("pending")
            session = self._admit(jnp.asarray(snap["key"]), pop, toolbox,
                                  cxpb=snap["cxpb"], mutpb=snap["mutpb"],
                                  name=name, gen=int(snap["gen"]),
                                  phase=snap["phase"],
                                  pending_host=pending_host,
                                  priority=int(snap.get("priority", 1)))
            want_rows = snap.get("rows")
            if want_rows is not None and int(want_rows) != session.bucket.rows:
                import warnings
                warnings.warn(
                    f"session {name!r} restored into bucket "
                    f"rows={session.bucket.rows} but was checkpointed at "
                    f"rows={want_rows}: the continuation will diverge from "
                    "the origin instance (match BucketPolicy / "
                    "shard_threshold for bitwise failover)")
            out[name] = session
        return out

    # -- adaptive bucket grid ------------------------------------------------

    def rebucket(self, *, max_buckets: int = 8,
                 warm: Sequence[str] = ("step",),
                 sizes: Optional[Sequence[int]] = None) -> dict:
        """Re-derive the bucket grid from the observed request-shape
        histogram at a quiesce point.

        The default power-of-two grid is an a-priori guess; after real
        traffic the service knows better.  ``rebucket()`` pauses dispatch,
        fits an explicit grid to ``self.shapes`` (at most ``max_buckets``
        sizes, padding-cost-greedy — :func:`deap_tpu.serve.derive_sizes`),
        re-pads every live session whose bucket changed (live rows are
        moved verbatim; the *continuation* trajectory is a function of the
        new bucket), installs the new policy, and eagerly compiles the
        ``warm`` request kinds (any of ``step``/``init``/``ask``) for every
        live session so steady-state traffic after the quiesce point
        triggers **zero** unplanned recompiles.  All compiles are counted
        through the ordinary compile-event tap (``compiles*`` counters +
        in-trace events), so the recompile budget of a rebucket is exactly
        observable.  Returns a summary dict (old/new sizes, moved
        sessions, compiles spent).

        ``sizes`` (optional) installs an EXPLICIT grid instead of
        deriving one from this instance's histogram — the predictive
        pre-warm path: a freshly scaled-out instance has observed no
        traffic (``derive_policy`` raises on an empty histogram), so the
        autoscaler pushes the fleet-merged grid the router's placement
        layer already tracks, and the first migrated-in session lands in
        a bucket compiled before its traffic arrives."""
        bad = [k for k in warm if k not in ("step", "init", "ask")]
        if bad:
            raise ValueError(f"cannot pre-warm kinds {bad!r} (tell needs a "
                             "pending offspring batch)")
        with self.quiesce():
            before = self.metrics.counter("compiles")
            old_sizes = self.policy.sizes
            if sizes is not None:
                if not sizes or any(int(r) < 1 for r in sizes):
                    raise ValueError(f"explicit bucket sizes {sizes!r} must "
                                     "be a non-empty list of positive rows")
                policy = BucketPolicy(
                    sizes=tuple(sorted(int(r) for r in sizes)),
                    min_rows=self.policy.min_rows,
                    max_rows=self.policy.max_rows, grow_beyond=True)
            else:
                policy = self.shapes.derive_policy(
                    max_buckets=max_buckets, min_rows=self.policy.min_rows,
                    max_rows=self.policy.max_rows)
            moved = []
            sessions = self.sessions()
            for name, s in sessions.items():
                rows = policy.rows_for(s.pop_size)
                if s.sharded:
                    rows = self._shard_rows(rows)
                if rows != s.bucket.rows:
                    self._move_session(s, rows)
                    moved.append(name)
            self.policy = policy
            # requests enqueued BEFORE the refit still carry program keys
            # built from the old buckets — rewrite them in place so they
            # dispatch through the new programs instead of feeding
            # new-shaped state to a stale executable
            self._dispatcher.remap_pending(self._remap_request)
            if moved:
                self._release_stale_buckets(sessions)
            self.metrics.inc("rebuckets")
            for kind in warm:
                for s in sessions.values():
                    self._warm_program(kind, s)
            spent = self.metrics.counter("compiles") - before
        if self.verbose:
            emit_text(f"[serve] rebucket: sizes={policy.sizes} "
                      f"moved={moved} compiles={spent}", self.sinks)
        return {"old_sizes": tuple(old_sizes), "sizes": policy.sizes,
                "moved": moved, "compiles": spent}

    def _release_stale_buckets(self, sessions: Dict[str, Session]) -> None:
        """Drop compiled slot/sharded programs and templates for buckets
        no live session occupies anymore — without this every rebucket
        that moves sessions strands a full program set per abandoned
        bucket for as long as the tenant's toolbox stays pinned.
        (Evaluate programs are keyed on observed batch row counts, not
        session buckets, and are left alone.)"""
        tb_ids = {id(s.toolbox) for s in sessions.values()}
        keep = {(id(s.toolbox), s.bucket) for s in sessions.values()}
        keep |= {("sharded", id(s.toolbox), s.bucket)
                 for s in sessions.values() if s.sharded}

        def stale(pk: tuple) -> bool:
            if (len(pk) == 2 and pk[0] in tb_ids
                    and isinstance(pk[1], BucketKey)):
                return pk not in keep
            if len(pk) == 3 and pk[0] == "sharded" and pk[1] in tb_ids:
                return pk not in keep
            return False

        with self._lock:
            self._programs = {k: v for k, v in self._programs.items()
                              if not stale(k[1])}
            self._templates = {k: v for k, v in self._templates.items()
                               if not (k[0] in tb_ids and k not in keep)}

    def _remap_request(self, req: Request) -> None:
        """Recompute one queued request's batching identity against the
        CURRENT policy/buckets (see :meth:`rebucket`)."""
        s = req.session
        if req.kind == "evaluate":
            eid, sig, _rows, nobj = req.program_key
            rows = self.policy.rows_for(req.payload["n"])
            req.program_key = (eid, sig, rows, nobj)
            req.capacity = rows
        elif s is not None:
            if s.sharded:
                req.program_key = ("sharded", id(s.toolbox), s.bucket)
            else:
                req.program_key = (id(s.toolbox), s.bucket)
                req.capacity = self.max_batch

    def _move_session(self, s: Session, rows: int) -> None:
        """Re-pad a live session's device state into a ``rows`` bucket
        (live rows are copied bit-for-bit; pad rows are rebuilt zeros)."""
        n = s.pop_size
        st = s._state
        state = dict(st,
                     genome=pad_rows(unpad_rows(st["genome"], n), rows),
                     values=pad_rows(st["values"][:n], rows),
                     valid=pad_rows(st["valid"][:n], rows))
        pending = s._pending
        if pending is not None:
            pg, pv, pvalid = pending
            pending = (pad_rows(unpad_rows(pg, n), rows),
                       pad_rows(pv[:n], rows),
                       pad_rows(pvalid[:n], rows))
        if s.sharded:
            state = self._place_sharded(state, rows)
            if pending is not None:
                pending = self._place_sharded(pending, rows)
        s._state = state
        s._pending = pending
        s.bucket = dataclasses.replace(s.bucket, rows=rows)

    def _warm_program(self, kind: str, s: Session) -> None:
        """AOT-compile ``kind`` for ``s``'s current bucket ahead of
        traffic (no state is advanced — only the program cache is
        populated, through the ordinary counted :meth:`_program` path)."""
        if s.sharded:
            program_key: tuple = ("sharded", id(s.toolbox), s.bucket)
            build = lambda: self._build_slot_program(  # noqa: E731
                kind, self._sharded_toolbox(s.toolbox), s.bucket.weights,
                vmapped=False)
            args = (self._place_sharded(s._state, s.bucket.rows),)
        else:
            program_key = (id(s.toolbox), s.bucket)
            tmpl = self._template_state(s)
            states = [s._state] + [tmpl] * (self.max_batch - 1)
            build = lambda: self._build_slot_program(  # noqa: E731
                kind, s.toolbox, s.bucket.weights)
            args = (_stack(states),)
        self._program(kind, program_key, build, args)
