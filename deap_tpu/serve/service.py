""":class:`EvolutionService` — many concurrent EC runs multiplexed onto one
device (mesh) as an async ask/tell service.

Each :class:`Session` is an independent evolution run: its padded state
lives on device between requests, and every request kind is executed by a
compiled program whose shapes come from the service's
:class:`~deap_tpu.serve.buckets.BucketPolicy`:

* ``step``    — one full :func:`~deap_tpu.algorithms.ea_step` generation
  (select → vary → evaluate on device); sessions sharing a toolbox and a
  bucket are **slot-packed**: up to ``max_batch`` sessions advance under
  one ``vmap`` dispatch, and a slot's result depends only on that slot, so
  multiplexed results are bitwise identical to the same session served
  alone (pinned by ``tests/test_serve.py``);
* ``ask`` / ``tell`` — the generate/update split for clients that evaluate
  externally: ``ask`` returns the varied offspring genomes, ``tell`` feeds
  fitness values back (``toolbox.quarantine`` applied to fresh rows);
* ``evaluate`` — fitness for an ad-hoc genome batch, **row-packed** across
  sessions into one padded bucket, deduplicated on device
  (:func:`~deap_tpu.serve.cache.rep_indices`) and served through the host
  :class:`~deap_tpu.serve.cache.FitnessCache` (content-addressed, never
  caches non-finite values).

Programs are compiled **ahead-of-time** (``jit().lower().compile()``) once
per ``(kind, bucket, toolbox)`` and re-dispatched from the cache — a shape
that would recompile raises instead of silently thrashing, and the
``compiles*`` counters in :class:`~deap_tpu.serve.metrics.ServeMetrics`
are therefore exact.  Backpressure, deadlines, cancellation and retry
semantics live in :class:`~deap_tpu.serve.dispatcher.BatchDispatcher`.

::

    svc = EvolutionService(max_batch=4)
    s1 = svc.open_session(key1, pop1, toolbox, cxpb=0.6, mutpb=0.3)
    s2 = svc.open_session(key2, pop2, toolbox)
    futs = [s.step(10) for s in (s1, s2)]          # pipelined + microbatched
    for f in futs[0]: f.result()
    print(svc.stats())
    svc.close()
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from ..base import Population, Fitness
from ..algorithms import ea_step, ea_ask, ea_tell, _norm_eval
from ..observability import events as _events
from ..observability.sinks import emit_text
from .buckets import (BucketPolicy, BucketKey, pad_rows, unpad_rows,
                      pad_population, genome_signature)
from .cache import FitnessCache, flatten_rows, row_digests, rep_indices
from .dispatcher import (BatchDispatcher, Request, ServeFuture, ServeError,
                         ServiceClosed)
from .metrics import ServeMetrics

__all__ = ["EvolutionService", "Session"]


def _stack(trees):
    """Stack a list of identically-shaped pytrees on a new leading axis."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *trees)


def _slot(tree, i: int):
    return jax.tree_util.tree_map(lambda x: x[i], tree)


def _host(tree):
    return jax.tree_util.tree_map(lambda x: np.asarray(x), tree)


def _as_raw_key(key) -> jax.Array:
    """Canonical uint32 key form, so session keys and slot templates always
    stack to one dtype (typed keys are unwrapped; the raw data drives the
    same threefry stream)."""
    key = jnp.asarray(key) if not isinstance(key, jax.Array) else key
    if jax.dtypes.issubdtype(key.dtype, jax.dtypes.prng_key):
        return jax.random.key_data(key)
    return key.astype(jnp.uint32)


class Session:
    """One live evolution run inside an :class:`EvolutionService`.

    All methods are thread-safe and **asynchronous**: they enqueue a
    request and return a :class:`~deap_tpu.serve.dispatcher.ServeFuture`
    (``step(n)`` returns a list of ``n`` chained futures).  State advances
    strictly in submission order; the service packs compatible requests
    from *different* sessions into shared device batches."""

    def __init__(self, service: "EvolutionService", name: str, toolbox,
                 bucket: BucketKey, state: Dict[str, jax.Array],
                 gen: int = 0, phase: str = "idle", pending=None):
        self._service = service
        self.name = name
        self.toolbox = toolbox
        self.bucket = bucket
        self._state = state          # swapped atomically by the dispatcher
        self._pending = pending      # offspring awaiting tell (phase=asked)
        self.gen = int(gen)
        self.phase = phase           # idle | asked
        self.closed = False
        # guards the phase check-and-transition (concurrent ask()/step()
        # from two client threads must not both pass the guard); NEVER
        # held across a submit — the dispatcher takes its own lock first
        # on some failure paths, and the reverse order would deadlock
        self._phase_lock = threading.Lock()

    def _rollback_ask(self) -> None:
        """Failure hook of an ask() that never executed (deadline miss,
        cancellation, batch fault): the session returns to 'idle' so the
        client can re-ask or step instead of being wedged."""
        with self._phase_lock:
            if self.phase == "asked" and self._pending is None:
                self.phase = "idle"

    # -- introspection -------------------------------------------------------

    @property
    def pop_size(self) -> int:
        return int(np.asarray(self._state["live_n"]))

    @property
    def weights(self) -> tuple:
        return self.bucket.weights

    def population(self) -> Population:
        """Current (unpadded, host-materialized) population."""
        st = self._state
        n = int(np.asarray(st["live_n"]))
        return Population(
            genome=unpad_rows(st["genome"], n),
            fitness=Fitness(values=st["values"][:n], valid=st["valid"][:n],
                            weights=self.bucket.weights))

    # -- request API ---------------------------------------------------------

    def step(self, n: int = 1, deadline: Optional[float] = None,
             block: bool = False) -> List[ServeFuture]:
        """Advance ``n`` generations.  Returns the list of ``n``
        per-generation futures (each resolves to ``{"gen", "nevals"}``) —
        always a list, so call sites never branch on ``n``.  ``deadline``
        is seconds from now; a generation not dispatched by then fails
        (later ones still run on the state reached so far)."""
        with self._phase_lock:
            if self.phase != "idle":
                raise ServeError(f"session {self.name!r} has an "
                                 "outstanding ask(); tell() first")
        return [self._service._submit(self, "step", {}, deadline, block)
                for _ in range(int(n))]

    def ask(self, deadline: Optional[float] = None) -> ServeFuture:
        """Produce the next offspring batch (selection + variation, no
        evaluation).  Resolves to the host genome rows awaiting external
        evaluation; the session then expects :meth:`tell`.  An ask that
        fails before executing (deadline, cancellation, fault) rolls the
        session back to 'idle'."""
        with self._phase_lock:
            if self.phase != "idle":
                raise ServeError(f"session {self.name!r} already asked")
            self.phase = "asked"
        try:
            return self._service._submit(self, "ask", {}, deadline,
                                         on_failure=self._rollback_ask)
        except BaseException:
            self._rollback_ask()
            raise

    def tell(self, values, deadline: Optional[float] = None) -> ServeFuture:
        """Complete an :meth:`ask` with externally computed objective
        ``values`` (``(pop, nobj)`` or ``(pop,)``, one row per live
        individual); quarantine applies to the freshly assigned rows.
        Resolves to ``{"gen", "nevals"}``."""
        with self._phase_lock:
            if self.phase != "asked":
                raise ServeError(f"session {self.name!r} has no "
                                 "outstanding ask()")
        values = np.asarray(values)
        if values.shape[0] != self.pop_size:
            raise ValueError(
                f"tell() got {values.shape[0]} fitness rows for a "
                f"population of {self.pop_size}: every live individual "
                "needs a value (zero-filling the gap would silently "
                "assign fitness 0.0)")
        return self._service._submit(self, "tell", {"values": values},
                                     deadline)

    def evaluate(self, genomes, deadline: Optional[float] = None
                 ) -> ServeFuture:
        """Fitness for an ad-hoc genome batch (same structure as the
        session's genomes, any row count within the bucket policy), served
        through the content-addressed cache.  Resolves to a host
        ``(rows, nobj)`` array."""
        return self._service._submit_evaluate(self, genomes, deadline)

    def close(self) -> None:
        """Detach from the service; queued requests fail at dispatch."""
        self.closed = True
        self._service._forget(self)


class EvolutionService:
    """Multi-tenant ask/tell evaluation service (see module docstring).

    Parameters
    ----------
    policy:
        Row :class:`~deap_tpu.serve.buckets.BucketPolicy` (default: powers
        of two from 8).
    max_batch:
        Slot count of step/ask/tell microbatches — up to this many
        sessions advance per dispatch.  Part of the compiled shape, so all
        comparisons across services require equal ``max_batch``.
    max_pending / batch_window:
        Queue bound (backpressure) and optional linger seconds to fill a
        partial batch.
    cache_capacity / dedup_max_flat_dim:
        Host fitness-cache entries; flat genome width beyond which the
        device sort/unique dedup is skipped (a variadic lexsort keys per
        column).
    eval_retries / retry_backoff:
        Transient-fault retry budget around every device dispatch
        (:func:`deap_tpu.resilience.with_retries`).
    sinks / stats_every:
        Observability: emit a stats :class:`MetricRecord` to ``sinks``
        every N batches (0 = never); compile events also go to the
        in-trace event tap when one is open.
    fault_hook:
        Test seam: called as ``fault_hook(kind, requests)`` before every
        batch execution (raise to inject an evaluation fault).
    """

    def __init__(self, *, policy: Optional[BucketPolicy] = None,
                 max_batch: int = 4, max_pending: int = 256,
                 batch_window: float = 0.0, cache_capacity: int = 4096,
                 dedup_max_flat_dim: int = 512, eval_retries: int = 2,
                 retry_backoff: float = 0.05, sinks: Sequence = (),
                 stats_every: int = 0, verbose: bool = False,
                 fault_hook=None, clock=time.monotonic):
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        self.policy = policy if policy is not None else BucketPolicy()
        self.max_batch = int(max_batch)
        self.dedup_max_flat_dim = int(dedup_max_flat_dim)
        self.sinks = list(sinks)
        self.stats_every = int(stats_every)
        self.verbose = bool(verbose)
        self.metrics = ServeMetrics()
        self.cache = FitnessCache(cache_capacity, metrics=self.metrics)
        self._fault_hook = fault_hook
        self._clock = clock
        self._programs: Dict[tuple, Any] = {}
        self._templates: Dict[tuple, Dict[str, jax.Array]] = {}
        # id() pins keep toolboxes/evaluators alive (program keys use
        # id(), which must not be recycled) — refcounted per session so a
        # long-lived service releases dead tenants' objects AND their
        # compiled programs instead of leaking them forever
        self._refs: Dict[int, Any] = {}
        self._refcounts: Dict[int, int] = {}
        self._sessions: Dict[str, Session] = {}
        self._names = 0
        self._lock = threading.Lock()
        self._closed = False
        self._dispatcher = BatchDispatcher(
            self._execute, max_pending=max_pending,
            batch_window=batch_window, metrics=self.metrics,
            retries=eval_retries, backoff=retry_backoff, clock=clock)

    # -- lifecycle -----------------------------------------------------------

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def close(self) -> None:
        self._closed = True
        self._dispatcher.close()

    @contextlib.contextmanager
    def quiesce(self):
        """Pause dispatch (in-flight batch completes) — session states are
        stable inside the context.  Queued requests resume after."""
        self._dispatcher.pause()
        try:
            yield
        finally:
            self._dispatcher.resume()

    def stats(self):
        """Current :class:`~deap_tpu.observability.sinks.MetricRecord` —
        counters (requests/compiles/cache/...) + gauges (queue depth,
        occupancy, latency p50/p90/p99)."""
        self.metrics.set_gauge("sessions", len(self._sessions))
        return self.metrics.snapshot(self._dispatcher.batches)

    # -- sessions ------------------------------------------------------------

    def open_session(self, key, population: Population, toolbox, *,
                     cxpb: float = 0.5, mutpb: float = 0.2,
                     name: Optional[str] = None, evaluate_initial: bool = True,
                     timeout: Optional[float] = 60.0) -> Session:
        """Register a run and (synchronously, by default) evaluate its
        initial population through the service.  ``population`` is the
        UNPADDED initial population; the service pads it to its bucket."""
        if self._closed:
            raise ServiceClosed("service is closed")
        bucket = self.policy.bucket_for(population)
        with self._lock:
            if name is None:
                name = f"session-{self._names}"
            self._names += 1
            if name in self._sessions:
                raise ValueError(f"session name {name!r} already open")
        state = self._make_state(key, population, bucket, cxpb, mutpb)
        session = Session(self, name, toolbox, bucket, state)
        with self._lock:
            self._sessions[name] = session
            self._pin_locked(session)
        if evaluate_initial:
            self._submit(session, "init", {}).result(timeout=timeout)
        return session

    def sessions(self) -> Dict[str, Session]:
        with self._lock:
            return dict(self._sessions)

    @staticmethod
    def _session_pins(session: Session) -> list:
        pins = [session.toolbox]
        evaluate = getattr(session.toolbox, "evaluate", None)
        if evaluate is not None:
            pins.append(evaluate)
        return pins

    def _pin_locked(self, session: Session) -> None:
        for obj in self._session_pins(session):
            oid = id(obj)
            self._refs[oid] = obj
            self._refcounts[oid] = self._refcounts.get(oid, 0) + 1

    def _forget(self, session: Session) -> None:
        """Drop a closed session and, when its toolbox/evaluator pins hit
        refcount zero, release the pinned objects plus every compiled
        program and slot template keyed on them (bounded memory in a
        long-lived multi-tenant service)."""
        with self._lock:
            if self._sessions.pop(session.name, None) is None:
                return          # already forgotten: don't double-release
            for obj in self._session_pins(session):
                oid = id(obj)
                left = self._refcounts.get(oid, 0) - 1
                if left > 0:
                    self._refcounts[oid] = left
                    continue
                self._refcounts.pop(oid, None)
                self._refs.pop(oid, None)
                self._programs = {k: v for k, v in self._programs.items()
                                  if k[1][0] != oid}
                self._templates = {k: v for k, v in self._templates.items()
                                   if k[0] != oid}

    def _make_state(self, key, population: Population, bucket: BucketKey,
                    cxpb: float, mutpb: float) -> Dict[str, jax.Array]:
        padded = pad_population(population, bucket.rows)
        return {"key": _as_raw_key(key),
                "genome": padded.genome,
                "values": jnp.asarray(padded.fitness.values, jnp.float32),
                "valid": padded.fitness.valid,
                "live_n": jnp.asarray(population.size, jnp.int32),
                "cxpb": jnp.asarray(cxpb, jnp.float32),
                "mutpb": jnp.asarray(mutpb, jnp.float32)}

    def _template_state(self, session: Session) -> Dict[str, jax.Array]:
        """The deterministic empty-slot filler of this session's bucket:
        zero rows, zero live count — stepped alongside real slots, its
        results are discarded and (live_n == 0) it assigns nothing."""
        pkey = (id(session.toolbox), session.bucket)
        tmpl = self._templates.get(pkey)
        if tmpl is None:
            zeros = jax.tree_util.tree_map(jnp.zeros_like,
                                           session._state["genome"])
            tmpl = {"key": jnp.zeros((2,), jnp.uint32),
                    "genome": zeros,
                    "values": jnp.zeros_like(session._state["values"]),
                    "valid": jnp.zeros_like(session._state["valid"]),
                    "live_n": jnp.asarray(0, jnp.int32),
                    "cxpb": jnp.asarray(0.0, jnp.float32),
                    "mutpb": jnp.asarray(0.0, jnp.float32)}
            self._templates[pkey] = tmpl
        return tmpl

    # -- request submission --------------------------------------------------

    def _deadline_at(self, deadline: Optional[float]) -> Optional[float]:
        return None if deadline is None else self._clock() + float(deadline)

    def _submit(self, session: Session, kind: str, payload: dict,
                deadline: Optional[float] = None, block: bool = False,
                on_failure=None) -> ServeFuture:
        if session.closed:
            raise ServiceClosed(f"session {session.name!r} is closed")
        req = Request(kind=kind,
                      program_key=(id(session.toolbox), session.bucket),
                      payload=payload, session=session, weight=1,
                      capacity=self.max_batch,
                      deadline=self._deadline_at(deadline))
        if on_failure is not None:
            req.future._on_failure = on_failure
        return self._dispatcher.submit(req, block=block)

    def _submit_evaluate(self, session: Session, genomes,
                         deadline: Optional[float] = None) -> ServeFuture:
        genomes = jax.tree_util.tree_map(jnp.asarray, genomes)
        sig = genome_signature(genomes)
        n = jax.tree_util.tree_leaves(genomes)[0].shape[0]
        rows = self.policy.rows_for(n)
        evaluate = session.toolbox.evaluate
        # normally pinned at open_session; setdefault covers an evaluator
        # registered on the toolbox after the session opened
        self._refs.setdefault(id(evaluate), evaluate)
        nobj = session.bucket.nobj
        req = Request(kind="evaluate",
                      program_key=(id(evaluate), sig, rows, nobj),
                      payload={"genome": genomes, "n": n},
                      session=session, weight=n, capacity=rows,
                      deadline=self._deadline_at(deadline))
        return self._dispatcher.submit(req)

    # -- compiled-program cache ----------------------------------------------

    def _program(self, kind: str, program_key: tuple, build, args):
        """AOT-compile on first use; every later dispatch reuses the
        executable, so the ``compiles`` counters count real XLA
        compilations exactly (a shape drift raises instead of silently
        recompiling)."""
        key = (kind, program_key)
        compiled = self._programs.get(key)
        if compiled is None:
            compiled = jax.jit(build()).lower(*args).compile()
            self._programs[key] = compiled
            self.metrics.inc("compiles")
            self.metrics.inc(f"compiles_{kind}")
            if _events.active():     # in-trace telemetry tap, if one is open
                _events.emit("serve_compiles", 1)
            if self.verbose:
                emit_text(f"[serve] compiled {kind} program "
                          f"#{self.metrics.counter('compiles')}", self.sinks)
        return compiled

    # -- program builders (one per request kind) -----------------------------

    def _build_slot_program(self, kind: str, toolbox, weights: tuple):
        def as_population(state):
            return Population(state["genome"],
                              Fitness(values=state["values"],
                                      valid=state["valid"], weights=weights))

        def live_of(state):
            return jnp.arange(state["valid"].shape[0]) < state["live_n"]

        def pack(state, pop):
            return {**state, "genome": pop.genome,
                    "values": pop.fitness.values, "valid": pop.fitness.valid}

        if kind == "step":
            def one(state):
                key, pop, nevals = ea_step(
                    state["key"], as_population(state), toolbox,
                    state["cxpb"], state["mutpb"], live=live_of(state))
                return {**pack(state, pop), "key": key}, nevals
            return jax.vmap(one)
        if kind == "init":
            def one(state):
                pop, nevals = ea_tell(toolbox, as_population(state),
                                      live=live_of(state))
                return pack(state, pop), nevals
            return jax.vmap(one)
        if kind == "ask":
            def one(state):
                key, off = ea_ask(state["key"], as_population(state),
                                  toolbox, state["cxpb"], state["mutpb"],
                                  live=live_of(state))
                return ({**state, "key": key}, off.genome,
                        off.fitness.values, off.fitness.valid)
            return jax.vmap(one)
        if kind == "tell":
            def one(state, pending, values):
                pg, pv, pvalid = pending
                pop, nevals = ea_tell(
                    toolbox, Population(pg, Fitness(pv, pvalid, weights)),
                    values, live=live_of(state))
                return pack(state, pop), nevals
            return jax.vmap(one)
        raise ValueError(f"unknown slot program kind {kind!r}")

    def _build_evaluate_program(self, evaluate, flat_dim: int):
        dedup = flat_dim <= self.dedup_max_flat_dim

        def prog(genome):
            values = jax.vmap(_norm_eval(evaluate))(genome)
            if dedup:
                rep, _ = rep_indices(flatten_rows(genome))
                values = values[rep]
            return values
        return prog

    # -- executors (dispatcher worker thread) --------------------------------

    def _execute(self, kind: str, program_key: tuple,
                 requests: List[Request]) -> list:
        if self._fault_hook is not None:
            self._fault_hook(kind, requests)
        if kind == "evaluate":
            return self._exec_evaluate(program_key, requests)
        return self._exec_slots(kind, program_key, requests)

    def _exec_slots(self, kind: str, program_key: tuple,
                    requests: List[Request]) -> list:
        sessions = [r.session for r in requests]
        tmpl = self._template_state(sessions[0])
        states = [s._state for s in sessions]
        states += [tmpl] * (self.max_batch - len(states))
        stacked = _stack(states)
        toolbox = sessions[0].toolbox
        weights = sessions[0].bucket.weights
        build = lambda: self._build_slot_program(kind, toolbox, weights)  # noqa: E731

        if kind == "tell":
            for s in sessions:
                if s._pending is None:
                    raise ServeError(
                        f"session {s.name!r} has no pending offspring (its "
                        "ask() may have failed) — re-ask before telling")
            pend = [s._pending for s in sessions]
            pend += [self._empty_pending(tmpl)] * \
                (self.max_batch - len(sessions))
            rows, nobj = sessions[0].bucket.rows, sessions[0].bucket.nobj
            vals = [self._pad_values(r.payload["values"], rows, nobj)
                    for r in requests]
            vals += [jnp.zeros((rows, nobj), jnp.float32)] * \
                (self.max_batch - len(requests))
            args = (stacked, _stack(pend), jnp.stack(vals))
        else:
            args = (stacked,)

        compiled = self._program(kind, program_key, build, args)
        out = compiled(*args)

        self.metrics.set_gauge("slot_occupancy",
                               len(requests) / self.max_batch)
        results = []
        if kind == "ask":
            new_states, off_g, off_v, off_valid = out
            for i, (r, s) in enumerate(zip(requests, sessions)):
                s._state = _slot(new_states, i)
                s._pending = (_slot(off_g, i), off_v[i], off_valid[i])
                n = s.pop_size
                results.append(_host(unpad_rows(_slot(off_g, i), n)))
        else:
            new_states, nevals = out
            nevals = np.asarray(nevals)
            for i, (r, s) in enumerate(zip(requests, sessions)):
                s._state = _slot(new_states, i)
                if kind == "step":
                    s.gen += 1
                    self.metrics.inc("steps")
                elif kind == "tell":
                    with s._phase_lock:
                        s._pending = None
                        s.phase = "idle"
                    s.gen += 1
                results.append({"gen": s.gen, "nevals": int(nevals[i])})
        self._maybe_emit_stats()
        return results

    @staticmethod
    def _empty_pending(tmpl):
        return (tmpl["genome"], tmpl["values"], tmpl["valid"])

    @staticmethod
    def _pad_values(values, rows: int, nobj: int) -> jax.Array:
        values = jnp.asarray(values, jnp.float32)
        if values.ndim == 1:
            values = values[:, None]
        return pad_rows(values, rows)

    def _exec_evaluate(self, program_key: tuple,
                       requests: List[Request]) -> list:
        evaluate_id, sig, rows, nobj = program_key
        evaluate = self._refs[evaluate_id]
        genomes = [r.payload["genome"] for r in requests]
        counts = [r.payload["n"] for r in requests]
        total = sum(counts)
        merged = jax.tree_util.tree_map(
            lambda *xs: jnp.concatenate(xs, axis=0), *genomes)
        padded = pad_rows(merged, rows)

        flat = np.asarray(flatten_rows(merged))
        digests = row_digests(flat)
        namespace = (evaluate_id, sig, nobj)
        hits = self.cache.lookup(namespace, digests)
        self.metrics.inc("dedup_rows", total - len(set(digests)))
        self.metrics.set_gauge("row_occupancy", total / rows)

        if all(h is not None for h in hits):
            values = np.stack(hits).astype(np.float32)
        else:
            flat_dim = flat.shape[1]
            build = lambda: self._build_evaluate_program(  # noqa: E731
                evaluate, flat_dim)
            compiled = self._program("evaluate", program_key, build,
                                     (padded,))
            # np.array (not asarray): device outputs view as read-only, and
            # cached rows are spliced over this buffer below
            values = np.array(compiled(padded))[:total]
            if values.ndim == 1:
                values = values[:, None]
            miss = [i for i, h in enumerate(hits) if h is None]
            self.cache.insert(namespace, [digests[i] for i in miss],
                              values[miss])
            for i, h in enumerate(hits):
                if h is not None:
                    values[i] = h
        self.metrics.inc("evaluations", total)

        results, off = [], 0
        for n in counts:
            results.append(np.array(values[off:off + n]))
            off += n
        self._maybe_emit_stats()
        return results

    def _maybe_emit_stats(self) -> None:
        if (self.stats_every and self.sinks
                and self._dispatcher.batches % self.stats_every == 0):
            self.metrics.emit(self.sinks, self._dispatcher.batches)

    # -- checkpoint / restore ------------------------------------------------

    def snapshot_sessions(self) -> Dict[str, dict]:
        """Host-side snapshot of every live session (unpadded state +
        run metadata) — the payload
        :func:`deap_tpu.resilience.save_session_states` persists."""
        out: Dict[str, dict] = {}
        with self.quiesce():
            for name, s in self.sessions().items():
                st = s._state
                n = int(np.asarray(st["live_n"]))
                snap = {"gen": s.gen, "phase": s.phase, "n": n,
                        "weights": s.bucket.weights,
                        "key": np.asarray(st["key"]),
                        "genome": _host(unpad_rows(st["genome"], n)),
                        "values": np.asarray(st["values"][:n]),
                        "valid": np.asarray(st["valid"][:n]),
                        "cxpb": float(np.asarray(st["cxpb"])),
                        "mutpb": float(np.asarray(st["mutpb"]))}
                if s._pending is not None:
                    pg, pv, pvalid = s._pending
                    snap["pending"] = {"genome": _host(unpad_rows(pg, n)),
                                       "values": np.asarray(pv[:n]),
                                       "valid": np.asarray(pvalid[:n])}
                out[name] = snap
        return out

    def checkpoint(self, path, **io_kwargs) -> None:
        """Persist every live session through the resilient checkpoint
        tier (see :func:`deap_tpu.resilience.save_session_states`)."""
        from ..resilience.runner import save_session_states
        save_session_states(path, self.snapshot_sessions(), **io_kwargs)

    def restore_sessions(self, path, toolboxes: Dict[str, Any],
                         **io_kwargs) -> Dict[str, Session]:
        """Re-open the sessions checkpointed at ``path``.  ``toolboxes``
        maps session name → toolbox (functions are not persisted); only
        named sessions are restored.  Bucketing re-applies the CURRENT
        policy, so restore works across policy changes."""
        from ..resilience.runner import load_session_states
        snaps = load_session_states(path, **io_kwargs)
        out: Dict[str, Session] = {}
        for name, toolbox in toolboxes.items():
            snap = snaps[name]
            pop = Population(
                genome=snap["genome"],
                fitness=Fitness(values=jnp.asarray(snap["values"]),
                                valid=jnp.asarray(snap["valid"]),
                                weights=tuple(snap["weights"])))
            bucket = self.policy.bucket_for(pop)
            with self._lock:
                if name in self._sessions:
                    raise ValueError(f"session name {name!r} already open")
            state = self._make_state(jnp.asarray(snap["key"]), pop, bucket,
                                     snap["cxpb"], snap["mutpb"])
            pending = None
            if "pending" in snap:
                p = snap["pending"]
                pending = (pad_rows(jax.tree_util.tree_map(
                               jnp.asarray, p["genome"]), bucket.rows),
                           pad_rows(jnp.asarray(p["values"]), bucket.rows),
                           pad_rows(jnp.asarray(p["valid"]), bucket.rows))
            session = Session(self, name, toolbox, bucket, state,
                              gen=int(snap["gen"]), phase=snap["phase"],
                              pending=pending)
            with self._lock:
                self._sessions[name] = session
                self._pin_locked(session)
            out[name] = session
        return out
