"""``deap-tpu-top`` — the live fleet dashboard.

Operating a router fleet today means curling N ``/v1/metrics``
endpoints by hand and summing counters in your head.  This module is
the one-screen replacement: point it at a router (backends discovered
through ``GET /v1/admin/fleet``) or at explicit instances, and it
renders fleet-aggregate throughput, per-instance queue depth /
pad-waste / compile events, per-tenant SLO counters and latency
quantiles into one refreshing plain-text screen::

    deap-tpu-top --router http://127.0.0.1:8700
    deap-tpu-top --instances 127.0.0.1:8701,127.0.0.1:8702
    deap-tpu-top --router ... --once --json      # one snapshot, scripted

Liveness discipline (the serve package's standing invariant, lint-
gated): there are **no polling sleeps** anywhere.  One tail thread per
instance blocks on the server's ``/v1/metrics?stream=1`` chunked
ND-JSON stream — which the server itself feeds from a Condition wait on
dispatcher activity — and pokes an :class:`threading.Event` the render
loop waits on (with a refresh-interval cap, so gauges re-render even
while traffic is quiet).  An idle fleet costs one blocked socket read
per instance, not a poll.

``--once`` takes one synchronous snapshot instead (no threads) and
exits; with ``--json`` it prints the machine-readable document — the
``fleet.counters`` section is the exact per-counter SUM of the
``instances`` sections (pinned by ``tests/test_serve_top.py``), so
scripts can alarm on fleet aggregates without re-implementing the
join.

This module's stdout is its interface (sanctioned print site, like
``serve/cli.py``).
"""

from __future__ import annotations

import argparse
import http.client
import json
import socket
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from .. import sanitize
from .net.client import _parse_address

__all__ = ["FleetTop", "aggregate", "main"]

#: per-instance counters shown as columns (the rest still sum into the
#: fleet aggregate)
_COLUMNS = ("steps", "requests", "completed", "failed", "rejected",
            "compiles")


def _get_json(url_host: str, url_port: int, path: str,
              timeout: float) -> Any:
    conn = http.client.HTTPConnection(url_host, url_port, timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        data = resp.read()
        if resp.status >= 400:
            raise OSError(f"HTTP {resp.status} on {path}: {data[:200]!r}")
        return json.loads(data.decode("utf-8"))
    finally:
        conn.close()


def aggregate(instances: Dict[str, Dict[str, Any]]) -> Dict[str, Any]:
    """Fleet rollup over per-instance metric records: counters SUM
    per name (``fleet["counters"][k] == sum(inst[k])`` — the pinned
    contract), summable gauges sum, ratio gauges report their fleet
    maximum under a ``_max`` suffix, per-tenant tables merge by
    summing, and the worst per-instance p99 is surfaced."""
    counters: Dict[str, int] = {}
    gauges: Dict[str, float] = {}
    tenants: Dict[str, Dict[str, int]] = {}
    worst_p99 = 0.0
    up = 0
    for rec in instances.values():
        if rec.get("error"):
            continue
        up += 1
        for k, v in (rec.get("counters") or {}).items():
            counters[k] = counters.get(k, 0) + int(v)
        g = rec.get("gauges") or {}
        for k in ("queue_depth", "sessions", "sharded_sessions"):
            if k in g:
                gauges[k] = gauges.get(k, 0.0) + float(g[k])
        for k in ("pad_waste", "slot_occupancy", "row_occupancy"):
            if k in g:
                gauges[f"{k}_max"] = max(gauges.get(f"{k}_max", 0.0),
                                         float(g[k]))
        worst_p99 = max(worst_p99, float(g.get("latency_p99_ms", 0.0)))
        for tenant, row in ((rec.get("meta") or {}).get("tenants")
                            or {}).items():
            dst = tenants.setdefault(tenant, {})
            for k, v in row.items():
                dst[k] = dst.get(k, 0) + int(v)
    gauges["latency_p99_ms_max"] = worst_p99
    return {"instances_up": up,
            "instances_total": len(instances),
            "counters": counters,
            "gauges": gauges,
            "tenants": tenants}


class FleetTop:
    """Scraper + aggregator behind the ``deap-tpu-top`` screen.

    ``router`` is a router URL whose ``/v1/admin/fleet`` names the
    backends; ``instances`` adds (or replaces, router-less) explicit
    ``name=url`` or bare ``url`` targets.  :meth:`collect_once` is the
    synchronous one-shot; :meth:`run_live` starts one stream-tail
    thread per instance and re-renders on activity."""

    #: lock-guarded shared state (``lock-discipline`` lint): the latest
    #: per-instance records are written by every stream-tail thread and
    #: read by the render loop; the live-connection registry is written
    #: by tail threads and drained by close()
    _GUARDED_BY = {"_lock": ("_latest", "_conns")}

    def __init__(self, *, router: Optional[str] = None,
                 instances: Tuple[str, ...] = (),
                 timeout: float = 5.0, clock=time.monotonic):
        if router is None and not instances:
            raise ValueError("need --router or --instances")
        self.router = router
        self.timeout = float(timeout)
        self.clock = clock
        self._explicit = tuple(instances)
        self._lock = sanitize.lock()
        self._latest: Dict[str, Dict[str, Any]] = {}
        self._conns: Dict[str, http.client.HTTPConnection] = {}
        self._wake = sanitize.event()
        self._stop = sanitize.event()
        self._threads: List[threading.Thread] = []
        self._prev: Optional[Tuple[float, Dict[str, int]]] = None

    # -- discovery -----------------------------------------------------------

    def _explicit_targets(self) -> Dict[str, str]:
        out: Dict[str, str] = {}
        for i, spec in enumerate(self._explicit):
            if "=" in spec:
                name, url = spec.split("=", 1)
            else:
                name, url = spec, spec
            host, port = _parse_address(url)
            out[name.strip() or f"inst{i}"] = f"http://{host}:{port}"
        return out

    def discover(self) -> Tuple[Dict[str, str], Optional[dict]]:
        """``({instance name: url}, router topology | None)`` — backends
        from the router's admin view plus any explicit instances."""
        targets = self._explicit_targets()
        topology = None
        if self.router is not None:
            host, port = _parse_address(self.router)
            topology = _get_json(host, port, "/v1/admin/fleet",
                                 self.timeout)
            for name, info in (topology.get("backends") or {}).items():
                url = info.get("url")
                if url:
                    targets.setdefault(name, url)
        return targets, topology

    # -- one-shot ------------------------------------------------------------

    def _fetch_instance(self, url: str) -> Dict[str, Any]:
        host, port = _parse_address(url)
        try:
            rec = _get_json(host, port, "/v1/metrics", self.timeout)
        except (OSError, ValueError, http.client.HTTPException) as e:
            return {"url": url, "error": f"{type(e).__name__}: {e}"}
        return {"url": url, "error": None,
                "gen": rec.get("gen", 0),
                "counters": rec.get("counters", {}),
                "gauges": rec.get("gauges", {}),
                "meta": rec.get("meta", {}) or {}}

    def collect_once(self) -> Dict[str, Any]:
        """One synchronous fleet snapshot: topology (when routed),
        per-instance records, and the fleet aggregate — the ``--once``
        / ``--json`` document."""
        targets, topology = self.discover()
        instances = {name: self._fetch_instance(url)
                     for name, url in sorted(targets.items())}
        doc: Dict[str, Any] = {
            "instances": instances,
            "fleet": aggregate(instances),
        }
        if topology is not None:
            doc["router"] = {"url": self.router,
                             "sessions": topology.get("sessions"),
                             "sick": topology.get("sick") or {},
                             "fleet_sizes": topology.get("fleet_sizes"),
                             "autoscale": topology.get("autoscale")}
        doc["throughput"] = self._throughput(doc["fleet"]["counters"])
        return doc

    def _throughput(self, counters: Dict[str, int]) -> Dict[str, float]:
        """steps/requests per second since the previous snapshot (first
        snapshot: absent — a rate needs two points)."""
        now = self.clock()
        prev = self._prev
        self._prev = (now, dict(counters))
        if prev is None or now <= prev[0]:
            return {}
        dt = now - prev[0]
        return {f"{k}_per_s": round(
                    max(0, counters.get(k, 0) - prev[1].get(k, 0)) / dt, 2)
                for k in ("steps", "requests", "evaluations")}

    # -- live mode -----------------------------------------------------------

    def _tail_instance(self, name: str, url: str) -> None:
        """Stream-tail thread: block on the instance's chunked ND-JSON
        metrics stream, publish each record, poke the render loop.  On
        stream end/error, wait on the STOP event (not a sleep) before
        reconnecting — an unreachable instance costs one bounded wait
        per attempt, and close() wakes it immediately."""
        host, port = _parse_address(url)
        while not self._stop.is_set():
            conn = http.client.HTTPConnection(host, port,
                                              timeout=max(self.timeout, 30))
            # registered so close() can sever a read blocked in
            # readline() — the stream's quiet window is ~25s and _stop
            # is only checked between records
            with self._lock:
                self._conns[name] = conn
            try:
                conn.request(
                    "GET", "/v1/metrics?stream=1&max=1000000&timeout=25")
                resp = conn.getresponse()
                if resp.status >= 400:
                    raise OSError(f"HTTP {resp.status}")
                while not self._stop.is_set():
                    line = resp.readline()
                    if not line:
                        break
                    line = line.strip()
                    if not line:
                        continue
                    rec = json.loads(line.decode("utf-8"))
                    with self._lock:
                        self._latest[name] = {
                            "url": url, "error": None,
                            "gen": rec.get("gen", 0),
                            "counters": rec.get("counters", {}),
                            "gauges": rec.get("gauges", {}),
                            "meta": rec.get("meta", {}) or {}}
                    self._wake.set()
            except (OSError, ValueError, http.client.HTTPException,
                    AttributeError) as e:
                # AttributeError is the expected shutdown shape: close()
                # severs this thread's connection under a blocked
                # readline(), which surfaces as a read on the torn-down
                # response object
                if self._stop.is_set():
                    break
                with self._lock:
                    self._latest[name] = {
                        "url": url, "error": f"{type(e).__name__}: {e}"}
                self._wake.set()
                # bounded reconnect backoff on the STOP event — wakes
                # instantly at close(), never a blind sleep
                self._stop.wait(1.0)
            finally:
                with self._lock:
                    if self._conns.get(name) is conn:
                        del self._conns[name]
                conn.close()

    def start_streams(self) -> Dict[str, str]:
        targets, _ = self.discover()
        for name, url in sorted(targets.items()):
            t = threading.Thread(target=self._tail_instance,
                                 args=(name, url),
                                 name=f"deap-tpu-top-{name}", daemon=True)
            t.start()
            self._threads.append(t)
        return targets

    def snapshot_live(self) -> Dict[str, Any]:
        with self._lock:
            instances = {k: dict(v) for k, v in self._latest.items()}
        doc = {"instances": instances, "fleet": aggregate(instances)}
        doc["throughput"] = self._throughput(doc["fleet"]["counters"])
        return doc

    def run_live(self, *, refresh: float = 2.0,
                 max_refreshes: Optional[int] = None,
                 out=None) -> int:
        """The dashboard loop: render on activity (stream records set
        the wake event) or every ``refresh`` seconds, whichever comes
        first.  ``max_refreshes`` bounds the loop for tests/scripting;
        interactive runs render until interrupted."""
        out = out if out is not None else sys.stdout
        targets = self.start_streams()
        # seed the table so the first frame shows every instance
        for name, url in targets.items():
            rec = self._fetch_instance(url)
            with self._lock:
                self._latest.setdefault(name, rec)
        frames = 0
        try:
            while max_refreshes is None or frames < max_refreshes:
                doc = self.snapshot_live()
                print(render_screen(doc, clear=out.isatty()), file=out)
                frames += 1
                if max_refreshes is not None and frames >= max_refreshes:
                    break
                self._wake.wait(refresh)
                self._wake.clear()
                if self._stop.is_set():
                    break
        except KeyboardInterrupt:
            pass
        finally:
            self.close()
        return 0

    def close(self) -> None:
        self._stop.set()
        self._wake.set()
        # sever the live streams: a tail thread blocked in readline()
        # only re-checks _stop between records, and closing the fd does
        # NOT wake a thread parked in recv() — the socket must be
        # shutdown() under it (both directions) to unblock the join
        with self._lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for conn in conns:
            try:
                if conn.sock is not None:
                    conn.sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=5.0)
        # keep any thread whose join timed out visible — a "clean"
        # close must not mask a straggler from the caller (or the
        # test-suite thread-leak gate)
        self._threads = [t for t in self._threads if t.is_alive()]


# ---------------------------------------------------------------------------
# rendering
# ---------------------------------------------------------------------------


def _fmt(v: Any) -> str:
    if isinstance(v, float):
        return f"{v:g}"
    return str(v)


def render_screen(doc: Dict[str, Any], clear: bool = False) -> str:
    """One plain-text frame of the dashboard."""
    lines: List[str] = []
    if clear:
        lines.append("\x1b[2J\x1b[H" + "")
    fleet = doc.get("fleet", {})
    counters = fleet.get("counters", {})
    gauges = fleet.get("gauges", {})
    thr = doc.get("throughput", {})
    head = (f"deap-tpu-top  instances {fleet.get('instances_up', 0)}/"
            f"{fleet.get('instances_total', 0)}  sessions "
            f"{_fmt(gauges.get('sessions', 0))}  queue "
            f"{_fmt(gauges.get('queue_depth', 0))}  pad-waste(max) "
            f"{_fmt(gauges.get('pad_waste_max', 0))}")
    if "steps_per_s" in thr:
        head += f"  steps/s {_fmt(thr['steps_per_s'])}"
    lines.append(head)
    router = doc.get("router")
    if router:
        sick = router.get("sick") or {}
        lines.append(f"router {router.get('url')}  routed-sessions "
                     f"{router.get('sessions')}  sick "
                     f"{sorted(sick) if sick else 'none'}")
        scale = router.get("autoscale")
        if scale:
            pol = scale.get("policy") or {}
            sig = scale.get("signals") or {}
            lines.append(
                f"autoscale {scale.get('decision', 'hold')}  instances "
                f"{_fmt(sig.get('instances', '?'))} "
                f"[{pol.get('min_instances', '?')}-"
                f"{pol.get('max_instances', '?')}]  queue "
                f"{_fmt(sig.get('queue_depth', 0))}  cooldown "
                f"{_fmt(scale.get('cooldown_remaining_s', 0))}s  "
                f"fabric-hits {counters.get('cache_fabric_hits', 0)}")
    lines.append(
        f"fleet  steps {counters.get('steps', 0)}  requests "
        f"{counters.get('requests', 0)}  completed "
        f"{counters.get('completed', 0)}  failed "
        f"{counters.get('failed', 0)}  compiles "
        f"{counters.get('compiles', 0)}  p99(worst) "
        f"{_fmt(gauges.get('latency_p99_ms_max', 0))}ms")
    cols = "".join(f"{c:>11s}" for c in _COLUMNS)
    lines.append(f"{'instance':16s}{cols}{'queue':>8s}{'pad%':>8s}"
                 f"{'p50ms':>9s}{'p99ms':>9s}")
    for name in sorted(doc.get("instances", {})):
        rec = doc["instances"][name]
        if rec.get("error"):
            lines.append(f"{name:16s}  DOWN: {rec['error']}")
            continue
        c = rec.get("counters", {})
        g = rec.get("gauges", {})
        row = "".join(f"{c.get(col, 0):>11d}" for col in _COLUMNS)
        pad = 100.0 * float(g.get("pad_waste", 0.0))
        lines.append(
            f"{name:16s}{row}{int(g.get('queue_depth', 0)):>8d}"
            f"{pad:>8.1f}{float(g.get('latency_p50_ms', 0.0)):>9.1f}"
            f"{float(g.get('latency_p99_ms', 0.0)):>9.1f}")
    tenants = fleet.get("tenants") or {}
    if tenants:
        lines.append("tenants (top by requests):")
        top = sorted(tenants.items(),
                     key=lambda kv: -kv[1].get("requests", 0))[:8]
        for tenant, row in top:
            lines.append(
                f"  {tenant:24s} req {row.get('requests', 0):>7d}  "
                f"done {row.get('completed', 0):>7d}  "
                f"miss {row.get('deadline_misses', 0):>5d}  "
                f"rej {row.get('rejected', 0):>5d}")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# console entry
# ---------------------------------------------------------------------------


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="deap-tpu-top",
        description="Live dashboard over a deap-tpu serving fleet: "
                    "fleet-aggregate throughput, per-instance queue/"
                    "pad-waste/compiles, per-tenant SLO counters.")
    ap.add_argument("--router", default=None,
                    help="router URL; backends discovered via "
                         "/v1/admin/fleet")
    ap.add_argument("--instances", default=None,
                    help="comma-separated instance URLs (optionally "
                         "name=url) to watch directly")
    ap.add_argument("--once", action="store_true",
                    help="one snapshot, then exit (no stream threads)")
    ap.add_argument("--json", action="store_true", dest="json_out",
                    help="with --once: print the machine-readable "
                         "snapshot (fleet.counters is the exact sum of "
                         "the instances' counters)")
    ap.add_argument("--refresh", type=float, default=2.0,
                    help="live mode: max seconds between re-renders "
                         "(activity re-renders sooner)")
    ap.add_argument("--max-refreshes", type=int, default=None,
                    help="live mode: render N frames then exit "
                         "(scripting/tests; default: until interrupted)")
    ap.add_argument("--timeout", type=float, default=5.0,
                    help="per-request HTTP timeout")
    args = ap.parse_args(argv)

    instances = tuple(s.strip() for s in (args.instances or "").split(",")
                      if s.strip())
    if args.json_out and not args.once:
        ap.error("--json requires --once (the live screen is text)")
    try:
        top = FleetTop(router=args.router, instances=instances,
                       timeout=args.timeout)
    except ValueError as e:
        ap.error(str(e))
    if args.once:
        try:
            doc = top.collect_once()
        except (OSError, ValueError, http.client.HTTPException) as e:
            print(f"deap-tpu-top: {type(e).__name__}: {e}",
                  file=sys.stderr)
            return 1
        if args.json_out:
            print(json.dumps(doc, indent=2, sort_keys=True))
        else:
            print(render_screen(doc))
        return 0 if doc["fleet"]["instances_up"] > 0 else 1
    return top.run_live(refresh=args.refresh,
                        max_refreshes=args.max_refreshes)


if __name__ == "__main__":
    sys.exit(main())
