""":class:`FleetRouter` — the control plane above N serving instances.

The router owns four tables (all under one lock): the **routing table**
(session name → backend), the **tenant table** (session → paying
tenant), per-backend :class:`~deap_tpu.serve.router.placement.BackendPlan`
placement state, and the down-set.  Around them it composes the three
fleet behaviors of this package:

* **placement** — create requests pass tenant admission
  (:class:`~deap_tpu.serve.router.tenants.WeightedFairScheduler`) and
  bucket-affinity scoring
  (:class:`~deap_tpu.serve.router.placement.PlacementPolicy`) before the
  router forwards them to the chosen instance;
* **health-driven failover** (:meth:`failover`) — when the
  :class:`~deap_tpu.serve.router.health.HealthMonitor` latches an
  instance sick, the router drives PR 7's drain→restore automatically:
  drain the sick instance, partition its snapshot across healthy
  instances by toolbox + bucket affinity, restore each part, and
  re-route.  A target that dies **mid-restore** is latched sick itself
  and its part re-placed on a third instance; sessions `h_restore`
  skipped (toolbox not in the target's registry) are likewise re-placed
  instead of dropped.  The drained instance — if still answering — gets
  a redirect (``POST /v1/admin/redirect``) so clients pointed directly
  at it follow the move;
* **tenancy** — every session-mutating forward passes the weighted-fair
  scheduler; over-quota tenants receive the typed
  :class:`~deap_tpu.serve.dispatcher.TenantQuotaExceeded` on the wire.

All state here is host bookkeeping; the router never decodes tensor
payloads except on the create path (it needs the genome's shape class to
place by affinity).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

from ... import sanitize
from ...observability.fleettrace import FleetTracer
from ...observability.sinks import emit_text
from ..buckets import genome_signature
from ..dispatcher import ServeError, SessionUnknown
from ..metrics import (ServeMetrics, ROUTER_COUNTERS, ROUTER_GAUGES,
                       AUTOSCALE_COUNTERS, AUTOSCALE_GAUGES)
from .backend import Backend, BackendDown, CircuitBreaker
from .health import HealthMonitor, HealthPolicy
from .placement import BackendPlan, PlacementPolicy, fleet_sizes
from .tenants import TenantQuota, WeightedFairScheduler

__all__ = ["FleetRouter"]


class FleetRouter:
    """Session placement, failover and tenant enforcement over a fleet
    of :class:`~deap_tpu.serve.net.server.NetServer` instances (see
    module docstring).

    Parameters
    ----------
    backends:
        :class:`~deap_tpu.serve.router.backend.Backend` handles (or
        ``(name, address)`` pairs) for the instances to front.
    placement:
        :class:`PlacementPolicy`; its ``bucket_policy`` must mirror the
        instances' own, or affinity keys on the wrong grid.
    quotas / default_quota / max_inflight:
        Tenant enforcement — see :class:`WeightedFairScheduler`.
    health:
        :class:`HealthPolicy` for the monitor (``start_health=False``
        leaves the loop unstarted; probes then run only via
        ``check_health()``, which tests and single-threaded drivers
        call explicitly).
    drain_timeout:
        Seconds a sick instance gets to flush its queue before the
        failover declares its sessions lost.
    breaker_policy:
        Keyword arguments for the :class:`CircuitBreaker` the router
        attaches to every backend that arrives without one
        (``fail_threshold`` / ``reset_s`` / ``probe_jitter``).  A
        backend constructed with its own breaker keeps it; the router
        only binds its metrics/health observer hooks onto it.  An open
        breaker classifies the backend *degraded*: idempotent GETs
        still route to it (they double as organic recovery probes) and
        its existing sessions stay put, but new sessions place
        elsewhere while any non-degraded candidate exists.
    """

    #: lock-guarded shared state (``lock-discipline`` lint): routing,
    #: tenant and placement tables plus the down-set and the name
    #: counter are written by every handler thread and the health
    #: monitor's failover path — writes only under ``self._lock``
    _GUARDED_BY = {"_lock": ("_routes", "_tenant_of", "_plans", "_down",
                             "_toolboxes_of", "_reserved", "_names")}

    def __init__(self, backends: Sequence, *,
                 placement: Optional[PlacementPolicy] = None,
                 quotas: Optional[Dict[str, TenantQuota]] = None,
                 default_quota: TenantQuota = TenantQuota(),
                 max_inflight: int = 16,
                 health: Optional[HealthPolicy] = None,
                 start_health: bool = True,
                 drain_timeout: float = 60.0,
                 breaker_policy: Optional[Dict[str, Any]] = None,
                 tracer: Optional[FleetTracer] = None,
                 sinks: Sequence = (), verbose: bool = False,
                 clock=None):
        import time
        self._clock = clock if clock is not None else time.monotonic
        self.backends: Dict[str, Backend] = {}
        for b in backends:
            backend = b if isinstance(b, Backend) else Backend(*b)
            if backend.name in self.backends:
                raise ValueError(f"duplicate backend name {backend.name!r}")
            self.backends[backend.name] = backend
        if not self.backends:
            raise ValueError("a fleet needs at least one backend")
        self.placement = (placement if placement is not None
                          else PlacementPolicy())
        self.scheduler = WeightedFairScheduler(
            max_inflight=max_inflight, quotas=quotas, default=default_quota)
        self.drain_timeout = float(drain_timeout)
        self.metrics = ServeMetrics(
            extra_counters=ROUTER_COUNTERS + AUTOSCALE_COUNTERS,
            extra_gauges=ROUTER_GAUGES + AUTOSCALE_GAUGES)
        self.tracer = (tracer if tracer is not None
                       else FleetTracer(clock=self._clock))
        self.sinks = list(sinks)
        self.verbose = bool(verbose)
        self._lock = sanitize.lock()
        # route-change signal: forwarders retrying a provably-unexecuted
        # request wait here for the failover to move their session (a
        # Condition with its own lock — never held while taking _lock's
        # critical sections, only around notify/wait)
        self._route_cv = sanitize.condition()
        self._routes: Dict[str, str] = {}        # session -> backend name
        self._tenant_of: Dict[str, Optional[str]] = {}
        self._plans: Dict[str, BackendPlan] = {
            n: BackendPlan() for n in self.backends}
        self._toolboxes_of: Dict[str, frozenset] = {}
        self._down: Dict[str, str] = {}          # backend name -> reason
        self._reserved: set = set()              # names mid-create
        self._names = 0
        self.health = HealthMonitor(
            list(self.backends.values()), self._on_sick,
            policy=health, metrics=self.metrics, clock=self._clock)
        # one circuit breaker per backend: transport failures trip it,
        # its state drives the health monitor's degraded tier and the
        # router_breaker_* counters (hooks bound, never stomped — tests
        # pre-attach breakers with injected clocks)
        for b in self.backends.values():
            if b.breaker is None:
                b.breaker = CircuitBreaker(b.name, clock=self._clock,
                                           **dict(breaker_policy or {}))
            b.breaker.bind(on_event=self._on_breaker_event,
                           on_state=self._on_breaker_state)
        # elastic control loop (attach_autoscaler) — None on static fleets
        self.autoscaler = None
        if start_health:
            self.health.start()

    # -- lifecycle -----------------------------------------------------------

    def close(self) -> None:
        self.health.stop()
        self.scheduler.close()
        for b in self.backends.values():
            b.drop_connections()

    def __enter__(self) -> "FleetRouter":
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- introspection -------------------------------------------------------

    def healthy(self) -> List[Backend]:
        with self._lock:
            return [b for n, b in self.backends.items()
                    if n not in self._down]

    def route_of(self, name: str) -> Backend:
        with self._lock:
            bn = self._routes.get(name)
        if bn is None:
            raise SessionUnknown(f"no session named {name!r} routed in "
                                 "this fleet")
        return self.backends[bn]

    def tenant_of(self, name: str) -> Optional[str]:
        with self._lock:
            return self._tenant_of.get(name)

    def _notify_routes(self) -> None:
        with self._route_cv:
            self._route_cv.notify_all()

    def wait_rerouted(self, name: str, old_backend: str,
                      timeout: Optional[float] = None) -> bool:
        """Block until session ``name`` is routed somewhere other than
        ``old_backend`` (failover moved it) or dropped entirely (lost);
        False on timeout.  Condition-based — wakes the moment a
        failover commits its re-routing."""
        def moved() -> bool:
            with self._lock:
                bn = self._routes.get(name)
            return bn != old_backend
        with self._route_cv:
            return self._route_cv.wait_for(moved, timeout=timeout)

    def topology(self) -> dict:
        """The admin view: backends, health, per-backend session counts,
        the fleet-wide learned bucket grid."""
        with self._lock:
            plans = dict(self._plans)
            down = dict(self._down)
            routes = dict(self._routes)
        sizes = fleet_sizes(plans.values())
        degraded = self.health.degraded()
        per_backend: Dict[str, dict] = {}
        for name, backend in self.backends.items():
            plan = plans.get(name)
            per_backend[name] = {
                "url": backend.url,
                "sessions": sum(1 for bn in routes.values() if bn == name),
                "placed_total": plan.sessions if plan else 0,
                "warm_classes": len(plan.warm) if plan else 0,
                "down": down.get(name),
                "degraded": degraded.get(name),
                "breaker": (backend.breaker.state()
                            if backend.breaker is not None else None),
            }
        self.metrics.set_gauge("router_backends_alive",
                               len(self.backends) - len(down))
        self.metrics.set_gauge("router_sessions_routed", len(routes))
        autoscale = (self.autoscaler.describe()
                     if self.autoscaler is not None else None)
        return {"backends": per_backend, "sessions": len(routes),
                "fleet_sizes": list(sizes) if sizes else None,
                "sick": down, "autoscale": autoscale}

    def stats(self):
        """Router-level :class:`MetricRecord` (the RouterServer's
        ``/v1/metrics`` body)."""
        with self._lock:
            alive = len(self.backends) - len(self._down)
            routed = len(self._routes)
        self.metrics.set_gauge("router_backends_alive", alive)
        self.metrics.set_gauge("router_sessions_routed", routed)
        self.metrics.set_gauge("router_inflight", self.scheduler.inflight)
        self.metrics.set_gauge("router_backends_degraded",
                               len(self.health.degraded()))
        return self.metrics.snapshot()

    def check_health(self):
        """One synchronous probe round (the started monitor does this on
        its own interval)."""
        return self.health.check_now()

    def derive_fleet_sizes(self, **kw) -> Optional[Tuple[int, ...]]:
        with self._lock:
            plans = list(self._plans.values())
        return fleet_sizes(plans, **kw)

    def live_fleet_rows(self) -> Tuple[int, ...]:
        """Union of the bucket-row classes the fleet is actually running
        (every plan's warm set).  This — not :meth:`derive_fleet_sizes`,
        which proposes an *ideal* grid for a coordinated whole-fleet
        rebucket — is the grid a scale-out target must be pre-warmed
        with: restore re-buckets under the TARGET's policy, so only the
        rows already in service keep migration/failover bitwise and
        compile-free."""
        with self._lock:
            rows = {r for plan in self._plans.values()
                    for (r, _sig) in plan.warm}
        return tuple(sorted(rows))

    # -- elastic fleet (autoscale) --------------------------------------------

    def attach_autoscaler(self, autoscaler) -> None:
        """Register the :class:`~deap_tpu.serve.autoscale.Autoscaler`
        driving this fleet so :meth:`topology` can report its state."""
        self.autoscaler = autoscaler

    def add_backend(self, backend: Backend) -> None:
        """Adopt a freshly-spawned instance into the fleet: register it,
        give it an empty placement plan, put it under health probing and
        attach/bind a circuit breaker.  The scale-out path — new
        sessions may place on it the moment this returns."""
        if backend.breaker is None:
            backend.breaker = CircuitBreaker(backend.name, clock=self._clock)
        backend.breaker.bind(on_event=self._on_breaker_event,
                             on_state=self._on_breaker_state)
        with self._lock:
            if backend.name in self.backends:
                raise ValueError(
                    f"duplicate backend name {backend.name!r}")
            self.backends[backend.name] = backend
            self._plans[backend.name] = BackendPlan()
            self._down.pop(backend.name, None)
        self.health.add_backend(backend)
        emit_text(f"[router] backend {backend.name} joined the fleet "
                  f"({backend.url})", self.sinks)
        self._notify_routes()

    def remove_backend(self, name: str) -> Backend:
        """Forget a drained instance (the scale-in path).  The caller
        must have moved its sessions first (:meth:`failover` does);
        removing a backend that still routes sessions raises."""
        with self._lock:
            backend = self.backends.get(name)
            if backend is None:
                raise ValueError(f"no backend named {name!r}")
            if len(self.backends) == 1:
                raise ValueError("refusing to remove the last backend")
            still = sorted(s for s, bn in self._routes.items()
                           if bn == name)
            if still:
                raise ValueError(
                    f"backend {name!r} still routes sessions {still}; "
                    "drain it first")
            del self.backends[name]
            self._plans.pop(name, None)
            self._down.pop(name, None)
            self._toolboxes_of.pop(name, None)
        self.health.remove_backend(name)
        backend.drop_connections()
        emit_text(f"[router] backend {name} left the fleet", self.sinks)
        self._notify_routes()
        return backend

    def revive(self, name: str) -> None:
        """Operator action: clear a failed-over backend's down-mark
        after the instance was restarted or replaced.  It rejoins
        placement (and the autoscaler's healthy count) immediately;
        health probing resumes with a clean slate.  ``failover`` only
        ever retires — without this the fleet can never regrow onto a
        recovered instance short of remove+re-add."""
        with self._lock:
            if name not in self.backends:
                raise ValueError(f"no backend named {name!r}")
            self._down.pop(name, None)
        self.health.revive(name)
        emit_text(f"[router] backend {name} revived", self.sinks)
        self._notify_routes()

    def pick_migration_target(self, snap: dict, *,
                              exclude: Sequence[str] = ()
                              ) -> Optional[Backend]:
        """Bucket-affinity placement for one exported session snapshot
        (the live-migration target choice — same scoring as the
        failover restore path)."""
        return self._pick_restore_target(snap, set(exclude))

    def reroute_session(self, name: str, target: Backend, n: int,
                        sig: tuple) -> None:
        """Atomically rewrite one session's route onto ``target`` (the
        live-migration commit): the placement plans move with it and
        every forwarder blocked in :meth:`wait_rerouted` wakes.  Between
        the source's export and this commit the session is routed at the
        source but rejects work with the migration redirect — the
        forwarder retry path bridges that window."""
        rows = self.placement.bucket_rows(n)
        with self._lock:
            old = self._routes.get(name)
            if old is None:
                raise SessionUnknown(
                    f"no session named {name!r} routed in this fleet")
            self._routes[name] = target.name
            if old in self._plans:
                self._plans[old].forget_session()
            self._plans[target.name].observe_placement(n, rows, sig)
        self._notify_routes()

    # -- toolbox registry model ----------------------------------------------

    def _toolboxes(self, backend: Backend,
                   refresh: bool = False) -> frozenset:
        with self._lock:
            known = self._toolboxes_of.get(backend.name)
        if known is not None and not refresh:
            return known
        try:
            names = frozenset(backend.toolboxes())
        except (BackendDown, OSError):
            return known if known is not None else frozenset()
        with self._lock:
            self._toolboxes_of[backend.name] = names
        return names

    def toolbox_union(self) -> List[str]:
        out: set = set()
        for b in self.healthy():
            out |= self._toolboxes(b)
        return sorted(out)

    # -- placement (create path) ---------------------------------------------

    def admit_session(self, body: dict) -> Tuple[Backend, Optional[str],
                                                 str, int, tuple]:
        """Admission for one create request: tenant session quota,
        global name reservation, affinity placement.  Returns
        ``(backend, tenant, name, n, sig)`` — the caller forwards the
        create and then calls :meth:`commit_session` (with the returned
        ``n``/``sig``) or :meth:`abort_session`."""
        tenant = body.get("tenant")
        tb_name = body.get("toolbox")
        genome = body.get("genome")
        if genome is None:
            raise ValueError("create body carries no genome")
        # the tenant's quota — not the client — decides the session's
        # load-shedding class: stamp it into the create body the router
        # forwards, so the instance dispatcher sheds by contract
        body["priority"] = self.scheduler.quota_of(tenant).priority
        sig = genome_signature(genome)
        import jax
        n = int(jax.tree_util.tree_leaves(genome)[0].shape[0])
        name = body.get("name")
        with self._lock:
            if name is None:
                name = f"fleet-{self._names}"
            self._names += 1
            if name in self._routes or name in self._reserved:
                raise ValueError(f"session name {name!r} already open "
                                 "in this fleet")
            self._reserved.add(name)
        try:
            # session-count quota BEFORE any placement work
            self.scheduler.session_opened(tenant)
            try:
                backend, warm = self._choose_backend(tb_name, n, sig)
            except BaseException:
                self.scheduler.session_closed(tenant)
                raise
        except BaseException:
            with self._lock:
                self._reserved.discard(name)
            raise
        self.metrics.inc("router_sessions_placed")
        if warm:
            self.metrics.inc("router_placements_warm")
        return backend, tenant, name, n, sig

    def _choose_backend(self, tb_name: Optional[str], n: int,
                        sig: tuple) -> Tuple[Backend, bool]:
        candidates = []
        for backend in self.healthy():
            if tb_name is not None and \
                    tb_name not in self._toolboxes(backend):
                continue
            with self._lock:
                plan = self._plans[backend.name]
            candidates.append((backend, plan))
        if not candidates:
            raise SessionUnknown(
                f"no healthy backend holds toolbox {tb_name!r}")
        # degraded backends (breaker open / half-open) are excluded from
        # NEW-session placement while any clean candidate exists; when
        # the whole eligible set is degraded, place anyway — a gray
        # failure must soften placement, never refuse service outright
        clean = [(b, p) for b, p in candidates
                 if not self.health.is_degraded(b.name)]
        return self.placement.choose(clean or candidates, n, sig)

    def commit_session(self, name: str, backend: Backend, n: int,
                       sig: tuple, tenant: Optional[str]) -> None:
        """Record a session the backend acknowledged.  A failover can
        beat this commit (the health loop declares ``backend`` down
        between the create forward succeeding and the handler thread
        reaching here) — never stomp its re-route, and never pin a new
        session to a downed backend."""
        rows = self.placement.bucket_rows(n)
        with self._lock:
            self._reserved.discard(name)
            rerouted = self._routes.get(name)
            down = backend.name in self._down
            if rerouted is None and not down:
                self._routes[name] = backend.name
                self._tenant_of[name] = tenant
                self._plans[backend.name].observe_placement(n, rows, sig)
                return
            if rerouted is not None:
                # the drain snapshot included this just-created session
                # and the failover restored it elsewhere: keep ITS route
                # (the restore path already observed the placement on
                # the new home) — only the tenancy record is ours to add
                self._tenant_of[name] = tenant
                return
        # backend went down pre-commit and no restore re-routed the
        # session: it died with the instance — account it lost and free
        # the tenant's quota slot (same contract as an undrainable loss)
        self.metrics.inc("router_sessions_lost")
        self.scheduler.session_closed(tenant)

    def abort_session(self, name: str, tenant: Optional[str]) -> None:
        """Create forwarding failed after admission — release the quota
        slot and the name reservation."""
        self.scheduler.session_closed(tenant)
        with self._lock:
            self._reserved.discard(name)

    def forget_session(self, name: str) -> None:
        with self._lock:
            bn = self._routes.pop(name, None)
            tenant = self._tenant_of.pop(name, None)
            if bn is not None:
                self._plans[bn].forget_session()
        if bn is not None:
            self.metrics.inc("router_sessions_closed")
            self.scheduler.session_closed(tenant)
            self._notify_routes()

    # -- circuit breakers ----------------------------------------------------

    def _on_breaker_event(self, kind: str) -> None:
        """Breaker observer hook (fired outside the breaker lock) —
        explicit literal counter names, per the metric-discipline
        lint."""
        if kind == "opened":
            self.metrics.inc("router_breaker_opens")
        elif kind == "probe":
            self.metrics.inc("router_breaker_probes")
        elif kind == "shortcircuit":
            self.metrics.inc("router_breaker_rejections")

    def _on_breaker_state(self, name: str, state: str) -> None:
        """Breaker state transitions drive the health monitor's
        degraded tier: an open (or probing half-open) breaker means the
        backend still serves idempotent reads but must not take NEW
        sessions until a probe closes the circuit."""
        if state == "open":
            self.health.set_degraded(name, "circuit open")
        elif state == "half_open":
            self.health.set_degraded(name, "circuit half-open (probing)")
        elif state == "closed":
            self.health.clear_degraded(name)

    # -- health-driven failover ----------------------------------------------

    def _on_sick(self, backend: Backend, reason: str) -> None:
        """HealthMonitor callback — contain failures: the monitor thread
        must survive a failover that throws."""
        try:
            self.failover(backend, reason=reason)
        except Exception as e:  # noqa: BLE001 — reported, never fatal
            self.metrics.inc("router_errors")
            emit_text(f"[router] failover of {backend.name} failed: {e!r}",
                      self.sinks)

    def failover(self, backend: Backend, *,
                 reason: str = "operator") -> dict:
        """Drain ``backend`` and re-place every one of its sessions on
        healthy instances (see module docstring).  Idempotent per
        backend: a second call on an already-down instance is a no-op
        summary."""
        t0 = self._clock()
        with self._lock:
            if backend.name in self._down:
                return {"backend": backend.name, "already_down": True}
            self._down[backend.name] = reason
        self.metrics.inc("router_failovers")
        emit_text(f"[router] failover of {backend.name} ({reason})",
                  self.sinks)
        try:
            snaps = backend.drain(self.drain_timeout)
        except (BackendDown, ServeError, OSError) as e:
            # the instance is gone (or cannot flush): its sessions have
            # no snapshot to move — account them lost and re-route
            # nothing.  This is the one failover shape that loses state;
            # everything drainable below moves bitwise.
            lost = self._forget_backend_sessions(backend.name)
            self.metrics.inc("router_sessions_lost", len(lost))
            emit_text(f"[router] {backend.name} undrainable ({e}); "
                      f"{len(lost)} sessions lost: {sorted(lost)}",
                      self.sinks)
            self._notify_routes()
            return {"backend": backend.name, "reason": reason,
                    "restored": {}, "lost": sorted(lost),
                    "seconds": self._clock() - t0}
        placed, lost = self._replace_sessions(snaps, exclude={backend.name})
        self.metrics.inc("router_failover_sessions", len(placed))
        if lost:
            self.metrics.inc("router_sessions_lost", len(lost))
        # re-route moved sessions; drop lost ones (their tenants' quota
        # slots free up — a lost session must not count against anyone)
        lost_tenants: List[Optional[str]] = []
        with self._lock:
            for sess, target in placed.items():
                self._routes[sess] = target.name
            for sess in lost:
                self._routes.pop(sess, None)
                lost_tenants.append(self._tenant_of.pop(sess, None))
        for tenant in lost_tenants:
            self.scheduler.session_closed(tenant)
        self._notify_routes()
        # point stale direct clients at the majority target (best effort
        # — the drained instance may already be gone)
        if placed:
            counts: Dict[str, int] = {}
            for target in placed.values():
                counts[target.name] = counts.get(target.name, 0) + 1
            majority = self.backends[max(counts, key=counts.get)]
            try:
                backend.set_redirect(majority.url)
            except (BackendDown, ServeError, OSError):
                pass
        seconds = self._clock() - t0
        self.metrics.set_gauge("router_failover_recovery_s", seconds)
        emit_text(f"[router] failover of {backend.name} complete: "
                  f"{len(placed)} sessions moved, {len(lost)} lost, "
                  f"{seconds:.3f}s", self.sinks)
        return {"backend": backend.name, "reason": reason,
                "restored": {s: t.name for s, t in placed.items()},
                "lost": sorted(lost), "seconds": seconds}

    def _forget_backend_sessions(self, backend_name: str) -> List[str]:
        with self._lock:
            gone = [s for s, bn in self._routes.items()
                    if bn == backend_name]
            tenants = [self._tenant_of.pop(s, None) for s in gone]
            for s in gone:
                self._routes.pop(s, None)
        for tenant in tenants:
            self.metrics.inc("router_sessions_closed")
            self.scheduler.session_closed(tenant)
        return gone

    def _replace_sessions(self, snaps: Dict[str, dict],
                          exclude: set) -> Tuple[Dict[str, Backend],
                                                 List[str]]:
        """Place a drained snapshot's sessions on healthy backends:
        partition by (toolbox availability, bucket affinity), restore
        each part, and keep re-placing any part whose target dies
        mid-restore or whose sessions the target skipped — until every
        session is restored somewhere or no candidate remains."""
        remaining = dict(snaps)
        placed: Dict[str, Backend] = {}
        vetoed: Dict[str, set] = {}      # session -> backends ruled out
        first_choice: Dict[str, str] = {}
        while remaining:
            assign: Dict[str, Dict[str, dict]] = {}
            unplaceable: List[str] = []
            for sess, snap in remaining.items():
                target = self._pick_restore_target(
                    snap, exclude | vetoed.get(sess, set()))
                if target is None:
                    unplaceable.append(sess)
                else:
                    assign.setdefault(target.name, {})[sess] = snap
                    first_choice.setdefault(sess, target.name)
            for sess in unplaceable:
                remaining.pop(sess)
            if not assign:
                break
            for target_name, part in assign.items():
                target = self.backends[target_name]
                try:
                    resp = target.restore(part)
                except (BackendDown, OSError) as e:
                    # mid-restore death: the target adopted nothing it
                    # acknowledged — latch it sick and re-place this
                    # part on a third instance next round.  force_sick
                    # fires the full failover for the target (NOT a
                    # pre-emptive _mark_down, which would turn that
                    # failover into an already-down no-op and strand the
                    # target's own native sessions routed but untended)
                    emit_text(f"[router] restore target {target_name} "
                              f"died mid-restore ({e}); re-placing "
                              f"{len(part)} sessions", self.sinks)
                    self.health.force_sick(target_name,
                                           f"died mid-restore: {e}")
                    for sess in part:
                        vetoed.setdefault(sess, set()).add(target_name)
                    continue
                except ServeError as e:
                    # h_restore rejected the WHOLE part (every session
                    # skipped — e.g. the registry lost the toolbox since
                    # the router last looked): the target adopted
                    # nothing; refresh its registry model and re-place
                    # the part on the next instance
                    emit_text(f"[router] {target_name} rejected restore "
                              f"({e}); re-placing {len(part)} sessions",
                              self.sinks)
                    for sess in part:
                        vetoed.setdefault(sess, set()).add(target_name)
                    self._toolboxes(target, refresh=True)
                    continue
                for sess in resp.get("restored", ()):
                    placed[sess] = target
                    remaining.pop(sess, None)
                    if first_choice.get(sess) != target_name:
                        self.metrics.inc("router_orphans_replaced")
                    with self._lock:
                        snap = snaps[sess]
                        self._plans[target_name].observe_placement(
                            int(snap.get("n", 1)),
                            self.placement.bucket_rows(
                                int(snap.get("n", 1))),
                            genome_signature(snap["genome"]))
                for sess, why in (resp.get("skipped") or {}).items():
                    # h_restore skipped the orphan (toolbox not in this
                    # registry) — rule the target out for it and try the
                    # next instance instead of dropping the session
                    emit_text(f"[router] {target_name} skipped {sess} "
                              f"({why}); re-placing", self.sinks)
                    vetoed.setdefault(sess, set()).add(target_name)
                    self._toolboxes(target, refresh=True)
        return placed, sorted(remaining)

    def _pick_restore_target(self, snap: dict,
                             exclude: set) -> Optional[Backend]:
        tb_name = snap.get("toolbox")
        candidates = []
        for backend in self.healthy():
            if backend.name in exclude:
                continue
            if tb_name is not None and \
                    tb_name not in self._toolboxes(backend):
                continue
            with self._lock:
                plan = self._plans[backend.name]
            candidates.append((backend, plan))
        if not candidates:
            return None
        choice, _warm = self.placement.choose(
            candidates, int(snap.get("n", 1)),
            genome_signature(snap["genome"]))
        return choice

    # -- forwarding support (RouterServer) -----------------------------------

    def note_forward_failure(self, backend: Backend, exc: Exception) -> None:
        """A forward to ``backend`` failed at the transport level: run a
        probe round NOW (the strike path — repeated failures latch the
        instance sick and fire failover without waiting out the poll
        interval)."""
        self.metrics.inc("router_errors")
        emit_text(f"[router] forward to {backend.name} failed: {exc}",
                  self.sinks)
        self.health.check_now()
