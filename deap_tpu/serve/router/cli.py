"""``deap-tpu-router`` — front a fleet of serving instances.

The fleet sibling of ``deap-tpu-serve --listen``: stand up a
:class:`~deap_tpu.serve.router.server.RouterServer` over N already-running
:class:`~deap_tpu.serve.net.server.NetServer` instances and serve the same
DTF1 protocol until interrupted — clients point an unchanged
:class:`~deap_tpu.serve.net.client.RemoteService` at the router URL.

    deap-tpu-router --listen 0.0.0.0:8070 \\
        --backend a=10.0.0.1:8077 --backend b=10.0.0.2:8077 \\
        --backend c=10.0.0.3:8077

    # tenant enforcement: gold gets 3x the fair share, 8 sessions max
    deap-tpu-router --listen :8070 --backend a=:8077 --backend b=:8078 \\
        --quota gold=sessions:8,weight:3 --quota free=sessions:1 \\
        --max-inflight 32

On SIGINT the router reports one JSON summary line (topology + counters)
and exits; exit status is non-zero when every backend is down.  Health
polling, failover and placement knobs map one-to-one onto
:class:`~deap_tpu.serve.router.health.HealthPolicy` /
:class:`~deap_tpu.serve.router.core.FleetRouter` — see
docs/serving.md ("Running a fleet").
"""

from __future__ import annotations

import argparse
import json
import sys
import threading

__all__ = ["main", "parse_backend", "parse_quota"]


def parse_backend(spec: str):
    """``name=host:port`` → ``(name, (host, port))``; host defaults to
    127.0.0.1 so ``a=:8077`` fronts a local instance."""
    name, eq, addr = spec.partition("=")
    if not eq or not name:
        raise argparse.ArgumentTypeError(
            f"--backend wants name=host:port, got {spec!r}")
    host, _, port = addr.rpartition(":")
    if not port:
        raise argparse.ArgumentTypeError(
            f"--backend {spec!r} carries no port")
    try:
        return name, (host or "127.0.0.1", int(port))
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"--backend {spec!r} port is not an integer")


def parse_quota(spec: str):
    """``tenant=sessions:8,pending:4,weight:3`` →
    ``(tenant, TenantQuota)``; omitted fields keep the unlimited/1.0
    defaults."""
    from .tenants import TenantQuota

    tenant, eq, body = spec.partition("=")
    if not eq or not tenant:
        raise argparse.ArgumentTypeError(
            f"--quota wants tenant=field:value[,...], got {spec!r}")
    fields = {"sessions": "max_sessions", "pending": "max_pending",
              "weight": "weight"}
    kw = {}
    for part in filter(None, body.split(",")):
        key, colon, val = part.partition(":")
        if not colon or key not in fields:
            raise argparse.ArgumentTypeError(
                f"--quota field {part!r} not in {sorted(fields)}")
        try:
            kw[fields[key]] = float(val) if key == "weight" else int(val)
        except ValueError:
            raise argparse.ArgumentTypeError(
                f"--quota {part!r} value is not numeric")
    try:
        return tenant, TenantQuota(**kw)
    except ValueError as e:
        raise argparse.ArgumentTypeError(str(e))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="deap-tpu-router",
        description="front N deap-tpu serving instances with placement, "
                    "health-driven failover and tenant enforcement "
                    "(clients use the unchanged RemoteService)")
    ap.add_argument("--listen", metavar="HOST:PORT", default="127.0.0.1:0",
                    help="router bind address (default loopback, "
                         "ephemeral port)")
    ap.add_argument("--backend", metavar="NAME=HOST:PORT",
                    type=parse_backend, action="append", required=True,
                    help="one serving instance to front (repeatable; "
                         "at least one)")
    ap.add_argument("--quota", metavar="TENANT=F:V[,F:V...]",
                    type=parse_quota, action="append", default=[],
                    help="per-tenant quota: fields sessions, pending, "
                         "weight (repeatable)")
    ap.add_argument("--max-inflight", type=int, default=16,
                    help="fleet-wide concurrent session-op forwards "
                         "shared weighted-fair across tenants")
    ap.add_argument("--probe-interval", type=float, default=2.0,
                    help="health poll period in seconds")
    ap.add_argument("--fail-after", type=int, default=2,
                    help="consecutive failed probes before failover")
    ap.add_argument("--drain-timeout", type=float, default=60.0,
                    help="seconds a sick instance gets to flush before "
                         "its sessions are declared lost")
    ap.add_argument("--quiet", action="store_true",
                    help="suppress the per-event router log lines")
    args = ap.parse_args(argv)

    from .core import FleetRouter
    from .health import HealthPolicy
    from .server import RouterServer

    names = [n for n, _ in args.backend]
    if len(set(names)) != len(names):
        ap.error(f"duplicate backend names in {names}")

    host, _, port = args.listen.rpartition(":")
    if not port:
        ap.error(f"--listen {args.listen!r} carries no port")
    router = FleetRouter(
        list(args.backend), quotas=dict(args.quota),
        max_inflight=args.max_inflight,
        health=HealthPolicy(interval_s=args.probe_interval,
                            fail_after=args.fail_after),
        drain_timeout=args.drain_timeout, verbose=not args.quiet)
    rc = 0
    with RouterServer(router, host=host or "127.0.0.1", port=int(port),
                      verbose=not args.quiet) as srv:
        print(f"[router] listening on {srv.url} fronting "
              f"{names} (ctrl-c to stop)")
        try:
            threading.Event().wait()      # serve until interrupted
        except KeyboardInterrupt:
            print("[router] shutting down", file=sys.stderr)
        topo = router.topology()
        rec = router.stats()
        if len(topo["sick"]) >= len(router.backends):
            rc = 1
    print(json.dumps({"mode": "router", "url": srv.url,
                      "topology": topo, "counters": rec.counters,
                      "rc": rc}))
    return rc


if __name__ == "__main__":
    sys.exit(main())
