""":class:`Backend` — the router's handle on one
:class:`~deap_tpu.serve.net.server.NetServer` instance.

Two traffic classes, deliberately separated:

* **forwarding** (:meth:`forward`) — raw DTF1 frames relayed
  byte-for-byte (payloads untouched, so compression negotiated between
  client and instance survives the hop).  Each router handler thread
  keeps its own keep-alive connection to the backend (thread-local
  pool), mirroring the stdlib frontend's one-handler-per-connection
  model; a send-phase failure retries once on a fresh connection (the
  request never hit the wire), a response-phase failure propagates — the
  instance may have executed a non-idempotent step;
* **control** (:meth:`healthz` / :meth:`metrics` / :meth:`trace_tail` /
  :meth:`drain` / :meth:`restore` / :meth:`set_redirect` /
  :meth:`toolboxes`) — per-call connections with their own (short)
  timeout so a wedged instance can never stall the health loop or a
  failover behind a long forward.
"""

from __future__ import annotations

import http.client
import json
import random
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ... import sanitize
from ..dispatcher import CircuitOpen, ServeError
from ..net import protocol
from ..net.client import _make_connection, _parse_url

__all__ = ["Backend", "BackendDown", "CircuitBreaker", "CircuitOpen"]


class BackendDown(ServeError):
    """The backend did not answer (connect/send/read failure) — the
    transport-level 'sick' signal, distinct from a typed service error
    the instance itself raised.  ``sent`` records whether the request
    reached the wire: ``False`` means the instance provably never saw it
    (a re-send cannot double-execute anything), ``True`` means it died
    mid-response and MAY have executed — the router never retries
    those."""

    def __init__(self, message: str, *, sent: bool = False):
        super().__init__(message)
        self.sent = bool(sent)


class CircuitBreaker:
    """Per-backend circuit breaker: closed → open → half-open.

    ``fail_threshold`` consecutive transport failures
    (:class:`BackendDown`) trip the breaker OPEN: further non-idempotent
    forwards are refused immediately with typed
    :class:`~deap_tpu.serve.dispatcher.CircuitOpen` instead of queueing
    behind a connect timeout to a wedged instance.  After a *jittered*
    probe delay (``reset_s * (1 + probe_jitter * u)``, ``u`` uniform —
    jitter so a fleet of routers doesn't re-probe a recovering backend in
    lockstep) the breaker goes HALF-OPEN and admits exactly one trial
    request; its success closes the breaker, its failure re-opens with a
    fresh jittered delay.  Idempotent GETs are never blocked — they pass
    through and their outcomes double as organic probes, which is what
    makes a breaker-open backend merely *degraded* (still readable)
    rather than down.

    ``clock``/``rng`` are injectable so drills pin the exact open/probe
    schedule; ``on_event(kind)`` (kind in ``"opened"``/``"probe"``/
    ``"shortcircuit"``) and ``on_state(name, state)`` are the metrics /
    health hooks, called OUTSIDE the breaker lock."""

    #: lock-guarded state machine, written from every router handler
    #: thread that forwards through this backend
    _GUARDED_BY = {"_lock": ("_state", "_failures", "_opened_at",
                             "_probe_delay", "_probe_inflight")}

    def __init__(self, name: str = "", *, fail_threshold: int = 3,
                 reset_s: float = 5.0, probe_jitter: float = 0.5,
                 clock: Callable[[], float] = time.monotonic,
                 rng: Optional[Callable[[], float]] = None,
                 on_event: Optional[Callable[[str], None]] = None,
                 on_state: Optional[Callable[[str, str], None]] = None):
        if fail_threshold < 1:
            raise ValueError("fail_threshold must be >= 1")
        if reset_s <= 0:
            raise ValueError("reset_s must be > 0")
        if probe_jitter < 0:
            raise ValueError("probe_jitter must be >= 0")
        self.name = str(name)
        self.fail_threshold = int(fail_threshold)
        self.reset_s = float(reset_s)
        self.probe_jitter = float(probe_jitter)
        self._clock = clock
        self._rng = rng if rng is not None else random.random
        self._on_event = on_event
        self._on_state = on_state
        self._lock = sanitize.lock()
        self._state = "closed"
        self._failures = 0
        self._opened_at = 0.0
        self._probe_delay = 0.0
        self._probe_inflight = False

    def bind(self, on_event: Optional[Callable[[str], None]] = None,
             on_state: Optional[Callable[[str, str], None]] = None) -> None:
        """Fill in UNSET observer hooks (the router wires its metrics /
        health callbacks onto breakers it did not construct — e.g. one a
        test pre-attached with an injected clock) without stomping hooks
        the constructor already received."""
        if self._on_event is None and on_event is not None:
            self._on_event = on_event
        if self._on_state is None and on_state is not None:
            self._on_state = on_state

    def _emit(self, events, state_change):
        if self._on_event is not None:
            for kind in events:
                self._on_event(kind)
        if state_change is not None and self._on_state is not None:
            self._on_state(self.name, state_change)

    def state(self) -> str:
        with self._lock:
            return self._state

    def before_request(self) -> None:
        """Admission gate for a non-idempotent forward.  Passes when
        closed, claims the single half-open probe slot when the probe
        delay has elapsed, and raises :class:`CircuitOpen` otherwise."""
        events: list = []
        state_change = None
        with self._lock:
            if self._state == "closed":
                return
            now = self._clock()
            if (self._state == "open"
                    and now - self._opened_at >= self._probe_delay):
                self._state = "half_open"
                self._probe_inflight = True
                events.append("probe")
                state_change = "half_open"
            elif self._state == "half_open" and not self._probe_inflight:
                # a previous probe resolved elsewhere (organic GET) but
                # the breaker is still deciding — admit one more trial
                self._probe_inflight = True
                events.append("probe")
            else:
                wait = max(0.0, self._opened_at + self._probe_delay
                           - self._clock())
                events.append("shortcircuit")
                self._emit(events, None)
                raise CircuitOpen(
                    f"backend {self.name} circuit is {self._state} "
                    f"(next probe in {wait:.2f}s); retry later or "
                    "against another instance")
        self._emit(events, state_change)

    def record_success(self) -> None:
        state_change = None
        with self._lock:
            self._failures = 0
            self._probe_inflight = False
            if self._state != "closed":
                self._state = "closed"
                state_change = "closed"
        self._emit((), state_change)

    def record_failure(self) -> None:
        events: list = []
        state_change = None
        with self._lock:
            self._failures += 1
            self._probe_inflight = False
            tripped = (self._state == "closed"
                       and self._failures >= self.fail_threshold)
            reopened = self._state == "half_open"
            if tripped or reopened:
                self._state = "open"
                self._opened_at = self._clock()
                self._probe_delay = self.reset_s * (
                    1.0 + self.probe_jitter * self._rng())
                events.append("opened")
                state_change = "open"
        self._emit(events, state_change)


class Backend:
    """One routable serving instance (see module docstring).

    ``breaker`` (optional) is this backend's :class:`CircuitBreaker`;
    when set, non-idempotent forwards pass its admission gate and every
    forward outcome feeds its state machine.  The router attaches one
    per backend (:class:`~deap_tpu.serve.router.core.FleetRouter`)."""

    def __init__(self, name: str, address, *, timeout: float = 600.0,
                 control_timeout: float = 10.0,
                 breaker: Optional[CircuitBreaker] = None,
                 ssl_context=None):
        self.name = str(name)
        scheme, self.host, self.port = _parse_url(address)
        #: TLS toward the instance: an ``ssl.SSLContext`` (verify mode /
        #: CA set included) applied to every forwarding and control
        #: connection; an https address with no context gets the stdlib
        #: default (system CAs)
        if ssl_context is None and scheme == "https":
            import ssl as _ssl
            ssl_context = _ssl.create_default_context()
        self.ssl_context = ssl_context
        self.timeout = float(timeout)
        self.control_timeout = float(control_timeout)
        self.breaker = breaker
        self._tls = threading.local()

    @property
    def url(self) -> str:
        scheme = "https" if self.ssl_context is not None else "http"
        return f"{scheme}://{self.host}:{self.port}"

    def __repr__(self) -> str:
        return f"Backend({self.name!r}, {self.url})"

    # -- forwarding ----------------------------------------------------------

    def _fwd_conn(self, fresh: bool = False) -> http.client.HTTPConnection:
        conn = getattr(self._tls, "conn", None)
        if fresh and conn is not None:
            conn.close()
            conn = None
        if conn is None:
            conn = _make_connection(self.host, self.port,
                                    timeout=self.timeout,
                                    ssl_context=self.ssl_context)
            self._tls.conn = conn
        return conn

    def forward(self, method: str, path: str, body: Optional[bytes],
                content_type: str = protocol.CONTENT_TYPE,
                accept: Optional[str] = None) -> Tuple[int, bytes]:
        """Relay one raw request; returns ``(status, response bytes)``.
        ``accept`` relays the client's ``X-DTF-Accept`` compression
        advertisement (the only negotiation channel a bodyless GET has).
        Raises :class:`BackendDown` when the instance cannot be reached
        (send retried once on a fresh connection — safe, the request
        never arrived) or stops answering mid-response, and
        :class:`CircuitOpen` (request NEVER sent) when this backend's
        breaker is open — idempotent GETs bypass the gate and double as
        organic recovery probes."""
        if self.breaker is not None and method != "GET":
            self.breaker.before_request()
        try:
            return self._forward_raw(method, path, body, content_type,
                                     accept)
        except BackendDown:
            if self.breaker is not None:
                self.breaker.record_failure()
            raise

    def _forward_raw(self, method: str, path: str, body: Optional[bytes],
                     content_type: str, accept: Optional[str]
                     ) -> Tuple[int, bytes]:
        headers = {"Content-Type": content_type}
        if accept:
            headers[protocol.ACCEPT_HEADER] = accept
        for attempt in (0, 1):
            conn = self._fwd_conn(fresh=attempt > 0)
            try:
                conn.request(method, path, body=body, headers=headers)
            except (http.client.HTTPException, OSError) as e:
                if attempt:
                    self.drop_connections()
                    raise BackendDown(
                        f"backend {self.name} unreachable at {self.url}: "
                        f"{e}", sent=False) from e
                continue            # stale keep-alive: one fresh retry
            try:
                resp = conn.getresponse()
                status, data = resp.status, resp.read()
            except (http.client.HTTPException, OSError) as e:
                # response-phase: the instance may have executed the
                # request — no silent re-send, surface the failure
                self.drop_connections()
                raise BackendDown(
                    f"backend {self.name} died mid-response on "
                    f"{method} {path}: {e}", sent=True) from e
            # ANY complete HTTP response — typed service errors included
            # — proves the transport is healthy: only BackendDown above
            # counts against the breaker
            if self.breaker is not None:
                self.breaker.record_success()
            return status, data
        raise AssertionError("unreachable")

    def drop_connections(self) -> None:
        """Drop THIS thread's pooled forwarding connection (other
        threads' pools drop lazily on their next send failure)."""
        conn = getattr(self._tls, "conn", None)
        if conn is not None:
            conn.close()
            self._tls.conn = None

    # -- control plane -------------------------------------------------------

    def _control(self, method: str, path: str, obj: Any = None,
                 timeout: Optional[float] = None) -> Any:
        conn = _make_connection(
            self.host, self.port,
            timeout=self.control_timeout if timeout is None else timeout,
            ssl_context=self.ssl_context)
        try:
            body = None if obj is None else protocol.encode_frame(obj)
            try:
                conn.request(method, path, body=body,
                             headers={"Content-Type":
                                      protocol.CONTENT_TYPE})
                resp = conn.getresponse()
                data = resp.read()
            except (http.client.HTTPException, OSError) as e:
                raise BackendDown(
                    f"backend {self.name} control call {method} {path} "
                    f"failed: {e}") from e
            if resp.status >= 400:
                try:
                    err = json.loads(data.decode("utf-8"))
                except (ValueError, UnicodeDecodeError):
                    raise ServeError(
                        f"backend {self.name}: HTTP {resp.status}: "
                        f"{data[:200]!r}")
                raise protocol.remote_exception(
                    err.get("error", "ServeError"), err.get("message", ""))
            if not data:
                return None
            if data[:4] == protocol.MAGIC:
                return protocol.decode_frame(data)
            return json.loads(data.decode("utf-8"))
        finally:
            conn.close()

    def healthz(self) -> dict:
        return self._control("GET", "/v1/healthz")

    def metrics(self) -> dict:
        return self._control("GET", "/v1/metrics")

    def trace_tail(self, max_spans: int = 256) -> dict:
        return self._control("GET", f"/v1/trace?max={int(max_spans)}")

    def toolboxes(self) -> List[str]:
        return list(self._control("GET", "/v1/toolboxes")["toolboxes"])

    def drain(self, timeout: float = 60.0) -> Dict[str, dict]:
        """Quiesce + snapshot (control call with the DRAIN timeout, not
        the short health one — a loaded instance needs time to flush)."""
        out = self._control("POST", "/v1/admin/drain",
                            {"timeout": float(timeout)},
                            timeout=timeout + self.control_timeout)
        return out["sessions"]

    def restore(self, snapshot: Dict[str, dict],
                timeout: float = 120.0) -> dict:
        """Adopt a snapshot; returns the full ``{"restored", "skipped"}``
        response — the router re-places skipped orphans elsewhere."""
        return self._control("POST", "/v1/admin/restore",
                             {"sessions": snapshot},
                             timeout=timeout)

    def set_redirect(self, url: Optional[str],
                     session: Optional[str] = None) -> None:
        """Record the failover redirect on the instance; with
        ``session`` it applies to that ONE session (the tombstone live
        migration leaves at the source)."""
        body: Dict[str, Any] = {"url": url}
        if session is not None:
            body["session"] = session
        self._control("POST", "/v1/admin/redirect", body)

    def migrate(self, name: str, timeout: float = 30.0) -> dict:
        """Live-migration source call: quiesce + export exactly one
        session; returns its snapshot (drain wire form, toolbox name
        included)."""
        out = self._control("POST", "/v1/admin/migrate",
                            {"name": name, "timeout": float(timeout)},
                            timeout=timeout + self.control_timeout)
        return out["session"]

    def rebucket(self, *, sizes: Optional[List[int]] = None,
                 max_buckets: int = 8,
                 warm: Tuple[str, ...] = ("step",),
                 timeout: float = 60.0) -> dict:
        """Bucket-grid refit on the instance; ``sizes`` installs an
        explicit grid (the autoscaler's predictive pre-warm — a fresh
        instance has no histogram to derive one from)."""
        body: Dict[str, Any] = {"max_buckets": int(max_buckets),
                                "warm": list(warm)}
        if sizes is not None:
            body["sizes"] = [int(r) for r in sizes]
        return self._control("POST", "/v1/admin/rebucket", body,
                             timeout=timeout)

    def profile(self) -> dict:
        """The instance's per-program device-phase profile table
        (roofline ``phase_split`` signals ride here)."""
        return self._control("GET", "/v1/profile")

    def cache_export(self, since: int, limit: int = 256) -> dict:
        """Pull the instance's fitness-cache journal after cursor
        ``since`` (portable namespaces); ``{"entries", "seq"}``."""
        return self._control("POST", "/v1/admin/cache/export",
                             {"since": int(since), "limit": int(limit)})

    def cache_import(self, entries: List[dict]) -> int:
        """Push exported entries into the instance's fabric table;
        returns rows admitted."""
        out = self._control("POST", "/v1/admin/cache/import",
                            {"entries": list(entries)})
        return int(out["admitted"])
