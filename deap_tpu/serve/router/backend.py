""":class:`Backend` — the router's handle on one
:class:`~deap_tpu.serve.net.server.NetServer` instance.

Two traffic classes, deliberately separated:

* **forwarding** (:meth:`forward`) — raw DTF1 frames relayed
  byte-for-byte (payloads untouched, so compression negotiated between
  client and instance survives the hop).  Each router handler thread
  keeps its own keep-alive connection to the backend (thread-local
  pool), mirroring the stdlib frontend's one-handler-per-connection
  model; a send-phase failure retries once on a fresh connection (the
  request never hit the wire), a response-phase failure propagates — the
  instance may have executed a non-idempotent step;
* **control** (:meth:`healthz` / :meth:`metrics` / :meth:`trace_tail` /
  :meth:`drain` / :meth:`restore` / :meth:`set_redirect` /
  :meth:`toolboxes`) — per-call connections with their own (short)
  timeout so a wedged instance can never stall the health loop or a
  failover behind a long forward.
"""

from __future__ import annotations

import http.client
import json
import threading
from typing import Any, Dict, List, Optional, Tuple

from ..dispatcher import ServeError
from ..net import protocol
from ..net.client import _parse_address

__all__ = ["Backend", "BackendDown"]


class BackendDown(ServeError):
    """The backend did not answer (connect/send/read failure) — the
    transport-level 'sick' signal, distinct from a typed service error
    the instance itself raised.  ``sent`` records whether the request
    reached the wire: ``False`` means the instance provably never saw it
    (a re-send cannot double-execute anything), ``True`` means it died
    mid-response and MAY have executed — the router never retries
    those."""

    def __init__(self, message: str, *, sent: bool = False):
        super().__init__(message)
        self.sent = bool(sent)


class Backend:
    """One routable serving instance (see module docstring)."""

    def __init__(self, name: str, address, *, timeout: float = 600.0,
                 control_timeout: float = 10.0):
        self.name = str(name)
        self.host, self.port = _parse_address(address)
        self.timeout = float(timeout)
        self.control_timeout = float(control_timeout)
        self._tls = threading.local()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def __repr__(self) -> str:
        return f"Backend({self.name!r}, {self.url})"

    # -- forwarding ----------------------------------------------------------

    def _fwd_conn(self, fresh: bool = False) -> http.client.HTTPConnection:
        conn = getattr(self._tls, "conn", None)
        if fresh and conn is not None:
            conn.close()
            conn = None
        if conn is None:
            conn = http.client.HTTPConnection(self.host, self.port,
                                              timeout=self.timeout)
            self._tls.conn = conn
        return conn

    def forward(self, method: str, path: str, body: Optional[bytes],
                content_type: str = protocol.CONTENT_TYPE,
                accept: Optional[str] = None) -> Tuple[int, bytes]:
        """Relay one raw request; returns ``(status, response bytes)``.
        ``accept`` relays the client's ``X-DTF-Accept`` compression
        advertisement (the only negotiation channel a bodyless GET has).
        Raises :class:`BackendDown` when the instance cannot be reached
        (send retried once on a fresh connection — safe, the request
        never arrived) or stops answering mid-response."""
        headers = {"Content-Type": content_type}
        if accept:
            headers[protocol.ACCEPT_HEADER] = accept
        for attempt in (0, 1):
            conn = self._fwd_conn(fresh=attempt > 0)
            try:
                conn.request(method, path, body=body, headers=headers)
            except (http.client.HTTPException, OSError) as e:
                if attempt:
                    self.drop_connections()
                    raise BackendDown(
                        f"backend {self.name} unreachable at {self.url}: "
                        f"{e}", sent=False) from e
                continue            # stale keep-alive: one fresh retry
            try:
                resp = conn.getresponse()
                return resp.status, resp.read()
            except (http.client.HTTPException, OSError) as e:
                # response-phase: the instance may have executed the
                # request — no silent re-send, surface the failure
                self.drop_connections()
                raise BackendDown(
                    f"backend {self.name} died mid-response on "
                    f"{method} {path}: {e}", sent=True) from e
        raise AssertionError("unreachable")

    def drop_connections(self) -> None:
        """Drop THIS thread's pooled forwarding connection (other
        threads' pools drop lazily on their next send failure)."""
        conn = getattr(self._tls, "conn", None)
        if conn is not None:
            conn.close()
            self._tls.conn = None

    # -- control plane -------------------------------------------------------

    def _control(self, method: str, path: str, obj: Any = None,
                 timeout: Optional[float] = None) -> Any:
        conn = http.client.HTTPConnection(
            self.host, self.port,
            timeout=self.control_timeout if timeout is None else timeout)
        try:
            body = None if obj is None else protocol.encode_frame(obj)
            try:
                conn.request(method, path, body=body,
                             headers={"Content-Type":
                                      protocol.CONTENT_TYPE})
                resp = conn.getresponse()
                data = resp.read()
            except (http.client.HTTPException, OSError) as e:
                raise BackendDown(
                    f"backend {self.name} control call {method} {path} "
                    f"failed: {e}") from e
            if resp.status >= 400:
                try:
                    err = json.loads(data.decode("utf-8"))
                except (ValueError, UnicodeDecodeError):
                    raise ServeError(
                        f"backend {self.name}: HTTP {resp.status}: "
                        f"{data[:200]!r}")
                raise protocol.remote_exception(
                    err.get("error", "ServeError"), err.get("message", ""))
            if not data:
                return None
            if data[:4] == protocol.MAGIC:
                return protocol.decode_frame(data)
            return json.loads(data.decode("utf-8"))
        finally:
            conn.close()

    def healthz(self) -> dict:
        return self._control("GET", "/v1/healthz")

    def metrics(self) -> dict:
        return self._control("GET", "/v1/metrics")

    def trace_tail(self, max_spans: int = 256) -> dict:
        return self._control("GET", f"/v1/trace?max={int(max_spans)}")

    def toolboxes(self) -> List[str]:
        return list(self._control("GET", "/v1/toolboxes")["toolboxes"])

    def drain(self, timeout: float = 60.0) -> Dict[str, dict]:
        """Quiesce + snapshot (control call with the DRAIN timeout, not
        the short health one — a loaded instance needs time to flush)."""
        out = self._control("POST", "/v1/admin/drain",
                            {"timeout": float(timeout)},
                            timeout=timeout + self.control_timeout)
        return out["sessions"]

    def restore(self, snapshot: Dict[str, dict],
                timeout: float = 120.0) -> dict:
        """Adopt a snapshot; returns the full ``{"restored", "skipped"}``
        response — the router re-places skipped orphans elsewhere."""
        return self._control("POST", "/v1/admin/restore",
                             {"sessions": snapshot},
                             timeout=timeout)

    def set_redirect(self, url: Optional[str]) -> None:
        self._control("POST", "/v1/admin/redirect", {"url": url})
