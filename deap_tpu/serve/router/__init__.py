"""Fleet control plane: the ``deap-tpu-router`` tier above N serving
instances.

One :class:`~deap_tpu.serve.net.server.NetServer` is a single point of
capacity AND of failure; the reference framework's distribution story —
swapping ``toolbox.map`` for a SCOOP pool (doc/tutorials/basic/part4.rst)
— never grows past one pool of workers.  This package is the layer the
ROADMAP's "millions of users" goal needs, built purely by *composing*
primitives the fleet already wire-exposes (drain/restore, ``/v1/metrics``,
``/v1/trace``, tenant counters):

* :mod:`~deap_tpu.serve.router.backend` — :class:`Backend`, the raw-frame
  forwarding + control-plane handle on one instance;
* :mod:`~deap_tpu.serve.router.placement` — bucket-histogram-aware
  placement (:class:`PlacementPolicy`): sibling shapes co-locate on
  instances with warm compiled programs;
* :mod:`~deap_tpu.serve.router.health` — :class:`HealthMonitor`: polls
  ``/v1/metrics``, joins ``/v1/trace`` spans, latches sick instances and
  fires automatic drain→restore failover;
* :mod:`~deap_tpu.serve.router.tenants` — quota enforcement + weighted-
  fair forwarding (:class:`WeightedFairScheduler`), the typed
  :class:`TenantQuotaExceeded` admission decision;
* :mod:`~deap_tpu.serve.router.core` — :class:`FleetRouter`, the routing
  table and failover driver;
* :mod:`~deap_tpu.serve.router.server` — :class:`RouterServer`, the DTF1
  HTTP frontend clients reach through an unchanged
  :class:`~deap_tpu.serve.net.client.RemoteService`.

``tools/bench_fleet.py`` is the scale proof (10³+ remote sessions across
≥3 instances, committed as ``BENCH_FLEET.json``); the in-gate drill lives
in ``tests/test_serve_router.py``.
"""

from .backend import Backend, BackendDown  # noqa: F401
from .core import FleetRouter  # noqa: F401
from .health import HealthMonitor, HealthPolicy, HealthSample  # noqa: F401
from .placement import (BackendPlan, PlacementPolicy,  # noqa: F401
                        fleet_sizes)
from .server import RouterServer  # noqa: F401
from .tenants import (TenantQuota, TenantQuotaExceeded,  # noqa: F401
                      WeightedFairScheduler)

__all__ = [
    "Backend", "BackendDown",
    "FleetRouter", "RouterServer",
    "HealthMonitor", "HealthPolicy", "HealthSample",
    "BackendPlan", "PlacementPolicy", "fleet_sizes",
    "TenantQuota", "TenantQuotaExceeded", "WeightedFairScheduler",
]
