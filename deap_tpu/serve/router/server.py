""":class:`RouterServer` — the fleet's DTF1 HTTP frontend.

Clients speak to the router exactly as they speak to one
:class:`~deap_tpu.serve.net.server.NetServer` — the same paths, the same
frame codec, the same typed error envelopes — so the existing
:class:`~deap_tpu.serve.net.client.RemoteService` works unchanged with a
router URL.  Per request the router:

1. **admits** — session creates pass tenant quotas and affinity
   placement (:meth:`FleetRouter.admit_session`); session-mutating ops
   take a weighted-fair forwarding slot first, so one tenant's burst
   cannot monopolize the fleet's dispatch parallelism;
2. **traces** — the client's ``__trace__`` header is adopted and
   REWRITTEN to the router's own hop
   (:func:`~deap_tpu.serve.net.protocol.rewrite_trace` — header-only,
   tensor payloads untouched), so the backend's span tree hangs off a
   ``router.forward`` span that hangs off the client hop;
3. **forwards** — raw frames relayed to the routed backend over pooled
   keep-alive connections.  Compression negotiated end-to-end survives
   the hop because payload bytes are never touched;
4. **retries safely** — a forward the backend never received
   (:class:`~deap_tpu.serve.router.backend.BackendDown` with
   ``sent=False``) or typed-rejected (``ServiceDraining``) re-routes
   after waiting for the failover to move the session, then retries —
   both cases provably never executed.  A mid-response death is NOT
   retried (the step may have applied); the client resyncs, exactly as
   it would against a bare instance.

Router-only surface (on top of the NetServer paths)::

    GET  /v1/admin/fleet            topology: backends, health, routes
                                    (?format=prometheus: ONE exposition
                                    covering the router plus every live
                                    backend's metrics, each sample
                                    labelled instance="..." — one scrape
                                    covers the fleet)
    POST /v1/admin/fleet/failover   {"backend": name} — manual drill
"""

from __future__ import annotations

import json
import threading
from typing import Optional, Sequence, Tuple
from urllib.parse import parse_qs, quote, unquote, urlparse

from ...observability.sinks import emit_text
from ...observability.sinks import MetricRecord
from ..dispatcher import (CircuitOpen, DeadlineExceeded, ServeError,
                          ServiceOverloaded, SessionUnknown,
                          TenantQuotaExceeded)
from ..metrics import prometheus_fleet_text, prometheus_text
from ..net import protocol
from ..net.httpcommon import FleetHTTPServer, FrameHTTPHandler
from .backend import Backend, BackendDown
from .core import FleetRouter

__all__ = ["RouterServer"]

#: session-op names whose forwards take a weighted-fair slot
_FAIR_OPS = ("step", "ask", "tell", "evaluate")


class RouterServer:
    """Serve a :class:`FleetRouter` over HTTP (see module docstring).

    ``failover_wait`` bounds how long a safely-retryable forward waits
    for the routing table to move its session before giving up;
    ``acquire_timeout`` bounds the weighted-fair slot wait (a saturated
    fleet then sheds typed :class:`ServiceOverloaded`, mirroring the
    instance-level queue bound)."""

    def __init__(self, router: FleetRouter, *, host: str = "127.0.0.1",
                 port: int = 0, failover_wait: float = 30.0,
                 acquire_timeout: float = 60.0, sinks: Sequence = (),
                 verbose: bool = False, ssl_context=None):
        self.router = router
        self.failover_wait = float(failover_wait)
        self.acquire_timeout = float(acquire_timeout)
        self.sinks = list(sinks) or list(router.sinks)
        self.verbose = bool(verbose)
        ctx = self

        class Handler(_RouterHandler):
            server_ctx = ctx

        self._httpd = FleetHTTPServer((host, port), Handler)
        # TLS termination: same shape as NetServer — wrap the listening
        # socket once; every accepted connection then handshakes before
        # the HTTP layer sees a byte
        self._ssl_context = ssl_context
        if ssl_context is not None:
            self._httpd.socket = ssl_context.wrap_socket(
                self._httpd.socket, server_side=True)
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "RouterServer":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever,
                name="deap-tpu-router-http", daemon=True)
            self._thread.start()
            if self.verbose:
                emit_text(f"[router] listening on {self.url} fronting "
                          f"{sorted(self.router.backends)}", self.sinks)
        return self

    def close(self) -> None:
        if self._thread is not None:
            self._httpd.shutdown()
            self._thread.join(timeout=10.0)
            self._thread = None
        self._httpd.server_close()
        self.router.close()

    def __enter__(self) -> "RouterServer":
        return self.start()

    def __exit__(self, *exc):
        self.close()
        return False

    @property
    def address(self) -> tuple:
        return self._httpd.server_address[:2]

    @property
    def url(self) -> str:
        host, port = self.address
        scheme = "https" if self._ssl_context is not None else "http"
        return f"{scheme}://{host}:{port}"


class _RouterHandler(FrameHTTPHandler):
    """One connection's requests, routed into the :class:`RouterServer`
    context.  The keep-alive wire plumbing — body read, byte counters,
    error envelopes, unread-body drain — is the shared
    :class:`~deap_tpu.serve.net.httpcommon.FrameHTTPHandler` base, the
    same copy the instance handler uses."""

    server_ctx: RouterServer = None     # bound by RouterServer
    log_prefix = "router"

    # -- plumbing ------------------------------------------------------------

    def _handler_metrics(self):
        ctx = self.server_ctx
        return ctx.router.metrics if ctx is not None else None

    def _log_conf(self):
        ctx = self.server_ctx
        if ctx is None:
            return False, ()
        return ctx.verbose, ctx.sinks

    def _send_error_obj(self, exc: BaseException) -> None:
        self.server_ctx.router.metrics.inc("router_errors")
        if isinstance(exc, TenantQuotaExceeded):
            # both rejection shapes — session quota at create, backlog
            # quota at the fair scheduler — count as admission decisions
            self.server_ctx.router.metrics.inc("router_quota_rejections")
        self._send_error_envelope(exc)

    def _respond_raw(self, status: int, data: bytes) -> None:
        """Relay a backend's response bytes (frame or error envelope —
        the client's decoder handles both).  Error envelopes are
        sanitized first: a backend's failover ``location`` must never
        reach a router client, or its redirect-following would re-point
        it AT the backend and bypass quotas/scheduling for good."""
        if status >= 400 and data[:4] != protocol.MAGIC:
            data = _strip_redirect(data)
        ctype = (protocol.CONTENT_TYPE if data[:4] == protocol.MAGIC
                 else "application/json")
        self._send(data, status=status, content_type=ctype)

    # -- routing -------------------------------------------------------------

    def _route(self, method: str) -> None:
        ctx = self.server_ctx
        router = ctx.router
        router.metrics.inc("router_requests")
        self._body_consumed = False
        url = urlparse(self.path)
        parts = [p for p in url.path.split("/") if p]
        try:
            if parts[:1] != ["v1"]:
                raise SessionUnknown(f"unknown path {url.path!r}")
            rest = parts[1:]
            if method == "GET" and rest == ["healthz"]:
                return self._healthz()
            if method == "GET" and rest == ["toolboxes"]:
                return self._send_json(
                    {"toolboxes": router.toolbox_union()})
            if method == "GET" and rest == ["metrics"]:
                return self._metrics(parse_qs(url.query))
            if method == "GET" and rest == ["trace"]:
                return self._trace_tail(parse_qs(url.query))
            if method == "GET" and rest == ["admin", "fleet"]:
                query = parse_qs(url.query)
                if query.get("format", [""])[0] == "prometheus":
                    return self._fleet_prometheus()
                return self._send_json(router.topology())
            if (method == "POST" and rest == ["admin", "fleet",
                                              "failover"]):
                return self._manual_failover()
            if rest[:1] == ["sessions"]:
                if method == "POST" and len(rest) == 1:
                    return self._create()
                if len(rest) == 2 and method in ("GET", "DELETE"):
                    return self._session_op(method, unquote(rest[1]), None)
                if method == "POST" and len(rest) == 3 \
                        and rest[2] in _FAIR_OPS:
                    return self._session_op(method, unquote(rest[1]),
                                            rest[2])
            raise SessionUnknown(f"unknown path {url.path!r}")
        except BrokenPipeError:
            raise
        except Exception as e:  # noqa: BLE001 — typed over the wire
            try:
                self._send_error_obj(e)
            except BrokenPipeError:
                pass

    # -- router-local endpoints ----------------------------------------------

    def _healthz(self) -> None:
        router = self.server_ctx.router
        sick = router.health.sick()
        self._send_json({
            "status": "ok" if len(sick) < len(router.backends) else "sick",
            "role": "router",
            "backends": {n: ("sick" if n in sick else "ok")
                         for n in router.backends},
            "sessions": router.stats().gauges["router_sessions_routed"]})

    def _metrics(self, query) -> None:
        rec = self.server_ctx.router.stats()
        if query.get("format", [""])[0] == "prometheus":
            return self._send(
                prometheus_text(rec).encode("utf-8"),
                content_type="text/plain; version=0.0.4; charset=utf-8")
        self._send_json(json.loads(rec.to_json()))

    def _fleet_prometheus(self) -> None:
        """``GET /v1/admin/fleet?format=prometheus`` — the whole fleet
        in one exposition: the router's own record plus every reachable
        backend's ``/v1/metrics`` snapshot, merged so each metric family
        is declared once and every sample carries an ``instance`` label.
        Unreachable/down backends degrade to a comment line (the scrape
        must not fail because one instance is mid-failover)."""
        router = self.server_ctx.router
        records = {"router": router.stats()}
        down: list = []
        sick = router.health.sick()
        live = [n for n in sorted(router.backends) if n not in sick]
        down += [f"# backend {n} sick: excluded"
                 for n in sorted(router.backends) if n in sick]
        # fetch the backends CONCURRENTLY: one wedged-but-not-yet-sick
        # instance must cost the scrape its own control timeout once,
        # not once per position in a sequential walk — a fleet scrape
        # that overruns Prometheus's scrape_timeout drops every
        # instance's samples, not just the slow one's
        results: dict = {}

        def fetch(name: str) -> None:
            try:
                results[name] = router.backends[name].metrics()
            except (BackendDown, ServeError, OSError, ValueError) as e:
                # ValueError covers a malformed/truncated body from an
                # instance mid-restart (Backend._control's json.loads;
                # UnicodeDecodeError is its subclass) — the scrape must
                # degrade that instance to a comment, not kill the thread
                results[name] = e
        threads = [threading.Thread(target=fetch, args=(n,),
                                    name=f"deap-tpu-router-scrape-{n}",
                                    daemon=True) for n in live]
        for t in threads:
            t.start()
        for t in threads:
            t.join()        # bounded by each backend's control timeout
        for name in live:
            rec = results.get(name)
            if rec is None or isinstance(rec, Exception):
                down.append(f"# backend {name} unreachable: "
                            f"{type(rec).__name__ if rec else 'missing'}")
                continue
            records[name] = MetricRecord(
                gen=int(rec.get("gen", 0)),
                counters=rec.get("counters", {}),
                gauges=rec.get("gauges", {}),
                meta=rec.get("meta", {}) or {})
        text = prometheus_fleet_text(records)
        if down:
            text += "\n".join(down) + "\n"
        self._send(text.encode("utf-8"),
                   content_type="text/plain; version=0.0.4; charset=utf-8")

    def _trace_tail(self, query) -> None:
        tracer = self.server_ctx.router.tracer
        n = int(query.get("max", ["256"])[0])
        trace_id = query.get("trace_id", [None])[0]
        self._send_json({"enabled": bool(tracer.enabled),
                         "dropped": tracer.dropped,
                         "spans": tracer.recent(n, trace_id=trace_id)})

    def _manual_failover(self) -> None:
        router = self.server_ctx.router
        raw = self._read_raw_body()
        if raw[:4] == protocol.MAGIC:
            body = protocol.decode_frame(raw)
        else:
            body = json.loads(raw.decode("utf-8")) if raw else {}
        name = body.get("backend")
        backend = router.backends.get(name)
        if backend is None:
            raise SessionUnknown(f"no backend named {name!r}")
        router.health.force_sick(name, "manual failover")
        self._send_json(
            {"backend": name, "sick": router.health.is_sick(name)})

    # -- the create path (decode once: placement needs the shape) ------------

    def _create(self) -> None:
        ctx = self.server_ctx
        router = ctx.router
        raw = self._read_raw_body()
        if raw[:4] != protocol.MAGIC:
            raise ValueError("session create requires a DTF1 frame body")
        body, meta = protocol.decode_frame_with_meta(raw)
        trace_ctx = router.tracer.adopt(meta["trace"])
        backend, tenant, name, n, sig = router.admit_session(body)
        body["name"] = name
        # re-encode (the one path the router must decode, for placement)
        # with the sender's own codec — the initial population is the
        # protocol's largest payload, and decoding must not strip its
        # compression for the router→backend leg
        frame = protocol.encode_frame(
            body, trace=None if trace_ctx is None else trace_ctx.wire(),
            accept=meta["accept"], compress=meta["compressed"])
        t0 = router.tracer.clock()
        try:
            status, data = backend.forward(
                "POST", "/v1/sessions", frame,
                accept=self.headers.get(protocol.ACCEPT_HEADER))
        except CircuitOpen:
            # the breaker refused pre-send (placement raced its opening)
            # — the backend never saw the create: release the admission
            # and surface the typed 503
            router.abort_session(name, tenant)
            raise
        except BackendDown as e:
            router.abort_session(name, tenant)
            router.note_forward_failure(backend, e)
            raise ServeError(f"create failed: {e}") from e
        router.metrics.inc("router_forwards")
        if status >= 400:
            router.abort_session(name, tenant)
        else:
            router.commit_session(name, backend, n, sig, tenant)
        if trace_ctx is not None:
            router.tracer.record(
                "router.forward POST /v1/sessions", trace_ctx, t0,
                router.tracer.clock(),
                attrs={"backend": backend.name, "status": status,
                       "session": name, "tenant": tenant})
        self._respond_raw(status, data)

    # -- forwarded session ops -----------------------------------------------

    def _session_op(self, method: str, name: str,
                    op: Optional[str]) -> None:
        ctx = self.server_ctx
        router = ctx.router
        t_in = router.tracer.clock()
        raw = self._read_raw_body() if method == "POST" else b""
        tenant = router.tenant_of(name)
        quoted = quote(name, safe="")
        path = (f"/v1/sessions/{quoted}/{op}" if op
                else f"/v1/sessions/{quoted}")
        # router hop in the span tree + deadline budget: adopt the
        # client context and remaining-deadline from the frame header,
        # swap in this hop's identity and the DECREMENTED budget — one
        # header rewrite, payloads untouched
        trace_ctx = None
        budget = None
        is_frame = raw[:4] == protocol.MAGIC
        if is_frame:
            _hdr, _off = protocol._split_header(raw)
            trace_ctx = router.tracer.adopt(_hdr.get("__trace__"))
            d = _hdr.get("__deadline__")
            if isinstance(d, (int, float)) and not isinstance(d, bool):
                budget = float(d)
        fair = op in _FAIR_OPS
        if fair:
            try:
                router.scheduler.acquire(tenant,
                                         timeout=ctx.acquire_timeout)
            except TimeoutError as e:
                raise ServiceOverloaded(
                    f"router forwarding saturated: {e}") from e
        t0 = router.tracer.clock()
        body = raw
        try:
            if is_frame and (trace_ctx is not None or budget is not None):
                kw = {}
                if trace_ctx is not None:
                    kw["trace"] = trace_ctx.wire()
                if budget is not None:
                    # everything the router spent on this request — body
                    # read, header parse, the fair-scheduler slot wait —
                    # comes out of the client's remaining budget
                    remaining = budget - (t0 - t_in)
                    if remaining <= 0.0:
                        router.metrics.inc("router_deadline_shed")
                        raise DeadlineExceeded(
                            f"deadline budget spent at the router hop "
                            f"({-remaining:.3f}s over, {budget:.3f}s "
                            "arrived); not forwarded")
                    kw["deadline"] = remaining
                body = protocol.rewrite_header(raw, **kw)
            status, data, backend = self._forward_routed(
                method, name, path, body,
                accept=self.headers.get(protocol.ACCEPT_HEADER))
        finally:
            if fair:
                router.scheduler.release(tenant)
        if method == "DELETE" and status < 400:
            router.forget_session(name)
        if trace_ctx is not None:
            router.tracer.record(
                f"router.forward {method} {path}", trace_ctx, t0,
                router.tracer.clock(),
                attrs={"backend": backend.name, "status": status,
                       "session": name, "tenant": tenant})
        self._respond_raw(status, data)

    def _forward_routed(self, method: str, name: str, path: str,
                        body: bytes, accept: Optional[str] = None
                        ) -> Tuple[int, bytes, Backend]:
        """Forward to the session's routed backend; re-route and retry
        ONLY failures that provably never executed (unreachable before
        send, or typed ServiceDraining rejections) — a failover in
        flight moves the session, and the retry lands on its new home."""
        ctx = self.server_ctx
        router = ctx.router
        last_exc: Optional[Exception] = None
        for attempt in range(3):
            backend = router.route_of(name)     # SessionUnknown when lost
            if attempt:
                router.metrics.inc("router_forward_retries")
            try:
                status, data = backend.forward(method, path, body or None,
                                               accept=accept)
            except CircuitOpen:
                # refused pre-send (provably unexecuted) — but the
                # session is still ROUTED here (breaker-open means
                # degraded, not failed over), so waiting for a re-route
                # would only time out: surface the typed 503 and let the
                # client back off until a probe closes the circuit
                raise
            except BackendDown as e:
                router.note_forward_failure(backend, e)
                if e.sent:
                    # the instance may have executed this op — never
                    # silently re-send a step/tell
                    raise ServeError(
                        f"backend {backend.name} died mid-request; resync "
                        f"the session state ({e})") from e
                last_exc = e
                router.wait_rerouted(name, backend.name,
                                     timeout=ctx.failover_wait)
                continue
            router.metrics.inc("router_forwards")
            if status < 400:
                return status, data, backend
            err = _envelope_error(data)
            retryable = err == "ServiceDraining"
            if err == "SessionUnknown":
                # a live migration's export can beat its route-table
                # commit: the source already exported (and forgot) the
                # session while the routing table still points there.
                # Provably unexecuted — wait for the commit, retry on
                # the new home.  A session the router itself no longer
                # routes is a genuine 404 and surfaces as-is.
                try:
                    retryable = router.route_of(name) is backend
                except SessionUnknown:
                    retryable = False
            if not retryable:
                return status, data, backend
            # typed rejection (draining / mid-migration): the op never
            # executed; wait for the re-route to commit, then retry
            last_exc = None
            if not router.wait_rerouted(name, backend.name,
                                        timeout=ctx.failover_wait):
                return status, data, backend
        if last_exc is not None:
            raise ServeError(
                f"session {name!r} unreachable after retries: "
                f"{last_exc}") from last_exc
        return status, data, backend

def _strip_redirect(data: bytes) -> bytes:
    """Drop ``location`` from a relayed JSON error envelope; anything
    unparsable is returned untouched."""
    try:
        doc = json.loads(data.decode("utf-8"))
    except (ValueError, UnicodeDecodeError):
        return data
    if not isinstance(doc, dict) or "location" not in doc:
        return data
    doc.pop("location")
    return json.dumps(doc).encode("utf-8")


def _envelope_error(data: bytes) -> Optional[str]:
    """The typed error class name from a JSON error envelope, or None
    for frames / unparsable bodies."""
    if data[:4] == protocol.MAGIC or not data:
        return None
    try:
        doc = json.loads(data.decode("utf-8"))
    except (ValueError, UnicodeDecodeError):
        return None
    err = doc.get("error")
    return err if isinstance(err, str) else None
