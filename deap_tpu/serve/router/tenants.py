"""Tenant *enforcement* at the fleet router: quotas and weighted-fair
scheduling.

PR 9 gave the fleet per-tenant SLO **attribution** (labelled counters in
``/v1/metrics``); this module turns attribution into **admission
decisions** at the layer above one instance:

* :class:`TenantQuota` — the per-tenant contract: how many live sessions
  a tenant may hold (``max_sessions``), how deep its queued-request
  backlog may grow (``max_pending``), and its ``weight`` in the fair
  scheduler.  A violated quota raises the typed
  :class:`~deap_tpu.serve.dispatcher.TenantQuotaExceeded`, which travels
  the wire as HTTP 429 and rebuilds typed client-side — an over-quota
  tenant gets an actionable error, not mystery latency;
* :class:`WeightedFairScheduler` — start-time fair queueing (virtual
  time) over the router's forwarding concurrency: each admitted request
  is stamped with a virtual finish tag ``max(V, last[tenant]) +
  cost/weight`` and grants go to the smallest tag whenever an in-flight
  slot frees.  Two saturating tenants with weights 1:3 therefore see
  their throughputs converge to 1:3 regardless of arrival order, and a
  quiet tenant's first request never waits behind a burst from a noisy
  one (its tag starts at the CURRENT virtual time, not the burst's
  backlog).

Everything is Condition-based waiting (the ``no-blocking-sleep`` pass
covers this package) and lock-disciplined via ``_GUARDED_BY``.
"""

from __future__ import annotations

import dataclasses
import heapq
import itertools
from typing import Dict, Optional

from ... import sanitize
from ..dispatcher import ServiceClosed, TenantQuotaExceeded

__all__ = ["TenantQuota", "WeightedFairScheduler", "TenantQuotaExceeded"]


@dataclasses.dataclass(frozen=True)
class TenantQuota:
    """One tenant's admission contract.  ``None`` limits are unlimited;
    ``weight`` must be positive (it divides the virtual-time cost).
    ``priority`` is the tenant's load-shedding class (higher = more
    important, default 1): the router stamps it onto every session the
    tenant opens, and under sustained queue pressure the instance
    dispatcher sheds lower-priority admissions first with typed
    :class:`~deap_tpu.serve.dispatcher.ServiceBrownout` — distinct from
    ``weight``, which divides *throughput* under fairness but never
    refuses work."""

    max_sessions: Optional[int] = None
    max_pending: Optional[int] = None
    weight: float = 1.0
    priority: int = 1

    def __post_init__(self):
        if not self.weight > 0:
            raise ValueError("TenantQuota.weight must be > 0")
        if int(self.priority) != self.priority or self.priority < 0:
            raise ValueError("TenantQuota.priority must be a "
                             "non-negative integer")


class WeightedFairScheduler:
    """Weighted-fair admission over a bounded forwarding concurrency.

    ``max_inflight`` bounds how many session-mutating requests the
    router forwards concurrently (the fleet's total dispatch
    parallelism); ``quotas`` maps tenant name → :class:`TenantQuota`,
    with ``default`` covering everyone unlisted.  Unnamed tenants
    (``tenant=None``) share one anonymous row.

    The scheduler is deliberately host-only bookkeeping: ``acquire``
    blocks (Condition wait) until the request's virtual-finish tag is
    the smallest among waiters and a slot is free, ``release`` frees the
    slot.  Session-count quota checks (:meth:`session_opened`) sit on
    the create path, backlog quotas on every queued acquire.
    """

    #: lock-guarded shared state (``lock-discipline`` lint): the virtual
    #: clock, per-tenant tags/counters and the waiter heap are written
    #: by every router handler thread — writes only under ``self._cv``
    _GUARDED_BY = {"_cv": ("_virtual", "_last_tag", "_pending", "_sessions",
                           "_waiting", "_inflight", "_granted", "_closed")}

    _ANON = "<anonymous>"

    def __init__(self, *, max_inflight: int = 8,
                 quotas: Optional[Dict[str, TenantQuota]] = None,
                 default: TenantQuota = TenantQuota()):
        if max_inflight < 1:
            raise ValueError("max_inflight must be >= 1")
        self.max_inflight = int(max_inflight)
        self.quotas = dict(quotas or {})
        self.default = default
        self._cv = sanitize.condition()
        self._virtual = 0.0                      # fair-queueing clock
        self._last_tag: Dict[str, float] = {}    # tenant -> last finish tag
        self._pending: Dict[str, int] = {}       # tenant -> queued acquires
        self._sessions: Dict[str, int] = {}      # tenant -> live sessions
        self._waiting: list = []                 # heap of (tag, seq, tenant)
        self._granted: Dict[int, float] = {}     # seq -> tag (grant latch)
        self._inflight = 0
        self._seq = itertools.count()
        self._closed = False

    def quota_of(self, tenant: Optional[str]) -> TenantQuota:
        return self.quotas.get(tenant or self._ANON, self.default)

    # -- session-count quota (create path) -----------------------------------

    def session_opened(self, tenant: Optional[str]) -> None:
        """Admit one more live session for ``tenant`` or raise the typed
        quota error.  Call :meth:`session_closed` exactly once per
        successful admission."""
        t = tenant or self._ANON
        q = self.quota_of(tenant)
        with self._cv:
            held = self._sessions.get(t, 0)
            if q.max_sessions is not None and held >= q.max_sessions:
                raise TenantQuotaExceeded(
                    f"tenant {t!r} holds {held} live sessions "
                    f"(max_sessions={q.max_sessions}); close one or raise "
                    "the quota")
            self._sessions[t] = held + 1

    def session_closed(self, tenant: Optional[str]) -> None:
        t = tenant or self._ANON
        with self._cv:
            left = self._sessions.get(t, 0) - 1
            if left > 0:
                self._sessions[t] = left
            else:
                self._sessions.pop(t, None)

    def sessions_of(self, tenant: Optional[str]) -> int:
        with self._cv:
            return self._sessions.get(tenant or self._ANON, 0)

    # -- weighted-fair request admission -------------------------------------

    def acquire(self, tenant: Optional[str],
                timeout: Optional[float] = None, cost: float = 1.0) -> None:
        """Block until this request is granted a forwarding slot under
        weighted fairness.  Raises :class:`TenantQuotaExceeded` when the
        tenant's queued backlog is at ``max_pending`` (the admission
        decision — shed at the edge, typed), ``TimeoutError`` when no
        slot frees within ``timeout``."""
        t = tenant or self._ANON
        q = self.quota_of(tenant)
        with self._cv:
            if self._closed:
                raise ServiceClosed("router scheduler is closed")
            backlog = self._pending.get(t, 0)
            if q.max_pending is not None and backlog >= q.max_pending:
                raise TenantQuotaExceeded(
                    f"tenant {t!r} has {backlog} requests queued "
                    f"(max_pending={q.max_pending}); slow down or raise "
                    "the quota")
            # start-time fair queueing: the tag advances the tenant's own
            # finish time but never starts before the global clock, so a
            # returning tenant competes from NOW, with no banked credit
            tag = max(self._virtual, self._last_tag.get(t, 0.0)) \
                + float(cost) / q.weight
            self._last_tag[t] = tag
            seq = next(self._seq)
            self._pending[t] = backlog + 1
            heapq.heappush(self._waiting, (tag, seq, t))
            self._grant_next_locked()       # a free slot grants NOW
            ok = self._cv.wait_for(
                lambda: self._closed or seq in self._granted,
                timeout=timeout)
            if self._closed or not ok:
                # back out — and re-run the grant loop: this waiter may
                # hold a latched slot that must pass to the next tag, or
                # the other waiters stall until an unrelated release
                self._drop_waiter_locked(seq, t)
                self._grant_next_locked()
                if self._closed:
                    raise ServiceClosed("router scheduler is closed")
                raise TimeoutError(
                    f"no forwarding slot within {timeout}s "
                    f"(inflight={self._inflight}/{self.max_inflight})")
            self._virtual = max(self._virtual, self._granted.pop(seq))
            self._drop_waiter_locked(seq, t, in_heap=False)
            self._inflight += 1
            self._grant_next_locked()

    def set_max_inflight(self, n: int) -> None:
        """Resize the forwarding concurrency live (an operator knob —
        e.g. tightened during an incident); waiters re-grant against the
        new bound immediately."""
        if n < 1:
            raise ValueError("max_inflight must be >= 1")
        with self._cv:
            self.max_inflight = int(n)
            self._grant_next_locked()

    def release(self, tenant: Optional[str]) -> None:
        """Free the slot :meth:`acquire` granted."""
        del tenant  # slot accounting is global; tenant kept for symmetry
        with self._cv:
            self._inflight = max(0, self._inflight - 1)
            self._grant_next_locked()
            self._cv.notify_all()

    def _drop_waiter_locked(self, seq: int, tenant: str, *,
                            in_heap: bool = True) -> None:
        """Remove a waiter's bookkeeping (granted, timed out, or
        failed).  ``in_heap=False`` is the granted fast path: the grant
        loop already heappopped the entry, so scanning the heap for it
        would rebuild O(n) waiters on EVERY successful acquire."""
        left = self._pending.get(tenant, 0) - 1
        if left > 0:
            self._pending[tenant] = left
        else:
            self._pending.pop(tenant, None)
        self._granted.pop(seq, None)
        if not in_heap:
            return
        if self._waiting and self._waiting[0][1] == seq:
            heapq.heappop(self._waiting)
        else:
            self._waiting = [w for w in self._waiting if w[1] != seq]
            heapq.heapify(self._waiting)

    def _grant_next_locked(self) -> None:
        """Latch grants for the smallest-tag waiters while slots are
        free.  Grants wake every waiter; each checks its own latch."""
        while self._waiting and \
                self._inflight + len(self._granted) < self.max_inflight:
            tag, seq, _t = heapq.heappop(self._waiting)
            self._granted[seq] = tag
        self._cv.notify_all()

    @property
    def inflight(self) -> int:
        with self._cv:
            return self._inflight

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()
