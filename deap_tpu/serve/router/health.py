"""Health-driven failover: the loop that turns PR 7's manual
drain→restore drill into an automatic reflex.

Every poll period the monitor probes each live backend out-of-band
(control connections, never the forwarding path):

* ``GET /v1/healthz`` — reachability and drain state;
* ``GET /v1/metrics`` — the ``failed``/``net_errors`` counter deltas
  since the previous poll (a device path throwing on every batch is sick
  even while its HTTP frontend answers politely);
* ``GET /v1/trace`` — the instance's recent span window, joined through
  :func:`~deap_tpu.observability.fleettrace.join_spans` /
  :func:`~deap_tpu.observability.fleettrace.span_tree`: spans carrying
  an ``error`` attribute count against the instance, and a request span
  stuck beyond ``stall_s`` (queue-wait phases dominating the window)
  marks degradation the counters alone miss.

A wedge *in progress* leaves no spans at all (phases are recorded when a
request dispatches, never while it waits), so the probe also tracks
queue **progress**: a nonzero ``queue_depth`` gauge with a flat
``completed`` counter for longer than ``stall_s`` is a wedged dispatch
pipeline even though every control route still answers politely.

``fail_after`` consecutive bad polls latch the instance **sick** and
fire ``on_sick(backend, reason)`` exactly once — the router's failover
driver.  A latched instance is probed no further until
:meth:`HealthMonitor.revive` (failover replaces it; flapping must not
re-trigger mid-drain).  The loop waits on a ``threading.Event`` (wakes
on :meth:`stop` immediately — no blocking sleep, per the
``no-blocking-sleep`` gate that covers this package).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from ... import sanitize
from ...observability.fleettrace import join_spans, span_tree
from .backend import Backend, BackendDown

__all__ = ["HealthPolicy", "HealthMonitor", "HealthSample"]


@dataclass(frozen=True)
class HealthPolicy:
    """Knobs of the health loop (see module docstring)."""

    interval_s: float = 2.0
    fail_after: int = 2
    max_failed_delta: int = 0       # failed-counter rise tolerated per poll
    max_error_spans: int = 0        # error spans tolerated per window
    stall_s: float = 30.0           # a span older than this and unfinished
    trace_window: int = 128


@dataclass
class HealthSample:
    """One probe's verdict for one backend."""

    ok: bool
    reason: str = ""
    queue_depth: float = 0.0
    failed_delta: int = 0
    error_spans: int = 0


class HealthMonitor:
    """Polls backends, latches sickness, drives the failover callback
    (see module docstring).  ``on_sick(backend, reason)`` runs on the
    monitor thread (or the :meth:`check_now` caller's)."""

    #: lock-guarded shared state (``lock-discipline`` lint): strike
    #: counts, the sick latch, the degraded map and the per-backend
    #: counter baselines are written by the monitor thread AND by
    #: check_now()/force_sick()/set_degraded() callers — writes only
    #: under ``self._lock``
    _GUARDED_BY = {"_lock": ("_strikes", "_sick", "_baseline", "_backends",
                             "_stalled_since", "_degraded")}

    def __init__(self, backends: List[Backend],
                 on_sick: Callable[[Backend, str], None], *,
                 policy: Optional[HealthPolicy] = None,
                 metrics=None, clock=None):
        import time
        self.policy = policy if policy is not None else HealthPolicy()
        self.on_sick = on_sick
        self._metrics = metrics
        self._clock = clock if clock is not None else time.monotonic
        self._lock = sanitize.lock()
        self._backends: Dict[str, Backend] = {b.name: b for b in backends}
        self._strikes: Dict[str, int] = {}
        self._sick: Dict[str, str] = {}          # name -> latched reason
        self._baseline: Dict[str, Dict[str, int]] = {}
        self._stalled_since: Dict[str, float] = {}  # name -> first flat poll
        #: gray-failure tier between healthy and the sick latch: a
        #: degraded backend (e.g. its circuit breaker is open) still
        #: serves idempotent GETs and keeps its routed sessions, but is
        #: excluded from NEW-session placement until the condition clears
        self._degraded: Dict[str, str] = {}      # name -> reason
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "HealthMonitor":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="deap-tpu-router-health", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    def _run(self) -> None:
        # Event.wait is the loop's only wait: it returns early the
        # instant stop() sets the event (notify-woken, not a nap)
        while not self._stop.wait(self.policy.interval_s):
            self.check_now()

    # -- registry ------------------------------------------------------------

    def add_backend(self, backend: Backend) -> None:
        with self._lock:
            self._backends[backend.name] = backend
            self._strikes.pop(backend.name, None)
            self._sick.pop(backend.name, None)

    def remove_backend(self, name: str) -> None:
        with self._lock:
            self._backends.pop(name, None)
            self._strikes.pop(name, None)
            self._sick.pop(name, None)
            self._baseline.pop(name, None)
            self._stalled_since.pop(name, None)
            self._degraded.pop(name, None)

    def revive(self, name: str) -> None:
        """Clear a sick latch (an operator replaced/restarted the
        instance) — probing resumes next poll."""
        with self._lock:
            self._sick.pop(name, None)
            self._strikes.pop(name, None)
            self._baseline.pop(name, None)
            self._stalled_since.pop(name, None)
            self._degraded.pop(name, None)

    def sick(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._sick)

    def is_sick(self, name: str) -> bool:
        with self._lock:
            return name in self._sick

    # -- degraded tier -------------------------------------------------------

    def set_degraded(self, name: str, reason: str) -> None:
        """Classify a backend *degraded* — NOT the binary sick latch: a
        breaker-open (or otherwise gray-failing) instance keeps serving
        idempotent reads and its existing sessions, but the router stops
        placing NEW sessions on it.  Idempotent by design: the breaker
        re-notifies on every re-open."""
        with self._lock:
            if name not in self._backends:
                return
            self._degraded[name] = str(reason)
        if self._metrics is not None:
            self._metrics.set_gauge("router_backends_degraded",
                                    len(self.degraded()))

    def clear_degraded(self, name: str) -> None:
        with self._lock:
            self._degraded.pop(name, None)
        if self._metrics is not None:
            self._metrics.set_gauge("router_backends_degraded",
                                    len(self.degraded()))

    def degraded(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._degraded)

    def is_degraded(self, name: str) -> bool:
        with self._lock:
            return name in self._degraded

    def force_sick(self, name: str, reason: str = "operator") -> None:
        """Latch a backend sick without waiting for probes (operator
        action, fault drills, tests) — fires the same failover path."""
        with self._lock:
            backend = self._backends.get(name)
            if backend is None or name in self._sick:
                return
            self._sick[name] = reason
        if self._metrics is not None:
            self._metrics.inc("router_backends_sick")
        self.on_sick(backend, reason)

    # -- probing -------------------------------------------------------------

    def probe(self, backend: Backend) -> HealthSample:
        """One out-of-band look at one backend (no state change)."""
        try:
            hz = backend.healthz()
            rec = backend.metrics()
        except (BackendDown, OSError) as e:
            return HealthSample(ok=False, reason=f"unreachable: {e}")
        counters = rec.get("counters", {})
        with self._lock:
            base = self._baseline.get(backend.name, {})
            self._baseline[backend.name] = dict(counters)
        failed_delta = (int(counters.get("failed", 0))
                        - int(base.get("failed", 0))) if base else 0
        sample = HealthSample(
            ok=True,
            queue_depth=float(rec.get("gauges", {}).get("queue_depth", 0.0)),
            failed_delta=failed_delta)
        if hz.get("draining"):
            # draining is a transition the router itself drives, not a
            # sickness — never strike for it
            return sample
        if failed_delta > self.policy.max_failed_delta:
            return HealthSample(ok=False, failed_delta=failed_delta,
                                reason=f"failed counter rose by "
                                       f"{failed_delta} since last poll")
        stall = self._stall_reason(backend.name, counters, base,
                                   sample.queue_depth)
        if stall:
            return HealthSample(ok=False, queue_depth=sample.queue_depth,
                                reason=stall)
        err_spans, stalled = self._trace_signals(backend)
        if err_spans > self.policy.max_error_spans:
            return HealthSample(ok=False, error_spans=err_spans,
                                reason=f"{err_spans} error spans in the "
                                       "recent trace window")
        if stalled:
            return HealthSample(ok=False, reason=stalled)
        return sample

    def _stall_reason(self, name: str, counters: Dict[str, int],
                      base: Dict[str, int], depth: float) -> str:
        """Queue-progress stall: requests queued (``queue_depth`` > 0)
        but nothing completing for longer than ``stall_s``.  Trace spans
        cannot see this (phases are recorded at dispatch, not while
        waiting), so an in-progress wedge would otherwise probe ok."""
        completed_delta = (int(counters.get("completed", 0))
                           - int(base.get("completed", 0))) if base else 0
        now = self._clock()
        with self._lock:
            if depth <= 0 or not base or completed_delta > 0:
                self._stalled_since.pop(name, None)
                return ""
            since = self._stalled_since.setdefault(name, now)
        if now - since > self.policy.stall_s:
            return (f"queue depth {depth:.0f} with no completions for "
                    f"{now - since:.1f}s (> stall_s="
                    f"{self.policy.stall_s}) — dispatch pipeline wedged")
        return ""

    def _trace_signals(self, backend: Backend):
        """(error span count, stall reason) from the backend's joined
        span window; a backend without tracing contributes nothing."""
        try:
            tail = backend.trace_tail(self.policy.trace_window)
        except (BackendDown, OSError):
            return 0, ""            # reachability already probed above
        spans = join_spans({backend.name: tail.get("spans", [])})
        errors = sum(1 for s in spans if (s.get("attrs") or {}).get("error"))
        # walk request roots: a root whose queue_wait child dominates a
        # window older than stall_s is a wedged dispatch pipeline
        for root in span_tree(spans):
            for child in root.get("children", ()):
                if (child.get("name") == "queue_wait"
                        and child.get("duration_s", 0.0)
                        > self.policy.stall_s):
                    return errors, (
                        f"queue_wait span of {child['duration_s']:.1f}s "
                        f"(> stall_s={self.policy.stall_s}) — dispatch "
                        "pipeline wedged")
        return errors, ""

    def check_now(self) -> Dict[str, HealthSample]:
        """One full probe round, synchronously (what the background loop
        runs each interval; tests and the router's on-forward-failure
        path call it directly)."""
        with self._lock:
            live = [(n, b) for n, b in self._backends.items()
                    if n not in self._sick]
        out: Dict[str, HealthSample] = {}
        newly_sick: List[tuple] = []
        for name, backend in live:
            if self._metrics is not None:
                self._metrics.inc("router_health_probes")
            sample = self.probe(backend)
            out[name] = sample
            with self._lock:
                if name not in self._backends or name in self._sick:
                    continue        # removed/latched while probing
                if sample.ok:
                    self._strikes.pop(name, None)
                    continue
                strikes = self._strikes.get(name, 0) + 1
                self._strikes[name] = strikes
                if strikes < self.policy.fail_after:
                    continue
                self._sick[name] = sample.reason
                newly_sick.append((backend, sample.reason))
        for backend, reason in newly_sick:
            if self._metrics is not None:
                self._metrics.inc("router_backends_sick")
            self.on_sick(backend, reason)
        return out
