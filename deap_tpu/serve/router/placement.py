"""Bucket-histogram-aware session placement.

The expensive resource in this fleet is not CPU — it is **compiled
programs**: every ``(bucket rows, genome signature, toolbox)`` class a
backend serves costs it one XLA compile per request kind, and a session
placed on an instance already serving its shape class rides warm
executables from the first step.  Placement therefore thinks in the same
vocabulary as :mod:`deap_tpu.serve.buckets`:

* the router mirrors each backend's shape traffic in a
  :class:`~deap_tpu.serve.buckets.ShapeHistogram` (every placement
  observes its row count) and remembers the backend's **warm set** —
  the ``(bucket rows, genome signature)`` classes it has already been
  sent;
* a new session's bucket is computed with the same
  :class:`~deap_tpu.serve.buckets.BucketPolicy` arithmetic the instances
  use, so "sibling shapes" (distinct row counts sharing one padded
  bucket) genuinely co-locate;
* :func:`fleet_sizes` folds all backends' histograms through
  :func:`~deap_tpu.serve.buckets.derive_sizes` — the fleet-wide learned
  grid an operator feeds back into per-instance ``rebucket`` calls.

Scoring (:meth:`PlacementPolicy.choose`) is warmth first, load second:
a warm backend wins unless its session count exceeds the fleet minimum
by more than ``spread`` — the knob trading compile savings against
hot-spotting everything onto one box.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from ..buckets import BucketPolicy, ShapeHistogram, derive_sizes

__all__ = ["BackendPlan", "PlacementPolicy", "fleet_sizes"]


class BackendPlan:
    """The router's model of one backend's placement state: observed
    shape histogram, warm ``(rows, genome signature)`` classes, and the
    live-session count.  Mutated only by the router under ITS routing
    lock — this object carries no lock of its own."""

    def __init__(self):
        self.histogram = ShapeHistogram()
        self.warm: set = set()
        self.sessions = 0

    def observe_placement(self, n: int, rows: int, sig: tuple) -> None:
        self.histogram.observe(n)
        self.warm.add((int(rows), sig))
        self.sessions += 1

    def forget_session(self) -> None:
        self.sessions = max(0, self.sessions - 1)


@dataclasses.dataclass(frozen=True)
class PlacementPolicy:
    """Warmth-first placement with a load-spread guard.

    ``bucket_policy`` must mirror the instances' own policy (the bucket
    a session pads to is a function of the policy, and affinity keyed on
    the wrong grid would co-locate nothing).  ``spread`` is the maximum
    session-count lead a warm backend may hold over the least-loaded
    backend and still win placement; beyond it the cold backend takes
    the session (paying one compile to keep the fleet balanced)."""

    bucket_policy: BucketPolicy = dataclasses.field(
        default_factory=BucketPolicy)
    spread: int = 16

    def bucket_rows(self, n: int) -> int:
        return self.bucket_policy.rows_for(int(n))

    def choose(self, candidates: Sequence[Tuple[object, BackendPlan]],
               n: int, sig: tuple) -> Tuple[object, bool]:
        """Pick a backend for an ``n``-row session with genome signature
        ``sig`` from ``(backend, plan)`` candidates (already filtered to
        healthy instances holding the session's toolbox).  Returns
        ``(backend, warm)`` — ``warm`` says an existing program class
        was hit (the ``router_placements_warm`` counter's source)."""
        if not candidates:
            raise ValueError("no placement candidates")
        rows = self.bucket_rows(n)
        key = (rows, sig)
        floor = min(plan.sessions for _b, plan in candidates)
        warm = [(b, p) for b, p in candidates
                if key in p.warm and p.sessions - floor <= self.spread]
        pool = warm if warm else list(candidates)
        backend, _plan = min(pool, key=lambda bp: bp[1].sessions)
        return backend, bool(warm)


def fleet_sizes(plans: Iterable[BackendPlan], *, max_buckets: int = 8,
                min_rows: int = 8, round_to: int = 1
                ) -> Optional[Tuple[int, ...]]:
    """The fleet-wide learned bucket grid: merge every backend's observed
    shape histogram and fit :func:`~deap_tpu.serve.buckets.derive_sizes`
    over the union (``None`` before any traffic).  Operators feed this
    into per-instance ``rebucket`` calls so the whole fleet converges on
    one grid — a prerequisite for cross-instance failover staying
    bitwise (restore re-buckets under the TARGET's policy)."""
    merged: Dict[int, int] = {}
    for plan in plans:
        for n, c in plan.histogram.counts().items():
            merged[n] = merged.get(n, 0) + c
    if not merged:
        return None
    return derive_sizes(merged, max_buckets=max_buckets, min_rows=min_rows,
                        round_to=round_to)
