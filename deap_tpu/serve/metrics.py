"""Host-side service metrics: counters, gauges and latency quantiles.

The generation loops' :class:`~deap_tpu.observability.metrics.MetricBuffer`
accumulates ON DEVICE because a whole run is one dispatch; the serving
layer's control plane is host threads, so its metrics are plain (locked)
python counters that snapshot into the same
:class:`~deap_tpu.observability.sinks.MetricRecord` shape the sink layer
already speaks — one stats pipeline, two producers.

Latency is tracked as a bounded reservoir of recent per-request wall times
per request kind; :meth:`ServeMetrics.latency_quantiles` reports p50/p90/p99
over the window (steady-state service quantiles, not all-time)."""

from __future__ import annotations

import collections
import threading
from typing import Dict, Iterable, Optional

from ..observability.sinks import MetricRecord, emit_record

__all__ = ["ServeMetrics", "SERVE_COUNTERS", "SERVE_GAUGES", "NET_COUNTERS"]

#: Counters the service maintains (cumulative over the service lifetime).
SERVE_COUNTERS = (
    "requests", "completed", "failed", "cancelled", "deadline_misses",
    "rejected", "batches", "retries", "compiles", "compiles_step",
    "compiles_init", "compiles_ask", "compiles_tell", "compiles_evaluate",
    "steps", "steps_sharded", "evaluations", "cache_hits", "cache_misses",
    "cache_evictions", "cache_nan_skipped", "cache_purged", "dedup_rows",
    "quarantined", "rebuckets",
)

#: Counters the network frontend (deap_tpu.serve.net) adds on top —
#: maintained in the same ServeMetrics store so one /metrics snapshot
#: covers both the HTTP edge and the device control plane.
NET_COUNTERS = (
    "net_requests", "net_errors", "net_streams",
    "net_bytes_in", "net_bytes_out",
)

#: Gauges (last-value).
SERVE_GAUGES = (
    "queue_depth", "sessions", "sharded_sessions", "slot_occupancy",
    "row_occupancy",
)


class ServeMetrics:
    """Thread-safe counter/gauge/latency store for one
    :class:`~deap_tpu.serve.service.EvolutionService`."""

    def __init__(self, latency_window: int = 2048):
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {
            k: 0 for k in SERVE_COUNTERS + NET_COUNTERS}
        self._gauges: Dict[str, float] = {k: 0.0 for k in SERVE_GAUGES}
        self._latency: Dict[str, collections.deque] = {}
        self._window = int(latency_window)

    # -- writers -------------------------------------------------------------

    def inc(self, name: str, value: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + int(value)

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = float(value)

    def observe_latency(self, kind: str, seconds: float) -> None:
        with self._lock:
            q = self._latency.get(kind)
            if q is None:
                q = self._latency[kind] = collections.deque(
                    maxlen=self._window)
            q.append(float(seconds))

    # -- readers -------------------------------------------------------------

    def counter(self, name: str) -> int:
        with self._lock:
            return int(self._counters.get(name, 0))

    def counters(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counters)

    def gauges(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._gauges)

    @staticmethod
    def _quantile(sorted_samples, q: float) -> float:
        if not sorted_samples:
            return 0.0
        i = min(len(sorted_samples) - 1,
                max(0, round(q * (len(sorted_samples) - 1))))
        return sorted_samples[i]

    def latency_quantiles(self, kinds: Optional[Iterable[str]] = None
                          ) -> Dict[str, float]:
        """``{"latency_<kind>_p50_ms": ..., ...}`` over the recent window
        (all kinds pooled under ``latency_p*`` as well)."""
        with self._lock:
            samples = {k: sorted(v) for k, v in self._latency.items()
                       if (kinds is None or k in kinds) and v}
        out: Dict[str, float] = {}
        pooled = sorted(s for v in samples.values() for s in v)
        for label, data in [("", pooled)] + [
                (f"{k}_", v) for k, v in sorted(samples.items())]:
            for q, name in ((0.5, "p50"), (0.9, "p90"), (0.99, "p99")):
                out[f"latency_{label}{name}_ms"] = \
                    self._quantile(data, q) * 1e3
        return out

    def snapshot(self, seq: int = 0) -> MetricRecord:
        """Everything as one :class:`MetricRecord` (``gen`` carries the
        batch sequence number — the service's notion of time)."""
        gauges = self.gauges()
        gauges.update(self.latency_quantiles())
        return MetricRecord(gen=int(seq), counters=self.counters(),
                            gauges=gauges, meta={"source": "serve"})

    def emit(self, sinks, seq: int = 0) -> None:
        emit_record(sinks, self.snapshot(seq))
