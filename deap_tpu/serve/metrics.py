"""Host-side service metrics: counters, gauges, latency quantiles, and
per-tenant attribution.

The generation loops' :class:`~deap_tpu.observability.metrics.MetricBuffer`
accumulates ON DEVICE because a whole run is one dispatch; the serving
layer's control plane is host threads, so its metrics are plain (locked)
python counters that snapshot into the same
:class:`~deap_tpu.observability.sinks.MetricRecord` shape the sink layer
already speaks — one stats pipeline, two producers.

Latency is tracked as a bounded reservoir of recent per-request wall times
per request kind; :meth:`ServeMetrics.latency_quantiles` reports p50/p90/p99
over the window (steady-state service quantiles, not all-time).  The
reservoirs are **snapshotted under the lock and sorted outside it** — a
metrics scrape sorting thousands of samples while holding the lock would
stall the dispatch worker's ``observe_latency`` mid-batch (regression-
pinned by ``tests/test_fleettrace.py``).

Per-tenant attribution: :meth:`ServeMetrics.inc_tenant` maintains a
second, session-name-keyed counter table (:data:`TENANT_COUNTERS` — the
SLO set: deadline misses, backpressure rejects, cache hits/misses, ...)
that rides in the snapshot's ``meta["tenants"]`` and becomes labelled
series in the Prometheus exposition (:func:`prometheus_text`, served at
``/v1/metrics?format=prometheus``).  Metric NAMES are static snake_case
identifiers from the registries below; tenant identity lives in the
table key / label, never in the metric name — the ``metric-discipline``
lint pass enforces exactly this split.
"""

from __future__ import annotations

import collections
import re
from typing import Dict, Iterable, Optional

from .. import sanitize
from ..observability.sinks import MetricRecord, emit_record

__all__ = ["ServeMetrics", "SERVE_COUNTERS", "SERVE_GAUGES", "NET_COUNTERS",
           "ROUTER_COUNTERS", "ROUTER_GAUGES", "TENANT_COUNTERS",
           "AUTOSCALE_COUNTERS", "AUTOSCALE_GAUGES",
           "prometheus_text", "prometheus_fleet_text"]

#: Counters the service maintains (cumulative over the service lifetime).
SERVE_COUNTERS = (
    "requests", "completed", "failed", "cancelled", "deadline_misses",
    "rejected", "batches", "retries", "compiles", "compiles_step",
    "compiles_init", "compiles_ask", "compiles_tell", "compiles_evaluate",
    "steps", "steps_sharded", "steps_streamed", "evaluations",
    "cache_hits", "cache_misses",
    "cache_evictions", "cache_nan_skipped", "cache_purged", "dedup_rows",
    "quarantined", "rebuckets", "rebuckets_auto", "rebucket_policy_errors",
    "deadline_shed", "brownout_sheds",
)

#: Counters the network frontend (deap_tpu.serve.net) adds on top —
#: maintained in the same ServeMetrics store so one /metrics snapshot
#: covers both the HTTP edge and the device control plane.
NET_COUNTERS = (
    "net_requests", "net_errors", "net_streams",
    "net_bytes_in", "net_bytes_out", "net_bytes_saved",
    "net_frames_compressed",
)

#: Counters of the fleet router (deap_tpu.serve.router) — the control
#: plane ABOVE one instance.  Kept in this module so the
#: ``metric-discipline`` lint's committed-registry diff covers router
#: inc-sites exactly like service ones; the router's ServeMetrics store
#: is constructed with ``extra_counters=ROUTER_COUNTERS``.
ROUTER_COUNTERS = (
    "router_requests", "router_errors", "router_forwards",
    "router_forward_retries", "router_sessions_placed",
    "router_sessions_closed", "router_placements_warm",
    "router_quota_rejections", "router_health_probes",
    "router_backends_sick", "router_failovers", "router_failover_sessions",
    "router_orphans_replaced", "router_sessions_lost",
    "router_breaker_opens", "router_breaker_probes",
    "router_breaker_rejections", "router_deadline_shed",
)

#: Gauges of the fleet router (last-value).
ROUTER_GAUGES = (
    "router_backends_alive", "router_sessions_routed",
    "router_inflight", "router_failover_recovery_s",
    "router_backends_degraded",
)

#: Counters of the elastic-fleet layer (deap_tpu.serve.autoscale): the
#: autoscaler control loop, per-session live migration, and the
#: cross-instance fitness-cache fabric.  The router's ServeMetrics
#: store is constructed with these as extras (the autoscaler and fabric
#: run beside the router); the fabric's per-instance counters
#: (``cache_fabric_hits``/``cache_fabric_imports``/…) are maintained by
#: each instance's own FitnessCache through its ordinary metrics tap.
AUTOSCALE_COUNTERS = (
    "autoscale_scale_out_events", "autoscale_scale_in_events",
    "autoscale_migrations", "autoscale_migration_failures",
    "autoscale_errors", "autoscale_prewarms",
    "cache_fabric_hits", "cache_fabric_exports", "cache_fabric_imports",
    "cache_fabric_syncs",
)

#: Gauges of the elastic-fleet layer (last-value).
AUTOSCALE_GAUGES = (
    "autoscale_instances", "autoscale_migration_downtime_s",
    "autoscale_last_decision_queue_depth",
)

#: Gauges (last-value).  The ``profile_*`` family is the device-phase
#: profiler's aggregate rollup (per-program records ride the snapshot's
#: ``meta["programs"]`` table and the labelled Prometheus series — a
#: program key must never become part of a metric NAME).
SERVE_GAUGES = (
    "queue_depth", "sessions", "sharded_sessions", "sessions_streamed",
    "slot_occupancy", "row_occupancy", "pad_waste",
    "profile_programs", "profile_flops_total",
    "profile_bytes_accessed_total", "profile_peak_bytes_max",
)

#: Per-tenant (per-session) counters — the SLO attribution set.  Tenant
#: identity is the table key (and the Prometheus label), NEVER part of a
#: metric name.
TENANT_COUNTERS = (
    "requests", "completed", "failed", "rejected", "deadline_misses",
    "steps", "cache_hits", "cache_misses",
)


class ServeMetrics:
    """Thread-safe counter/gauge/latency store for one
    :class:`~deap_tpu.serve.service.EvolutionService`.

    ``max_tenants`` bounds the per-tenant table: when a fresh tenant
    would exceed it, the oldest tenant's row is evicted (the table is a
    live attribution view, not an accounting ledger — long-lived fleets
    must not leak a row per dead session forever).

    ``extra_counters`` / ``extra_gauges`` pre-register additional name
    families in the snapshot (the router passes
    :data:`ROUTER_COUNTERS`/:data:`ROUTER_GAUGES`) — backend snapshots
    stay free of zero-valued router series."""

    #: lock-guarded shared state (``lock-discipline`` lint + runtime
    #: sanitizer): every counter/gauge/reservoir/tenant table access
    #: is shared between the dispatch worker and scraper threads
    _GUARDED_BY = {"_lock": ("_counters", "_gauges", "_latency",
                             "_tenants")}

    def __init__(self, latency_window: int = 2048, max_tenants: int = 4096,
                 extra_counters: Iterable[str] = (),
                 extra_gauges: Iterable[str] = ()):
        self._lock = sanitize.lock()
        self._counters: Dict[str, int] = {
            k: 0 for k in SERVE_COUNTERS + NET_COUNTERS
            + tuple(extra_counters)}
        self._gauges: Dict[str, float] = {
            k: 0.0 for k in SERVE_GAUGES + tuple(extra_gauges)}
        self._latency: Dict[str, collections.deque] = {}
        self._window = int(latency_window)
        self._tenants: "collections.OrderedDict[str, Dict[str, int]]" = \
            collections.OrderedDict()
        self.max_tenants = int(max_tenants)

    # -- writers -------------------------------------------------------------

    def inc(self, name: str, value: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + int(value)

    def inc_tenant(self, tenant: Optional[str], name: str,
                   value: int = 1) -> None:
        """Count ``value`` under ``tenant``'s row (no-op for ``None`` —
        requests without a session have no tenant to attribute to)."""
        if tenant is None:
            return
        with self._lock:
            row = self._tenants.get(tenant)
            if row is None:
                while len(self._tenants) >= self.max_tenants:
                    self._tenants.popitem(last=False)
                row = self._tenants[tenant] = {}
            row[name] = row.get(name, 0) + int(value)

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = float(value)

    def observe_latency(self, kind: str, seconds: float) -> None:
        with self._lock:
            q = self._latency.get(kind)
            if q is None:
                q = self._latency[kind] = collections.deque(
                    maxlen=self._window)
            q.append(float(seconds))

    # -- readers -------------------------------------------------------------

    def counter(self, name: str) -> int:
        with self._lock:
            return int(self._counters.get(name, 0))

    def counters(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counters)

    def gauges(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._gauges)

    def tenant_counters(self) -> Dict[str, Dict[str, int]]:
        """``{tenant: {counter: value}}`` snapshot."""
        with self._lock:
            return {t: dict(row) for t, row in self._tenants.items()}

    @staticmethod
    def _quantile(sorted_samples, q: float) -> float:
        if not sorted_samples:
            return 0.0
        i = min(len(sorted_samples) - 1,
                max(0, round(q * (len(sorted_samples) - 1))))
        return sorted_samples[i]

    def latency_quantiles(self, kinds: Optional[Iterable[str]] = None
                          ) -> Dict[str, float]:
        """``{"latency_<kind>_p50_ms": ..., ...}`` over the recent window
        (all kinds pooled under ``latency_p*`` as well).  The reservoirs
        are copied under the lock; the O(n log n) sorts run OUTSIDE it so
        a scrape never stalls ``observe_latency`` on the dispatch
        worker."""
        with self._lock:
            samples = {k: list(v) for k, v in self._latency.items()
                       if (kinds is None or k in kinds) and v}
        for v in samples.values():
            v.sort()
        out: Dict[str, float] = {}
        pooled = sorted(s for v in samples.values() for s in v)
        for label, data in [("", pooled)] + [
                (f"{k}_", v) for k, v in sorted(samples.items())]:
            for q, name in ((0.5, "p50"), (0.9, "p90"), (0.99, "p99")):
                out[f"latency_{label}{name}_ms"] = \
                    self._quantile(data, q) * 1e3
        return out

    def snapshot(self, seq: int = 0) -> MetricRecord:
        """Everything as one :class:`MetricRecord` (``gen`` carries the
        batch sequence number — the service's notion of time; per-tenant
        counters ride in ``meta["tenants"]``)."""
        gauges = self.gauges()
        gauges.update(self.latency_quantiles())
        meta: dict = {"source": "serve"}
        tenants = self.tenant_counters()
        if tenants:
            meta["tenants"] = tenants
        return MetricRecord(gen=int(seq), counters=self.counters(),
                            gauges=gauges, meta=meta)

    def emit(self, sinks, seq: int = 0) -> None:
        emit_record(sinks, self.snapshot(seq))


# ---------------------------------------------------------------------------
# Prometheus text exposition
# ---------------------------------------------------------------------------

_PROM_PREFIX = "deap_tpu_serve"

#: ``latency_<kind?>_p<q>_ms`` gauge names (the reservoir snapshot) —
#: exported as the proper ``deap_tpu_latency_seconds`` summary series
#: instead of flat per-quantile gauge names
_LATENCY_GAUGE_RE = re.compile(
    r"\Alatency_(?:(?P<kind>.+)_)?p(?P<q>50|90|99)_ms\Z")
_QUANTILE_OF = {"50": "0.5", "90": "0.9", "99": "0.99"}

#: per-program profile values exported as labelled gauge series (the
#: program key is a label, never a metric name)
_PROGRAM_SERIES = (
    ("calls", "program_calls"),
    ("device_min_s", "program_device_min_seconds"),
    ("compile_s", "program_compile_seconds"),
)
_PROGRAM_AOT_SERIES = (
    ("flops", "program_flops"),
    ("bytes_accessed", "program_bytes_accessed"),
    ("peak_bytes_upper_bound", "program_peak_bytes"),
    ("collective_count", "program_collectives"),
)


def _prom_label(value: str) -> str:
    """Escape a label value per the Prometheus text format."""
    return (value.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _label_str(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_prom_label(str(v))}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _families_of(record: MetricRecord,
                 instance: Optional[str] = None) -> "collections.OrderedDict":
    """``{metric name: (type, [(labels, formatted value), ...])}`` for
    one record — the shared decomposition :func:`prometheus_text`
    renders directly and :func:`prometheus_fleet_text` merges across
    instances (so a fleet exposition declares each TYPE exactly once)."""
    base = {} if instance is None else {"instance": str(instance)}
    fams: "collections.OrderedDict[str, tuple]" = collections.OrderedDict()

    def add(metric: str, typ: str, labels: Dict[str, str],
            value: str) -> None:
        fam = fams.setdefault(metric, (typ, []))
        fam[1].append((dict(base, **labels), value))

    # 0.0.4 text format: a TYPE line must name the SAMPLE's metric
    # exactly, so the counter families carry their _total suffix in both
    for name in sorted(record.counters):
        add(f"{_PROM_PREFIX}_{name}_total", "counter", {},
            str(int(record.counters[name])))
    latency: list = []
    for name in sorted(record.gauges):
        m = _LATENCY_GAUGE_RE.match(name)
        if m is not None:
            latency.append((m.group("kind") or "all",
                            _QUANTILE_OF[m.group("q")],
                            float(record.gauges[name]) / 1e3))
            continue
        add(f"{_PROM_PREFIX}_{name}", "gauge", {},
            f"{float(record.gauges[name]):g}")
    # reservoir quantiles as one summary family, labelled by request
    # kind ("all" = the pooled reservoir) and quantile
    for kind, quantile, seconds in latency:
        add("deap_tpu_latency_seconds", "summary",
            {"kind": kind, "quantile": quantile}, f"{seconds:g}")
    tenants = record.meta.get("tenants") or {}
    by_counter: Dict[str, list] = {}
    for tenant in sorted(tenants):
        for cname, v in sorted(tenants[tenant].items()):
            by_counter.setdefault(cname, []).append((tenant, v))
    for cname in sorted(by_counter):
        for tenant, v in by_counter[cname]:
            add(f"{_PROM_PREFIX}_tenant_{cname}_total", "counter",
                {"tenant": tenant}, str(int(v)))
    # per-program device-phase profiles (meta["programs"], when the
    # service runs with its profiler enabled): program key as a label
    programs = record.meta.get("programs") or {}
    for key in sorted(programs):
        prof = programs[key]
        labels = {"program": key, "kind": str(prof.get("kind", ""))}
        for field, series in _PROGRAM_SERIES:
            v = prof.get(field)
            if v is not None:
                add(f"{_PROM_PREFIX}_{series}", "gauge", labels,
                    f"{float(v):g}")
        aot = prof.get("aot") or {}
        for field, series in _PROGRAM_AOT_SERIES:
            v = aot.get(field)
            if v is not None:
                add(f"{_PROM_PREFIX}_{series}", "gauge", labels,
                    f"{float(v):g}")
    add(f"{_PROM_PREFIX}_batches_seq", "gauge", {}, str(int(record.gen)))
    return fams


def _render_families(fams) -> str:
    lines = []
    for metric, (typ, samples) in fams.items():
        lines.append(f"# TYPE {metric} {typ}")
        for labels, value in samples:
            lines.append(f"{metric}{_label_str(labels)} {value}")
    return "\n".join(lines) + "\n"


def prometheus_text(record: MetricRecord,
                    instance: Optional[str] = None) -> str:
    """Render a serve :class:`MetricRecord` in the Prometheus text
    exposition format (version 0.0.4): counters as
    ``deap_tpu_serve_<name>_total``, gauges as
    ``deap_tpu_serve_<name>``, the latency reservoir quantiles as
    summary-style ``deap_tpu_latency_seconds{kind=...,quantile=...}``
    series (seconds, per request kind plus the pooled ``kind="all"``),
    per-tenant SLO counters as
    ``deap_tpu_serve_tenant_<name>_total{tenant="..."}`` and — when the
    record carries the profiler's ``meta["programs"]`` table —
    per-compiled-program ``deap_tpu_serve_program_*{program=...}``
    series.  ``instance`` (optional) adds an ``instance`` label to every
    sample — the fleet exposition's disambiguator."""
    return _render_families(_families_of(record, instance))


def prometheus_fleet_text(records: Dict[str, MetricRecord]) -> str:
    """One exposition covering a whole fleet: ``{instance name:
    record}`` merged so each metric family is declared once and every
    sample carries its ``instance`` label — what the router serves at
    ``GET /v1/admin/fleet?format=prometheus`` (one scrape, N
    instances)."""
    merged: "collections.OrderedDict[str, tuple]" = collections.OrderedDict()
    for inst, rec in records.items():
        for metric, (typ, samples) in _families_of(rec, inst).items():
            fam = merged.setdefault(metric, (typ, []))
            fam[1].extend(samples)
    return _render_families(merged)
