"""Telemetry-driven automatic bucket-grid refits.

``EvolutionService.rebucket()`` (PR 7) refits the pad-and-bucket grid to
the observed :class:`~deap_tpu.serve.buckets.ShapeHistogram` — but only
when an operator calls it.  :class:`RebucketPolicy` closes the ROADMAP's
control loop: it watches the same telemetry the operator would (histogram
drift since the grid was last fitted, the ``pad_waste`` gauge) and
triggers the refit itself, at the same quiesce point, with the same
zero-unplanned-recompile guarantee (``warm`` programs are compiled inside
the quiesce, so steady-state traffic after the fire never compiles —
pinned by the drift drill in ``tests/test_fleettrace.py``).

The policy runs on the dispatcher's worker thread (the ``after_batch``
hook — after a batch completes, outside the queue lock), which makes the
fire path trivially safe: the worker already owns all device dispatch,
and ``rebucket()``'s pause/resume is re-entrant from that position.

Stability knobs, because a control loop that thrashes is worse than an
operator who never calls it:

* **hysteresis** (``hold``) — the trigger condition must hold for
  ``hold`` consecutive ticks before a fire (one weird batch is noise);
* **cooldown** (``cooldown_s``) — a refit quiesces the fleet and spends
  compiles; never fire twice within the window;
* **no-op suppression** — before firing, the policy derives the grid it
  WOULD install; when that equals the current grid the fire is skipped
  and the baseline re-anchored (drift without a better grid is not
  actionable).
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Optional, Sequence

__all__ = ["RebucketPolicy", "pad_waste_of"]


def pad_waste_of(service) -> float:
    """Fraction of padded rows that carry no live individual, over every
    live session: ``1 - sum(live) / sum(bucket rows)`` (0.0 with no
    sessions).  The gauge the policy watches — high waste means the grid
    no longer fits the traffic."""
    live = rows = 0
    for s in service.sessions().values():
        live += s.pop_size
        rows += s.bucket.rows
    return 0.0 if rows == 0 else 1.0 - live / rows


class RebucketPolicy:
    """Auto-trigger for :meth:`EvolutionService.rebucket` (see module
    docstring).  Install with :meth:`EvolutionService.set_rebucket_policy`
    (or the ``rebucket_policy=`` constructor argument); the service calls
    :meth:`tick` after every dispatched batch.

    Parameters
    ----------
    pad_waste_threshold:
        Fire only while :func:`pad_waste_of` is at or above this (default
        0.25: a quarter of every padded dispatch is dead rows).
    drift_threshold:
        Fire only while the normalized L1 distance between the current
        shape histogram and the one the grid was last fitted to is at or
        above this (0..1; 1.0 = disjoint traffic; a never-fitted policy
        treats any traffic as full drift).
    hold:
        Consecutive qualifying ticks required before a fire (hysteresis).
    cooldown_s:
        Minimum seconds between fires.
    max_buckets / warm:
        Forwarded to :meth:`EvolutionService.rebucket`.
    clock:
        Monotonic time source (injectable for tests).
    """

    def __init__(self, *, pad_waste_threshold: float = 0.25,
                 drift_threshold: float = 0.5, hold: int = 2,
                 cooldown_s: float = 60.0, max_buckets: int = 8,
                 warm: Sequence[str] = ("step",),
                 clock: Callable[[], float] = time.monotonic):
        if hold < 1:
            raise ValueError("hold must be >= 1")
        self.pad_waste_threshold = float(pad_waste_threshold)
        self.drift_threshold = float(drift_threshold)
        self.hold = int(hold)
        self.cooldown_s = float(cooldown_s)
        self.max_buckets = int(max_buckets)
        self.warm = tuple(warm)
        self.clock = clock
        self._fitted: Dict[int, int] = {}
        self._streak = 0
        self._last_fire: Optional[float] = None
        #: summary dict of the most recent fire (operator introspection)
        self.last_fire_info: Optional[dict] = None

    # -- telemetry terms -----------------------------------------------------

    def observe_baseline(self, service) -> None:
        """Anchor the drift baseline to the service's CURRENT histogram —
        called at install time, so drift measures change since the
        operator last knew the traffic, not since the service booted."""
        self._fitted = dict(service.shapes.counts())

    def drift(self, counts: Dict[int, int]) -> float:
        """Normalized L1 distance between ``counts`` and the histogram
        at the last (re)fit: ``0.5 * sum |p - q|`` over the union of
        observed sizes, in [0, 1]."""
        if not counts:
            return 0.0
        if not self._fitted:
            return 1.0
        tot_p = sum(counts.values())
        tot_q = sum(self._fitted.values())
        keys = set(counts) | set(self._fitted)
        return 0.5 * sum(abs(counts.get(k, 0) / tot_p
                             - self._fitted.get(k, 0) / tot_q)
                         for k in keys)

    # -- the control loop ----------------------------------------------------

    def tick(self, service) -> Optional[dict]:
        """One policy evaluation (called by the service after every
        batch).  Returns the :meth:`EvolutionService.rebucket` summary
        when this tick fired, else ``None``.  Always refreshes the
        ``pad_waste`` gauge so the term the policy watches is the one the
        operator sees on ``/v1/metrics``."""
        counts = service.shapes.counts()
        waste = pad_waste_of(service)
        service.metrics.set_gauge("pad_waste", waste)
        if not counts or not service.sessions():
            self._streak = 0
            return None
        if (self._last_fire is not None
                and self.clock() - self._last_fire < self.cooldown_s):
            return None
        if (waste < self.pad_waste_threshold
                or self.drift(counts) < self.drift_threshold):
            self._streak = 0
            return None
        self._streak += 1
        if self._streak < self.hold:
            return None
        # no-op suppression: derive the grid this fire would install;
        # identical sizes mean the drift is not actionable — re-anchor
        preview = service.shapes.derive_policy(
            max_buckets=self.max_buckets,
            min_rows=service.policy.min_rows,
            max_rows=service.policy.max_rows)
        if tuple(preview.sizes) == tuple(service.policy.sizes):
            self._fitted = counts
            self._streak = 0
            return None
        info = service.rebucket(max_buckets=self.max_buckets,
                                warm=self.warm)
        service.metrics.inc("rebuckets_auto")
        self._fitted = counts
        self._streak = 0
        self._last_fire = self.clock()
        self.last_fire_info = info
        return info
