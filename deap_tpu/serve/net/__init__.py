"""Network frontend for the serving layer: evolution over the wire.

The in-process :class:`~deap_tpu.serve.service.EvolutionService`
multiplexes tenants that live in the same interpreter; this package is the
edge in front of it — a stdlib HTTP frontend (``[serve]`` extra stays
dependency-free), a binary JSON+tensor wire format, and a thin remote
client mirroring the in-process ``Session`` API:

* :mod:`~deap_tpu.serve.net.protocol` — the frame codec (one JSON header +
  contiguous raw little-endian tensor payloads; bit-exact round trips for
  every genome/fitness dtype) and the HTTP error mapping;
* :mod:`~deap_tpu.serve.net.server` — :class:`NetServer`: session
  create/ask/tell/step/evaluate/close over HTTP, a streaming
  ``/v1/metrics`` endpoint, and the ``/v1/admin`` drain/restore/rebucket
  surface that cross-instance failover rides on;
* :mod:`~deap_tpu.serve.net.client` — :class:`RemoteService` /
  :class:`RemoteSession`: the future-based ask/tell/step/evaluate API of
  the in-process session, backed by a pipelined HTTP worker; trajectories
  are **bitwise identical** to serving the same session in-process
  (pinned by ``tests/test_serve_net.py``).

Kept out of ``deap_tpu.serve``'s import path on purpose: importing the
service layer must not cost an HTTP stack, so ``from deap_tpu.serve.net
import NetServer, RemoteService`` is the entry point.
"""

from .protocol import (encode_frame, decode_frame,  # noqa: F401
                       decode_frame_with_trace, remote_exception,
                       status_of, CONTENT_TYPE, MAGIC)
from .server import NetServer  # noqa: F401
from .client import RemoteService, RemoteSession  # noqa: F401

__all__ = [
    "NetServer", "RemoteService", "RemoteSession",
    "encode_frame", "decode_frame", "decode_frame_with_trace",
    "remote_exception", "status_of", "CONTENT_TYPE", "MAGIC",
]
