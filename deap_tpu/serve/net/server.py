""":class:`NetServer` — the HTTP frontend of one
:class:`~deap_tpu.serve.service.EvolutionService` instance.

Pure stdlib (``http.server.ThreadingHTTPServer``): one handler thread per
connection blocks on the service's futures — socket waits and Condition
waits only, never ``time.sleep`` (``tools/check_no_blocking_sleep.py``
walks this package too).  Toolboxes cannot travel over a wire, so the
server owns a **toolbox registry**: clients name a registered toolbox at
session create, and the name is remembered per session so a drain
snapshot can be restored on any instance holding the same registry.

Surface (all frames — see :mod:`~deap_tpu.serve.net.protocol` — unless
noted)::

    GET    /v1/healthz                      liveness + drain state (JSON)
    GET    /v1/toolboxes                    registry names (JSON)
    POST   /v1/sessions                     create (key/genome/weights/...)
    GET    /v1/sessions/{name}              current population + phase
    DELETE /v1/sessions/{name}              close
    POST   /v1/sessions/{name}/step         {"n": k} -> k per-gen results
    POST   /v1/sessions/{name}/ask          -> offspring genome rows
    POST   /v1/sessions/{name}/tell         {"values": tensor}
    POST   /v1/sessions/{name}/evaluate     {"genome": tensor} -> values
    GET    /v1/metrics                      one MetricRecord (JSON); add
                                            ?stream=1&max=K&timeout=S for
                                            chunked ND-JSON tailing
    GET    /v1/profile                      per-compiled-program device-
                                            phase profiles (JSON): AOT
                                            cost/memory records + min-of-k
                                            measured execute walls
    POST   /v1/admin/drain                  failover step 1: quiesce +
                                            snapshot every live session
    POST   /v1/admin/restore                failover step 2: adopt a
                                            drained snapshot
    POST   /v1/admin/rebucket               adaptive bucket-grid refit
    POST   /v1/admin/redirect               failover step 3: record where
                                            drained sessions now live (a
                                            typed redirect for stale
                                            clients; {"session": name}
                                            scopes it to one migrated
                                            session)
    POST   /v1/admin/migrate                live-migration source side:
                                            quiesce + export exactly one
                                            session (neighbors keep
                                            serving)
    POST   /v1/admin/cache/export           fitness-cache fabric: local
                                            inserts after a cursor, in
                                            portable namespaces
    POST   /v1/admin/cache/import           admit another instance's
                                            exported cache entries

Cross-instance failover is drain → ship the frame → restore: the snapshot
carries each session's toolbox *name*, bucket rows and raw PRNG key, so
the restoring instance continues every trajectory **bitwise** when its
policy/registry match (pinned by the tier-1 failover drill in
``tests/test_serve_net.py``).
"""

from __future__ import annotations

import json
import threading
from typing import Any, Dict, Optional, Sequence
from urllib.parse import parse_qs, unquote, urlparse

import numpy as np
import jax.numpy as jnp

from ... import sanitize
from ...base import Population, Fitness
from ...observability import fleettrace
from ...observability.sinks import emit_text
from ..dispatcher import ServiceDraining, SessionUnknown
from ..metrics import prometheus_text
from . import protocol
from .httpcommon import FleetHTTPServer, FrameHTTPHandler

__all__ = ["NetServer"]


class NetServer:
    """Serve an :class:`~deap_tpu.serve.service.EvolutionService` over
    HTTP (see module docstring).

    Parameters
    ----------
    service:
        The (already constructed) in-process service instance.
    toolboxes:
        Name → toolbox registry clients may open sessions against.
    host / port:
        Bind address; ``port=0`` picks a free port (read it back from
        :attr:`address` / :attr:`url`).
    result_timeout:
        Server-side cap on waiting for one request's device futures.
    sinks / verbose:
        Request-log routing (library output goes through the
        observability sink layer, never bare prints).
    """

    #: lock-guarded shared state (``lock-discipline`` lint pass): the
    #: session→toolbox name map is written by concurrent HTTP handler
    #: threads (create/close/restore), and the failover/migration
    #: redirect targets by the admin endpoint — writes only under
    #: ``self._lock``
    _GUARDED_BY = {"_lock": ("_session_toolbox", "_redirect",
                             "_session_redirects")}

    def __init__(self, service, toolboxes: Dict[str, Any], *,
                 host: str = "127.0.0.1", port: int = 0,
                 result_timeout: float = 600.0, sinks: Sequence = (),
                 compress_min_bytes: int = 4096, verbose: bool = False,
                 ssl_context=None):
        self.service = service
        self.toolboxes = dict(toolboxes)
        self.result_timeout = float(result_timeout)
        self.sinks = list(sinks)
        #: raw tensor-payload size below which a response is never
        #: compressed even for a zlib-advertising peer (deflating a tiny
        #: ask result costs more CPU than the bytes it saves)
        self.compress_min_bytes = int(compress_min_bytes)
        self.verbose = bool(verbose)
        self._session_toolbox: Dict[str, str] = {}
        #: where this instance's sessions went after a drain (set by
        #: POST /v1/admin/redirect, typically by the fleet router once
        #: restore succeeded elsewhere): attached to ServiceDraining /
        #: SessionUnknown error envelopes so direct clients follow the
        #: failover transparently
        self._redirect: Optional[str] = None
        #: per-session redirects (live migration leaves one behind for
        #: exactly the migrated session; its neighbors keep serving
        #: here, so the instance-wide target must stay unset)
        self._session_redirects: Dict[str, str] = {}
        self._lock = sanitize.lock()
        # cross-instance cache fabric: evaluators become portable under
        # their registry toolbox's name (every instance of the fleet
        # holding the same registry agrees on it)
        for tb_name, tb in self.toolboxes.items():
            self._register_cache_alias(tb_name, tb)
        net = self

        class Handler(_Handler):
            server_ctx = net

        self._httpd = FleetHTTPServer((host, port), Handler)
        #: TLS termination: an ``ssl.SSLContext`` wraps the listening
        #: socket (every accepted connection handshakes before HTTP) and
        #: flips :attr:`url` to https so redirects/topology advertise
        #: the scheme peers must speak
        self._ssl_context = ssl_context
        if ssl_context is not None:
            self._httpd.socket = ssl_context.wrap_socket(
                self._httpd.socket, server_side=True)
        self._thread: Optional[threading.Thread] = None

    def _register_cache_alias(self, tb_name: str, toolbox) -> None:
        evaluate = getattr(toolbox, "evaluate", None)
        if evaluate is not None:
            self.service.cache.register_namespace_alias(
                id(evaluate), tb_name)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "NetServer":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever,
                name="deap-tpu-serve-http", daemon=True)
            self._thread.start()
            if self.verbose:
                emit_text(f"[serve.net] listening on {self.url}", self.sinks)
        return self

    def close(self) -> None:
        if self._thread is not None:
            self._httpd.shutdown()
            self._thread.join(timeout=10.0)
            self._thread = None
        self._httpd.server_close()

    def __enter__(self) -> "NetServer":
        return self.start()

    def __exit__(self, *exc):
        self.close()
        return False

    @property
    def address(self) -> tuple:
        return self._httpd.server_address[:2]

    @property
    def url(self) -> str:
        host, port = self.address
        scheme = "https" if self._ssl_context is not None else "http"
        return f"{scheme}://{host}:{port}"

    # -- session helpers -----------------------------------------------------

    def _session(self, name: str):
        s = self.service.sessions().get(name)
        if s is None:
            raise SessionUnknown(f"no live session named {name!r}")
        return s

    def _result(self, future):
        return future.result(timeout=self.result_timeout)

    # -- route bodies (called from the handler; return encodable objects) ----

    def h_healthz(self) -> dict:
        return {"status": "draining" if self.service.draining else "ok",
                "sessions": len(self.service.sessions()),
                "draining": bool(self.service.draining)}

    def h_create(self, body: dict) -> dict:
        tb_name = body["toolbox"]
        toolbox = self.toolboxes.get(tb_name)
        if toolbox is None:
            raise SessionUnknown(f"no registered toolbox named {tb_name!r}")
        genome = _as_device(body["genome"])
        n = _rows_of(genome)
        weights = tuple(float(w) for w in body["weights"])
        if body.get("values") is not None:
            fitness = Fitness(values=jnp.asarray(body["values"], jnp.float32),
                              valid=jnp.asarray(body["valid"], bool),
                              weights=weights)
        else:
            fitness = Fitness.empty(n, weights)
        pop = Population(genome=genome, fitness=fitness)
        session = self.service.open_session(
            jnp.asarray(np.asarray(body["key"])), pop, toolbox,
            cxpb=float(body.get("cxpb", 0.5)),
            mutpb=float(body.get("mutpb", 0.2)),
            name=body.get("name"),
            evaluate_initial=bool(body.get("evaluate_initial", True)),
            priority=int(body.get("priority", 1)),
            timeout=self.result_timeout)
        # the evaluator may have been registered on the toolbox after
        # construction (or re-created after a purge) — keep its fabric
        # alias current at every admission
        self._register_cache_alias(tb_name, toolbox)
        with self._lock:
            self._session_toolbox[session.name] = tb_name
            # a re-created name supersedes any migration leftover: the
            # session lives HERE now, a stale redirect would bounce it
            self._session_redirects.pop(session.name, None)
        return {"name": session.name, "gen": session.gen,
                "pop": session.pop_size, "rows": session.bucket.rows,
                "sharded": session.sharded}

    def h_get_session(self, name: str) -> dict:
        s = self._session(name)
        p = s.population()
        return {"name": s.name, "gen": s.gen, "phase": s.phase,
                "pop": s.pop_size, "rows": s.bucket.rows,
                "sharded": s.sharded, "weights": s.bucket.weights,
                "genome": p.genome, "values": np.asarray(p.fitness.values),
                "valid": np.asarray(p.fitness.valid)}

    def h_close_session(self, name: str) -> dict:
        self._session(name).close()
        with self._lock:
            self._session_toolbox.pop(name, None)
        return {"closed": name}

    def h_step(self, name: str, body: dict) -> dict:
        s = self._session(name)
        futures = s.step(int(body.get("n", 1)),
                         deadline=body.get("deadline"))
        results = []
        for f in futures:
            try:
                results.append({"ok": self._result(f)})
            except Exception as e:  # noqa: BLE001 — per-gen error travels
                results.append({"error": type(e).__name__,
                                "message": str(e)})
        return {"results": results, "gen": s.gen}

    def h_ask(self, name: str, body: dict) -> dict:
        s = self._session(name)
        off = self._result(s.ask(deadline=body.get("deadline")))
        return {"offspring": off, "gen": s.gen}

    def h_tell(self, name: str, body: dict) -> dict:
        s = self._session(name)
        out = self._result(s.tell(np.asarray(body["values"]),
                                  deadline=body.get("deadline")))
        return {"ok": out}

    def h_evaluate(self, name: str, body: dict) -> dict:
        s = self._session(name)
        values = self._result(s.evaluate(_as_device(body["genome"]),
                                         deadline=body.get("deadline")))
        return {"values": np.asarray(values)}

    def h_drain(self, body: dict) -> dict:
        snaps = self.service.drain(timeout=body.get("timeout", 60.0))
        # resolve toolbox names AFTER the drain: the session set is frozen
        # now, so a create that raced the drain gate is either in the
        # snapshot (and resolvable below) or was rejected — never admitted
        # yet unnamed
        with self._lock:
            names = dict(self._session_toolbox)
        # sessions opened OUTSIDE this frontend (in-process, or restored
        # from a disk checkpoint) have no recorded registry name —
        # reverse-map their toolbox object so the snapshot stays
        # restorable on any instance holding the same registry
        rev = {id(tb): tn for tn, tb in self.toolboxes.items()}
        for name, sess in self.service.sessions().items():
            if name not in names:
                tn = rev.get(id(sess.toolbox))
                if tn is not None:
                    names[name] = tn
        for name, snap in snaps.items():
            snap["toolbox"] = names.get(name)
        if self.verbose:
            emit_text(f"[serve.net] drained {len(snaps)} sessions",
                      self.sinks)
        return {"sessions": snaps}

    def h_restore(self, body: dict) -> dict:
        snaps = body["sessions"]
        toolboxes: Dict[str, Any] = {}
        skipped: Dict[str, str] = {}
        for name, snap in snaps.items():
            tb_name = snap.get("toolbox")
            toolbox = self.toolboxes.get(tb_name)
            if toolbox is None:
                # one orphan (session drained with a toolbox this
                # registry doesn't hold) must not block the restorable
                # majority's failover — skip it and say so
                skipped[name] = (f"toolbox {tb_name!r} not in this "
                                 "instance's registry")
                continue
            toolboxes[name] = toolbox
        if snaps and not toolboxes:
            raise SessionUnknown(
                "no session in the snapshot names a toolbox in this "
                f"instance's registry (skipped: {skipped})")
        restored = self.service.adopt_sessions(
            {n: snaps[n] for n in toolboxes}, toolboxes)
        for name in restored:
            self._register_cache_alias(snaps[name].get("toolbox"),
                                       toolboxes[name])
        with self._lock:
            for name in restored:
                self._session_toolbox[name] = snaps[name].get("toolbox")
                self._session_redirects.pop(name, None)
        if self.verbose:
            emit_text(f"[serve.net] restored {sorted(restored)} "
                      f"skipped {sorted(skipped)}", self.sinks)
        return {"restored": sorted(restored), "skipped": skipped}

    def h_profile(self) -> dict:
        """``GET /v1/profile`` — the device-phase profiler's
        per-program table (see
        :class:`~deap_tpu.observability.profiling.ProgramProfiler`):
        AOT flop/byte/peak records joined with min-of-k measured
        execute walls, keyed by readable program identity."""
        prof = self.service.profiler
        return {"enabled": bool(prof.enabled),
                "programs": prof.profiles()}

    def h_rebucket(self, body: dict) -> dict:
        sizes = body.get("sizes")
        return self.service.rebucket(
            max_buckets=int(body.get("max_buckets", 8)),
            warm=tuple(body.get("warm", ("step",))),
            sizes=None if sizes is None else [int(r) for r in sizes])

    def h_migrate(self, body: dict) -> dict:
        """``POST /v1/admin/migrate`` — live-migration source side:
        quiesce exactly one session at a dispatch boundary
        (:meth:`~deap_tpu.serve.service.EvolutionService.export_session`)
        and hand back its snapshot in the drain wire form (toolbox name
        included, so ``/v1/admin/restore`` on the target consumes it
        verbatim).  Every other session keeps serving untouched."""
        name = body["name"]
        with self._lock:
            tb_name = self._session_toolbox.get(name)
        if tb_name is None:
            # in-process / checkpoint-restored session: reverse-map the
            # toolbox object exactly like h_drain
            sess = self.service.sessions().get(name)
            if sess is not None:
                rev = {id(tb): tn for tn, tb in self.toolboxes.items()}
                tb_name = rev.get(id(sess.toolbox))
        snap = self.service.export_session(
            name, timeout=body.get("timeout", 30.0))
        snap["toolbox"] = tb_name
        with self._lock:
            self._session_toolbox.pop(name, None)
        if self.verbose:
            emit_text(f"[serve.net] exported session {name!r} for "
                      "migration", self.sinks)
        return {"session": snap, "name": name}

    def h_cache_export(self, body: dict) -> dict:
        """``POST /v1/admin/cache/export`` — the fabric's pull side:
        locally inserted fitness rows journaled after cursor ``since``,
        re-keyed to portable (toolbox-name) namespaces.  Bounded by
        ``limit``; the new cursor rides back for the next exchange."""
        entries, seq = self.service.cache.export_since(
            int(body.get("since", 0)), int(body.get("limit", 256)))
        return {"entries": entries, "seq": seq}

    def h_cache_import(self, body: dict) -> dict:
        """``POST /v1/admin/cache/import`` — admit another instance's
        exported entries into this instance's fabric table."""
        return {"admitted":
                self.service.cache.import_entries(body["entries"])}

    def h_redirect(self, body: dict) -> dict:
        """Failover step 3 (optional): record where the drained sessions
        now live, so clients still pointed HERE get a typed redirect in
        the error envelope instead of a dead end.  ``{"url": null}``
        clears it.  With ``"session"`` in the body the redirect applies
        to that ONE session (what live migration leaves behind); it
        shadows the instance-wide target for that session's paths."""
        url = body.get("url")
        session = body.get("session")
        with self._lock:
            if session is None:
                self._redirect = None if url is None else str(url)
            elif url is None:
                self._session_redirects.pop(str(session), None)
            else:
                self._session_redirects[str(session)] = str(url)
        return {"location": url, "session": session}

    @property
    def redirect_location(self) -> Optional[str]:
        with self._lock:
            return self._redirect

    def redirect_for(self, session: Optional[str]) -> Optional[str]:
        """The redirect a stale client of ``session`` should follow: the
        session's own migration target when one is recorded, else the
        instance-wide drain target."""
        with self._lock:
            if session is not None:
                url = self._session_redirects.get(session)
                if url is not None:
                    return url
            return self._redirect


def _as_device(tree):
    """Decoded wire genome (numpy arrays in plain containers) → device
    arrays, container structure preserved (pytree genomes allowed)."""
    import jax
    return jax.tree_util.tree_map(jnp.asarray, tree)


def _rows_of(genome) -> int:
    import jax
    return jax.tree_util.tree_leaves(genome)[0].shape[0]


class _Handler(FrameHTTPHandler):
    """Routes one connection's requests into the :class:`NetServer`
    context.  Keep-alive HTTP/1.1 with explicit Content-Length (chunked
    only on the metrics stream); the wire plumbing — body read, byte
    counters, error envelopes, keep-alive drain — lives in
    :class:`~deap_tpu.serve.net.httpcommon.FrameHTTPHandler`, shared
    with the router's handler."""

    server_ctx: NetServer = None  # bound by NetServer
    log_prefix = "serve.net"

    # -- plumbing ------------------------------------------------------------

    def _handler_metrics(self):
        net = self.server_ctx
        return net.service.metrics if net is not None else None

    def _log_conf(self):
        net = self.server_ctx
        if net is None:
            return False, ()
        return net.verbose, net.sinks

    def _body(self) -> Any:
        net = self.server_ctx
        tracer = net.service.tracer if net is not None else None
        t0 = tracer.clock() if tracer is not None else 0.0
        data = self._read_raw_body()
        if not data:
            return {}
        if data[:4] == protocol.MAGIC:
            obj, meta = protocol.decode_frame_with_meta(data)
            trace_in = meta["trace"]
            # deadline-budget propagation: the frame header carries the
            # client's REMAINING budget (decremented at each upstream
            # hop); the effective deadline is the tighter of that and
            # whatever the body itself asks for, so a stale body field
            # can never extend a budget the hops already spent
            if meta["deadline"] is not None and isinstance(obj, dict):
                d = obj.get("deadline")
                obj["deadline"] = (meta["deadline"] if d is None
                                   else min(float(d), meta["deadline"]))
            # payload-compression negotiation: remember what the PEER
            # can inflate (response-side), and account an inbound
            # compressed frame's savings
            self._accept = tuple(dict.fromkeys(
                tuple(getattr(self, "_accept", ())) + tuple(meta["accept"])))
            if meta["compressed"]:
                net.service.metrics.inc("net_frames_compressed")
                net.service.metrics.inc(
                    "net_bytes_saved",
                    max(0, meta["payload_bytes"]
                        - meta["wire_payload_bytes"]))
        else:
            obj, trace_in = json.loads(data.decode("utf-8")), None
        if tracer is not None and trace_in is not None:
            # adopt the sender's context: this request's server-side span
            # (a child of the client hop), the wire-decode phase under
            # it, and the thread-local handoff service._submit picks its
            # per-request children from
            ctx = tracer.adopt(trace_in)
            if ctx is not None:
                self._trace_ctx = ctx
                self._trace_t0 = t0
                tracer.phase("wire_decode", ctx, t0, tracer.clock(),
                             attrs={"bytes": len(data)})
                fleettrace.set_current(ctx)
        return obj

    def _encode_response(self, obj: Any) -> bytes:
        """Encode a response frame, compressing the tensor payload when
        the request advertised a codec this build holds and the payload
        clears the server's size floor; savings feed ``net_bytes_saved``."""
        net = self.server_ctx
        codec = next((c for c in getattr(self, "_accept", ())
                      if c in protocol.WIRE_CODECS), None)
        payload, stats = protocol.encode_frame_ex(
            obj, compress=codec,
            min_compress_bytes=net.compress_min_bytes)
        saved = stats["payload_bytes"] - stats["wire_payload_bytes"]
        if saved > 0:
            net.service.metrics.inc("net_frames_compressed")
            net.service.metrics.inc("net_bytes_saved", saved)
        return payload

    def _send_obj(self, obj: Any, status: int = 200) -> None:
        tracer = self.server_ctx.service.tracer
        ctx = getattr(self, "_trace_ctx", None)
        if ctx is not None and tracer.enabled:
            t0 = tracer.clock()
            payload = self._encode_response(obj)
            self._send(payload, status=status)
            tracer.phase("response_encode", ctx, t0, tracer.clock(),
                         attrs={"bytes": len(payload)})
        else:
            self._send(self._encode_response(obj), status=status)

    def _send_error_obj(self, exc: BaseException) -> None:
        net = self.server_ctx
        net.service.metrics.inc("net_errors")
        # a drained instance that knows its replacement attaches the
        # typed redirect (draining rejections AND post-drain lookup
        # misses — the two shapes a stale client sees after failover);
        # a migrated session's OWN redirect wins over the instance-wide
        # one, so one hot tenant's move never bounces its neighbors
        location = (net.redirect_for(getattr(self, "_session_name", None))
                    if isinstance(exc, (ServiceDraining, SessionUnknown))
                    else None)
        self._send_error_envelope(exc, location=location)
        if protocol.status_of(exc) == 500:
            # 500 = an UNMAPPED exception — a service bug, not a protocol
            # outcome (draining/deadline envelopes stay quiet) — dump the
            # flight recorder for the postmortem (rate-limited inside
            # dump(), so an error storm costs one dump per window)
            net.service.tracer.dump(f"error:{type(exc).__name__}",
                                    net.sinks)

    def _route(self, method: str) -> None:
        net = self.server_ctx
        net.service.metrics.inc("net_requests")
        self._body_consumed = False
        self._trace_ctx = None
        self._trace_t0 = 0.0
        self._session_name = None
        # per-request negotiation state: a keep-alive connection serves
        # many requests, and a stale accept list would compress a reply
        # for a peer that did not advertise on THIS request.  The HTTP
        # header channel covers bodyless GETs (the full-population read
        # is the response most worth compressing); a frame body's
        # __accept__ list unions in via _body()
        hdr = self.headers.get(protocol.ACCEPT_HEADER, "")
        self._accept = tuple(c.strip() for c in hdr.split(",")
                             if c.strip())
        url = urlparse(self.path)
        parts = [p for p in url.path.split("/") if p]
        try:
            if parts[:1] != ["v1"]:
                raise SessionUnknown(f"unknown path {url.path!r}")
            rest = parts[1:]
            if method == "GET" and rest == ["healthz"]:
                return self._send_json(net.h_healthz())
            if method == "GET" and rest == ["toolboxes"]:
                return self._send_json(
                    {"toolboxes": sorted(net.toolboxes)})
            if method == "GET" and rest == ["metrics"]:
                return self._metrics(parse_qs(url.query))
            if method == "GET" and rest == ["trace"]:
                return self._trace_tail(parse_qs(url.query))
            if method == "GET" and rest == ["profile"]:
                return self._send_json(net.h_profile())
            if rest[:1] == ["sessions"]:
                if method == "POST" and len(rest) == 1:
                    return self._send_obj(net.h_create(self._body()))
                # names arrive percent-encoded (clients quote arbitrary
                # session names into the path)
                if len(rest) == 2:
                    self._session_name = unquote(rest[1])
                    if method == "GET":
                        return self._send_obj(
                            net.h_get_session(self._session_name))
                    if method == "DELETE":
                        return self._send_obj(
                            net.h_close_session(self._session_name))
                if method == "POST" and len(rest) == 3:
                    name, op = unquote(rest[1]), rest[2]
                    self._session_name = name
                    fn = {"step": net.h_step, "ask": net.h_ask,
                          "tell": net.h_tell,
                          "evaluate": net.h_evaluate}.get(op)
                    if fn is not None:
                        return self._send_obj(fn(name, self._body()))
            if method == "POST" and rest[:1] == ["admin"] and len(rest) == 2:
                fn = {"drain": net.h_drain, "restore": net.h_restore,
                      "rebucket": net.h_rebucket,
                      "redirect": net.h_redirect,
                      "migrate": net.h_migrate}.get(rest[1])
                if fn is not None:
                    return self._send_obj(fn(self._body()))
            if (method == "POST" and rest[:2] == ["admin", "cache"]
                    and len(rest) == 3):
                fn = {"export": net.h_cache_export,
                      "import": net.h_cache_import}.get(rest[2])
                if fn is not None:
                    return self._send_obj(fn(self._body()))
            raise SessionUnknown(f"unknown path {url.path!r}")
        except BrokenPipeError:
            raise
        except Exception as e:  # noqa: BLE001 — typed over the wire
            try:
                self._send_error_obj(e)
            except BrokenPipeError:
                pass
        finally:
            # close the request span and clear the thread-local handoff —
            # this handler thread serves many keep-alive requests, and a
            # stale context would misparent the NEXT request's spans
            ctx = getattr(self, "_trace_ctx", None)
            if ctx is not None:
                fleettrace.set_current(None)
                tracer = net.service.tracer
                tracer.record(f"http.{method} {url.path}", ctx,
                              self._trace_t0, tracer.clock())

    # -- metrics stream ------------------------------------------------------

    def _trace_tail(self, query: Dict[str, list]) -> None:
        """``GET /v1/trace`` — tail the service's span ring (the live
        window of the flight recorder): optional ``max`` span count and
        ``trace_id`` filter.  Plain JSON, curl-able beside /v1/metrics."""
        tracer = self.server_ctx.service.tracer
        n = int(query.get("max", ["256"])[0])
        trace_id = query.get("trace_id", [None])[0]
        self._send_json({"enabled": bool(tracer.enabled),
                         "dropped": tracer.dropped,
                         "spans": tracer.recent(n, trace_id=trace_id)})

    def _metrics(self, query: Dict[str, list]) -> None:
        net = self.server_ctx
        svc = net.service
        if query.get("format", [""])[0] == "prometheus":
            return self._send(
                prometheus_text(svc.stats()).encode("utf-8"),
                content_type="text/plain; version=0.0.4; charset=utf-8")
        if query.get("stream", ["0"])[0] not in ("1", "true"):
            return self._send_json(json.loads(svc.stats().to_json()))
        svc.metrics.inc("net_streams")
        max_records = int(query.get("max", ["10"])[0])
        timeout = float(query.get("timeout", ["30"])[0])
        self.send_response(200)
        self.send_header("Content-Type", "application/x-ndjson")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()

        def chunk(line: str) -> None:
            data = (line + "\n").encode("utf-8")
            self.wfile.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
            svc.metrics.inc("net_bytes_out", len(data))

        seen = -1
        per_wait = min(timeout, 1.0)
        deadline = timeout
        waited = 0.0
        emitted = 0
        try:
            while emitted < max_records:
                # Condition-based tail of service activity (no polling
                # sleep): emit a record whenever the batch counter moves,
                # give up after `timeout` quiet seconds
                now = svc.wait_for_activity(seen, timeout=per_wait)
                if now == seen:
                    waited += per_wait
                    if waited >= deadline:
                        break
                    continue
                waited = 0.0
                seen = now
                # per-batch records skip the per-program profile table
                # (per-scrape rebuild work the stream's consumers never
                # read); the one-shot GET stays the full view
                chunk(svc.stats(programs=False).to_json())
                emitted += 1
            self.wfile.write(b"0\r\n\r\n")
        except BrokenPipeError:
            pass
