""":class:`RemoteService` / :class:`RemoteSession` — the in-process
``Session`` API over the wire.

A ``RemoteSession`` mirrors :class:`deap_tpu.serve.service.Session`:
``step(n)`` returns ``n`` :class:`~deap_tpu.serve.dispatcher.ServeFuture`
objects, ``ask``/``tell``/``evaluate`` return one — the same shapes, the
same typed exceptions (rebuilt from the wire error envelope), the same
bitwise trajectories (pinned against in-process serving by
``tests/test_serve_net.py``).  Ordering is preserved the same way the
in-process dispatcher preserves it: one background worker thread owns the
session-mutating HTTP connection and sends requests strictly in
submission order, resolving futures as responses land.  ``step(n)``
travels as ONE request carrying ``n`` (a per-generation result list comes
back), so pipelined stepping costs one round trip per *call*, not per
generation.

Failover from the client's side is symmetric to the server's
drain/restore::

    snap = RemoteService(a_url).drain()      # instance A quiesces + snapshots
    b = RemoteService(b_url)
    b.restore(snap)                          # instance B adopts every session
    s = b.attach("run-0")                    # continue, bitwise

Synchronous reads (``population()``, ``stats()``, admin calls) use
per-call connections so they never queue behind a long step pipeline.
"""

from __future__ import annotations

import http.client
import json
import queue
import threading
import time
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple
from urllib.parse import quote

import numpy as np
import jax
import jax.numpy as jnp

from ... import sanitize
from ...base import Population, Fitness
from ...observability.fleettrace import FleetTracer
from ...observability.sinks import MetricRecord
from ...resilience.retry import with_retries, RetriesExhausted
from ..dispatcher import (DeadlineExceeded, ServeError, ServeFuture,
                          ServiceClosed)
from . import protocol

__all__ = ["RemoteService", "RemoteSession"]


def _parse_url(address) -> Tuple[str, str, int]:
    """``(scheme, host, port)`` of an address — tuple/list, bare
    ``host:port`` (scheme defaults to http), or an http(s) URL."""
    if isinstance(address, (tuple, list)):
        return "http", str(address[0]), int(address[1])
    addr = str(address)
    scheme = "http"
    for s in ("http", "https"):
        prefix = f"{s}://"
        if addr.startswith(prefix):
            scheme, addr = s, addr[len(prefix):]
            break
    addr = addr.rstrip("/")
    host, _, port = addr.rpartition(":")
    if not host:
        raise ValueError(f"address {address!r} needs host:port")
    return scheme, host, int(port)


def _parse_address(address) -> Tuple[str, int]:
    return _parse_url(address)[1:]


def _make_connection(host: str, port: int, *, timeout: float,
                     ssl_context=None) -> http.client.HTTPConnection:
    """One client connection; an ``ssl.SSLContext`` switches it to TLS
    (``HTTPSConnection`` — the context's verify mode/CA set governs how
    the server certificate is checked)."""
    if ssl_context is not None:
        return http.client.HTTPSConnection(host, port, timeout=timeout,
                                           context=ssl_context)
    return http.client.HTTPConnection(host, port, timeout=timeout)


class _Worker:
    """One thread + FIFO queue owning the ordered (session-mutating) HTTP
    connection — the client-side mirror of the dispatcher's single worker
    thread.  Jobs run strictly in submission order; a job's ``resolve``
    callback receives ``(result, exception)``."""

    #: lock-guarded shared state (``lock-discipline`` lint + runtime
    #: sanitizer): the failover retarget latch is written from any
    #: redirect-following thread and consumed by the worker
    _GUARDED_BY = {"_target_lock": ("_pending_target",)}

    def __init__(self, host: str, port: int, timeout: float,
                 request_timeout: Optional[float] = None,
                 retry_budget: int = 2, backoff: float = 0.05,
                 max_backoff: float = 2.0,
                 rng: Optional[Callable[[], float]] = None,
                 ssl_context=None):
        self._host, self._port, self._timeout = host, port, timeout
        self._ssl_context = ssl_context
        #: per-request response deadline (socket timeout on the ordered
        #: connection): a hung backend fails the ONE waiting future with
        #: typed DeadlineExceeded instead of blocking this worker thread
        #: forever; None falls back to the connection timeout
        self._request_timeout = request_timeout
        #: send-phase reconnect budget PER REQUEST: a request that never
        #: hit the wire may be re-sent at most this many times, each
        #: retry backed off exponentially with full jitter so a fleet of
        #: clients doesn't hammer a flapping backend in lockstep
        self._retry_budget = int(retry_budget)
        if self._retry_budget < 0:
            raise ValueError("retry_budget must be >= 0")
        self._backoff = float(backoff)
        self._max_backoff = float(max_backoff)
        self._rng = rng
        #: set by close(): interrupts any in-progress backoff nap so a
        #: closing client never waits out a retry schedule
        self._wake = sanitize.event()
        self._conn: Optional[http.client.HTTPConnection] = None
        self._jobs: "queue.Queue" = queue.Queue()
        self._closed = False
        # retargets land here from ANY thread (a _sync caller following
        # a redirect) and are applied by the worker thread itself at its
        # next _connection() — the worker owns the live connection, and
        # closing it cross-thread would kill a response mid-read
        self._target_lock = sanitize.lock()
        self._pending_target: Optional[Tuple[str, int]] = None
        self._thread = threading.Thread(target=self._run,
                                        name="deap-tpu-remote", daemon=True)
        self._thread.start()

    def retarget(self, host: str, port: int) -> None:
        """Point the ordered connection at a new instance (failover
        redirect).  Thread-safe: the new address is latched and the
        worker thread applies it — dropping its own connection — before
        its next request."""
        with self._target_lock:
            self._pending_target = (host, int(port))

    def submit(self, job: Callable, resolve: Callable) -> None:
        if self._closed:
            raise ServiceClosed("remote client is closed")
        self._jobs.put((job, resolve))

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._wake.set()          # abort any backoff nap in progress
            self._jobs.put(None)
            self._thread.join(timeout=10.0)
        if self._conn is not None:
            self._conn.close()
            self._conn = None

    def _connection(self) -> http.client.HTTPConnection:
        with self._target_lock:
            target, self._pending_target = self._pending_target, None
        if target is not None and target != (self._host, self._port):
            self._host, self._port = target
            self._drop_connection()
        if self._conn is None:
            t = (self._request_timeout if self._request_timeout is not None
                 else self._timeout)
            self._conn = _make_connection(self._host, self._port, timeout=t,
                                          ssl_context=self._ssl_context)
        return self._conn

    def _backoff_wait(self, delay: float) -> None:
        """Interruptible backoff nap between send-phase reconnects —
        an Event wait, never a blocking sleep, so close() aborts the
        schedule instead of waiting it out."""
        if self._wake.wait(delay):
            raise ServiceClosed("remote client closed during backoff")

    def _attempt(self, job: Callable) -> Any:
        """One send attempt; a send-phase failure drops the (poisoned)
        connection before propagating so the next attempt reconnects."""
        try:
            return job(self._connection())
        except _SendFailed:
            self._drop_connection()
            raise

    def _run(self) -> None:
        # the per-request send retry policy: only _SendFailed (request
        # provably never hit the wire) is retried — capped exponential
        # backoff with FULL jitter, at most retry_budget re-sends.  A
        # response-phase failure is never re-sent: the server may have
        # executed the request, and re-sending would double-apply it.
        while True:
            item = self._jobs.get()
            if item is None:
                while not self._jobs.empty():      # fail queued stragglers
                    tail = self._jobs.get()
                    if tail is not None:
                        tail[1](None, ServiceClosed("remote client closed"))
                return
            job, resolve = item
            send = with_retries(
                lambda: self._attempt(job), retries=self._retry_budget,
                backoff=self._backoff, max_backoff=self._max_backoff,
                jitter=True, rng=self._rng, retry_on=(_SendFailed,),
                sleep=self._backoff_wait)
            try:
                result = send()
            except RetriesExhausted as e:
                # every send attempt failed before reaching the wire —
                # surface the last transport error, budget spent
                resolve(None, e.last.cause
                        if isinstance(e.last, _SendFailed) else e.last)
                continue
            except TimeoutError as e:
                # the per-request deadline passed with no response: the
                # typed failure the serving stack already speaks.  The
                # connection is poisoned (a late response would answer
                # the WRONG request) — drop it; the worker moves on to
                # the next job instead of blocking forever
                self._drop_connection()
                resolve(None, DeadlineExceeded(
                    "no response from "
                    f"{self._host}:{self._port} within "
                    f"{self._request_timeout or self._timeout}s "
                    f"({e or 'socket timeout'})"))
                continue
            except (http.client.HTTPException, OSError) as e:
                # response-phase failure: the server MAY have executed the
                # request (a step/tell is not idempotent), so fail the
                # future instead of silently re-sending — the caller can
                # resync via population()/attach()
                self._drop_connection()
                resolve(None, e)
                continue
            except Exception as e:  # noqa: BLE001
                resolve(None, e)
                continue
            resolve(result, None)

    def _drop_connection(self) -> None:
        if self._conn is not None:
            self._conn.close()
            self._conn = None


class _SendFailed(Exception):
    """Transport failure BEFORE the request reached the wire — the server
    cannot have executed it, so a retry on a fresh connection is safe.
    (A response-phase failure is NOT retried: the server may already have
    applied a step/tell, and re-sending would silently double-apply.)"""

    def __init__(self, cause: BaseException):
        super().__init__(str(cause))
        self.cause = cause


def _request(conn: http.client.HTTPConnection, method: str, path: str,
             obj: Any = None, trace: Any = None,
             deadline: Optional[float] = None,
             compress: Optional[str] = None,
             accept: Tuple[str, ...] = ("zlib",)) -> Any:
    body = (None if obj is None
            else protocol.encode_frame(obj, trace=trace, deadline=deadline,
                                       compress=compress, accept=accept))
    headers = {"Content-Type": protocol.CONTENT_TYPE}
    if accept:
        # bodyless requests (population GETs — the responses most worth
        # compressing) advertise through the HTTP header channel
        headers[protocol.ACCEPT_HEADER] = ",".join(accept)
    try:
        conn.request(method, path, body=body, headers=headers)
    except (http.client.HTTPException, OSError) as e:
        # an incomplete HTTP request is never processed server-side
        raise _SendFailed(e)
    resp = conn.getresponse()
    data = resp.read()
    if resp.status >= 400:
        try:
            err = json.loads(data.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            raise ServeError(f"HTTP {resp.status}: {data[:200]!r}")
        exc = protocol.remote_exception(err.get("error", "ServeError"),
                                        err.get("message", ""))
        # a drained instance's envelope may carry the replacement's URL;
        # the caller (RemoteService) follows it — the rejected request
        # never executed, so a re-send cannot double-apply
        loc = err.get("location")
        if isinstance(loc, str) and loc:
            exc.remote_location = loc
        raise exc
    if not data:
        return None
    if data[:4] == protocol.MAGIC:
        return protocol.decode_frame(data)
    return json.loads(data.decode("utf-8"))


class RemoteService:
    """Client handle on one :class:`~deap_tpu.serve.net.server.NetServer`
    instance (see module docstring).  ``address`` is ``"host:port"``,
    ``(host, port)`` or an ``http://`` URL.

    ``request_timeout`` bounds each ordered request's wait for a
    response: a hung backend fails that ONE future with typed
    :class:`~deap_tpu.serve.dispatcher.DeadlineExceeded` (and the worker
    reconnects for the next job) instead of wedging the ordered pipeline
    forever.  ``compress="zlib"`` deflates outgoing tensor payloads (big
    tells/evaluates); the client always advertises what it can inflate,
    so servers compress responses regardless.  ``follow_redirects``
    (default on) makes the client transparently re-target when a drained
    instance's error envelope names the replacement — the failover moves
    without the caller seeing an exception.

    ``retry_budget`` caps how many times ONE request may be re-sent after
    a send-phase transport failure (the request provably never reached
    the wire); the re-sends back off exponentially with full jitter, so
    a flapping backend sees a bounded, de-synchronized retry stream
    instead of every client hammering it in lockstep."""

    def __init__(self, address, *, timeout: float = 600.0,
                 request_timeout: Optional[float] = None,
                 compress: Optional[str] = None,
                 follow_redirects: bool = True,
                 retry_budget: int = 2,
                 tracer: Optional[FleetTracer] = None,
                 ssl_context=None):
        scheme, self.host, self.port = _parse_url(address)
        #: TLS client side: an ``ssl.SSLContext`` governs certificate
        #: verification for every connection (ordered worker, per-call
        #: syncs, the metrics stream).  An ``https://`` address with no
        #: explicit context gets the stdlib default (system CAs,
        #: hostname verification on).
        if ssl_context is None and scheme == "https":
            import ssl as _ssl
            ssl_context = _ssl.create_default_context()
        self.ssl_context = ssl_context
        self.timeout = float(timeout)
        self.request_timeout = (None if request_timeout is None
                                else float(request_timeout))
        if compress is not None and compress not in protocol.WIRE_CODECS:
            raise ValueError(f"unknown wire codec {compress!r} "
                             f"(have {sorted(protocol.WIRE_CODECS)})")
        self.compress = compress
        self.follow_redirects = bool(follow_redirects)
        #: client-side span recorder: every ordered (session-mutating)
        #: request mints a root TraceContext here that rides the DTF1
        #: frame header, so the server's span tree links back to the
        #: client hop.  Pass FleetTracer(enabled=False) to opt out.
        self.tracer = tracer if tracer is not None else FleetTracer(
            capacity=1024)
        self._worker = _Worker(self.host, self.port, self.timeout,
                               request_timeout=self.request_timeout,
                               retry_budget=retry_budget,
                               ssl_context=ssl_context)
        self._closed = False

    # -- plumbing ------------------------------------------------------------

    def _redirect_target(self, exc: BaseException) -> Optional[Tuple[str,
                                                                     int]]:
        """(host, port) of the replacement instance a typed error names,
        when redirect-following applies."""
        loc = getattr(exc, "remote_location", None)
        if not self.follow_redirects or not loc:
            return None
        try:
            return _parse_address(loc)
        except ValueError:
            return None

    def _retarget(self, host: str, port: int) -> None:
        """Re-point this client at a replacement instance.  Called on
        the ordered worker thread (which owns the ordered connection) or
        from a _sync caller — either way the rejected request is about
        to be re-sent to the new address."""
        self.host, self.port = host, int(port)
        self._worker.retarget(host, port)

    def _sync(self, method: str, path: str, obj: Any = None) -> Any:
        """Out-of-band request on a fresh connection (never queues behind
        the ordered worker); follows at most one failover redirect."""
        for _hop in range(2):
            conn = _make_connection(self.host, self.port,
                                    timeout=self.timeout,
                                    ssl_context=self.ssl_context)
            try:
                return _request(conn, method, path, obj,
                                compress=self.compress)
            except ServeError as e:
                target = self._redirect_target(e)
                if target is None or _hop:
                    raise
                self._retarget(*target)
            finally:
                conn.close()

    def _ordered_raw(self, method: str, path: str, obj: Any,
                     resolve: Callable[[Any, Optional[BaseException]], None],
                     deadline: Optional[float] = None) -> None:
        """Queue one request on the ordered worker connection;
        ``resolve(result, exc)`` runs on the worker thread.  With tracing
        on, the request's root :class:`TraceContext` is minted HERE (at
        submission) and reused verbatim across the worker's send-phase
        reconnect retry — a retried request keeps its trace identity.
        ``deadline`` (seconds from now) becomes the request's deadline
        BUDGET: the time already burned waiting in the client queue (and
        across reconnect backoffs) is subtracted at send, so the header's
        ``__deadline__`` carries what actually remains."""
        ctx = self.tracer.context() if self.tracer.enabled else None
        t_submit = time.monotonic()

        def job(conn):
            t0 = self.tracer.clock() if ctx is not None else 0.0
            wire_ctx = None if ctx is None else ctx.wire()
            budget = (None if deadline is None else
                      max(0.0, float(deadline)
                          - (time.monotonic() - t_submit)))
            try:
                out = _request(conn, method, path, obj, trace=wire_ctx,
                               deadline=budget, compress=self.compress)
            except ServeError as e:
                # transparent redirect-on-failover: the drained instance
                # rejected this request (never executed) and named its
                # replacement — re-send there, keeping trace identity
                target = self._redirect_target(e)
                if target is None:
                    raise
                self._retarget(*target)
                budget = (None if deadline is None else
                          max(0.0, float(deadline)
                              - (time.monotonic() - t_submit)))
                out = _request(self._worker._connection(), method, path,
                               obj, trace=wire_ctx, deadline=budget,
                               compress=self.compress)
            if ctx is not None:
                self.tracer.record(f"client.{method} {path}", ctx, t0,
                                   self.tracer.clock())
            return out
        self._worker.submit(job, resolve)

    def _ordered(self, method: str, path: str, obj: Any,
                 on_result: Callable[[Any, ServeFuture], None] = None,
                 deadline: Optional[float] = None) -> ServeFuture:
        future = ServeFuture()

        def resolve(result, exc):
            if exc is not None:
                future._set_exception(exc)
            elif on_result is not None:
                on_result(result, future)
            else:
                future._set_result(result)

        self._ordered_raw(method, path, obj, resolve, deadline=deadline)
        return future

    # -- service surface -----------------------------------------------------

    def healthz(self) -> dict:
        return self._sync("GET", "/v1/healthz")

    def toolboxes(self) -> List[str]:
        return self._sync("GET", "/v1/toolboxes")["toolboxes"]

    def stats(self) -> MetricRecord:
        rec = self._sync("GET", "/v1/metrics")
        return MetricRecord(gen=rec["gen"], counters=rec["counters"],
                            gauges=rec["gauges"], meta=rec.get("meta", {}))

    def profile(self) -> dict:
        """``GET /v1/profile`` — the server's per-compiled-program
        device-phase profiles (``{"enabled", "programs": {key: ...}}``;
        see :class:`~deap_tpu.observability.profiling.ProgramProfiler`)."""
        return self._sync("GET", "/v1/profile")

    def trace_tail(self, *, max_spans: int = 256,
                   trace_id: Optional[str] = None) -> dict:
        """``GET /v1/trace`` — the server's recent span window
        (``{"enabled", "dropped", "spans": [...]}``), optionally filtered
        to one ``trace_id`` (e.g. a span's id from this client's own
        ``tracer.recent()``)."""
        path = f"/v1/trace?max={int(max_spans)}"
        if trace_id is not None:
            path += f"&trace_id={quote(str(trace_id), safe='')}"
        return self._sync("GET", path)

    def stream_metrics(self, *, max_records: int = 10,
                       timeout: float = 30.0) -> Iterator[MetricRecord]:
        """Tail the server's metrics stream: yields a
        :class:`MetricRecord` per service activity wave (chunked ND-JSON
        under the hood)."""
        conn = _make_connection(self.host, self.port, timeout=self.timeout,
                                ssl_context=self.ssl_context)
        try:
            conn.request("GET", f"/v1/metrics?stream=1&max={int(max_records)}"
                                f"&timeout={float(timeout)}")
            resp = conn.getresponse()
            if resp.status >= 400:
                raise ServeError(f"HTTP {resp.status} on metrics stream")
            while True:
                line = resp.readline()
                if not line:
                    return
                line = line.strip()
                if not line:
                    continue
                rec = json.loads(line.decode("utf-8"))
                yield MetricRecord(gen=rec["gen"], counters=rec["counters"],
                                   gauges=rec["gauges"],
                                   meta=rec.get("meta", {}))
        finally:
            conn.close()

    def open_session(self, key, population: Population, toolbox: str, *,
                     cxpb: float = 0.5, mutpb: float = 0.2,
                     name: Optional[str] = None,
                     tenant: Optional[str] = None,
                     evaluate_initial: bool = True) -> "RemoteSession":
        """Mirror of :meth:`EvolutionService.open_session`, with
        ``toolbox`` a *name* in the server's registry (functions don't
        travel).  ``tenant`` names the paying tenant for fleet-router
        admission (quotas + weighted-fair scheduling); a plain NetServer
        ignores it."""
        fit = population.fitness
        body = {"toolbox": str(toolbox),
                "key": _raw_key(key),
                "genome": _host_tree(population.genome),
                "weights": tuple(fit.weights),
                "cxpb": float(cxpb), "mutpb": float(mutpb),
                "evaluate_initial": bool(evaluate_initial)}
        if bool(np.asarray(fit.valid).any()):
            body["values"] = np.asarray(fit.values, np.float32)
            body["valid"] = np.asarray(fit.valid)
        if name is not None:
            body["name"] = str(name)
        if tenant is not None:
            body["tenant"] = str(tenant)
        out = self._sync("POST", "/v1/sessions", body)
        return RemoteSession(self, out["name"], gen=int(out["gen"]),
                             weights=tuple(fit.weights),
                             pop=int(out["pop"]))

    def attach(self, name: str) -> "RemoteSession":
        """Handle on a session that already lives server-side (opened by
        another client, or restored there by failover)."""
        info = self._sync("GET", f"/v1/sessions/{quote(name, safe='')}")
        return RemoteSession(self, name, gen=int(info["gen"]),
                             weights=tuple(info["weights"]),
                             pop=int(info["pop"]))

    # -- failover ------------------------------------------------------------

    def drain(self, timeout: float = 60.0) -> Dict[str, dict]:
        """Quiesce the instance and fetch its full session snapshot (the
        object :meth:`restore` feeds to the replacement instance)."""
        return self._sync("POST", "/v1/admin/drain",
                          {"timeout": float(timeout)})["sessions"]

    def restore(self, snapshot: Dict[str, dict]) -> List[str]:
        """Adopt a drained snapshot on this instance; returns the restored
        session names (attach with :meth:`attach`)."""
        return self._sync("POST", "/v1/admin/restore",
                          {"sessions": snapshot})["restored"]

    def rebucket(self, *, max_buckets: int = 8,
                 warm: tuple = ("step",)) -> dict:
        return self._sync("POST", "/v1/admin/rebucket",
                          {"max_buckets": int(max_buckets),
                           "warm": list(warm)})

    def close(self) -> None:
        """Close the client (the server and its sessions stay up)."""
        self._closed = True
        self._worker.close()

    def __enter__(self) -> "RemoteService":
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class RemoteSession:
    """Wire mirror of :class:`deap_tpu.serve.service.Session` — same
    future-based API, same typed failures, protocol state enforced
    server-side (an out-of-order ``tell`` fails its future with the same
    :class:`ServeError` the in-process session raises)."""

    def __init__(self, service: RemoteService, name: str, *, gen: int = 0,
                 weights: tuple = (), pop: Optional[int] = None):
        self._service = service
        self.name = name
        self.gen = int(gen)
        self.weights = tuple(weights)
        self._pop = pop           # population size never changes server-side
        self.closed = False

    def _path(self, op: str = "") -> str:
        # names are chosen by clients and may hold '/', spaces, '?', ... —
        # percent-encode so every name that create accepted stays routable
        base = f"/v1/sessions/{quote(self.name, safe='')}"
        return f"{base}/{op}" if op else base

    # -- request API (mirrors Session) ---------------------------------------

    def step(self, n: int = 1,
             deadline: Optional[float] = None) -> List[ServeFuture]:
        """Advance ``n`` generations; returns ``n`` futures resolving to
        ``{"gen", "nevals"}``.  One wire round trip for the whole call —
        the per-generation results fan back out onto the futures (a
        generation that failed server-side fails only its own future,
        exactly like in-process serving)."""
        futures = [ServeFuture() for _ in range(int(n))]

        def resolve(result, exc):
            if exc is not None:      # transport failure fails every gen
                for f in futures:
                    f._set_exception(exc)
                return
            for f, r in zip(futures, result["results"]):
                if "error" in r:
                    f._set_exception(protocol.remote_exception(
                        r["error"], r.get("message", "")))
                else:
                    self.gen = int(r["ok"]["gen"])
                    f._set_result(r["ok"])

        self._service._ordered_raw("POST", self._path("step"),
                                   {"n": int(n), "deadline": deadline},
                                   resolve, deadline=deadline)
        return futures

    def ask(self, deadline: Optional[float] = None) -> ServeFuture:
        """Resolves to the offspring genome rows awaiting external
        evaluation (host numpy, same bits the in-process ask returns)."""
        def keep_gen(result, future):
            self.gen = int(result["gen"])
            future._set_result(result["offspring"])
        return self._service._ordered("POST", self._path("ask"),
                                      {"deadline": deadline},
                                      on_result=keep_gen, deadline=deadline)

    def tell(self, values,
             deadline: Optional[float] = None) -> ServeFuture:
        def keep_gen(result, future):
            self.gen = int(result["ok"]["gen"])
            future._set_result(result["ok"])
        return self._service._ordered(
            "POST", self._path("tell"),
            {"values": np.asarray(values), "deadline": deadline},
            on_result=keep_gen, deadline=deadline)

    def evaluate(self, genomes,
                 deadline: Optional[float] = None) -> ServeFuture:
        def unwrap(result, future):
            future._set_result(result["values"])
        return self._service._ordered(
            "POST", self._path("evaluate"),
            {"genome": _host_tree(genomes), "deadline": deadline},
            on_result=unwrap, deadline=deadline)

    # -- introspection -------------------------------------------------------

    def population(self) -> Population:
        """Current population, fetched synchronously (mirrors the
        in-process accessor)."""
        info = self._service._sync("GET", self._path())
        self.gen = int(info["gen"])
        self._pop = int(info["pop"])
        return Population(
            genome=jax.tree_util.tree_map(jnp.asarray, info["genome"]),
            fitness=Fitness(values=jnp.asarray(info["values"], jnp.float32),
                            valid=jnp.asarray(info["valid"], bool),
                            weights=tuple(info["weights"])))

    @property
    def pop_size(self) -> int:
        # cached from create/attach — a session's size is immutable, and
        # the full-state GET would ship the whole population for one int
        if self._pop is None:
            self._pop = int(self._service._sync("GET", self._path())["pop"])
        return self._pop

    def close(self) -> None:
        if not self.closed:
            self.closed = True
            self._service._sync("DELETE", self._path())


def _raw_key(key) -> np.ndarray:
    key = jnp.asarray(key) if not isinstance(key, jax.Array) else key
    if jax.dtypes.issubdtype(key.dtype, jax.dtypes.prng_key):
        key = jax.random.key_data(key)
    return np.asarray(key).astype(np.uint32)


def _host_tree(tree):
    """Genome pytree → host numpy leaves, container structure preserved
    (what the frame codec serializes)."""
    return jax.tree_util.tree_map(np.asarray, tree)
