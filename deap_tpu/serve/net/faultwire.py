""":class:`FaultWire` — a fault-injecting TCP proxy on the DTF1 path.

A drill places one proxy in front of each backend and points the fleet
router at the proxies; every HTTP exchange relayed asks the drill's
:class:`~deap_tpu.resilience.chaos.ChaosInjector` which faults hit this
exchange and executes them on the real socket path — the router, client
and instance all experience genuine wire failures, not mocked
exceptions.  Fault semantics (direction-sensitive; ``"request"`` faults
provably never reach the upstream):

* ``partition`` / ``drop`` — the exchange's connection dies without the
  upstream seeing the request (``direction="response"``: the upstream
  executes, the reply never returns — the asymmetric half);
* ``wedge`` — wedge-after-headers: the proxy reads the full request and
  then goes silent (``response``: relays the request, returns only the
  response head, then stalls) until the peer gives up;
* ``delay`` — holds the exchange for ``seconds`` before relaying;
* ``throttle`` — relays the body at ``bytes_per_s``;
* ``truncate`` — cuts the body to ``frac`` of its bytes and rewrites
  ``Content-Length`` to match, producing a well-framed HTTP message
  carrying a truncated DTF1 frame (what
  :func:`~deap_tpu.serve.net.protocol.decode_frame` must reject with a
  typed ``ProtocolError``, not a struct crash);
* ``corrupt`` — XORs a 64-byte window in the middle of the body
  (length preserved);
* ``drip`` — relays the response ``chunk`` bytes per ``seconds``.

All waits are ``threading.Event`` waits on the proxy's stop event
(never ``time.sleep`` — the ``no-blocking-sleep`` gate covers this
package), so :meth:`close` interrupts every in-flight fault
immediately.
"""

from __future__ import annotations

import socket
import threading
from typing import Dict, List, Optional, Tuple

from ... import sanitize
from ...resilience.chaos import ChaosFault, ChaosInjector

__all__ = ["FaultWire"]

_CRLF2 = b"\r\n\r\n"


class _Message:
    """One parsed HTTP message: raw head bytes, lowercase header map,
    body bytes (for chunked bodies, the raw chunk framing — relayed
    verbatim, length-rewriting faults skip it)."""

    def __init__(self, head: bytes, headers: Dict[str, str], body: bytes,
                 chunked: bool):
        self.head = head
        self.headers = headers
        self.body = body
        self.chunked = chunked

    def serialize(self, body: Optional[bytes] = None) -> bytes:
        """Wire bytes; passing a REPLACEMENT body rewrites
        ``Content-Length`` to match (chunked messages relay as-is)."""
        if body is None or self.chunked:
            return self.head + self.body
        if body != self.body and not self.chunked:
            head = _rewrite_content_length(self.head, len(body))
            return head + body
        return self.head + body


def _rewrite_content_length(head: bytes, n: int) -> bytes:
    lines = head[:-len(_CRLF2)].split(b"\r\n")
    out = []
    for line in lines:
        if line.lower().startswith(b"content-length:"):
            out.append(b"Content-Length: " + str(n).encode())
        else:
            out.append(line)
    return b"\r\n".join(out) + _CRLF2


class _Reader:
    """Buffered HTTP-message reader over one socket, interruptible by
    the proxy's stop event (short socket timeouts, re-checked per
    recv)."""

    def __init__(self, sock: socket.socket, stop: threading.Event):
        self.sock = sock
        self.buf = b""
        self._stop = stop
        sock.settimeout(0.25)

    def _fill(self) -> bool:
        """One recv into the buffer; False on EOF/stop/error."""
        while not self._stop.is_set():
            try:
                data = self.sock.recv(65536)
            except socket.timeout:
                continue
            except OSError:
                return False
            if not data:
                return False
            self.buf += data
            return True
        return False

    def _until(self, marker: bytes) -> Optional[int]:
        while marker not in self.buf:
            if not self._fill():
                return None
        return self.buf.index(marker)

    def _take(self, n: int) -> Optional[bytes]:
        while len(self.buf) < n:
            if not self._fill():
                return None
        out, self.buf = self.buf[:n], self.buf[n:]
        return out

    def read_message(self) -> Optional[_Message]:
        """The next complete message, or ``None`` on EOF/stop.  Chunked
        bodies are consumed up to the terminal ``0\\r\\n\\r\\n`` and
        kept as raw framing (our servers send no trailers)."""
        end = self._until(_CRLF2)
        if end is None:
            return None
        head = self._take(end + len(_CRLF2))
        headers: Dict[str, str] = {}
        for line in head[:-len(_CRLF2)].split(b"\r\n")[1:]:
            k, _, v = line.partition(b":")
            headers[k.strip().lower().decode("latin-1")] = \
                v.strip().decode("latin-1")
        chunked = "chunked" in headers.get("transfer-encoding", "").lower()
        if chunked:
            end = self._until(b"0\r\n\r\n")
            if end is None:
                return None
            body = self._take(end + 5)
            if body is None:
                return None
            return _Message(head, headers, body, chunked=True)
        n = int(headers.get("content-length", "0") or "0")
        body = self._take(n) if n else b""
        if body is None:
            return None
        return _Message(head, headers, body, chunked=False)


class FaultWire:
    """Fault-injecting HTTP relay in front of one backend (see module
    docstring).

    ``target`` is this proxy's name in the injector's plan;
    ``upstream`` is the fronted instance's ``(host, port)``.  The
    proxy listens on ``(host, port)`` (``port=0`` picks a free one —
    read :attr:`address` back and point the router's
    :class:`~deap_tpu.serve.router.backend.Backend` at it)."""

    #: lock-guarded shared state: the live-socket set is written by the
    #: accept loop and every relay thread, and swept by close()
    _GUARDED_BY = {"_lock": ("_conns",)}

    def __init__(self, upstream: Tuple[str, int], target: str,
                 injector: ChaosInjector, *, host: str = "127.0.0.1",
                 port: int = 0):
        self.upstream = (str(upstream[0]), int(upstream[1]))
        self.target = str(target)
        self.injector = injector
        self._stop = threading.Event()
        self._lock = sanitize.lock()
        self._conns: set = set()
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(64)
        self._listener.settimeout(0.25)
        self._threads: List[threading.Thread] = []
        self._accept_thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------------

    @property
    def address(self) -> Tuple[str, int]:
        return self._listener.getsockname()[:2]

    @property
    def url(self) -> str:
        host, port = self.address
        return f"http://{host}:{port}"

    def start(self) -> "FaultWire":
        if self._accept_thread is None:
            self._accept_thread = threading.Thread(
                target=self._accept_loop,
                name=f"deap-tpu-faultwire-{self.target}", daemon=True)
            self._accept_thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns)
        for sock in conns:
            try:
                sock.close()
            except OSError:
                pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5.0)
            self._accept_thread = None
        for t in self._threads:
            t.join(timeout=5.0)

    def __enter__(self) -> "FaultWire":
        return self.start()

    def __exit__(self, *exc):
        self.close()
        return False

    # -- relay ---------------------------------------------------------------

    def _track(self, sock: socket.socket) -> None:
        with self._lock:
            self._conns.add(sock)

    def _untrack(self, sock: Optional[socket.socket]) -> None:
        if sock is None:
            return
        with self._lock:
            self._conns.discard(sock)
        try:
            sock.close()
        except OSError:
            pass

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                client, _addr = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            self._track(client)
            t = threading.Thread(target=self._relay, args=(client,),
                                 name=f"deap-tpu-faultwire-{self.target}-c",
                                 daemon=True)
            self._threads.append(t)
            t.start()

    def _relay(self, client: socket.socket) -> None:
        upstream: Optional[socket.socket] = None
        up_reader: Optional[_Reader] = None
        cl_reader = _Reader(client, self._stop)
        try:
            while not self._stop.is_set():
                req = cl_reader.read_message()
                if req is None:
                    return
                faults = self.injector.decide(self.target,
                                              _exchange_class(req.head))
                req_faults = [f for f in faults
                              if f.leg.direction in ("request", "both")]
                resp_faults = [f for f in faults
                               if f.leg.direction in ("response", "both")]
                body = req.body
                for f in req_faults:
                    kind = f.leg.kind
                    if kind in ("partition", "drop"):
                        return          # upstream never sees the request
                    if kind == "wedge":
                        # wedge-after-headers: the request was read, the
                        # upstream never hears of it; hold the line dead
                        # until the peer (or the drill) gives up
                        self._stop.wait(float(f.leg.param("seconds", 60.0)))
                        return
                    if kind == "delay":
                        if self._stop.wait(
                                float(f.leg.param("seconds", 0.05))):
                            return
                    elif kind == "truncate" and not req.chunked and body:
                        body = body[:int(len(body)
                                         * float(f.leg.param("frac", 0.5)))]
                    elif kind == "corrupt":
                        body = _corrupt(body, int(f.leg.param("xor", 0xFF)))
                if upstream is None:
                    upstream = socket.create_connection(self.upstream,
                                                        timeout=10.0)
                    self._track(upstream)
                    up_reader = _Reader(upstream, self._stop)
                throttle = next((f for f in req_faults
                                 if f.leg.kind == "throttle"), None)
                if not self._send(upstream, req.serialize(body), throttle):
                    return
                resp = up_reader.read_message()
                if resp is None:
                    return
                if not self._relay_response(client, resp, resp_faults):
                    return
                if "close" in resp.headers.get("connection", "").lower():
                    return
        finally:
            self._untrack(client)
            self._untrack(upstream)

    def _relay_response(self, client: socket.socket, resp: _Message,
                        faults: List[ChaosFault]) -> bool:
        body = resp.body
        for f in faults:
            kind = f.leg.kind
            if kind in ("partition", "drop"):
                # asymmetric half: the upstream DID execute; the reply
                # dies on the return path
                return False
            if kind == "wedge":
                if not self._send(client, resp.head, None):
                    return False
                self._stop.wait(float(f.leg.param("seconds", 60.0)))
                return False
            if kind == "delay":
                if self._stop.wait(float(f.leg.param("seconds", 0.05))):
                    return False
            elif kind == "truncate" and not resp.chunked and body:
                body = body[:int(len(body)
                                 * float(f.leg.param("frac", 0.5)))]
            elif kind == "corrupt":
                body = _corrupt(body, int(f.leg.param("xor", 0xFF)))
            elif kind == "drip":
                if not self._send(client, resp.serialize(body), None,
                                  chunk=int(f.leg.param("chunk", 256)),
                                  pace_s=float(f.leg.param("seconds",
                                                           0.01))):
                    return False
                return True
        throttle = next((f for f in faults if f.leg.kind == "throttle"),
                        None)
        return self._send(client, resp.serialize(body), throttle)

    def _send(self, sock: socket.socket, data: bytes,
              throttle: Optional[ChaosFault], *, chunk: int = 0,
              pace_s: float = 0.0) -> bool:
        """Write ``data``, optionally bandwidth-throttled or dripped in
        fixed chunks; False on peer loss or proxy stop."""
        if throttle is not None:
            bps = max(1.0, float(throttle.leg.param("bytes_per_s", 65536)))
            chunk, pace_s = max(1, int(bps * 0.05)), 0.05
        try:
            if chunk <= 0:
                sock.sendall(data)
                return True
            for i in range(0, len(data), chunk):
                sock.sendall(data[i:i + chunk])
                if i + chunk < len(data) and self._stop.wait(pace_s):
                    return False
            return True
        except OSError:
            return False


def _exchange_class(head: bytes) -> str:
    """``"data"`` for session-plane requests, ``"control"`` for
    healthz/metrics/trace/admin — what a leg's ``scope`` matches, so a
    plan can build gray failures (data path broken, control plane
    polite) or full partitions (both)."""
    line = head.split(b"\r\n", 1)[0]
    parts = line.split(b" ")
    path = parts[1] if len(parts) > 1 else b""
    return "data" if path.startswith(b"/v1/sessions") else "control"


def _corrupt(body: bytes, xor: int) -> bytes:
    """XOR a 64-byte window in the middle of the body (length
    preserved) — far enough in to hit a DTF1 tensor payload on large
    frames and the header JSON on small ones; either way the receiver
    must fail TYPED, never crash."""
    if not body:
        return body
    i = len(body) // 2
    window = bytes(b ^ (xor & 0xFF) for b in body[i:i + 64])
    return body[:i] + window + body[i + len(window):]
