"""Wire format of the serving frontend: JSON control + raw tensor framing.

Every request/response body is one **frame**::

    MAGIC(4) | header_len:u32le | header JSON (utf-8) | tensor payloads...

The header is an arbitrary JSON document in which tensors appear as
``{"__tensor__": i}`` placeholders; slot ``i`` of the header's
``"__tensors__"`` manifest records ``(dtype, shape)`` and the payloads
follow the header back-to-back in slot order as raw little-endian
contiguous bytes.  Encoding is bit-exact for every array dtype the
framework serves (float32/16, bfloat16 via its uint16 bit view, ints,
bools) — fitness and genomes survive a round trip bitwise, which the
failover drill depends on.  Python tuples are tagged (``"__tuple__"``)
so objective ``weights`` come back hashable, and ``bytes`` values ride as
base64 (``"__bytes__"``).

No pickle anywhere on the wire: a frame can describe only JSON scalars,
containers and typed arrays, so a malicious peer can at worst send wrong
numbers, not code.

**Payload compression** (negotiated, optional): a sender may zlib the
concatenated tensor payload section — at pop=10⁶/dim=100 a single tell
is ~400 MB raw — marking the frame header with ``"__zip__": "zlib"``;
the decoder inflates before slicing, so arrays round-trip **bit-exact**
(zlib is lossless — NaN payloads and signed zeros included, pinned by
test).  Negotiation rides the header too: a request that advertises
``"__accept__": ["zlib"]`` invites the responder to compress its reply;
a peer that never advertises never receives a compressed frame, and a
legacy decoder that ignores both keys still decodes every UNcompressed
frame identically.  The router forwards frames verbatim (payload bytes
untouched), so end-to-end compression survives the extra hop.

Error mapping: service-layer exceptions travel as
``{"error": <class name>, "message": ...}`` JSON with a matching HTTP
status (:data:`ERROR_STATUS`); :func:`remote_exception` rebuilds the
typed exception on the client so ``RemoteSession`` raises exactly what
the in-process ``Session`` would.  A draining instance that knows where
its sessions went may add ``"location"`` to the envelope — the typed
redirect the client follows transparently on failover.
"""

from __future__ import annotations

import base64
import json
import struct
import zlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..dispatcher import (ServeError, ServiceClosed, ServiceOverloaded,
                          DeadlineExceeded, RequestCancelled,
                          ServiceDraining, SessionUnknown,
                          TenantQuotaExceeded, CircuitOpen, ServiceBrownout)
from ..buckets import BucketOverflow

__all__ = ["MAGIC", "CONTENT_TYPE", "ACCEPT_HEADER", "encode_frame",
           "encode_frame_ex", "decode_frame", "decode_frame_with_trace",
           "decode_frame_with_meta", "rewrite_trace", "rewrite_header",
           "status_of", "error_payload", "remote_exception", "ERROR_STATUS",
           "ProtocolError", "WIRE_CODECS"]


class ProtocolError(ServeError, ValueError):
    """A frame that violates the DTF1 wire format: bad magic, truncated
    header, or a tensor manifest whose declared byte lengths exceed the
    remaining body.  Subclasses :class:`ValueError` so pre-existing
    ``except ValueError`` edges still catch it, and :class:`ServeError`
    so it travels the typed error envelope (status 400) instead of
    crashing the handler with a struct unpack error."""

#: payload codecs this build can negotiate (name -> (deflate, inflate))
WIRE_CODECS = {"zlib": (zlib.compress, zlib.decompress)}


def _inflate_zlib_bounded(data: bytes, max_bytes: int) -> bytes:
    """Inflate at most ``max_bytes`` (+1 sentinel byte) of output — the
    decompression-bomb guard: a frame's payload may never expand past
    what its own tensor manifest accounts for, so a few-MB frame cannot
    allocate gigabytes before the manifest size check runs."""
    d = zlib.decompressobj()
    out = d.decompress(data, max_bytes + 1)
    if len(out) > max_bytes:
        raise ValueError(
            f"compressed payload inflates past the {max_bytes} bytes its "
            "tensor manifest declares (rejecting decompression bomb)")
    return out


#: decode-side inflate per codec, bounded by the manifest's declared
#: byte total (the compress side stays the plain function in
#: :data:`WIRE_CODECS`)
_INFLATE_BOUNDED = {"zlib": _inflate_zlib_bounded}

#: HTTP request header carrying the sender's acceptable payload codecs —
#: the negotiation channel for BODYLESS requests (a GET of a session's
#: full population is exactly the response most worth compressing, and
#: has no frame to advertise in).  Comma-separated codec names; the
#: frame-header ``__accept__`` list and this header are unioned.
ACCEPT_HEADER = "X-DTF-Accept"

MAGIC = b"DTF1"
CONTENT_TYPE = "application/x-deap-frame"

_HEAD = struct.Struct("<I")


def _to_array(x) -> np.ndarray:
    # jax.Array reaches here via __array__; ascontiguousarray also
    # collapses any host view weirdness so tobytes() is the row-major bits
    return np.ascontiguousarray(np.asarray(x))


def _pack(obj: Any, tensors: List[np.ndarray]) -> Any:
    if isinstance(obj, dict):
        bad = [k for k in obj if not isinstance(k, str)]
        if bad:
            # silently stringifying keys would rewrite a pytree genome's
            # structure server-side; fail at the edge instead
            raise TypeError(
                f"wire frames require str dict keys, got {bad[:3]!r}")
        return {k: _pack(v, tensors) for k, v in obj.items()}
    if isinstance(obj, tuple):
        return {"__tuple__": [_pack(v, tensors) for v in obj]}
    if isinstance(obj, list):
        return [_pack(v, tensors) for v in obj]
    if isinstance(obj, bytes):
        return {"__bytes__": base64.b64encode(obj).decode("ascii")}
    if isinstance(obj, (bool, int, float, str)) or obj is None:
        return obj
    if isinstance(obj, (np.bool_, np.integer, np.floating)):
        return obj.item()
    if hasattr(obj, "__array__") or isinstance(obj, np.ndarray):
        a = _to_array(obj)
        if a.dtype == object:
            raise TypeError("object arrays are not wire-encodable")
        tensors.append(a)
        return {"__tensor__": len(tensors) - 1}
    raise TypeError(f"cannot wire-encode {type(obj).__name__}")


def _unpack(obj: Any, tensors: List[np.ndarray]) -> Any:
    if isinstance(obj, dict):
        if "__tensor__" in obj and len(obj) == 1:
            return tensors[obj["__tensor__"]]
        if "__tuple__" in obj and len(obj) == 1:
            return tuple(_unpack(v, tensors) for v in obj["__tuple__"])
        if "__bytes__" in obj and len(obj) == 1:
            return base64.b64decode(obj["__bytes__"])
        return {k: _unpack(v, tensors) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_unpack(v, tensors) for v in obj]
    return obj


def _dtype_token(dt: np.dtype) -> str:
    """Wire name of a dtype: the byte-order-explicit ``str`` form for
    native numpy dtypes, the registered NAME for extension dtypes
    (bfloat16, float8_*, ... — their ``str`` is an opaque void like
    ``<V2`` that would not round-trip)."""
    if dt.kind == "V":
        return dt.name
    return dt.str


def _dtype_of(token: str) -> np.dtype:
    if token and token[0] in "<>|=":
        return np.dtype(token).newbyteorder("<")
    import ml_dtypes
    try:
        return np.dtype(getattr(ml_dtypes, token))
    except (AttributeError, TypeError):
        raise ValueError(f"unknown wire dtype {token!r}")


def encode_frame_ex(obj: Any, trace: Any = None, *,
                    deadline: Optional[float] = None,
                    compress: Optional[str] = None,
                    accept: Tuple[str, ...] = (),
                    min_compress_bytes: int = 4096
                    ) -> Tuple[bytes, Dict[str, int]]:
    """Encode a frame and report its payload accounting.

    Returns ``(frame_bytes, stats)`` with ``stats["payload_bytes"]`` the
    raw tensor-payload size and ``stats["wire_payload_bytes"]`` what
    actually hit the wire — their difference feeds the server's
    ``net_bytes_saved`` counter.  ``compress`` names a
    :data:`WIRE_CODECS` codec to deflate the payload section with
    (applied only when the raw payload reaches ``min_compress_bytes`` —
    deflating a 100-byte ask header costs more than it saves); ``accept``
    advertises the codecs THIS peer can inflate, inviting the responder
    to compress its reply.  ``deadline`` (optional, seconds) is the
    sender's REMAINING deadline budget, stored in the header under
    ``"__deadline__"`` — every forwarding hop subtracts its own dwell
    time (:func:`rewrite_header`) so the terminal dispatcher sees the
    true budget left, not the budget the client started with."""
    tensors: List[np.ndarray] = []
    body = _pack(obj, tensors)
    header = {"body": body,
              "__tensors__": [{"dtype": _dtype_token(a.dtype),
                               "shape": list(a.shape)}
                              for a in tensors]}
    if trace is not None:
        header["__trace__"] = trace
    if deadline is not None:
        header["__deadline__"] = float(deadline)
    if accept:
        header["__accept__"] = [c for c in accept if c in WIRE_CODECS]
    payload_parts = []
    for a in tensors:
        if a.dtype.kind == "V":
            # extension dtypes (bfloat16 & friends) carry their raw bits;
            # single-byte-lane or little-endian hosts only — every
            # supported platform (x86/ARM/TPU hosts) is little-endian
            payload_parts.append(a.tobytes())
        else:
            # canonical little-endian payload, whatever the host order
            payload_parts.append(
                a.astype(a.dtype.newbyteorder("<"), copy=False).tobytes())
    payload = b"".join(payload_parts)
    raw_bytes = len(payload)
    if (compress is not None and compress in WIRE_CODECS
            and raw_bytes >= int(min_compress_bytes)):
        deflated = WIRE_CODECS[compress][0](payload)
        if len(deflated) < raw_bytes:   # incompressible data ships raw
            header["__zip__"] = compress
            payload = deflated
    hdr = json.dumps(header, allow_nan=True).encode("utf-8")
    frame = b"".join([MAGIC, _HEAD.pack(len(hdr)), hdr, payload])
    return frame, {"payload_bytes": raw_bytes,
                   "wire_payload_bytes": len(payload)}


def encode_frame(obj: Any, trace: Any = None, *,
                 deadline: Optional[float] = None,
                 compress: Optional[str] = None,
                 accept: Tuple[str, ...] = (),
                 min_compress_bytes: int = 4096) -> bytes:
    """Encode a JSON-plus-arrays object tree into one wire frame.

    ``trace`` (optional) is a small JSON-safe dict — the
    :meth:`~deap_tpu.observability.fleettrace.TraceContext.wire` form —
    stored in the frame HEADER under ``"__trace__"``, beside the tensor
    manifest: request tracing is header metadata, invisible to the body
    the decoder hands back (a peer that ignores it decodes identically).
    ``deadline`` is the remaining deadline budget in seconds
    (``"__deadline__"`` header — see :func:`encode_frame_ex`);
    ``compress``/``accept`` are the payload-compression negotiation
    (see :func:`encode_frame_ex`, which also reports bytes saved)."""
    return encode_frame_ex(obj, trace, deadline=deadline, compress=compress,
                           accept=accept,
                           min_compress_bytes=min_compress_bytes)[0]


def _split_header(data: bytes) -> Tuple[dict, int]:
    """Parse and validate the frame prefix; returns ``(header dict,
    payload offset)``."""
    if len(data) < 8 or data[:4] != MAGIC:
        raise ProtocolError("not a deap-tpu wire frame (bad magic)")
    (hlen,) = _HEAD.unpack_from(data, 4)
    hdr_end = 8 + hlen
    if len(data) < hdr_end:
        raise ProtocolError(
            f"truncated frame header: header declares {hlen} bytes, "
            f"{len(data) - 8} present")
    try:
        header = json.loads(data[8:hdr_end].decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        # a corrupted-on-the-wire header must surface as the typed
        # protocol error, not a bare json traceback in the handler
        raise ProtocolError(f"undecodable frame header: {e}") from e
    if not isinstance(header, dict):
        raise ProtocolError("frame header is not a JSON object")
    return header, hdr_end


def decode_frame(data: bytes) -> Any:
    """Decode :func:`encode_frame` output back into the object tree
    (arrays come back as numpy, bitwise equal to what was encoded)."""
    return decode_frame_with_meta(data)[0]


def decode_frame_with_trace(data: bytes):
    """Like :func:`decode_frame`, additionally returning the frame
    header's ``"__trace__"`` dict (``None`` when the sender attached no
    trace context) — what the server handler adopts request spans
    from."""
    obj, meta = decode_frame_with_meta(data)
    return obj, meta["trace"]


def decode_frame_with_meta(data: bytes) -> Tuple[Any, Dict[str, Any]]:
    """Full decode: ``(object tree, meta)`` where ``meta`` carries the
    header's negotiation state — ``trace`` (adopted by the server
    handler), ``accept`` (codecs the sender can inflate, so the responder
    knows whether it may compress its reply), ``compressed`` (codec name
    or ``None``), and the ``payload_bytes``/``wire_payload_bytes`` pair
    the byte-savings counters are computed from."""
    header, off = _split_header(data)
    codec = header.get("__zip__")
    wire_payload = len(data) - off
    # manifest first: its declared byte total bounds the inflate below
    specs: List[tuple] = []
    declared = 0
    for spec in header.get("__tensors__", ()):
        dt = _dtype_of(spec["dtype"])
        shape = tuple(int(s) for s in spec["shape"])
        nbytes = dt.itemsize * int(np.prod(shape, dtype=np.int64))
        if nbytes < 0:
            raise ValueError("negative tensor extent in manifest")
        specs.append((dt, shape, nbytes))
        declared += nbytes
    if codec is None and declared > wire_payload:
        # reject BEFORE touching any tensor bytes: the manifest promises
        # more payload than the body carries (a frame cut mid-flight),
        # and trusting it would hand np.frombuffer an out-of-bounds read
        raise ProtocolError(
            f"truncated frame: tensor manifest declares {declared} "
            f"payload bytes but only {wire_payload} remain in the body")
    if codec is not None:
        if codec not in WIRE_CODECS:
            raise ValueError(f"unknown payload codec {codec!r}")
        payload = _INFLATE_BOUNDED[codec](data[off:], declared)
        off = 0
    else:
        payload = data
    start = off
    tensors: List[np.ndarray] = []
    for dt, shape, nbytes in specs:
        if off + nbytes > len(payload):
            raise ProtocolError(
                f"truncated tensor payload: slot needs {nbytes} bytes, "
                f"{len(payload) - off} remain")
        a = np.frombuffer(payload, dtype=dt, count=nbytes // dt.itemsize,
                          offset=off)
        a = a.reshape(shape)
        if dt.kind != "V":
            a = a.astype(dt.newbyteorder("="), copy=True)
        else:
            a = a.copy()
        tensors.append(a)
        off += nbytes
    if off != len(payload):
        raise ValueError(f"{len(payload) - off} trailing bytes after "
                         "tensors")
    trace = header.get("__trace__")
    accept = tuple(c for c in header.get("__accept__", ())
                   if isinstance(c, str))
    deadline = header.get("__deadline__")
    return _unpack(header["body"], tensors), {
        "trace": trace if isinstance(trace, dict) else None,
        "accept": accept,
        "compressed": codec,
        "deadline": (float(deadline)
                     if isinstance(deadline, (int, float))
                     and not isinstance(deadline, bool) else None),
        "payload_bytes": off - start,
        "wire_payload_bytes": wire_payload,
    }


#: sentinel distinguishing "leave this header key alone" from an
#: explicit ``None`` (which strips the key) in :func:`rewrite_header`
_KEEP = object()


def rewrite_header(data: bytes, *, trace: Any = _KEEP,
                   deadline: Any = _KEEP) -> bytes:
    """Rewrite a frame's metadata header keys IN PLACE of the old ones,
    leaving the tensor payload bytes untouched — how the router edits
    its hop into a possibly-huge (possibly-compressed) frame without
    ever decoding the tensors.  ``trace`` replaces ``"__trace__"`` and
    ``deadline`` (seconds of remaining budget) replaces
    ``"__deadline__"``; passing ``None`` strips the key, omitting the
    argument keeps whatever the frame carried.  One re-serialize covers
    every edited key, so the trace hop and the deadline decrement cost a
    single header rewrite at the router."""
    header, off = _split_header(data)
    for key, value in (("__trace__", trace), ("__deadline__", deadline)):
        if value is _KEEP:
            continue
        if value is None:
            header.pop(key, None)
        elif key == "__deadline__":
            header[key] = float(value)
        else:
            header[key] = value
    hdr = json.dumps(header, allow_nan=True).encode("utf-8")
    return b"".join([MAGIC, _HEAD.pack(len(hdr)), hdr, data[off:]])


def rewrite_trace(data: bytes, trace: Any) -> bytes:
    """Replace (or insert/remove) a frame's ``"__trace__"`` header,
    payload untouched (:func:`rewrite_header` with only ``trace``).
    ``trace=None`` strips the header."""
    return rewrite_header(data, trace=trace)


# ---------------------------------------------------------------------------
# error mapping
# ---------------------------------------------------------------------------

#: service exception class -> HTTP status (client rebuilds by class name)
ERROR_STATUS: Dict[type, int] = {
    SessionUnknown: 404,
    BucketOverflow: 413,
    TenantQuotaExceeded: 429,
    ServiceBrownout: 429,
    ServiceOverloaded: 429,
    RequestCancelled: 409,
    DeadlineExceeded: 504,
    CircuitOpen: 503,
    ServiceDraining: 503,
    ServiceClosed: 503,
    ProtocolError: 400,
    ServeError: 409,
    ValueError: 400,
    KeyError: 400,
    TypeError: 400,
}

_BY_NAME = {cls.__name__: cls for cls in ERROR_STATUS}


def status_of(exc: BaseException) -> int:
    for cls, status in ERROR_STATUS.items():
        if isinstance(exc, cls):
            return status
    return 500


def error_payload(exc: BaseException,
                  location: Optional[str] = None) -> bytes:
    """The JSON error envelope.  ``location`` (optional) is the typed
    redirect a drained instance attaches once it knows where its
    sessions were restored — :class:`RemoteService` re-targets and
    retries transparently (safe: the erroring instance rejected the
    request before executing it)."""
    doc = {"error": type(exc).__name__, "message": str(exc)}
    if location:
        doc["location"] = str(location)
    return json.dumps(doc).encode("utf-8")


def remote_exception(name: str, message: str) -> BaseException:
    """Rebuild the typed service exception a peer reported; unknown
    classes degrade to :class:`ServeError` with the name prefixed."""
    cls = _BY_NAME.get(name)
    if cls is None:
        return ServeError(f"{name}: {message}")
    return cls(message)
