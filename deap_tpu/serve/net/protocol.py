"""Wire format of the serving frontend: JSON control + raw tensor framing.

Every request/response body is one **frame**::

    MAGIC(4) | header_len:u32le | header JSON (utf-8) | tensor payloads...

The header is an arbitrary JSON document in which tensors appear as
``{"__tensor__": i}`` placeholders; slot ``i`` of the header's
``"__tensors__"`` manifest records ``(dtype, shape)`` and the payloads
follow the header back-to-back in slot order as raw little-endian
contiguous bytes.  Encoding is bit-exact for every array dtype the
framework serves (float32/16, bfloat16 via its uint16 bit view, ints,
bools) — fitness and genomes survive a round trip bitwise, which the
failover drill depends on.  Python tuples are tagged (``"__tuple__"``)
so objective ``weights`` come back hashable, and ``bytes`` values ride as
base64 (``"__bytes__"``).

No pickle anywhere on the wire: a frame can describe only JSON scalars,
containers and typed arrays, so a malicious peer can at worst send wrong
numbers, not code.

Error mapping: service-layer exceptions travel as
``{"error": <class name>, "message": ...}`` JSON with a matching HTTP
status (:data:`ERROR_STATUS`); :func:`remote_exception` rebuilds the
typed exception on the client so ``RemoteSession`` raises exactly what
the in-process ``Session`` would.
"""

from __future__ import annotations

import base64
import json
import struct
from typing import Any, Dict, List

import numpy as np

from ..dispatcher import (ServeError, ServiceClosed, ServiceOverloaded,
                          DeadlineExceeded, RequestCancelled,
                          ServiceDraining, SessionUnknown)
from ..buckets import BucketOverflow

__all__ = ["MAGIC", "CONTENT_TYPE", "encode_frame", "decode_frame",
           "decode_frame_with_trace", "status_of", "error_payload",
           "remote_exception", "ERROR_STATUS"]

MAGIC = b"DTF1"
CONTENT_TYPE = "application/x-deap-frame"

_HEAD = struct.Struct("<I")


def _to_array(x) -> np.ndarray:
    # jax.Array reaches here via __array__; ascontiguousarray also
    # collapses any host view weirdness so tobytes() is the row-major bits
    return np.ascontiguousarray(np.asarray(x))


def _pack(obj: Any, tensors: List[np.ndarray]) -> Any:
    if isinstance(obj, dict):
        bad = [k for k in obj if not isinstance(k, str)]
        if bad:
            # silently stringifying keys would rewrite a pytree genome's
            # structure server-side; fail at the edge instead
            raise TypeError(
                f"wire frames require str dict keys, got {bad[:3]!r}")
        return {k: _pack(v, tensors) for k, v in obj.items()}
    if isinstance(obj, tuple):
        return {"__tuple__": [_pack(v, tensors) for v in obj]}
    if isinstance(obj, list):
        return [_pack(v, tensors) for v in obj]
    if isinstance(obj, bytes):
        return {"__bytes__": base64.b64encode(obj).decode("ascii")}
    if isinstance(obj, (bool, int, float, str)) or obj is None:
        return obj
    if isinstance(obj, (np.bool_, np.integer, np.floating)):
        return obj.item()
    if hasattr(obj, "__array__") or isinstance(obj, np.ndarray):
        a = _to_array(obj)
        if a.dtype == object:
            raise TypeError("object arrays are not wire-encodable")
        tensors.append(a)
        return {"__tensor__": len(tensors) - 1}
    raise TypeError(f"cannot wire-encode {type(obj).__name__}")


def _unpack(obj: Any, tensors: List[np.ndarray]) -> Any:
    if isinstance(obj, dict):
        if "__tensor__" in obj and len(obj) == 1:
            return tensors[obj["__tensor__"]]
        if "__tuple__" in obj and len(obj) == 1:
            return tuple(_unpack(v, tensors) for v in obj["__tuple__"])
        if "__bytes__" in obj and len(obj) == 1:
            return base64.b64decode(obj["__bytes__"])
        return {k: _unpack(v, tensors) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_unpack(v, tensors) for v in obj]
    return obj


def _dtype_token(dt: np.dtype) -> str:
    """Wire name of a dtype: the byte-order-explicit ``str`` form for
    native numpy dtypes, the registered NAME for extension dtypes
    (bfloat16, float8_*, ... — their ``str`` is an opaque void like
    ``<V2`` that would not round-trip)."""
    if dt.kind == "V":
        return dt.name
    return dt.str


def _dtype_of(token: str) -> np.dtype:
    if token and token[0] in "<>|=":
        return np.dtype(token).newbyteorder("<")
    import ml_dtypes
    try:
        return np.dtype(getattr(ml_dtypes, token))
    except (AttributeError, TypeError):
        raise ValueError(f"unknown wire dtype {token!r}")


def encode_frame(obj: Any, trace: Any = None) -> bytes:
    """Encode a JSON-plus-arrays object tree into one wire frame.

    ``trace`` (optional) is a small JSON-safe dict — the
    :meth:`~deap_tpu.observability.fleettrace.TraceContext.wire` form —
    stored in the frame HEADER under ``"__trace__"``, beside the tensor
    manifest: request tracing is header metadata, invisible to the body
    the decoder hands back (a peer that ignores it decodes identically)."""
    tensors: List[np.ndarray] = []
    body = _pack(obj, tensors)
    header = {"body": body,
              "__tensors__": [{"dtype": _dtype_token(a.dtype),
                               "shape": list(a.shape)}
                              for a in tensors]}
    if trace is not None:
        header["__trace__"] = trace
    hdr = json.dumps(header, allow_nan=True).encode("utf-8")
    parts = [MAGIC, _HEAD.pack(len(hdr)), hdr]
    for a in tensors:
        if a.dtype.kind == "V":
            # extension dtypes (bfloat16 & friends) carry their raw bits;
            # single-byte-lane or little-endian hosts only — every
            # supported platform (x86/ARM/TPU hosts) is little-endian
            parts.append(a.tobytes())
        else:
            # canonical little-endian payload, whatever the host order
            parts.append(a.astype(a.dtype.newbyteorder("<"), copy=False)
                          .tobytes())
    return b"".join(parts)


def decode_frame(data: bytes) -> Any:
    """Decode :func:`encode_frame` output back into the object tree
    (arrays come back as numpy, bitwise equal to what was encoded)."""
    return decode_frame_with_trace(data)[0]


def decode_frame_with_trace(data: bytes):
    """Like :func:`decode_frame`, additionally returning the frame
    header's ``"__trace__"`` dict (``None`` when the sender attached no
    trace context) — what the server handler adopts request spans
    from."""
    if len(data) < 8 or data[:4] != MAGIC:
        raise ValueError("not a deap-tpu wire frame (bad magic)")
    (hlen,) = _HEAD.unpack_from(data, 4)
    hdr_end = 8 + hlen
    if len(data) < hdr_end:
        raise ValueError("truncated frame header")
    header = json.loads(data[8:hdr_end].decode("utf-8"))
    tensors: List[np.ndarray] = []
    off = hdr_end
    for spec in header.get("__tensors__", ()):
        dt = _dtype_of(spec["dtype"])
        shape = tuple(int(s) for s in spec["shape"])
        nbytes = dt.itemsize * int(np.prod(shape, dtype=np.int64))
        if off + nbytes > len(data):
            raise ValueError("truncated tensor payload")
        a = np.frombuffer(data, dtype=dt, count=nbytes // dt.itemsize,
                          offset=off)
        a = a.reshape(shape)
        if dt.kind != "V":
            a = a.astype(dt.newbyteorder("="), copy=True)
        else:
            a = a.copy()
        tensors.append(a)
        off += nbytes
    if off != len(data):
        raise ValueError(f"{len(data) - off} trailing bytes after tensors")
    trace = header.get("__trace__")
    return _unpack(header["body"], tensors), (
        trace if isinstance(trace, dict) else None)


# ---------------------------------------------------------------------------
# error mapping
# ---------------------------------------------------------------------------

#: service exception class -> HTTP status (client rebuilds by class name)
ERROR_STATUS: Dict[type, int] = {
    SessionUnknown: 404,
    BucketOverflow: 413,
    ServiceOverloaded: 429,
    RequestCancelled: 409,
    DeadlineExceeded: 504,
    ServiceDraining: 503,
    ServiceClosed: 503,
    ServeError: 409,
    ValueError: 400,
    KeyError: 400,
    TypeError: 400,
}

_BY_NAME = {cls.__name__: cls for cls in ERROR_STATUS}


def status_of(exc: BaseException) -> int:
    for cls, status in ERROR_STATUS.items():
        if isinstance(exc, cls):
            return status
    return 500


def error_payload(exc: BaseException) -> bytes:
    return json.dumps({"error": type(exc).__name__,
                       "message": str(exc)}).encode("utf-8")


def remote_exception(name: str, message: str) -> BaseException:
    """Rebuild the typed service exception a peer reported; unknown
    classes degrade to :class:`ServeError` with the name prefixed."""
    cls = _BY_NAME.get(name)
    if cls is None:
        return ServeError(f"{name}: {message}")
    return cls(message)
