"""Shared HTTP plumbing of the fleet's two DTF1 frontends.

:class:`~deap_tpu.serve.net.server.NetServer`'s handler and the
router's (:class:`~deap_tpu.serve.router.server.RouterServer`) speak the
same keep-alive HTTP/1.1 dialect — explicit Content-Length framing,
byte-counted request/response metrics, typed JSON error envelopes, and
the drain-unread-body rule that keeps an error reply from poisoning the
next request on the connection.  That plumbing was duplicated between
the two handler classes (the accepted debt from the router PR's review
round); this module is the single copy both inherit.

Subclasses implement :meth:`_route` (the verb dispatch) and
:meth:`_handler_metrics` (which :class:`~deap_tpu.serve.metrics.
ServeMetrics` instance the byte counters land on), and may override
``log_prefix`` / :meth:`_log_conf` for their request-log identity.
"""

from __future__ import annotations

import json
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Sequence, Tuple

from ...observability.sinks import emit_text
from . import protocol

__all__ = ["FleetHTTPServer", "FrameHTTPHandler"]


class FleetHTTPServer(ThreadingHTTPServer):
    """Both frontends' HTTP server class.  The stdlib default listen
    backlog (5) drops connections with ECONNRESET the moment a fleet
    loadgen points a few dozen clients at one frontend; the backlog must
    cover at least the largest client pool a bench drives."""

    daemon_threads = True
    request_queue_size = 128


class FrameHTTPHandler(BaseHTTPRequestHandler):
    """Keep-alive DTF1/JSON request handler base (see module docstring).

    The stdlib handler instantiates per connection and calls one
    ``do_<VERB>`` per request; all three verbs funnel into the
    subclass's ``_route(method)``."""

    protocol_version = "HTTP/1.1"
    #: bound by the owning server to its context object (NetServer /
    #: RouterServer) via a closure subclass
    server_ctx = None
    #: request-log tag (``[serve.net]`` / ``[router]``)
    log_prefix = "serve"

    # -- identity hooks ------------------------------------------------------

    def _handler_metrics(self):
        """The ServeMetrics the byte/request counters land on (``None``
        before the server context is bound — counting is skipped)."""
        raise NotImplementedError

    def _log_conf(self) -> Tuple[bool, Sequence]:
        """(verbose, sinks) for the request log."""
        return False, ()

    def log_message(self, fmt, *args):  # stdlib default prints to stderr
        verbose, sinks = self._log_conf()
        if verbose:
            emit_text(f"[{self.log_prefix}] {self.address_string()} "
                      f"{fmt % args}", sinks)

    # -- request body --------------------------------------------------------

    def _read_raw_body(self) -> bytes:
        """Read the request body (Content-Length framing), count it, and
        mark it consumed for :meth:`_drain_body`."""
        length = int(self.headers.get("Content-Length", 0) or 0)
        data = self.rfile.read(length) if length else b""
        self._body_consumed = True
        metrics = self._handler_metrics()
        if metrics is not None:
            metrics.inc("net_bytes_in", len(data))
        return data

    def _drain_body(self) -> None:
        """Consume an unread request body before replying on an error
        path — leftover body bytes would be parsed as the NEXT request
        line on this keep-alive connection, poisoning every subsequent
        exchange."""
        if getattr(self, "_body_consumed", False):
            return
        length = int(self.headers.get("Content-Length", 0) or 0)
        if length:
            self.rfile.read(length)
        self._body_consumed = True

    # -- responses -----------------------------------------------------------

    def _send(self, payload: bytes, status: int = 200,
              content_type: str = protocol.CONTENT_TYPE) -> None:
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)
        metrics = self._handler_metrics()
        if metrics is not None:
            metrics.inc("net_bytes_out", len(payload))

    def _send_json(self, obj, status: int = 200) -> None:
        self._send(json.dumps(obj).encode("utf-8"), status=status,
                   content_type="application/json")

    def _send_error_envelope(self, exc: BaseException,
                             location: Optional[str] = None) -> None:
        """The shared error tail: drain any unread body, then reply with
        the typed JSON envelope at the exception's mapped HTTP status
        (optionally carrying a failover redirect ``location``)."""
        self._drain_body()
        self._send(protocol.error_payload(exc, location=location),
                   status=protocol.status_of(exc),
                   content_type="application/json")

    # -- verbs ---------------------------------------------------------------

    def _route(self, method: str) -> None:
        raise NotImplementedError

    def do_GET(self):  # noqa: N802 (stdlib API)
        self._route("GET")

    def do_POST(self):  # noqa: N802
        self._route("POST")

    def do_DELETE(self):  # noqa: N802
        self._route("DELETE")
