"""Pad-and-bucket shape selection — how the service keeps XLA from
recompiling in steady state.

Every request the :class:`~deap_tpu.serve.dispatcher.BatchDispatcher`
executes runs a compiled program whose shapes come from a SMALL, FIXED set
of buckets, not from whatever population size a client happened to open.  A
session with ``pop=100`` rows is padded (zero rows appended, a ``live``
prefix mask carried as data) up to the enclosing bucket — by default the
next power of two — so every session whose genome structure matches shares
one compiled program per request kind.  Steady-state compile count ==
number of distinct buckets in use; ``tests/test_serve.py`` pins it via the
service's ``compiles`` counter.

The bucketing policy is deliberately asymmetric:

* the **population (row) axis pads** — a pad row is masked out of
  selection, variation, evaluation and counters by the ``live``-mask
  contract of :func:`deap_tpu.algorithms.ea_step`, so padding is
  semantics-free;
* the **genome (dim) axis does not pad** — a zero-padded genome column
  would flow into the user's evaluate function and change the objective.
  Distinct trailing genome shapes therefore land in distinct buckets: the
  bucket key is effectively a ``(pop_bucket, dim)`` pair (generalized to a
  full genome signature for pytree genomes).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from ..base import Population, Fitness

__all__ = ["BucketPolicy", "BucketKey", "BucketOverflow", "genome_signature",
           "pad_rows", "unpad_rows", "pad_population"]


class BucketOverflow(ValueError):
    """The requested row count exceeds the policy's largest bucket."""


def genome_signature(genome: Any) -> tuple:
    """Hashable structural identity of a genome pytree: treedef plus each
    leaf's ``(dtype, trailing shape)``.  Two populations with equal
    signatures (and any row counts) can share bucket programs."""
    leaves, treedef = jax.tree_util.tree_flatten(genome)
    return (treedef,
            tuple((str(l.dtype), tuple(l.shape[1:])) for l in leaves))


@dataclasses.dataclass(frozen=True)
class BucketKey:
    """One compiled-program shape class: padded row count + genome
    signature + objective structure."""

    rows: int
    genome_sig: tuple
    nobj: int
    weights: tuple

    def describe(self) -> str:
        dims = "/".join("x".join(map(str, s)) or "scalar"
                        for _, s in self.genome_sig[1])
        return f"rows={self.rows} dim={dims} nobj={self.nobj}"


@dataclasses.dataclass(frozen=True)
class BucketPolicy:
    """Row-bucket selection.

    ``sizes`` — explicit ascending bucket grid; a request lands in the
    smallest listed size that fits (:class:`BucketOverflow` beyond the
    largest).  Empty (default): next power of two, floored at
    ``min_rows``, capped at ``max_rows`` when set.
    """

    sizes: Tuple[int, ...] = ()
    min_rows: int = 8
    max_rows: Optional[int] = None

    def __post_init__(self):
        if self.sizes and tuple(sorted(self.sizes)) != tuple(self.sizes):
            raise ValueError("BucketPolicy.sizes must be ascending")

    def rows_for(self, n: int) -> int:
        """Bucketed row count for ``n`` live rows."""
        if n < 1:
            raise ValueError("row count must be >= 1")
        if self.sizes:
            for s in self.sizes:
                if n <= s:
                    return int(s)
            raise BucketOverflow(
                f"{n} rows exceeds the largest bucket {self.sizes[-1]}")
        rows = max(int(self.min_rows), 1)
        while rows < n:
            rows *= 2
        if self.max_rows is not None and rows > self.max_rows:
            raise BucketOverflow(
                f"{n} rows needs bucket {rows} > max_rows={self.max_rows}")
        return rows

    def bucket_for(self, population: Population) -> BucketKey:
        """Bucket of a (live, unpadded) population."""
        return BucketKey(rows=self.rows_for(population.size),
                         genome_sig=genome_signature(population.genome),
                         nobj=population.fitness.nobj,
                         weights=population.fitness.weights)


def pad_rows(tree: Any, rows: int):
    """Pad every leaf's leading axis to ``rows`` with zeros (appended, so
    the live rows form a PREFIX — the layout the ``live``-mask contract of
    :func:`deap_tpu.algorithms.ea_step` requires)."""
    def pad(x):
        n = x.shape[0]
        if n == rows:
            return jnp.asarray(x)
        if n > rows:
            raise ValueError(f"cannot pad {n} rows down to {rows}")
        width = [(0, rows - n)] + [(0, 0)] * (x.ndim - 1)
        return jnp.pad(jnp.asarray(x), width)
    return jax.tree_util.tree_map(pad, tree)


def unpad_rows(tree: Any, n: int):
    """Strip pad rows: slice every leaf back to its first ``n`` rows."""
    return jax.tree_util.tree_map(lambda x: x[:n], tree)


def pad_population(population: Population, rows: int) -> Population:
    """Pad a population to ``rows``: genome and fitness values get zero
    rows, validity gets ``False`` (pad rows lose every masked comparison
    and are skipped by live-masked evaluation)."""
    return Population(
        genome=pad_rows(population.genome, rows),
        fitness=Fitness(values=pad_rows(population.fitness.values, rows),
                        valid=pad_rows(population.fitness.valid, rows),
                        weights=population.fitness.weights))
