"""Pad-and-bucket shape selection — how the service keeps XLA from
recompiling in steady state.

Every request the :class:`~deap_tpu.serve.dispatcher.BatchDispatcher`
executes runs a compiled program whose shapes come from a SMALL, FIXED set
of buckets, not from whatever population size a client happened to open.  A
session with ``pop=100`` rows is padded (zero rows appended, a ``live``
prefix mask carried as data) up to the enclosing bucket — by default the
next power of two — so every session whose genome structure matches shares
one compiled program per request kind.  Steady-state compile count ==
number of distinct buckets in use; ``tests/test_serve.py`` pins it via the
service's ``compiles`` counter.

The bucketing policy is deliberately asymmetric:

* the **population (row) axis pads** — a pad row is masked out of
  selection, variation, evaluation and counters by the ``live``-mask
  contract of :func:`deap_tpu.algorithms.ea_step`, so padding is
  semantics-free;
* the **genome (dim) axis does not pad** — a zero-padded genome column
  would flow into the user's evaluate function and change the objective.
  Distinct trailing genome shapes therefore land in distinct buckets: the
  bucket key is effectively a ``(pop_bucket, dim)`` pair (generalized to a
  full genome signature for pytree genomes).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from .. import sanitize
from ..base import Population, Fitness

__all__ = ["BucketPolicy", "BucketKey", "BucketOverflow", "genome_signature",
           "pad_rows", "unpad_rows", "pad_population",
           "ShapeHistogram", "derive_sizes"]


class BucketOverflow(ValueError):
    """The requested row count exceeds the policy's largest bucket."""


def genome_signature(genome: Any) -> tuple:
    """Hashable structural identity of a genome pytree: treedef plus each
    leaf's ``(dtype, trailing shape)``.  Two populations with equal
    signatures (and any row counts) can share bucket programs."""
    leaves, treedef = jax.tree_util.tree_flatten(genome)
    return (treedef,
            tuple((str(l.dtype), tuple(l.shape[1:])) for l in leaves))


@dataclasses.dataclass(frozen=True)
class BucketKey:
    """One compiled-program shape class: padded row count + genome
    signature + objective structure."""

    rows: int
    genome_sig: tuple
    nobj: int
    weights: tuple

    def describe(self) -> str:
        dims = "/".join("x".join(map(str, s)) or "scalar"
                        for _, s in self.genome_sig[1])
        return f"rows={self.rows} dim={dims} nobj={self.nobj}"


@dataclasses.dataclass(frozen=True)
class BucketPolicy:
    """Row-bucket selection.

    ``sizes`` — explicit ascending bucket grid; a request lands in the
    smallest listed size that fits.  Beyond the largest listed size:
    :class:`BucketOverflow` by default, or — with ``grow_beyond`` — fall
    back to doubling from the largest size (how adaptively derived grids
    stay open to tenants bigger than anything yet observed).  Empty
    ``sizes`` (default): next power of two, floored at ``min_rows``.
    ``max_rows``, when set, caps every path.
    """

    sizes: Tuple[int, ...] = ()
    min_rows: int = 8
    max_rows: Optional[int] = None
    grow_beyond: bool = False

    def __post_init__(self):
        if self.sizes and tuple(sorted(self.sizes)) != tuple(self.sizes):
            raise ValueError("BucketPolicy.sizes must be ascending")

    def rows_for(self, n: int) -> int:
        """Bucketed row count for ``n`` live rows."""
        if n < 1:
            raise ValueError("row count must be >= 1")
        if self.sizes:
            for s in self.sizes:
                if n <= s:
                    if self.max_rows is not None and s > self.max_rows:
                        raise BucketOverflow(
                            f"{n} rows lands in listed bucket {s} > "
                            f"max_rows={self.max_rows}")
                    return int(s)
            if not self.grow_beyond:
                raise BucketOverflow(
                    f"{n} rows exceeds the largest bucket {self.sizes[-1]}")
            rows = int(self.sizes[-1])
        else:
            rows = max(int(self.min_rows), 1)
        while rows < n:
            rows *= 2
        if self.max_rows is not None and rows > self.max_rows:
            raise BucketOverflow(
                f"{n} rows needs bucket {rows} > max_rows={self.max_rows}")
        return rows

    def bucket_for(self, population: Population) -> BucketKey:
        """Bucket of a (live, unpadded) population."""
        return BucketKey(rows=self.rows_for(population.size),
                         genome_sig=genome_signature(population.genome),
                         nobj=population.fitness.nobj,
                         weights=population.fitness.weights)


class ShapeHistogram:
    """Observed request-shape histogram: live row counts → occurrence
    counts.  The service records every admitted shape (session opens,
    restores, ad-hoc evaluate batches) here; at a quiesce point
    :meth:`derive_policy` turns the histogram into an *explicit* bucket
    grid fitted to the traffic actually seen, instead of the a-priori
    power-of-two grid.  Thread-safe (request threads write, rebucket
    reads)."""

    #: lock-guarded shared state (``lock-discipline`` lint +
    #: runtime sanitizer): request threads write, rebucket reads
    _GUARDED_BY = {"_lock": ("_counts",)}

    def __init__(self):
        self._lock = sanitize.lock()
        self._counts: Dict[int, int] = {}

    def observe(self, n: int, weight: int = 1) -> None:
        """Record ``weight`` requests of ``n`` live rows."""
        n = int(n)
        if n < 1:
            raise ValueError("row count must be >= 1")
        with self._lock:
            self._counts[n] = self._counts.get(n, 0) + int(weight)

    def counts(self) -> Dict[int, int]:
        with self._lock:
            return dict(self._counts)

    def __len__(self) -> int:
        with self._lock:
            return len(self._counts)

    def clear(self) -> None:
        with self._lock:
            self._counts.clear()

    def derive_policy(self, *, max_buckets: int = 8, min_rows: int = 8,
                      round_to: int = 1,
                      max_rows: Optional[int] = None) -> "BucketPolicy":
        """Fit an explicit :class:`BucketPolicy` grid to the histogram
        (see :func:`derive_sizes`).  Raises when nothing was observed —
        an empty histogram has no traffic to fit.  The derived policy is
        ``grow_beyond=True``: a tenant larger than anything yet observed
        doubles up from the largest learned size instead of being
        rejected (an observability-driven refit must never become an
        admission regression).  ``max_rows`` carries the operator's hard
        admission cap through the refit — a rebucket must never widen
        what the previous policy admitted."""
        sizes = derive_sizes(self.counts(), max_buckets=max_buckets,
                             min_rows=min_rows, round_to=round_to)
        return BucketPolicy(sizes=sizes, min_rows=min_rows,
                            max_rows=max_rows, grow_beyond=True)


def derive_sizes(counts: Dict[int, int], *, max_buckets: int = 8,
                 min_rows: int = 8, round_to: int = 1) -> Tuple[int, ...]:
    """Fit an ascending explicit bucket grid to an observed
    ``{rows: count}`` histogram.

    Every observed row count lands exactly on a grid size (rounded up to
    ``round_to`` and floored at ``min_rows``), then adjacent sizes are
    greedily coalesced until at most ``max_buckets`` remain — each merge
    removes the size whose traffic pays the least total padding by moving
    up to the next size (cost = count × row gap).  The result wastes the
    minimum pad rows this greedy can find while capping the number of
    compiled programs per request kind at ``max_buckets``."""
    if not counts:
        raise ValueError("cannot derive a bucket grid from an empty "
                         "shape histogram")
    if max_buckets < 1:
        raise ValueError("max_buckets must be >= 1")
    if round_to < 1:
        raise ValueError("round_to must be >= 1")

    def snap(n: int) -> int:
        return max(int(min_rows), -(-int(n) // round_to) * round_to)

    weight: Dict[int, int] = {}
    for n, c in counts.items():
        s = snap(n)
        weight[s] = weight.get(s, 0) + int(c)
    sizes = sorted(weight)
    while len(sizes) > max_buckets:
        # merging sizes[i] into sizes[i+1] pads each of its rows' requests
        # up by the gap; drop the cheapest merge each round
        costs = [weight[sizes[i]] * (sizes[i + 1] - sizes[i])
                 for i in range(len(sizes) - 1)]
        i = costs.index(min(costs))
        weight[sizes[i + 1]] += weight.pop(sizes[i])
        del sizes[i]
    return tuple(sizes)


def pad_rows(tree: Any, rows: int):
    """Pad every leaf's leading axis to ``rows`` with zeros (appended, so
    the live rows form a PREFIX — the layout the ``live``-mask contract of
    :func:`deap_tpu.algorithms.ea_step` requires)."""
    def pad(x):
        n = x.shape[0]
        if n == rows:
            return jnp.asarray(x)
        if n > rows:
            raise ValueError(f"cannot pad {n} rows down to {rows}")
        width = [(0, rows - n)] + [(0, 0)] * (x.ndim - 1)
        return jnp.pad(jnp.asarray(x), width)
    return jax.tree_util.tree_map(pad, tree)


def unpad_rows(tree: Any, n: int):
    """Strip pad rows: slice every leaf back to its first ``n`` rows."""
    return jax.tree_util.tree_map(lambda x: x[:n], tree)


def pad_population(population: Population, rows: int) -> Population:
    """Pad a population to ``rows``: genome and fitness values get zero
    rows, validity gets ``False`` (pad rows lose every masked comparison
    and are skipped by live-masked evaluation)."""
    return Population(
        genome=pad_rows(population.genome, rows),
        fitness=Fitness(values=pad_rows(population.fitness.values, rows),
                        valid=pad_rows(population.fitness.valid, rows),
                        weights=population.fitness.weights))
