"""Request queue + microbatching worker — the service's control plane.

One daemon thread owns all device dispatch.  Clients (any thread) submit
:class:`Request` objects into a **bounded** pending deque — a full queue
rejects (:class:`ServiceOverloaded`) or blocks with a timeout, so overload
backpressures at the edge instead of growing an unbounded heap.  The worker
coalesces compatible pending requests into one fixed-shape microbatch per
dispatch:

* **group identity** — requests batch together iff they share
  ``(kind, program_key)``: same compiled program, same bucket shapes;
* **capacity** — a batch packs requests while the sum of their ``weight``
  stays within the group's ``capacity`` (step/ask/tell weigh 1 against the
  slot count; evaluate requests weigh their row count against the row
  bucket);
* **per-session FIFO** — at most one request per session per batch, and a
  session's later request never overtakes its earlier one (stateful kinds
  would otherwise race their own state);
* **deadlines** — a request whose deadline passed before dispatch fails
  with :class:`DeadlineExceeded` and never reaches the device: deadline
  misses fail the *request*, not the service;
* **cancellation** — :meth:`ServeFuture.cancel` wins any race that
  resolves before dispatch; cancelled requests are dropped at collection.

Execution runs under :func:`deap_tpu.resilience.with_retries` (transient
``OSError``/``TimeoutError``-class faults back off and retry; anything
else fails the batch's requests and the worker moves on).  Waiting uses
``threading.Condition`` timeouts only — no blocking ``time.sleep`` on any
service path (``tools/check_no_blocking_sleep.py`` pins it as a tier-1
static pass).
"""

from __future__ import annotations

import collections
import dataclasses
import itertools
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from .. import sanitize
from ..resilience.retry import with_retries, RetriesExhausted

__all__ = ["ServeFuture", "Request", "BatchDispatcher", "ServeError",
           "ServiceClosed", "ServiceOverloaded", "DeadlineExceeded",
           "RequestCancelled", "ServiceDraining", "SessionUnknown",
           "TenantQuotaExceeded", "CircuitOpen", "ServiceBrownout"]


class ServeError(RuntimeError):
    """Base class of service-layer failures."""


class ServiceClosed(ServeError):
    """The service (or the request's session) was closed."""


class ServiceDraining(ServeError):
    """The service is draining for failover: no new work is admitted.
    Clients should retry against the instance the sessions restore on."""


class SessionUnknown(ServeError):
    """No live session with that name (network frontend lookup miss)."""


class ServiceOverloaded(ServeError):
    """The bounded request queue is full — shed load or retry later."""


class TenantQuotaExceeded(ServeError):
    """The request's tenant is over an admission quota (session count or
    queued-request backlog) at the fleet router — a per-tenant admission
    decision, distinct from :class:`ServiceOverloaded` (whole-service
    backpressure).  Raised by
    :mod:`deap_tpu.serve.router.tenants` and rebuilt typed on the client
    from the wire error envelope."""


class CircuitOpen(ServeError):
    """A per-backend circuit breaker is open: the backend failed enough
    consecutive forwards that the router stopped sending it work until a
    half-open probe succeeds (:class:`deap_tpu.serve.router.backend.
    CircuitBreaker`).  The request was NEVER sent — retrying against the
    fleet later (or another instance) is always safe.  Travels the typed
    error envelope with status 503."""


class ServiceBrownout(ServeError):
    """The request was shed by priority under sustained queue pressure:
    the dispatcher's pending queue stayed at/above its brownout watermark
    and this admission's priority class is lower than work already
    queued.  Distinct from :class:`ServiceOverloaded` (the queue is not
    necessarily full — the service is degrading *selectively* so
    higher-priority tenants keep their deadlines).  Status 429; clients
    should back off longer than for a plain overload."""


class DeadlineExceeded(ServeError):
    """The request's deadline passed before it was dispatched."""


class RequestCancelled(ServeError):
    """The request was cancelled before it was dispatched."""


class ServeFuture:
    """Completion handle for one submitted request (thread-safe).

    ``result(timeout)`` blocks until resolution and returns the request's
    payload result or raises its failure; ``cancel()`` succeeds iff the
    request has not started executing."""

    #: resolution state shared between the dispatch worker and any
    #: number of waiting client threads (``_on_failure`` is deliberately
    #: NOT declared: sessions assign the rollback hook after
    #: construction but before the future is published via submit)
    _GUARDED_BY = {"_lock": ("_result", "_exc", "_cancelled", "_started")}

    def __init__(self):
        self._event = sanitize.event()
        self._lock = sanitize.lock()
        self._result: Any = None
        self._exc: Optional[BaseException] = None
        self._cancelled = False
        self._started = False
        #: optional hook run exactly once when the future resolves with a
        #: failure (cancellation included) — sessions use it to roll back
        #: protocol state (e.g. an ask() that never executed)
        self._on_failure: Optional[Callable[[], None]] = None

    # -- dispatcher side -----------------------------------------------------

    def _start(self) -> bool:
        """Claim the future for execution; False if already cancelled."""
        with self._lock:
            if self._cancelled:
                return False
            self._started = True
            return True

    def _set_result(self, value: Any) -> None:
        with self._lock:
            if self._event.is_set():
                return
            self._result = value
            self._event.set()

    def _set_exception(self, exc: BaseException) -> None:
        with self._lock:
            if self._event.is_set():
                return
            self._exc = exc
            self._event.set()
            hook, self._on_failure = self._on_failure, None
        if hook is not None:
            hook()

    # -- client side ---------------------------------------------------------

    def cancel(self) -> bool:
        """Request cancellation.  True iff the request will never execute
        (it had not been claimed by a batch); a started request cannot be
        recalled from the device."""
        with self._lock:
            if self._started or self._event.is_set():
                return False
            self._cancelled = True
            self._exc = RequestCancelled("request cancelled")
            self._event.set()
            hook, self._on_failure = self._on_failure, None
        if hook is not None:
            hook()
        return True

    def cancelled(self) -> bool:
        with self._lock:
            return self._cancelled

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> Any:
        if not self._event.wait(timeout):
            raise TimeoutError("request not complete")
        # the event's set() already orders these reads after the writer,
        # but they take the lock anyway: _GUARDED_BY declares them, and
        # an exception the lockset sanitizer must special-case is worth
        # more than an uncontended acquire on an already-resolved future
        with self._lock:
            exc, result = self._exc, self._result
        if exc is not None:
            raise exc
        return result

    def exception(self, timeout: Optional[float] = None
                  ) -> Optional[BaseException]:
        if not self._event.wait(timeout):
            raise TimeoutError("request not complete")
        with self._lock:
            return self._exc


_req_ids = itertools.count()


@dataclasses.dataclass
class Request:
    """One unit of queued work.  ``program_key`` is the batching identity
    (same compiled program + bucket); ``weight``/``capacity`` implement
    slot- or row-packing; ``session`` scopes the per-session FIFO rule
    (``None`` → unconstrained); ``trace`` is this request's
    :class:`~deap_tpu.observability.fleettrace.TraceContext` (``None``
    when tracing is off) — the span every phase the request crosses
    hangs its child spans off."""

    kind: str
    program_key: tuple
    payload: Dict[str, Any]
    session: Any = None
    weight: int = 1
    capacity: int = 1
    deadline: Optional[float] = None
    future: ServeFuture = dataclasses.field(default_factory=ServeFuture)
    submitted: float = 0.0
    seq: int = dataclasses.field(default_factory=lambda: next(_req_ids))
    trace: Any = None
    #: tenant priority class (higher = more important; router tenants
    #: stamp it from their quota).  Under sustained queue pressure the
    #: dispatcher sheds admissions whose priority is lower than work
    #: already queued (:class:`ServiceBrownout`).
    priority: int = 1

    @property
    def tenant(self) -> Optional[str]:
        """Session name for per-tenant metric attribution."""
        return getattr(self.session, "name", None)


class BatchDispatcher:
    """Bounded queue + single worker thread (see module docstring).

    ``execute`` is called on the worker thread as
    ``execute(kind, program_key, requests) -> list_of_results`` (one result
    per request, same order) and is wrapped in
    :func:`~deap_tpu.resilience.with_retries` with ``retries`` /
    ``backoff`` (transient classes only).  ``clock`` is the monotonic
    deadline clock, injectable for tests."""

    #: lock-guarded shared state, enforced statically by the
    #: ``lock-discipline`` lint pass: every write to these attributes
    #: must sit under ``with self._cv:`` (or in a ``*_locked`` method
    #: whose callers all hold it) — the queue, the worker's lifecycle
    #: flags, and the batch counter are shared between every client
    #: thread and the dispatch worker
    _GUARDED_BY = {"_cv": ("_pending", "_closed", "_draining", "_paused",
                           "_busy", "_batches", "_pressure_since",
                           "_inflight")}

    def __init__(self, execute: Callable[[str, tuple, List[Request]], list],
                 *, max_pending: int = 256, batch_window: float = 0.0,
                 metrics=None, retries: int = 2, backoff: float = 0.05,
                 retry_on: tuple = (OSError, TimeoutError, ConnectionError),
                 clock: Callable[[], float] = time.monotonic,
                 on_retry: Optional[Callable] = None,
                 tracer=None, after_batch: Optional[Callable] = None,
                 brownout_watermark: Optional[float] = None,
                 brownout_grace_s: float = 0.0):
        if max_pending < 1:
            raise ValueError("max_pending must be >= 1")
        if brownout_watermark is not None and not (
                0.0 < float(brownout_watermark) <= 1.0):
            raise ValueError("brownout_watermark must be in (0, 1]")
        self._execute_once = execute
        self._metrics = metrics
        #: fleettrace.FleetTracer (or None): queue-wait phase spans and
        #: the per-request "serve.<kind>" spans are recorded here
        self._tracer = tracer
        #: called on the worker thread after every dispatched batch,
        #: OUTSIDE the queue lock and with the worker not busy — the
        #: service hangs its auto-rebucket policy tick here (it may
        #: pause/resume this dispatcher, which is re-entrant from this
        #: position).  Exceptions are contained: a policy bug must not
        #: kill the one thread that owns device dispatch.
        self._after_batch = after_batch

        def _note_retry(attempt, exc, delay):
            if metrics is not None:
                metrics.inc("retries")
            if on_retry is not None:
                on_retry(attempt, exc, delay)

        # the backoff sleep inside with_retries runs on the WORKER thread
        # between attempts of an already-failing batch — queued requests
        # wait behind it by design (the device path is down).
        self._execute = with_retries(
            execute, retries=retries, backoff=backoff, retry_on=retry_on,
            on_retry=_note_retry)
        self.max_pending = int(max_pending)
        self.batch_window = float(batch_window)
        #: queue depth at/above which brownout pressure accrues
        #: (``None`` disables priority shedding entirely)
        self._brownout_depth = (
            None if brownout_watermark is None
            else max(1, int(float(brownout_watermark) * max_pending)))
        self._brownout_grace_s = float(brownout_grace_s)
        self._clock = clock
        self._cv = sanitize.condition()
        self._pending: "collections.deque[Request]" = collections.deque()
        self._closed = False
        self._draining = False
        self._paused = False
        self._busy = False
        self._batches = 0
        #: ``id(session)`` of every session with a request in the batch
        #: the worker currently has in flight — the single-session
        #: quiesce predicate (live migration) waits on this, never on
        #: the global ``_busy`` flag, so one hot session can reach a
        #: dispatch boundary while its neighbors keep streaming batches
        self._inflight: set = set()
        #: clock at which queue depth first reached the brownout
        #: watermark; ``None`` while below it
        self._pressure_since: Optional[float] = None
        self._thread = threading.Thread(
            target=self._run, name="deap-tpu-serve-dispatch", daemon=True)
        self._thread.start()

    # -- client side ---------------------------------------------------------

    def submit(self, request: Request, *, block: bool = False,
               timeout: Optional[float] = None) -> ServeFuture:
        """Enqueue; on a full queue either raise :class:`ServiceOverloaded`
        (default) or block up to ``timeout`` for space."""
        return self.submit_many([request], block=block,
                                timeout=timeout)[0]

    def submit_many(self, requests: List[Request], *, block: bool = False,
                    timeout: Optional[float] = None) -> List[ServeFuture]:
        """Enqueue several requests **atomically**: either every request
        is queued or none is.  This is how ``Session.step(n)`` pipelines
        its n generations — a drain (or close, or full queue) racing the
        submission must never split the pipeline, queueing a prefix that
        executes while the caller is told the call failed.  The failover
        retry story depends on it: a ``ServiceDraining`` rejection
        PROVES nothing of the call ran, so re-sending the whole call to
        the restored instance cannot double-apply a generation."""
        if not requests:
            return []
        now = self._clock()
        for request in requests:
            request.submitted = now
        with self._cv:
            if self._closed:
                raise ServiceClosed("service is closed")
            if self._draining:
                # checked under the queue lock: once set_draining()
                # returns, NOTHING can slip into the queue behind the
                # drain wait — the failover snapshot sits at a boundary
                # every client observed
                raise ServiceDraining("service is draining for failover")
            self._check_migrating_locked(requests)
            if any(r.deadline is not None and now > r.deadline
                   for r in requests):
                # deadline-budget shed: the remaining budget that rode in
                # with the request (client hop + router hop already
                # subtracted) is spent on ARRIVAL — queueing it would
                # only burn a batch slot on work nobody is waiting for.
                # The whole atomic batch fails together (none of it ran,
                # so a re-send with a fresh budget is safe).
                for r in requests:
                    self._shed_expired(r, now)
                return [r.future for r in requests]
            self._check_brownout_locked(requests, now)
            if len(requests) > self.max_pending:
                # an atomic batch bigger than the queue can EVER hold
                # would wait on a predicate no completion satisfies —
                # fail fast instead of hanging (or spin-rejecting) the
                # caller forever
                if self._metrics is not None:
                    self._metrics.inc("rejected", len(requests))
                    for r in requests:
                        self._metrics.inc_tenant(r.tenant, "rejected")
                raise ServiceOverloaded(
                    f"an atomic batch of {len(requests)} requests can "
                    f"never fit the queue (max_pending="
                    f"{self.max_pending}); split the call or raise "
                    "max_pending")
            if len(self._pending) + len(requests) > self.max_pending:
                # cancelled/expired entries still hold queue slots until
                # the worker reaches them — resolve them here instead of
                # shedding live work while the queue is full of corpses
                self._pending = collections.deque(
                    r for r in self._pending if not self._prune_locked(r))
            if len(self._pending) + len(requests) > self.max_pending:
                if not block or not self._cv.wait_for(
                        lambda: self._closed or self._draining
                        or (len(self._pending) + len(requests)
                            <= self.max_pending),
                        timeout=timeout):
                    if self._metrics is not None:
                        self._metrics.inc("rejected", len(requests))
                        for r in requests:
                            self._metrics.inc_tenant(r.tenant, "rejected")
                    raise ServiceOverloaded(
                        f"{len(self._pending)} requests pending "
                        f"(max_pending={self.max_pending})")
                if self._closed:
                    raise ServiceClosed("service is closed")
                if self._draining:
                    # a drain that landed while this submission was
                    # blocked on queue space: enqueueing now would slip
                    # work behind the drain wait, after set_draining()
                    # promised the pending queue can only shrink
                    raise ServiceDraining(
                        "service is draining for failover")
                # a migration quiesce that landed while this submission
                # was blocked: same atomicity promise, per session
                self._check_migrating_locked(requests)
            self._pending.extend(requests)
            if self._metrics is not None:
                self._metrics.inc("requests", len(requests))
                # per-request tenant rows: a batch is not required to be
                # single-session, so requests[0] must not absorb them all
                for r in requests:
                    self._metrics.inc_tenant(r.tenant, "requests")
                self._metrics.set_gauge("queue_depth", len(self._pending))
            self._cv.notify_all()
        return [r.future for r in requests]

    def _shed_expired(self, req: Request, now: float) -> None:
        """Fail a request whose deadline budget was already spent at
        submission (pre-dispatch shed).  Counts ``deadline_shed`` on top
        of the ordinary miss accounting, and records the same error span
        :meth:`_prune_locked` would — a shed must look identical to a
        queue-pruned miss to the health monitor's trace window."""
        req.future._set_exception(DeadlineExceeded(
            f"deadline budget spent {now - req.deadline:.3f}s before "
            "submission (shed pre-dispatch)"))
        if self._metrics is not None:
            self._metrics.inc("deadline_shed")
            self._metrics.inc("deadline_misses")
            self._metrics.inc_tenant(req.tenant, "deadline_misses")
        if self._tracer is not None and req.trace is not None:
            self._tracer.record(
                f"serve.{req.kind}", req.trace, req.submitted, now,
                attrs={"error": "DeadlineExceeded", "session": req.tenant})

    def _check_brownout_locked(self, requests: List[Request],
                               now: float) -> None:
        """Priority load shedding (holds ``_cv``).  While the queue sits
        at/above the brownout watermark for longer than the grace
        period, an admission whose priority class is LOWER than work
        already queued is refused with :class:`ServiceBrownout` — the
        graceful middle ground between admitting everything (every
        tenant's deadline misses) and a hard :class:`ServiceOverloaded`
        at the brim.  Equal-priority traffic is never shed here, so a
        fleet with uniform priorities behaves exactly as before."""
        if self._brownout_depth is None:
            return
        if len(self._pending) >= self._brownout_depth:
            if self._pressure_since is None:
                self._pressure_since = now
        else:
            self._pressure_since = None
            return
        if now - self._pressure_since < self._brownout_grace_s:
            return
        queued_top = max((r.priority for r in self._pending), default=None)
        incoming = min(r.priority for r in requests)
        if queued_top is None or incoming >= queued_top:
            return
        if self._metrics is not None:
            self._metrics.inc("brownout_sheds", len(requests))
            for r in requests:
                self._metrics.inc_tenant(r.tenant, "rejected")
        raise ServiceBrownout(
            f"priority {incoming} admission shed: queue at "
            f"{len(self._pending)}/{self.max_pending} holds priority "
            f"{queued_top} work (sustained {now - self._pressure_since:.1f}s "
            "over the brownout watermark)")

    def set_draining(self, value: bool = True) -> None:
        """Reject (``ServiceDraining``) every submission from now on —
        atomic with respect to in-flight :meth:`submit` calls, so after
        this returns the pending queue can only shrink."""
        with self._cv:
            self._draining = bool(value)
            self._cv.notify_all()

    def _check_migrating_locked(self, requests: List[Request]) -> None:
        """Reject (``ServiceDraining``) any request for a session whose
        ``migrating`` flag is up (holds ``_cv``).  The flag flips under
        this same lock (:meth:`set_session_migrating`), so the drain
        atomicity promise holds per session: once the flip returns, that
        session's pending work can only shrink — the migration snapshot
        sits at a boundary every one of its clients observed."""
        for r in requests:
            if r.session is not None and getattr(
                    r.session, "migrating", False):
                raise ServiceDraining(
                    f"session {getattr(r.session, 'name', '?')!r} "
                    "is migrating")

    def set_session_migrating(self, session, value: bool = True) -> None:
        """Flip one session's ``migrating`` flag under the queue lock —
        atomic with respect to in-flight :meth:`submit` calls, exactly
        like :meth:`set_draining` but scoped to one session.  Neighbor
        sessions keep submitting and dispatching throughout."""
        with self._cv:
            session.migrating = bool(value)
            self._cv.notify_all()

    def wait_session_idle(self, session,
                          timeout: Optional[float] = None) -> bool:
        """Block until ``session`` has nothing queued and nothing in the
        worker's in-flight batch (or ``timeout`` elapses; True on idle).
        With the session's ``migrating`` flag already up this is the
        single-session quiesce point: after it returns True the
        session's device state is at a dispatch boundary and can be
        snapshotted without pausing the dispatcher."""
        sid = id(session)
        with self._cv:
            return self._cv.wait_for(
                lambda: sid not in self._inflight
                and not any(r.session is session for r in self._pending),
                timeout=timeout)

    def pause(self) -> None:
        """Stop dispatching new batches (in-flight one completes) —
        checkpoint quiesce uses this."""
        with self._cv:
            self._paused = True
            self._cv.wait_for(lambda: not self._busy)

    def resume(self) -> None:
        with self._cv:
            self._paused = False
            self._cv.notify_all()

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Block until the queue is empty and no batch is in flight."""
        with self._cv:
            return self._cv.wait_for(
                lambda: not self._pending and not self._busy,
                timeout=timeout)

    def close(self, timeout: float = 10.0) -> None:
        """Stop the worker; every still-pending request fails with
        :class:`ServiceClosed`."""
        with self._cv:
            if self._closed:
                return
            self._closed = True
            while self._pending:
                self._pending.popleft().future._set_exception(
                    ServiceClosed("service closed with request pending"))
            self._cv.notify_all()
        self._thread.join(timeout=timeout)

    @property
    def queue_depth(self) -> int:
        with self._cv:
            return len(self._pending)

    @property
    def batches(self) -> int:
        with self._cv:
            return self._batches

    def remap_pending(self, fn: Callable[[Request], None]) -> None:
        """Apply ``fn`` to every still-queued request under the queue
        lock.  The rebucket quiesce uses this to rewrite queued requests'
        ``program_key``/``capacity`` after sessions moved buckets —
        without it, a request enqueued before the refit would dispatch
        its new-shaped state through the stale compiled program."""
        with self._cv:
            for req in self._pending:
                fn(req)

    def wait_for_batches(self, seen: int,
                         timeout: Optional[float] = None) -> int:
        """Block until the dispatched-batch count exceeds ``seen`` (or the
        dispatcher closes, or ``timeout`` elapses) and return the current
        count.  A Condition wait, not a poll — the streaming metrics
        endpoint tails service activity through this without burning a
        busy loop."""
        with self._cv:
            self._cv.wait_for(
                lambda: self._batches > seen or self._closed,
                timeout=timeout)
            return self._batches

    # -- worker side ---------------------------------------------------------

    def _prune_locked(self, req: Request) -> bool:
        """Resolve a request that must not run; True if it was pruned."""
        if req.future.cancelled():
            if self._metrics is not None:
                self._metrics.inc("cancelled")
            return True
        if req.session is not None and getattr(req.session, "closed", False):
            req.future._set_exception(ServiceClosed(
                f"session {getattr(req.session, 'name', '?')} is closed"))
            return True
        if req.deadline is not None and self._clock() > req.deadline:
            req.future._set_exception(DeadlineExceeded(
                f"deadline passed {self._clock() - req.deadline:.3f}s "
                "before dispatch"))
            if self._metrics is not None:
                self._metrics.inc("deadline_misses")
                self._metrics.inc_tenant(req.tenant, "deadline_misses")
            if self._tracer is not None and req.trace is not None:
                self._tracer.record(
                    f"serve.{req.kind}", req.trace, req.submitted,
                    self._clock(), attrs={"error": "DeadlineExceeded",
                                          "session": req.tenant})
            return True
        return False

    def _collect_locked(self) -> List[Request]:
        """Pop the next microbatch (FIFO anchor + compatible followers)."""
        batch: List[Request] = []
        anchor_key = None
        weight = 0
        capacity = 0
        sessions_seen = set()
        keep: "collections.deque[Request]" = collections.deque()
        while self._pending:
            req = self._pending.popleft()
            if self._prune_locked(req):
                continue
            sess = id(req.session) if req.session is not None else None
            if anchor_key is None:
                anchor_key = (req.kind, req.program_key)
                capacity = req.capacity
            if ((req.kind, req.program_key) == anchor_key
                    and weight + req.weight <= capacity
                    and (sess is None or sess not in sessions_seen)):
                batch.append(req)
                weight += req.weight
            else:
                keep.append(req)
            if sess is not None:
                # a skipped session's LATER requests must also wait,
                # preserving per-session order
                sessions_seen.add(sess)
        self._pending = keep
        if self._metrics is not None:
            self._metrics.set_gauge("queue_depth", len(self._pending))
        return batch

    def _run(self) -> None:
        while True:
            with self._cv:
                self._cv.wait_for(
                    lambda: self._closed
                    or (self._pending and not self._paused))
                if self._closed:
                    return
                batch = self._collect_locked()
                if (batch and self.batch_window > 0
                        and sum(r.weight for r in batch) < batch[0].capacity):
                    # linger once for stragglers, then take what arrived.
                    # wait() released the lock, so pause()/close() may have
                    # happened meanwhile — re-check before dispatching: a
                    # quiesced service must not swap session states under a
                    # checkpoint, and a closed one must fail, not run
                    self._cv.wait(self.batch_window)
                    self._pending.extendleft(reversed(batch))
                    if self._closed:
                        while self._pending:
                            self._pending.popleft().future._set_exception(
                                ServiceClosed(
                                    "service closed with request pending"))
                        return
                    if self._paused:
                        continue
                    batch = self._collect_locked()
                if not batch:
                    continue
                self._busy = True
                self._inflight = {id(r.session) for r in batch
                                  if r.session is not None}
            try:
                self._dispatch(batch)
            finally:
                with self._cv:
                    self._busy = False
                    self._inflight = set()
                    self._batches += 1
                    self._cv.notify_all()
            if self._after_batch is not None:
                try:
                    self._after_batch()
                except Exception:  # noqa: BLE001 — the hook reports its
                    pass           # own failures; the worker must survive

    def _dispatch(self, batch: List[Request]) -> None:
        live = [r for r in batch if r.future._start()]
        if not live:
            return
        kind, program_key = live[0].kind, live[0].program_key
        tracer = self._tracer
        start = self._clock()
        if tracer is not None:
            # queue-wait phase: submission to the moment this batch
            # claimed the worker (explicit bounds — t0 happened long
            # before the tracer saw the request)
            for r in live:
                if r.trace is not None:
                    tracer.phase("queue_wait", r.trace, r.submitted, start,
                                 attrs={"session": r.tenant})
        try:
            results = self._execute(kind, program_key, live)
        except (Exception, RetriesExhausted) as e:  # noqa: BLE001
            now = self._clock()
            for r in live:
                r.future._set_exception(e)
                if self._metrics is not None:
                    self._metrics.inc_tenant(r.tenant, "failed")
                if tracer is not None and r.trace is not None:
                    tracer.record(f"serve.{kind}", r.trace, r.submitted, now,
                                  attrs={"error": type(e).__name__,
                                         "session": r.tenant})
            if self._metrics is not None:
                self._metrics.inc("failed", len(live))
            return
        now = self._clock()
        for r, res in zip(live, results):
            r.future._set_result(res)
            if self._metrics is not None:
                self._metrics.observe_latency(kind, now - r.submitted)
                self._metrics.inc_tenant(r.tenant, "completed")
            if tracer is not None and r.trace is not None:
                tracer.record(f"serve.{kind}", r.trace, r.submitted, now,
                              attrs={"session": r.tenant})
        if self._metrics is not None:
            self._metrics.inc("completed", len(live))
            self._metrics.inc("batches")
