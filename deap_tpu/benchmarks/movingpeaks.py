"""Moving Peaks dynamic benchmark — array-native equivalent of
``deap/benchmarks/movingpeaks.py`` (Branke 1999; fluctuating peak count per
du Plessis & Engelbrecht 2013).

The reference keeps peaks as Python lists mutated in place
(movingpeaks.py:61-332).  Here the landscape is a pytree of arrays —
positions ``(maxpeaks, dim)``, heights/widths ``(maxpeaks,)``, an ``active``
mask for the fluctuating-peak-count mode — so evaluation is a peak×individual
broadcast reducible on device, and :meth:`change_peaks` is a pure functional
update driven by a PRNG key.  A thin stateful wrapper preserves the
reference's ``__call__`` / offline-error bookkeeping API
(movingpeaks.py:209-260).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import numpy as np
import jax
import jax.numpy as jnp

__all__ = ["cone", "sphere", "function1", "MovingPeaks",
           "SCENARIO_1", "SCENARIO_2", "SCENARIO_3"]


def cone(individual, position, height, width):
    """h - w·||x - p|| (reference movingpeaks.py:33-43)."""
    d = jnp.sqrt(jnp.sum((individual - position) ** 2, axis=-1))
    return height - width * d


def sphere(individual, position, height, width):
    """h·||x - p||² (reference movingpeaks.py:45-50)."""
    return height * jnp.sum((individual - position) ** 2, axis=-1)


def function1(individual, position, height, width):
    """h / (1 + w·||x - p||²) (reference movingpeaks.py:52-59)."""
    return height / (1.0 + width * jnp.sum((individual - position) ** 2, axis=-1))


SCENARIO_1 = {"pfunc": function1, "npeaks": 5, "bfunc": None,
              "min_coord": 0.0, "max_coord": 100.0,
              "min_height": 30.0, "max_height": 70.0, "uniform_height": 50.0,
              "min_width": 0.0001, "max_width": 0.2, "uniform_width": 0.1,
              "lambda_": 0.0, "move_severity": 1.0, "height_severity": 7.0,
              "width_severity": 0.01, "period": 5000}

SCENARIO_2 = {"pfunc": cone, "npeaks": 10, "bfunc": None,
              "min_coord": 0.0, "max_coord": 100.0,
              "min_height": 30.0, "max_height": 70.0, "uniform_height": 50.0,
              "min_width": 1.0, "max_width": 12.0, "uniform_width": 0.0,
              "lambda_": 0.5, "move_severity": 1.5, "height_severity": 7.0,
              "width_severity": 1.0, "period": 5000}

SCENARIO_3 = {"pfunc": cone, "npeaks": 50, "bfunc": lambda x: 10,
              "min_coord": 0.0, "max_coord": 100.0,
              "min_height": 30.0, "max_height": 70.0, "uniform_height": 0.0,
              "min_width": 1.0, "max_width": 12.0, "uniform_width": 0.0,
              "lambda_": 0.5, "move_severity": 1.0, "height_severity": 1.0,
              "width_severity": 0.5, "period": 1000}


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class PeaksState:
    position: jax.Array        # (maxpeaks, dim)
    height: jax.Array          # (maxpeaks,)
    width: jax.Array           # (maxpeaks,)
    last_change: jax.Array     # (maxpeaks, dim)
    active: jax.Array          # (maxpeaks,) bool


class MovingPeaks:
    """Dynamic multimodal landscape (reference MovingPeaks,
    movingpeaks.py:61-332).

    :param dim: search-space dimensionality.
    :param key: jax PRNG key (replaces the reference's injected ``random``
        module, movingpeaks.py:129).
    Scenario keyword args as in the reference table (docstring table at
    movingpeaks.py:82-104); ``npeaks`` may be an int or a
    ``[min, initial, max]`` triple with ``number_severity`` for the
    fluctuating-count mode.
    """

    def __init__(self, dim, key=None, **kargs):
        sc = dict(SCENARIO_1)
        sc.update(kargs)
        if key is None:
            key = jax.random.PRNGKey(0)
        self.key = key
        self.dim = dim
        self.pfunc = sc["pfunc"]
        self.basis_function = sc["bfunc"]
        npeaks = sc["npeaks"]
        self.minpeaks = self.maxpeaks_n = None
        if hasattr(npeaks, "__getitem__"):
            self.minpeaks, npeaks, self.maxpeaks_n = npeaks
            self.number_severity = sc["number_severity"]
            cap = self.maxpeaks_n
        else:
            cap = npeaks
        self.cap = cap
        for name in ("min_coord", "max_coord", "min_height", "max_height",
                     "min_width", "max_width", "lambda_", "move_severity",
                     "height_severity", "width_severity", "period"):
            setattr(self, name, sc[name])

        k1, k2, k3, k4, self.key = jax.random.split(self.key, 5)
        position = jax.random.uniform(k1, (cap, dim), minval=self.min_coord,
                                      maxval=self.max_coord)
        if sc["uniform_height"] != 0:
            height = jnp.full((cap,), sc["uniform_height"])
        else:
            height = jax.random.uniform(k2, (cap,), minval=self.min_height,
                                        maxval=self.max_height)
        if sc["uniform_width"] != 0:
            width = jnp.full((cap,), sc["uniform_width"])
        else:
            width = jax.random.uniform(k3, (cap,), minval=self.min_width,
                                       maxval=self.max_width)
        last_change = jax.random.uniform(k4, (cap, dim)) - 0.5
        active = jnp.arange(cap) < npeaks
        self.state = PeaksState(position, height, width, last_change, active)

        self._optimum = None
        self._error = None
        self._offline_error = 0.0
        self.nevals = 0

    # -- evaluation ---------------------------------------------------------

    def peak_values(self, individual, state: PeaksState | None = None):
        """All peak responses for one individual, inactive peaks -> -inf."""
        s = state if state is not None else self.state
        vals = self.pfunc(individual[None, :], s.position, s.height, s.width)
        vals = jnp.where(s.active, vals, -jnp.inf)
        if self.basis_function is not None:
            vals = jnp.concatenate(
                [vals, jnp.asarray(self.basis_function(individual)).reshape(1)])
        return vals

    def evaluate(self, individual, state: PeaksState | None = None):
        """Pure evaluation (max over peaks) — vmap/jit-safe, no offline-error
        bookkeeping."""
        return jnp.max(self.peak_values(individual, state)),

    def __call__(self, individual, count=True):
        """Stateful evaluation with offline-error tracking (reference
        movingpeaks.py:209-260)."""
        fitness = float(self.evaluate(jnp.asarray(individual))[0])
        if count:
            self.nevals += 1
            if self._optimum is None:
                self._optimum = self.globalMaximum()[0]
                self._error = abs(fitness - self._optimum)
            self._error = min(self._error, abs(fitness - self._optimum))
            self._offline_error += self._error
            if self.period > 0 and self.nevals % self.period == 0:
                self.changePeaks()
        return fitness,

    def globalMaximum(self):
        """Value and position of the highest peak (reference
        movingpeaks.py:183-192)."""
        s = self.state
        at_center = self.pfunc(s.position, s.position, s.height, s.width)
        at_center = jnp.where(s.active, at_center, -jnp.inf)
        i = int(jnp.argmax(at_center))
        return float(at_center[i]), np.asarray(s.position[i])

    def maximums(self):
        """All visible local maxima, sorted best-first (reference
        movingpeaks.py:194-207)."""
        s = self.state
        at_center = self.pfunc(s.position, s.position, s.height, s.width)
        out = []
        for i in range(self.cap):
            if not bool(s.active[i]):
                continue
            val = float(at_center[i])
            if val >= float(self.evaluate(s.position[i])[0]):
                out.append((val, np.asarray(s.position[i])))
        return sorted(out, key=lambda t: t[0], reverse=True)

    def offlineError(self):
        return self._offline_error / self.nevals if self.nevals else 0.0

    def currentError(self):
        return self._error

    # -- dynamics -----------------------------------------------------------

    def change_peaks_state(self, key, state: PeaksState) -> PeaksState:
        """Functional peak update (reference changePeaks,
        movingpeaks.py:262-332): correlated position shift with boundary
        reflection, Gaussian height/width change with reflection, optional
        birth/death of peaks in fluctuating mode."""
        k_num, k_shift, k_h, k_w, k_new = jax.random.split(key, 5)
        cap, dim = state.position.shape
        active = state.active

        if self.minpeaks is not None:
            ku1, ku2, kpick = jax.random.split(k_num, 3)
            npeaks = jnp.sum(active)
            r = self.maxpeaks_n - self.minpeaks
            u = jax.random.uniform(ku1, ())
            amount = jnp.round(r * jax.random.uniform(ku2, ())
                               * self.number_severity).astype(jnp.int32)
            shrink = u < 0.5
            n_del = jnp.minimum(npeaks - self.minpeaks, amount)
            n_add = jnp.minimum(self.maxpeaks_n - npeaks, amount)
            # random priority over slots: deactivate n_del active ones, or
            # activate n_add inactive ones
            prio = jax.random.uniform(kpick, (cap,))
            act_rank = jnp.argsort(jnp.argsort(jnp.where(active, prio, jnp.inf)))
            inact_rank = jnp.argsort(jnp.argsort(jnp.where(active, jnp.inf, prio)))
            deactivate = active & (act_rank < n_del)
            activate = ~active & (inact_rank < n_add)
            new_active = jnp.where(shrink, active & ~deactivate,
                                   active | activate)
            born = new_active & ~active
            kp, kh, kw, kc = jax.random.split(k_new, 4)
            pos_new = jax.random.uniform(kp, (cap, dim), minval=self.min_coord,
                                         maxval=self.max_coord)
            h_new = jax.random.uniform(kh, (cap,), minval=self.min_height,
                                       maxval=self.max_height)
            w_new = jax.random.uniform(kw, (cap,), minval=self.min_width,
                                       maxval=self.max_width)
            c_new = jax.random.uniform(kc, (cap, dim)) - 0.5
            state = PeaksState(
                position=jnp.where(born[:, None], pos_new, state.position),
                height=jnp.where(born, h_new, state.height),
                width=jnp.where(born, w_new, state.width),
                last_change=jnp.where(born[:, None], c_new, state.last_change),
                active=new_active)
            active = new_active

        # correlated shift, normalized to move_severity
        shift = jax.random.uniform(k_shift, (cap, dim)) - 0.5
        norm = jnp.sqrt(jnp.sum(shift ** 2, axis=1, keepdims=True))
        shift = jnp.where(norm > 0, self.move_severity * shift / norm, 0.0)
        shift = shift * (1.0 - self.lambda_) + self.lambda_ * state.last_change
        norm = jnp.sqrt(jnp.sum(shift ** 2, axis=1, keepdims=True))
        shift = jnp.where(norm > 0, self.move_severity * shift / norm, 0.0)
        new_pos = state.position + shift
        low, high = self.min_coord, self.max_coord
        reflect = (new_pos < low) | (new_pos > high)
        reflected = jnp.where(new_pos < low, 2.0 * low - new_pos,
                              jnp.where(new_pos > high, 2.0 * high - new_pos,
                                        new_pos))
        final_shift = jnp.where(reflect, -shift, shift)

        def bounce(value, change, lo, hi):
            new = value + change
            return jnp.where(new < lo, 2.0 * lo - value - change,
                             jnp.where(new > hi, 2.0 * hi - value - change, new))

        dh = jax.random.normal(k_h, (cap,)) * self.height_severity
        dw = jax.random.normal(k_w, (cap,)) * self.width_severity
        return PeaksState(
            position=reflected,
            height=bounce(state.height, dh, self.min_height, self.max_height),
            width=bounce(state.width, dw, self.min_width, self.max_width),
            last_change=final_shift,
            active=active)

    def changePeaks(self):
        key, self.key = jax.random.split(self.key)
        self.state = self.change_peaks_state(key, self.state)
        self._optimum = None
