"""Benchmark utilities — array-native equivalents of
``deap/benchmarks/tools.py``: evaluation-transform decorators
(``translate``/``rotate``/``noise``/``scale``/``bound``, reference
tools.py:25-255) and multi-objective quality metrics
(``diversity``/``convergence``/``hypervolume``/``igd``, tools.py:256-331).

The decorators wrap per-individual array evaluation functions, so they
compose with vmap: the transform becomes part of the traced evaluation
kernel.  Each decorated function carries a re-configuration method of the
same name, exactly like the reference.
"""

from __future__ import annotations

from functools import wraps

import numpy as np
import jax
import jax.numpy as jnp

from ..base import Fitness
from ..ops import hv as _hv_mod

__all__ = ["translate", "rotate", "noise", "scale", "bound",
           "diversity", "convergence", "hypervolume", "igd"]


class translate:
    """Apply the inverse translation to the individual before evaluating
    (reference tools.py:25-62)."""

    def __init__(self, vector):
        self.vector = jnp.asarray(vector)

    def __call__(self, func):
        @wraps(func)
        def wrapper(individual, *args, **kargs):
            return func(individual - self.vector, *args, **kargs)
        wrapper.translate = self.translate
        return wrapper

    def translate(self, vector):
        self.vector = jnp.asarray(vector)


class rotate:
    """Apply the inverse rotation matrix before evaluating (reference
    tools.py:64-115)."""

    def __init__(self, matrix):
        self.matrix = jnp.linalg.inv(jnp.asarray(matrix))

    def __call__(self, func):
        @wraps(func)
        def wrapper(individual, *args, **kargs):
            return func(self.matrix @ individual, *args, **kargs)
        wrapper.rotate = self.rotate
        return wrapper

    def rotate(self, matrix):
        self.matrix = jnp.linalg.inv(jnp.asarray(matrix))


class noise:
    """Add random noise to each objective (reference tools.py:117-169).
    Noise functions take a PRNG key (``f(key) -> scalar``) — the explicit-key
    analogue of the reference's zero-arg ``random.gauss`` partials.  The
    decorated evaluate gains a ``key`` keyword argument."""

    def __init__(self, noise):
        if callable(noise) or noise is None:
            self.rand_funcs = (noise,)
            self._broadcast = True
        else:
            self.rand_funcs = tuple(noise)
            self._broadcast = False

    def __call__(self, func):
        @wraps(func)
        def wrapper(individual, *args, key=None, **kargs):
            result = func(individual, *args, **kargs)
            if key is None:
                return result
            result = tuple(jnp.asarray(r) for r in result)
            funcs = self.rand_funcs * len(result) if self._broadcast else self.rand_funcs
            keys = jax.random.split(key, len(result))
            return tuple(
                r if f is None else r + f(k)
                for r, f, k in zip(result, funcs, keys))
        wrapper.noise = self.noise
        return wrapper

    def noise(self, noise):
        self.__init__(noise)


class scale:
    """Apply the inverse scaling factor before evaluating (reference
    tools.py:171-210)."""

    def __init__(self, factor):
        self.factor = 1.0 / jnp.asarray(factor)

    def __call__(self, func):
        @wraps(func)
        def wrapper(individual, *args, **kargs):
            return func(individual * self.factor, *args, **kargs)
        wrapper.scale = self.scale
        return wrapper

    def scale(self, factor):
        self.factor = 1.0 / jnp.asarray(factor)


class bound:
    """Bring operator outputs back into [low, up] by clipping, wrapping or
    mirroring (reference tools.py:212-255; the reference's body is a py2-era
    no-op stub — the documented semantics are implemented here)."""

    def __init__(self, bounds, type="clip"):
        self.low = jnp.asarray(bounds[0])
        self.up = jnp.asarray(bounds[1])
        if type == "mirror":
            self.bound = self._mirror
        elif type == "wrap":
            self.bound = self._wrap
        elif type == "clip":
            self.bound = self._clip
        else:
            raise ValueError(f"unknown bound type {type!r}")

    def _clip(self, individual):
        return jnp.clip(individual, self.low, self.up)

    def _wrap(self, individual):
        span = self.up - self.low
        return self.low + jnp.mod(individual - self.low, span)

    def _mirror(self, individual):
        span = self.up - self.low
        t = jnp.mod(individual - self.low, 2 * span)
        return self.low + jnp.where(t > span, 2 * span - t, t)

    def __call__(self, func):
        @wraps(func)
        def wrapper(*args, **kargs):
            out = func(*args, **kargs)
            if isinstance(out, tuple):
                return tuple(self.bound(o) for o in out)
            return self.bound(out)
        wrapper.bound = self.bound
        return wrapper


# ---------------------------------------------------------------------------
# Multi-objective quality metrics (reference tools.py:256-331)
# ---------------------------------------------------------------------------


def _front_values(front):
    """Accept a Fitness, a (n, nobj) raw-objective array, or a Population."""
    if isinstance(front, Fitness):
        return np.asarray(front.values)
    if hasattr(front, "fitness"):
        return np.asarray(front.fitness.values)
    return np.asarray(front)


def diversity(first_front, first, last):
    """Deb's NSGA-II diversity (spread) metric on a biobjective front
    (reference tools.py:256-277); lower is better.  ``first_front`` must be
    ordered along the front."""
    vals = _front_values(first_front)
    df = np.hypot(vals[0, 0] - first[0], vals[0, 1] - first[1])
    dl = np.hypot(vals[-1, 0] - last[0], vals[-1, 1] - last[1])
    dt = np.hypot(np.diff(vals[:, 0]), np.diff(vals[:, 1]))
    if len(dt) == 0:
        return float(df + dl)
    dm = np.mean(dt)
    return float((df + dl + np.sum(np.abs(dt - dm)))
                 / (df + dl + len(dt) * dm))


def convergence(first_front, optimal_front):
    """Mean distance from front members to the nearest optimal point
    (reference tools.py:278-296); lower is better."""
    vals = _front_values(first_front)
    opt = np.asarray(optimal_front)
    d = np.sqrt(((vals[:, None, :] - opt[None, :, :]) ** 2).sum(-1))
    return float(np.mean(np.min(d, axis=1)))


def hypervolume(front, ref=None):
    """Absolute hypervolume of a front (reference tools.py:299-312): computed
    on ``-wvalues`` (implicit minimization); default reference point is the
    worst value + 1 per objective."""
    if isinstance(front, Fitness):
        wobj = -np.asarray(front.wvalues)
    elif hasattr(front, "fitness"):
        wobj = -np.asarray(front.fitness.wvalues)
    else:
        wobj = np.asarray(front)
    if ref is None:
        ref = np.max(wobj, axis=0) + 1
    return float(_hv_mod.hypervolume(wobj, ref))


def igd(A, Z):
    """Inverse generational distance (reference tools.py:314-321)."""
    A = np.asarray(A)
    Z = np.asarray(Z)
    d = np.sqrt(((A[:, None, :] - Z[None, :, :]) ** 2).sum(-1))
    return float(np.mean(np.min(d, axis=0)))
