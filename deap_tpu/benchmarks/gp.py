"""Symbolic-regression target functions — array-native equivalents of
``deap/benchmarks/gp.py`` (reference gp.py:18-130).  ``data`` is a 1-D array
of input variables; every function is jnp math, vmappable over sample
points."""

from __future__ import annotations

import jax.numpy as jnp

__all__ = ["kotanchek", "salustowicz_1d", "salustowicz_2d", "unwrapped_ball",
           "rational_polynomial", "rational_polynomial2", "sin_cos", "ripple"]


def kotanchek(data):
    """Kotanchek (reference gp.py:18-31)."""
    return jnp.exp(-(data[0] - 1.0) ** 2) / (3.2 + (data[1] - 2.5) ** 2)


def salustowicz_1d(data):
    """Salustowicz 1-D (reference gp.py:33-45)."""
    x = data[0]
    return (jnp.exp(-x) * x ** 3 * jnp.cos(x) * jnp.sin(x)
            * (jnp.cos(x) * jnp.sin(x) ** 2 - 1.0))


def salustowicz_2d(data):
    """Salustowicz 2-D (reference gp.py:47-59)."""
    x = data[0]
    return (jnp.exp(-x) * x ** 3 * jnp.cos(x) * jnp.sin(x)
            * (jnp.cos(x) * jnp.sin(x) ** 2 - 1.0) * (data[1] - 5.0))


def unwrapped_ball(data):
    """Unwrapped ball (reference gp.py:60-73)."""
    return 10.0 / (5.0 + jnp.sum((data - 3.0) ** 2))


def rational_polynomial(data):
    """3-D rational polynomial (reference gp.py:74-87)."""
    return (30.0 * (data[0] - 1.0) * (data[2] - 1.0)
            / (data[1] ** 2 * (data[0] - 10.0)))


def rational_polynomial2(data):
    """2-D rational polynomial (reference gp.py:116-130)."""
    return (((data[0] - 3.0) ** 4 + (data[1] - 3.0) ** 3 - (data[1] - 3.0))
            / ((data[1] - 2.0) ** 4 + 10.0))


def sin_cos(data):
    """sin·cos product (reference gp.py:88-101; the reference body is
    missing its ``return`` — a py2-era bug — the documented formula is
    implemented here)."""
    return 6.0 * jnp.sin(data[0]) * jnp.cos(data[1])


def ripple(data):
    """Ripple (reference gp.py:102-115)."""
    return ((data[0] - 3.0) * (data[1] - 3.0)
            + 2.0 * jnp.sin((data[0] - 4.0) * (data[1] - 4.0)))
