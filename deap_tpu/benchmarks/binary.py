"""Binary benchmark functions — array-native equivalents of
``deap/benchmarks/binary.py``: the ``bin2float`` decoding decorator and the
deceptive trap / Chuang / Royal Road functions (reference binary.py:20-143).

Individuals are 1-D 0/1 integer arrays; string-parsing of the reference
(``int("".join(...), 2)``) becomes a dot product with a power-of-two basis.
"""

from __future__ import annotations

from functools import wraps

import jax.numpy as jnp

__all__ = ["bin2float", "trap", "inv_trap", "chuang_f1", "chuang_f2",
           "chuang_f3", "royal_road1", "royal_road2"]


def _bits_to_int(bits):
    """Big-endian bit vector -> integer value (float to allow >53-bit safely
    in f32/f64 scaled use)."""
    n = bits.shape[-1]
    basis = 2.0 ** jnp.arange(n - 1, -1, -1)
    return jnp.sum(bits * basis, axis=-1)


def bin2float(min_, max_, nbits):
    """Decorator decoding a binary genome into ``len//nbits`` floats in
    [min_, max_] before calling the wrapped function (reference
    binary.py:20-42)."""
    def wrap(function):
        @wraps(function)
        def wrapped_function(individual, *args, **kargs):
            nelem = individual.shape[-1] // nbits
            genes = individual[: nelem * nbits].reshape(nelem, nbits)
            div = 2.0 ** nbits - 1.0
            decoded = min_ + (_bits_to_int(genes) / div) * (max_ - min_)
            return function(decoded, *args, **kargs)
        return wrapped_function
    return wrap


def trap(individual):
    """Deceptive trap: k if all ones, else k-1-u (reference binary.py:44-51)."""
    u = jnp.sum(individual)
    k = individual.shape[-1]
    return jnp.where(u == k, float(k), k - 1.0 - u)


def inv_trap(individual):
    """Inverted trap: k if all zeros, else u-1 (reference binary.py:54-60)."""
    u = jnp.sum(individual)
    k = individual.shape[-1]
    return jnp.where(u == 0, float(k), u - 1.0)


def _blocks(x, start, stop, size):
    return x[start:stop].reshape(-1, size)


def chuang_f1(individual):
    """Chuang & Hsu deceptive f1: 40+1 bits, traps switched by the last bit
    (reference binary.py:62-77)."""
    blocks = _blocks(individual, 0, individual.shape[-1] - 1, 4)
    inv = jnp.sum(jnp.vectorize(inv_trap, signature="(k)->()")(blocks))
    reg = jnp.sum(jnp.vectorize(trap, signature="(k)->()")(blocks))
    return jnp.where(individual[-1] == 0, inv, reg),


def chuang_f2(individual):
    """Chuang & Hsu deceptive f2: 40+2 bits, four optima selected by the two
    last bits (reference binary.py:80-100)."""
    n = individual.shape[-1]
    pairs = individual[: n - 2].reshape(-1, 8)
    first = pairs[:, :4]
    second = pairs[:, 4:]
    ti = jnp.sum(jnp.vectorize(trap, signature="(k)->()")(first))
    ii = jnp.sum(jnp.vectorize(inv_trap, signature="(k)->()")(first))
    tj = jnp.sum(jnp.vectorize(trap, signature="(k)->()")(second))
    ij = jnp.sum(jnp.vectorize(inv_trap, signature="(k)->()")(second))
    b0, b1 = individual[-2], individual[-1]
    total = jnp.where((b0 == 0) & (b1 == 0), ii + ij,
             jnp.where((b0 == 0) & (b1 == 1), ii + tj,
              jnp.where((b0 == 1) & (b1 == 0), ti + ij, ti + tj)))
    return total,


def chuang_f3(individual):
    """Chuang & Hsu deceptive f3: 40+1 bits with a wrapped trap block
    (reference binary.py:103-118)."""
    n = individual.shape[-1]
    blocks0 = individual[: n - 1].reshape(-1, 4)
    inv0 = jnp.sum(jnp.vectorize(inv_trap, signature="(k)->()")(blocks0))
    shifted = individual[2: n - 3].reshape(-1, 4)
    inv1 = jnp.sum(jnp.vectorize(inv_trap, signature="(k)->()")(shifted))
    wrapped = jnp.concatenate([individual[-2:], individual[:2]])
    alt = inv1 + trap(wrapped)
    return jnp.where(individual[-1] == 0, inv0, alt),


def royal_road1(individual, order):
    """Royal Road R1 (reference binary.py:121-131): ``order`` points per
    complete all-ones block of length ``order``."""
    nelem = individual.shape[-1] // order
    blocks = individual[: nelem * order].reshape(nelem, order)
    value = _bits_to_int(blocks)
    max_value = 2.0 ** order - 1.0
    return jnp.sum(order * jnp.floor(value / max_value)),


def royal_road2(individual, order):
    """Royal Road R2 (reference binary.py:134-142): sum of R1 at doubling
    block sizes up to order**2."""
    total = 0.0
    norder = order
    while norder < order ** 2:
        total = total + royal_road1(individual, norder)[0]
        norder *= 2
    return total,
