"""Benchmark objective functions — array-native equivalents of
``deap/benchmarks/__init__.py`` (all ~34 continuous single- and
multi-objective functions, same formulas, same tuple-returning convention).

Each function maps one individual (a 1-D jnp array) to a tuple of objective
scalars, exactly like the reference's generator-sum implementations (e.g.
rastrigin at benchmarks/__init__.py:220-241); the framework vmaps them over
the population, so every formula below compiles to a handful of fused
elementwise + reduction kernels over a ``(pop, dim)`` array.

Multi-objective families: Kursawe, Schaffer, ZDT1-4/6, DTLZ1-7, Fonseca,
Poloni, Dent (reference benchmarks/__init__.py:364-688).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from . import binary, gp, movingpeaks, tools  # noqa: F401  (subpackages)

pi = jnp.pi

__all__ = [
    "rand", "plane", "sphere", "cigar", "rosenbrock", "h1", "ackley",
    "bohachevsky", "griewank", "rastrigin", "rastrigin_scaled",
    "rastrigin_skew", "schaffer", "schwefel", "himmelblau", "shekel",
    "kursawe", "schaffer_mo", "zdt1", "zdt2", "zdt3", "zdt4", "zdt6",
    "dtlz1", "dtlz2", "dtlz3", "dtlz4", "dtlz5", "dtlz6", "dtlz7",
    "fonseca", "poloni", "dent",
]


# --- unimodal (reference benchmarks/__init__.py:26-117) --------------------

def rand(individual, key):
    """Random test objective (reference :26-42).  Unlike the reference's
    global-``random`` draw, takes an explicit PRNG key."""
    return jax.random.uniform(key, ()),


def plane(individual):
    """Plane test objective (reference :44-60)."""
    return individual[0],


def sphere(individual):
    """Sphere: sum x_i^2 (reference :62-78)."""
    return jnp.sum(individual * individual),


def cigar(individual):
    """Cigar: x_0^2 + 1e6 * sum x_i^2 (reference :80-96)."""
    return individual[0] ** 2 + 1e6 * jnp.sum(individual * individual),


def rosenbrock(individual):
    """Rosenbrock (reference :98-118)."""
    x = individual[:-1]
    y = individual[1:]
    return jnp.sum(100.0 * (x * x - y) ** 2 + (1.0 - x) ** 2),


def h1(individual):
    """H1 2-D maximization landscape (reference :120-146)."""
    x0, x1 = individual[0], individual[1]
    num = jnp.sin(x0 - x1 / 8.0) ** 2 + jnp.sin(x1 + x0 / 8.0) ** 2
    denum = jnp.sqrt((x0 - 8.6998) ** 2 + (x1 - 6.7665) ** 2) + 1.0
    return num / denum,


# --- multimodal (reference :150-361) ---------------------------------------

def ackley(individual):
    """Ackley (reference :150-171)."""
    n = individual.shape[-1]
    return (20.0 - 20.0 * jnp.exp(-0.2 * jnp.sqrt(jnp.mean(individual ** 2)))
            + jnp.e - jnp.exp(jnp.mean(jnp.cos(2.0 * pi * individual)))),


def bohachevsky(individual):
    """Bohachevsky (reference :174-194)."""
    x = individual[:-1]
    x1 = individual[1:]
    return jnp.sum(x ** 2 + 2.0 * x1 ** 2 - 0.3 * jnp.cos(3.0 * pi * x)
                   - 0.4 * jnp.cos(4.0 * pi * x1) + 0.7),


def griewank(individual):
    """Griewank (reference :197-217)."""
    i = jnp.arange(1, individual.shape[-1] + 1, dtype=individual.dtype)
    return (jnp.sum(individual ** 2) / 4000.0
            - jnp.prod(jnp.cos(individual / jnp.sqrt(i))) + 1.0),


def rastrigin(individual):
    """Rastrigin (reference :220-241) — the flagship GA benchmark config."""
    n = individual.shape[-1]
    return 10.0 * n + jnp.sum(individual ** 2
                              - 10.0 * jnp.cos(2.0 * pi * individual)),


def rastrigin_scaled(individual):
    """Scaled Rastrigin (reference :242-251)."""
    n = individual.shape[-1]
    i = jnp.arange(n, dtype=individual.dtype)
    s = 10.0 ** (i / (n - 1)) * individual
    return 10.0 * n + jnp.sum(s ** 2 - 10.0 * jnp.cos(2.0 * pi * s)),


def rastrigin_skew(individual):
    """Skewed Rastrigin (reference :253-265)."""
    n = individual.shape[-1]
    s = jnp.where(individual > 0, 10.0 * individual, individual)
    return 10.0 * n + jnp.sum(s ** 2 - 10.0 * jnp.cos(2.0 * pi * s)),


def schaffer(individual):
    """Schaffer (reference :267-288)."""
    x = individual[:-1]
    x1 = individual[1:]
    s = x ** 2 + x1 ** 2
    return jnp.sum(s ** 0.25 * (jnp.sin(50.0 * s ** 0.1) ** 2 + 1.0)),


def schwefel(individual):
    """Schwefel (reference :291-313)."""
    n = individual.shape[-1]
    return (418.9828872724339 * n
            - jnp.sum(individual * jnp.sin(jnp.sqrt(jnp.abs(individual))))),


def himmelblau(individual):
    """Himmelblau 2-D (reference :315-338)."""
    x0, x1 = individual[0], individual[1]
    return ((x0 * x0 + x1 - 11.0) ** 2 + (x0 + x1 * x1 - 7.0) ** 2),


def shekel(individual, a, c):
    """Shekel multimodal family (reference :341-361); ``a`` (m, dim) peak
    locations, ``c`` (m,) widths."""
    a = jnp.asarray(a)
    c = jnp.asarray(c)
    d2 = jnp.sum((individual[None, :] - a) ** 2, axis=1)
    return jnp.sum(1.0 / (c + d2)),


# --- multi-objective (reference :364-688) ----------------------------------

def kursawe(individual):
    """Kursawe (reference :364-376)."""
    x = individual[:-1]
    y = individual[1:]
    f1 = jnp.sum(-10.0 * jnp.exp(-0.2 * jnp.sqrt(x * x + y * y)))
    f2 = jnp.sum(jnp.abs(individual) ** 0.8
                 + 5.0 * jnp.sin(individual ** 3))
    return f1, f2


def schaffer_mo(individual):
    """Schaffer bi-objective on one attribute (reference :379-389)."""
    return individual[0] ** 2, (individual[0] - 2.0) ** 2


def _zdt_g(individual):
    n = individual.shape[-1]
    return 1.0 + 9.0 * jnp.sum(individual[1:]) / (n - 1)


def zdt1(individual):
    """ZDT1 (reference :391-403) — the NSGA-II CI benchmark."""
    g = _zdt_g(individual)
    f1 = individual[0]
    f2 = g * (1.0 - jnp.sqrt(f1 / g))
    return f1, f2


def zdt2(individual):
    """ZDT2 (reference :405-419)."""
    g = _zdt_g(individual)
    f1 = individual[0]
    f2 = g * (1.0 - (f1 / g) ** 2)
    return f1, f2


def zdt3(individual):
    """ZDT3 (reference :421-435)."""
    g = _zdt_g(individual)
    f1 = individual[0]
    f2 = g * (1.0 - jnp.sqrt(f1 / g) - f1 / g * jnp.sin(10.0 * pi * f1))
    return f1, f2


def zdt4(individual):
    """ZDT4 (reference :437-450)."""
    n = individual.shape[-1]
    tail = individual[1:]
    g = 1.0 + 10.0 * (n - 1) + jnp.sum(tail ** 2
                                       - 10.0 * jnp.cos(4.0 * pi * tail))
    f1 = individual[0]
    f2 = g * (1.0 - jnp.sqrt(f1 / g))
    return f1, f2


def zdt6(individual):
    """ZDT6 (reference :452-465)."""
    n = individual.shape[-1]
    g = 1.0 + 9.0 * (jnp.sum(individual[1:]) / (n - 1)) ** 0.25
    f1 = 1.0 - jnp.exp(-4.0 * individual[0]) * jnp.sin(6.0 * pi * individual[0]) ** 6
    f2 = g * (1.0 - (f1 / g) ** 2)
    return f1, f2


def dtlz1(individual, obj):
    """DTLZ1 (reference :467-493); ``obj`` objectives, linear front."""
    xm = individual[obj - 1:]
    g = 100.0 * (xm.shape[-1] + jnp.sum((xm - 0.5) ** 2
                                        - jnp.cos(20.0 * pi * (xm - 0.5))))
    f = [0.5 * jnp.prod(individual[:obj - 1]) * (1.0 + g)]
    for m in range(obj - 2, -1, -1):
        f.append(0.5 * jnp.prod(individual[:m]) * (1.0 - individual[m]) * (1.0 + g))
    return tuple(f)


def _dtlz_spherical(individual, obj, g, transform=lambda x: x):
    xc = transform(individual[:obj - 1])
    cos_t = jnp.cos(0.5 * pi * xc)
    f = [(1.0 + g) * jnp.prod(cos_t)]
    for m in range(obj - 2, -1, -1):
        f.append((1.0 + g) * jnp.prod(cos_t[:m]) * jnp.sin(0.5 * pi * xc[m]))
    return tuple(f)


def dtlz2(individual, obj):
    """DTLZ2 (reference :495-521); spherical front."""
    xm = individual[obj - 1:]
    g = jnp.sum((xm - 0.5) ** 2)
    return _dtlz_spherical(individual, obj, g)


def dtlz3(individual, obj):
    """DTLZ3 (reference :523-548); spherical front, Rastrigin-like g."""
    xm = individual[obj - 1:]
    g = 100.0 * (xm.shape[-1] + jnp.sum((xm - 0.5) ** 2
                                        - jnp.cos(20.0 * pi * (xm - 0.5))))
    return _dtlz_spherical(individual, obj, g)


def dtlz4(individual, obj, alpha):
    """DTLZ4 (reference :550-577); meta-variable mapping x -> x^alpha."""
    xm = individual[obj - 1:]
    g = jnp.sum((xm - 0.5) ** 2)
    return _dtlz_spherical(individual, obj, g, transform=lambda x: x ** alpha)


def dtlz5(ind, n_objs):
    """DTLZ5 (reference :579-597); degenerate curve front.  Reproduces the
    reference's exact index conventions (theta over ``ind[1:]`` in f_0)."""
    gval = jnp.sum((ind[n_objs - 1:] - 0.5) ** 2)
    theta = lambda x: pi / (4.0 * (1.0 + gval)) * (1.0 + 2.0 * gval * x)
    fit = [(1.0 + gval) * jnp.cos(pi / 2.0 * ind[0]) * jnp.prod(jnp.cos(theta(ind[1:])))]
    for m in range(n_objs - 1, 0, -1):
        if m == 1:
            fit.append((1.0 + gval) * jnp.sin(pi / 2.0 * ind[0]))
        else:
            fit.append((1.0 + gval) * jnp.cos(pi / 2.0 * ind[0])
                       * jnp.prod(jnp.cos(theta(ind[1:m - 1])))
                       * jnp.sin(theta(ind[m - 1])))
    return tuple(fit)


def dtlz6(ind, n_objs):
    """DTLZ6 (reference :599-617); like DTLZ5 with g = sum x^0.1."""
    gval = jnp.sum(ind[n_objs - 1:] ** 0.1)
    theta = lambda x: pi / (4.0 * (1.0 + gval)) * (1.0 + 2.0 * gval * x)
    fit = [(1.0 + gval) * jnp.cos(pi / 2.0 * ind[0]) * jnp.prod(jnp.cos(theta(ind[1:])))]
    for m in range(n_objs - 1, 0, -1):
        if m == 1:
            fit.append((1.0 + gval) * jnp.sin(pi / 2.0 * ind[0]))
        else:
            fit.append((1.0 + gval) * jnp.cos(pi / 2.0 * ind[0])
                       * jnp.prod(jnp.cos(theta(ind[1:m - 1])))
                       * jnp.sin(theta(ind[m - 1])))
    return tuple(fit)


def dtlz7(ind, n_objs):
    """DTLZ7 (reference :619-628); disconnected front."""
    tail = ind[n_objs - 1:]
    gval = 1.0 + 9.0 / tail.shape[-1] * jnp.sum(tail)
    head = ind[:n_objs - 1]
    fit = [ind[i] for i in range(n_objs - 1)]
    fit.append((1.0 + gval) * (n_objs - jnp.sum(
        head / (1.0 + gval) * (1.0 + jnp.sin(3.0 * pi * head)))))
    return tuple(fit)


def fonseca(individual):
    """Fonseca & Fleming (reference :630-643)."""
    x = individual[:3]
    f1 = 1.0 - jnp.exp(-jnp.sum((x - 1.0 / jnp.sqrt(3.0)) ** 2))
    f2 = 1.0 - jnp.exp(-jnp.sum((x + 1.0 / jnp.sqrt(3.0)) ** 2))
    return f1, f2


def poloni(individual):
    """Poloni (reference :645-668)."""
    x1, x2 = individual[0], individual[1]
    a1 = 0.5 * jnp.sin(1.0) - 2.0 * jnp.cos(1.0) + jnp.sin(2.0) - 1.5 * jnp.cos(2.0)
    a2 = 1.5 * jnp.sin(1.0) - jnp.cos(1.0) + 2.0 * jnp.sin(2.0) - 0.5 * jnp.cos(2.0)
    b1 = 0.5 * jnp.sin(x1) - 2.0 * jnp.cos(x1) + jnp.sin(x2) - 1.5 * jnp.cos(x2)
    b2 = 1.5 * jnp.sin(x1) - jnp.cos(x1) + 2.0 * jnp.sin(x2) - 0.5 * jnp.cos(x2)
    return (1.0 + (a1 - b1) ** 2 + (a2 - b2) ** 2,
            (x1 + 3.0) ** 2 + (x2 + 1.0) ** 2)


def dent(individual, lambda_=0.85):
    """Dent (reference :670-687)."""
    x1, x2 = individual[0], individual[1]
    d = lambda_ * jnp.exp(-(x1 - x2) ** 2)
    s = jnp.sqrt(1.0 + (x1 + x2) ** 2)
    t = jnp.sqrt(1.0 + (x1 - x2) ** 2)
    f1 = 0.5 * (s + t + x1 - x2) + d
    f2 = 0.5 * (s + t - x1 + x2) + d
    return f1, f2
