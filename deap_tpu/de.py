"""Differential Evolution — array-native.

The reference implements DE purely as examples (examples/de/basic.py:40-77:
rand/1/bin with ``selRandom(k=3)`` donors, one forced crossover index, greedy
replacement; examples/de/sphere.py uses a low-level variant; de/dynamic.py
runs multi-population DE with brownian individuals on MovingPeaks).  Here a
whole generation is one jitted kernel: donor indices are drawn per-agent,
the trial vector is built with a bernoulli + forced-index mask, and the
greedy selection is a vectorized ``where``.

``de_step`` covers the classic strategies via ``variant``:

* ``"rand/1/bin"`` (reference basic.py) — donor base is a random distinct
  agent;
* ``"best/1/bin"`` — donor base is the population best;
* ``"rand/2/bin"`` / ``"best/2/bin"`` — two difference pairs.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

from .algorithms import _hof_setup, _norm_eval, _record
from .base import Fitness, Population
from .utils.support import Logbook
from .observability.sinks import emit_text

__all__ = ["de_step", "de"]


def _distinct_indices(key, n: int, k: int) -> jax.Array:
    """(n, k) donor indices, each row drawn without replacement and biased
    away from the row's own index (reference draws ``selRandom(pop, k=3)``
    — which *can* collide; we do one better and exclude self/duplicates via
    per-row permutation)."""
    keys = jax.random.split(key, n)

    def row(i, k_r):
        perm = jax.random.permutation(k_r, n - 1)[:k]
        return jnp.where(perm >= i, perm + 1, perm)   # skip self

    return jax.vmap(row)(jnp.arange(n), keys)


def de_step(key, population: Population, evaluate: Callable,
            cr: float = 0.25, f: float = 1.0,
            variant: str = "rand/1/bin") -> Population:
    """One DE generation (reference examples/de/basic.py:55-77), jittable.

    For each agent ``x``: pick donors, build ``v = base + f*(b - c)``
    (one or two difference pairs), binomial-crossover into a trial ``y``
    with at least one mutated component (the reference's forced
    ``index = randrange(NDIM)``), evaluate, keep the better of ``x``/``y``.
    """
    genome = population.genome
    if not isinstance(genome, jnp.ndarray):
        raise TypeError("de_step requires a flat (pop, dim) genome array")
    n, dim = genome.shape
    base_kind, ndiff, _ = variant.split("/")
    ndiff = int(ndiff)
    if n < 2 + 2 * ndiff:
        raise ValueError(
            f"variant {variant!r} needs a population of at least "
            f"{2 + 2 * ndiff} (got {n}) to draw distinct donors")

    k_idx, k_cr, k_force = jax.random.split(key, 3)
    donors = _distinct_indices(k_idx, n, 1 + 2 * ndiff)

    w = population.fitness.masked_wvalues()[:, 0]
    if base_kind == "best":
        base = genome[jnp.argmax(w)][None, :]
    else:
        base = genome[donors[:, 0]]
    diff = jnp.zeros_like(genome)
    for d in range(ndiff):
        b = genome[donors[:, 1 + 2 * d]]
        c = genome[donors[:, 2 + 2 * d]]
        diff = diff + (b - c)
    v = base + f * diff

    cross = jax.random.uniform(k_cr, (n, dim)) < cr
    forced = jax.random.randint(k_force, (n,), 0, dim)
    cross = cross | (jnp.arange(dim)[None, :] == forced[:, None])
    y = jnp.where(cross, v, genome)

    weights = population.fitness.weights
    y_vals = jax.vmap(_norm_eval(evaluate))(y)
    y_w = (y_vals * jnp.asarray(weights, y_vals.dtype))[:, 0]

    keep_trial = y_w > w
    new_genome = jnp.where(keep_trial[:, None], y, genome)
    new_vals = jnp.where(keep_trial[:, None], y_vals, population.fitness.values)
    fit = Fitness(values=new_vals,
                  valid=population.fitness.valid | keep_trial,
                  weights=weights)
    return Population(genome=new_genome, fitness=fit)


def de(key, population: Population, evaluate: Callable, ngen: int,
       cr: float = 0.25, f: float = 1.0, variant: str = "rand/1/bin",
       stats=None, halloffame=None, verbose=False):
    """Scanned DE loop (the reference example's main(), basic.py:40-88).
    The initial population is evaluated first, like the reference's
    pre-loop eval.  Returns ``(population, logbook)``."""
    vals = jax.vmap(_norm_eval(evaluate))(population.genome)
    population = population.evaluated(vals)

    hof_state, hof_upd = _hof_setup(halloffame, population)

    def gen(carry, _):
        key, pop, hof = carry
        key, k = jax.random.split(key)
        pop = de_step(k, pop, evaluate, cr=cr, f=f, variant=variant)
        if hof is not None:
            hof = hof_upd(hof, pop)
        return (key, pop, hof), _record(stats, pop, pop.size)

    (key, population, hof_state), stacked = lax.scan(
        gen, (key, population, hof_state), None, length=ngen)

    logbook = Logbook()
    logbook.header = ["gen", "nevals"] + (stats.fields if stats else [])
    logbook.record_stacked(gen=jnp.arange(1, ngen + 1), **stacked)
    if halloffame is not None:
        halloffame.state = hof_state
    if verbose:
        emit_text(logbook.stream)
    return population, logbook
