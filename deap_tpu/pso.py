"""Particle Swarm Optimization — array-native.

The reference implements PSO purely as examples over creator-built particle
classes (examples/pso/basic.py:27-50 — `Particle = list` with ``speed``,
``smin``/``smax``, ``best`` attributes; update rule at basic.py:40-50), plus
a constriction-coefficient multiswarm variant for dynamic landscapes
(examples/pso/multiswarm.py:83-97) and a species-based variant
(examples/pso/speciation.py).  Here the whole swarm is one
:class:`PSOState` pytree — positions, velocities, personal bests — and one
jitted step updates every particle on the MXU-friendly ``(pop, dim)`` layout.

Three entry points:

* :func:`pso_init` / :func:`pso_step` / :func:`pso` — canonical gbest PSO
  (basic.py's ``phi1``/``phi2`` rule with speed limits).
* ``constriction=True`` — Clerc–Kennedy χ update used by the dynamic
  multiswarm example (multiswarm.py:83-97: ``chi=0.729843788, c=2.05``).
* :func:`multiswarm_step` — multi-swarm PSO with exclusion + anti-convergence
  + quantum-cloud reinitialisation (Blackwell & Branke, as in
  examples/pso/multiswarm.py): swarms are a stacked leading axis, vmapped.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

from .base import Fitness, Population
from .utils.support import Logbook
from .observability.sinks import emit_text

__all__ = ["PSOState", "pso_init", "pso_step", "pso",
           "MultiswarmState", "multiswarm_init", "multiswarm_step"]


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class PSOState:
    """Whole-swarm state: the array-native equivalent of the reference's
    per-particle ``speed``/``best`` attributes plus the global ``best``
    (examples/pso/basic.py:27-77)."""

    position: jax.Array        # (pop, dim)
    speed: jax.Array           # (pop, dim)
    pbest: jax.Array           # (pop, dim)   personal best position
    pbest_w: jax.Array         # (pop,)       personal best weighted fitness
    gbest: jax.Array           # (dim,)       global best position
    gbest_w: jax.Array         # ()           global best weighted fitness


def _weighted(evaluate: Callable, weights) -> Callable:
    if len(weights) != 1:
        raise ValueError("PSO supports single-objective fitness")
    from .algorithms import _norm_eval
    w = float(weights[0])
    norm = _norm_eval(evaluate)
    return lambda x: norm(x)[0] * w


def pso_init(key, n: int, dim: int, pmin: float, pmax: float,
             smin: float, smax: float) -> PSOState:
    """Uniform positions in [pmin, pmax], speeds in [smin, smax]
    (reference generate(), examples/pso/basic.py:33-38)."""
    kp, ks = jax.random.split(key)
    pos = jax.random.uniform(kp, (n, dim), minval=pmin, maxval=pmax)
    spd = jax.random.uniform(ks, (n, dim), minval=smin, maxval=smax)
    return PSOState(position=pos, speed=spd, pbest=pos,
                    # explicit dtype: a bare float fill traces weak-typed,
                    # and the first strong-f32 value fed back here (e.g. a
                    # checkpoint restore) would fork a recompile — pinned
                    # by the program-contract recompile-hazard pass
                    pbest_w=jnp.full((n,), -jnp.inf, jnp.float32),
                    gbest=pos[0],
                    gbest_w=jnp.asarray(-jnp.inf, jnp.float32))


def pso_step(key, state: PSOState, evaluate: Callable, weights=(-1.0,),
             phi1: float = 2.0, phi2: float = 2.0,
             smin: float | None = None, smax: float | None = None,
             constriction: bool = False, chi: float = 0.729843788,
             c: float = 2.05) -> tuple[PSOState, jax.Array]:
    """One synchronous PSO generation, jit-friendly.

    Canonical rule (reference updateParticle, basic.py:40-50):
    ``v += u1*(pbest - x) + u2*(gbest - x)``, with per-component speed
    clamping to [smin, smax] by magnitude; constriction rule
    (multiswarm.py:83-97): ``v = chi*(v + ce1*(gbest-x) + ce2*(pbest-x))
    - (1-chi)*v`` — we reproduce the reference's net effect
    ``v_new = v + a`` with ``a = chi*(ce1_p + ce2_g) - (1-chi)*v``.

    Evaluation happens *first* (as in the reference main loop,
    basic.py:61-72: evaluate, update bests, then move), so the returned
    state's bests reflect the *pre-move* positions.  Returns
    ``(new_state, raw_fitness_of_evaluated_positions)``.
    """
    one = _weighted(evaluate, weights)
    wfit = jax.vmap(one)(state.position)              # (pop,)

    better = wfit > state.pbest_w
    pbest = jnp.where(better[:, None], state.position, state.pbest)
    pbest_w = jnp.where(better, wfit, state.pbest_w)

    i_best = jnp.argmax(pbest_w)
    g_better = pbest_w[i_best] > state.gbest_w
    gbest = jnp.where(g_better, pbest[i_best], state.gbest)
    gbest_w = jnp.where(g_better, pbest_w[i_best], state.gbest_w)

    k1, k2 = jax.random.split(key)
    shape = state.position.shape
    if constriction:
        ce1 = c * jax.random.uniform(k1, shape)
        ce2 = c * jax.random.uniform(k2, shape)
        a = (chi * (ce1 * (gbest - state.position)
                    + ce2 * (pbest - state.position))
             - (1.0 - chi) * state.speed)
        speed = state.speed + a
    else:
        u1 = jax.random.uniform(k1, shape, maxval=phi1)
        u2 = jax.random.uniform(k2, shape, maxval=phi2)
        speed = (state.speed + u1 * (pbest - state.position)
                 + u2 * (gbest - state.position))
        if smin is not None or smax is not None:
            mag = jnp.abs(speed)
            lo = 0.0 if smin is None else smin
            hi = jnp.inf if smax is None else smax
            speed = jnp.sign(speed) * jnp.clip(mag, lo, hi)
    position = state.position + speed

    new = PSOState(position=position, speed=speed, pbest=pbest,
                   pbest_w=pbest_w, gbest=gbest, gbest_w=gbest_w)
    w0 = float(weights[0])
    return new, wfit / w0


def pso(key, state: PSOState, evaluate: Callable, ngen: int,
        weights=(-1.0,), stats=None, verbose=False, **step_kwargs):
    """Scanned gbest-PSO loop (the reference's example main loop,
    basic.py:52-77).  Returns ``(final_state, logbook)``."""

    def gen(carry, _):
        key, st = carry
        key, k = jax.random.split(key)
        st, raw = pso_step(k, st, evaluate, weights, **step_kwargs)
        rec = {}
        if stats is not None:
            pop = Population(
                genome=st.position,
                fitness=Fitness(values=raw[:, None],
                                valid=jnp.ones(raw.shape[0], bool),
                                weights=tuple(weights)))
            rec = stats.compile(pop)
        return (key, st), rec

    (key, state), stacked = lax.scan(gen, (key, state), None, length=ngen)
    logbook = Logbook()
    logbook.header = ["gen"] + (stats.fields if stats else [])
    logbook.record_stacked(gen=jnp.arange(1, ngen + 1), **stacked)
    if verbose:
        emit_text(logbook.stream)
    return state, logbook


# ---------------------------------------------------------------------------
# Multiswarm PSO for dynamic landscapes (examples/pso/multiswarm.py)
# ---------------------------------------------------------------------------


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class MultiswarmState:
    """Stacked swarms: leading axis = swarm.  ``active`` masks live swarms
    (the reference grows/kills python lists of swarms; we keep a static
    capacity and a mask — SURVEY §7's masked dynamic-size rule)."""

    position: jax.Array        # (ns, np, dim)
    speed: jax.Array           # (ns, np, dim)
    pbest: jax.Array           # (ns, np, dim)
    pbest_w: jax.Array         # (ns, np)
    sbest: jax.Array           # (ns, dim)    per-swarm best
    sbest_w: jax.Array         # (ns,)
    active: jax.Array          # (ns,) bool


def multiswarm_init(key, nswarm: int, nparticle: int, dim: int,
                    pmin: float, pmax: float, active: int | None = None
                    ) -> MultiswarmState:
    kp, ks = jax.random.split(key)
    span = (pmax - pmin) / 2.0
    pos = jax.random.uniform(kp, (nswarm, nparticle, dim),
                             minval=pmin, maxval=pmax)
    spd = jax.random.uniform(ks, (nswarm, nparticle, dim),
                             minval=-span, maxval=span)
    act = jnp.arange(nswarm) < (nswarm if active is None else active)
    return MultiswarmState(
        position=pos, speed=spd, pbest=pos,
        pbest_w=jnp.full((nswarm, nparticle), -jnp.inf, jnp.float32),
        sbest=pos[:, 0],
        sbest_w=jnp.full((nswarm,), -jnp.inf, jnp.float32),
        active=act)


def _quantum_cloud(key, centre, rcloud, shape):
    """NUVD quantum cloud around ``centre`` (reference convertQuantum,
    multiswarm.py:57-77): direction ~ N(0,1) normalized, radius
    ``rcloud * |N(0, 1/3)|``."""
    kd, ku = jax.random.split(key)
    direction = jax.random.normal(kd, shape)
    norm = jnp.linalg.norm(direction, axis=-1, keepdims=True)
    u = jnp.abs(jax.random.normal(ku, shape[:-1] + (1,)) / 3.0)
    return centre + rcloud * direction * u / jnp.maximum(norm, 1e-12)


def multiswarm_step(key, state: MultiswarmState, evaluate: Callable,
                    weights=(1.0,), rexcl: float = 0.5, rcloud: float = 0.5,
                    chi: float = 0.729843788, c: float = 2.05,
                    ) -> tuple[MultiswarmState, jax.Array]:
    """One generation of multiswarm PSO with exclusion + anti-convergence
    (reference main loop, examples/pso/multiswarm.py:100-210):

    1. constriction-PSO update within each swarm (vmapped);
    2. **exclusion**: of any two swarms whose bests are closer than
       ``rexcl``, the worse one is reinitialised as a quantum cloud around
       its best;
    3. **anti-convergence**: if all swarms have converged (radius <
       ``rexcl``), the worst swarm is randomised as a quantum cloud — the
       masked-capacity stand-in for the reference's "add an extra swarm".

    Returns ``(state, per-swarm best raw fitness)``.
    """
    w0 = float(weights[0])
    one = _weighted(evaluate, weights)
    ns, npart, dim = state.position.shape

    wfit = jax.vmap(jax.vmap(one))(state.position)          # (ns, np)

    better = wfit > state.pbest_w
    pbest = jnp.where(better[..., None], state.position, state.pbest)
    pbest_w = jnp.where(better, wfit, state.pbest_w)

    i_best = jnp.argmax(pbest_w, axis=1)                    # (ns,)
    row = jnp.take_along_axis(pbest, i_best[:, None, None], 1)[:, 0]
    row_w = jnp.take_along_axis(pbest_w, i_best[:, None], 1)[:, 0]
    s_better = row_w > state.sbest_w
    sbest = jnp.where(s_better[:, None], row, state.sbest)
    sbest_w = jnp.where(s_better, row_w, state.sbest_w)

    k1, k2, k3, k4 = jax.random.split(key, 4)
    shape = state.position.shape
    ce1 = c * jax.random.uniform(k1, shape)
    ce2 = c * jax.random.uniform(k2, shape)
    a = (chi * (ce1 * (sbest[:, None] - state.position)
                + ce2 * (pbest - state.position))
         - (1.0 - chi) * state.speed)
    speed = state.speed + a
    position = state.position + speed

    # exclusion: pairwise distances between swarm bests.  Exactly one of a
    # close pair is reinitialised (the strictly worse one; index breaks
    # ties), matching the reference's one-per-pair semantics.
    d = jnp.linalg.norm(sbest[:, None] - sbest[None, :], axis=-1)
    both = state.active[:, None] & state.active[None, :]
    close = (d < rexcl) & both & ~jnp.eye(ns, dtype=bool)
    idx = jnp.arange(ns)
    worse = (sbest_w[:, None] < sbest_w[None, :]) | (
        (sbest_w[:, None] == sbest_w[None, :]) & (idx[:, None] > idx[None, :]))
    reinit = jnp.any(close & worse, axis=1)                  # (ns,)

    # anti-convergence: all active swarms converged -> reinit the worst
    radius = jnp.max(
        jnp.linalg.norm(position - sbest[:, None], axis=-1), axis=1)
    all_conv = jnp.all(~state.active | (radius < rexcl))
    masked_w = jnp.where(state.active, sbest_w, jnp.inf)
    worst = jnp.argmin(masked_w)
    reinit = reinit | (all_conv & (jnp.arange(ns) == worst))

    cloud = _quantum_cloud(k3, sbest[:, None], rcloud, shape)
    span = jnp.max(jnp.abs(speed))
    new_speed = jax.random.uniform(k4, shape, minval=-span, maxval=span)
    position = jnp.where(reinit[:, None, None], cloud, position)
    speed = jnp.where(reinit[:, None, None], new_speed, speed)
    pbest = jnp.where(reinit[:, None, None], position, pbest)
    pbest_w = jnp.where(reinit[:, None], -jnp.inf, pbest_w)

    new = MultiswarmState(position=position, speed=speed, pbest=pbest,
                          pbest_w=pbest_w, sbest=sbest, sbest_w=sbest_w,
                          active=state.active)
    return new, sbest_w / w0
