"""Bounded retries with exponential backoff for flaky I/O.

A long run on a preemptible TPU slice talks to two unreliable services: a
shared filesystem (checkpoint writes) and the distributed coordinator
(:func:`deap_tpu.parallel.initialize_cluster`).  Both fail transiently —
an NFS server hiccup or a coordinator that is still booting must not kill
an otherwise-healthy run.  :func:`with_retries` is the one retry policy
both paths share; the clock and sleep are injectable so tests can assert
the exact backoff sequence without real waiting
(tests/test_resilience.py).
"""

from __future__ import annotations

import random
import time
from functools import wraps
from typing import Callable

__all__ = ["with_retries", "RetriesExhausted"]


class RetriesExhausted(RuntimeError):
    """Raised when every attempt failed.  ``__cause__`` is the last
    underlying exception; ``attempts`` counts the calls made."""

    def __init__(self, attempts: int, last: BaseException):
        super().__init__(
            f"gave up after {attempts} attempt(s): {last!r}")
        self.attempts = attempts
        self.last = last


def with_retries(fn: Callable | None = None, *, retries: int = 3,
                 backoff: float = 0.5, factor: float = 2.0,
                 max_backoff: float = 60.0, timeout: float | None = None,
                 retry_on: tuple = (OSError, TimeoutError, ConnectionError),
                 sleep: Callable[[float], None] = time.sleep,
                 clock: Callable[[], float] = time.monotonic,
                 jitter: bool = False,
                 rng: Callable[[], float] | None = None,
                 on_retry: Callable | None = None):
    """Wrap ``fn`` so transient failures are retried with exponential
    backoff.

    * ``retries`` — how many times to retry after the first failure
      (``retries + 1`` total attempts).
    * ``backoff`` / ``factor`` / ``max_backoff`` — delay before retry
      ``i`` (0-based) is ``min(backoff * factor**i, max_backoff)``.
    * ``timeout`` — total deadline in seconds measured on ``clock``; once
      waiting for the next attempt would cross it, give up immediately.
    * ``retry_on`` — exception classes considered transient; anything else
      propagates on the first occurrence (a ``ValueError`` from a corrupt
      checkpoint must not be retried into oblivion).
    * ``jitter`` — FULL jitter (AWS-style): the actual delay before retry
      ``i`` is uniform in ``[0, min(backoff * factor**i, max_backoff)]``.
      Off by default so existing callers keep their exact deterministic
      backoff sequence; reconnect storms (every client of a crashed
      backend retrying in lockstep) are what it exists to break up.
    * ``rng`` — zero-arg callable returning a float in ``[0, 1)`` used by
      ``jitter`` (defaults to :func:`random.random`); injectable so tests
      can pin the jittered sequence.
    * ``sleep`` / ``clock`` — injectable for deterministic tests.
    * ``on_retry(attempt, exc, delay)`` — optional observer hook
      (receives the post-jitter delay actually slept).

    When every attempt fails, raises :class:`RetriesExhausted` chained to
    the last exception.  Usable as a decorator (``@with_retries(...)``) or
    as a direct wrapper (``with_retries(fn, retries=5)`` returns the
    wrapped callable).
    """
    if retries < 0:
        raise ValueError("retries must be >= 0")
    draw = rng if rng is not None else random.random

    def deco(func: Callable) -> Callable:
        @wraps(func)
        def wrapper(*args, **kwargs):
            start = clock()
            last: BaseException | None = None
            for attempt in range(retries + 1):
                try:
                    return func(*args, **kwargs)
                except retry_on as e:          # noqa: PERF203
                    last = e
                    if attempt == retries:
                        break
                    delay = min(backoff * factor ** attempt, max_backoff)
                    if jitter:
                        delay *= draw()
                    if timeout is not None and \
                            clock() - start + delay > timeout:
                        break
                    if on_retry is not None:
                        on_retry(attempt, e, delay)
                    if delay > 0:
                        sleep(delay)
            raise RetriesExhausted(attempt + 1, last) from last
        return wrapper

    return deco if fn is None else deco(fn)
