"""Deterministic fault injection for the resilience test suite.

The round-3 incident (deap_tpu/selftest.py) taught this codebase that
robustness claims must be *driven*, not hoped for: every recovery path in
:func:`deap_tpu.resilience.run_resumable` is exercised by injecting the
fault it recovers from.  A :class:`FaultPlan` declares the faults; a
:class:`FaultInjector` is handed to ``run_resumable(..., faults=...)``
and deterministically delivers them:

* ``nan_at_gen`` — the evaluation of generation ``g`` returns NaN for the
  chosen rows (the driver splits its scan segment so generation ``g``
  runs with the poisoned evaluator; everything else is untouched).
* ``ckpt_fail_times`` — the first N checkpoint saves raise ``OSError``
  (a flaky shared filesystem); combined with ``ckpt_delay`` the virtual
  clock also makes them *slow*, driving ``with_retries`` timeout logic
  without real sleeping.
* ``preempt_at_gen`` — once the run reaches generation ``g`` the injector
  delivers the same preemption flag a real SIGTERM sets, so the driver
  takes the checkpoint-and-exit path.

The injector records everything it did (``saves_failed``,
``gens_poisoned``, ``preempts_delivered``) so tests can assert the fault
actually fired — a recovery test whose fault never triggered is a false
pass.  ``gens_poisoned`` records that the poisoned evaluator was
*installed* for that generation; the poison provably lands whenever any
row is re-evaluated that generation (see ``_poison_rows``), which a
strict test should confirm through the observable effect — the
quarantine sentinel or NaN in that generation's stats, as
``tests/test_resilience.py`` does.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Sequence

import jax
import jax.numpy as jnp

__all__ = ["FaultPlan", "FaultInjector", "VirtualClock"]


class VirtualClock:
    """A manually-advanced monotonic clock with a matching ``sleep`` —
    lets backoff/timeout logic run instantly in tests."""

    def __init__(self, start: float = 0.0):
        self.now = float(start)
        self.sleeps: list[float] = []

    def time(self) -> float:
        return self.now

    def sleep(self, dt: float) -> None:
        self.sleeps.append(float(dt))
        self.now += float(dt)


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """Declarative fault schedule (all faults optional).

    ``nan_at_gen`` is 1-based (the loop's generation numbering); a plan
    targeting the generation-0 initial evaluation is rejected rather than
    silently never firing."""

    nan_at_gen: int | None = None        # poison this generation's eval
    nan_rows: Sequence[int] = (0,)       # rows to poison
    nan_value: float = float("nan")      # or e.g. inf
    ckpt_fail_times: int = 0             # first N saves raise OSError
    ckpt_delay: float = 0.0              # virtual seconds per save
    preempt_at_gen: int | None = None    # deliver preemption at gen >= g

    def __post_init__(self):
        if self.nan_at_gen is not None and self.nan_at_gen < 1:
            raise ValueError(
                f"nan_at_gen={self.nan_at_gen}: generations are 1-based; "
                "a gen-0 (initial-evaluation) fault would silently never "
                "fire")


class FaultInjector:
    """Stateful delivery of a :class:`FaultPlan` (one run per injector —
    counters are not reset on resume, which is exactly what a flaky
    filesystem looks like to a restarted process)."""

    def __init__(self, plan: FaultPlan, clock: VirtualClock | None = None):
        self.plan = plan
        self.clock = clock if clock is not None else VirtualClock()
        self.saves_attempted = 0
        self.saves_failed = 0
        self.gens_poisoned: list[int] = []
        self.preempts_delivered = 0

    # -- checkpoint I/O ------------------------------------------------------

    def wrap_save(self, save_fn: Callable) -> Callable:
        """Make ``save_fn`` fail the first ``ckpt_fail_times`` calls and
        cost ``ckpt_delay`` virtual seconds per attempt."""
        def save(*args, **kwargs):
            self.saves_attempted += 1
            if self.plan.ckpt_delay:
                self.clock.now += self.plan.ckpt_delay
            if self.saves_failed < self.plan.ckpt_fail_times:
                self.saves_failed += 1
                raise OSError(
                    f"injected checkpoint write failure "
                    f"#{self.saves_failed}/{self.plan.ckpt_fail_times}")
            return save_fn(*args, **kwargs)
        return save

    # -- evaluator poisoning -------------------------------------------------

    def poisons_gen(self, gen: int) -> bool:
        return self.plan.nan_at_gen is not None and gen == self.plan.nan_at_gen

    def _poison_rows(self, values, skip):
        """``nan_rows`` names the target rows when every row is assigned;
        when the loop only assigns rows whose fitness is invalid (the
        reference's invalid-only economy — ``skip`` marks the rest), the
        same COUNT of actually-evaluated rows is poisoned instead, so the
        fault is guaranteed to land whenever anything is evaluated at all
        (a poison written to a skipped row would be silently discarded by
        the masked assignment — the false-pass class this module exists
        to prevent)."""
        if skip is None:
            rows = jnp.asarray(tuple(self.plan.nan_rows), jnp.int32)
        else:
            invalid_first = jnp.argsort(jnp.asarray(skip, bool))
            rows = invalid_first[:len(tuple(self.plan.nan_rows))]
        return values.at[rows].set(self.plan.nan_value)

    def poison_toolbox(self, toolbox, gen: int):
        """A shallow toolbox copy whose population-level evaluation writes
        ``nan_value`` into evaluated rows (see :meth:`_poison_rows`) —
        registered as ``evaluate_population`` so it overrides either
        evaluation tier and receives the ``skip`` mask."""
        import copy
        from ..algorithms import _norm_eval, _accepts_skip

        self.gens_poisoned.append(int(gen))

        if hasattr(toolbox, "evaluate_population"):
            base = toolbox.evaluate_population
            base_skip = _accepts_skip(base)

            def eval_pop(genome, skip=None):
                values = base(genome, skip=skip) if base_skip else base(genome)
                if values.ndim == 1:
                    values = values[:, None]
                return self._poison_rows(values, skip)
        else:
            per_ind = _norm_eval(toolbox.evaluate)

            def eval_pop(genome, skip=None):
                values = jax.vmap(per_ind)(genome)
                return self._poison_rows(values, skip)

        tb = copy.copy(toolbox)
        tb.evaluate_population = eval_pop
        return tb

    # -- preemption ----------------------------------------------------------

    def maybe_preempt(self, gen: int, deliver: Callable[[], None]) -> None:
        """Call ``deliver()`` (once) when the run has reached the planned
        preemption generation — the simulated SIGTERM."""
        if (self.plan.preempt_at_gen is not None
                and gen >= self.plan.preempt_at_gen
                and not self.preempts_delivered):
            self.preempts_delivered += 1
            deliver()
