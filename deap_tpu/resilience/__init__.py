"""Resilient evolution runtime.

Long runs on preemptible TPU slices fail in three boring, fatal ways: the
pod is preempted (SIGTERM, then gone), a user evaluator emits NaN/Inf and
silently poisons selection, or the shared filesystem flakes during a
checkpoint write.  This package makes all three survivable — and, per the
round-3 lesson, *provably* so: every recovery path is driven by the
deterministic fault-injection harness in :mod:`.faultinject`
(tests/test_resilience.py, ``deap-tpu-faultdrill``).

* :func:`run_resumable` — segment-and-checkpoint driver for the
  ``ea_simple`` family with SIGTERM-triggered saves, cross-host
  agreement, and bit-exact resume (:mod:`.runner`).
* :class:`Quarantine` — non-finite fitness policies (``penalize`` /
  ``resample`` / ``raise``) honored by
  :func:`deap_tpu.algorithms.evaluate_population` via
  ``toolbox.quarantine`` (:mod:`.quarantine`).
* :func:`with_retries` — bounded exponential-backoff retry used for
  checkpoint I/O and the cluster coordinator connection (:mod:`.retry`).
* :class:`FaultPlan` / :class:`FaultInjector` — declarative fault
  schedules for tests and drills (:mod:`.faultinject`).
* :class:`ChaosPlan` / :class:`ChaosInjector` — their wire-level
  sibling: seed-deterministic network-fault schedules executed by
  :class:`deap_tpu.serve.net.faultwire.FaultWire` proxies during fleet
  chaos drills (:mod:`.chaos`, ``deap-tpu-chaosdrill``).
* :func:`save_session_states` / :func:`load_session_states` — the
  retried checkpoint tier for every live session of a
  :class:`deap_tpu.serve.EvolutionService` (:mod:`.runner`).
"""

from .retry import with_retries, RetriesExhausted  # noqa: F401
from .quarantine import (Quarantine, NonFiniteFitnessError,  # noqa: F401
                         nonfinite_rows)
from .faultinject import FaultPlan, FaultInjector, VirtualClock  # noqa: F401
from .chaos import (ChaosLeg, ChaosPlan, ChaosFault,  # noqa: F401
                    ChaosInjector, canonical_plan)
from .runner import (run_resumable, Preempted,  # noqa: F401
                     save_session_states, load_session_states)

__all__ = [
    "run_resumable", "Preempted",
    "save_session_states", "load_session_states",
    "Quarantine", "NonFiniteFitnessError", "nonfinite_rows",
    "with_retries", "RetriesExhausted",
    "FaultPlan", "FaultInjector", "VirtualClock",
    "ChaosLeg", "ChaosPlan", "ChaosFault", "ChaosInjector",
    "canonical_plan",
]
