"""Recovery drill: exercise every resilience path ON THE TARGET BACKEND.

The CPU test suite keeps the recovery logic algorithmically honest; this
tool is the deployment-time probe (the resilience sibling of
``deap-tpu-selftest``): it runs checkpoint/restore, preemption-resume,
non-finite quarantine and retried-I/O drills against whatever
``jax.devices()`` gives, and exits non-zero if ANY recovery path fails.

    deap-tpu-faultdrill                       # target backend
    JAX_PLATFORMS=cpu deap-tpu-faultdrill
    python -m deap_tpu.resilience.faultdrill  # equivalent module form

Each drill injects its fault through
:mod:`deap_tpu.resilience.faultinject` — a drill whose fault never fired
counts as a FAILURE, not a pass.
"""

import os
import sys
import tempfile
from pathlib import Path

import numpy as np


POP = int(os.environ.get("FAULTDRILL_POP", 64))
NGEN = int(os.environ.get("FAULTDRILL_NGEN", 12))


def _setup():
    import jax
    import jax.numpy as jnp
    from deap_tpu import base
    from deap_tpu.ops import crossover, mutation, selection

    tb = base.Toolbox()
    tb.register("evaluate", lambda g: (jnp.sum(g),))
    tb.register("mate", crossover.cx_two_point)
    tb.register("mutate", mutation.mut_flip_bit, indpb=0.05)
    tb.register("select", selection.sel_tournament, tournsize=3)
    key = jax.random.PRNGKey(7)
    g = jax.random.bernoulli(key, 0.5, (POP, 32)).astype(jnp.float32)
    pop = base.Population(genome=g, fitness=base.Fitness.empty(POP, (1.0,)))
    return tb, pop, jax.random.fold_in(key, 1)


def _check(name, fn, failures):
    try:
        fn()
    except Exception as e:                                 # noqa: BLE001
        print(f"  {name:44s} FAILED  ({type(e).__name__}: {e})")
        failures.append(name)
    else:
        print(f"  {name:44s} ok")


def _drill_preempt_resume(root: Path):
    """Kill mid-run (injected preemption), resume, compare against an
    uninterrupted run — population, logbook AND telemetry MetricBuffer
    must be bitwise identical."""
    from deap_tpu.resilience import (run_resumable, Preempted, FaultPlan,
                                     FaultInjector)
    from deap_tpu.observability import Telemetry
    kw = dict(loop_kwargs=dict(cxpb=0.6, mutpb=0.3), checkpoint_every=4)

    tb, pop, key = _setup()
    tel_ref = Telemetry(flush_every=4)
    ref_pop, ref_lb = run_resumable(key, pop, tb, NGEN,
                                    ckpt_path=root / "ref.ckpt",
                                    telemetry=tel_ref, **kw)

    tb, pop, key = _setup()
    inj = FaultInjector(FaultPlan(preempt_at_gen=NGEN // 2))
    tel_cut = Telemetry(flush_every=4)
    try:
        run_resumable(key, pop, tb, NGEN, ckpt_path=root / "cut.ckpt",
                      telemetry=tel_cut, faults=inj, **kw)
        raise AssertionError("injected preemption never fired")
    except Preempted:
        pass
    tb2, pop2, key2 = _setup()
    tel_res = Telemetry(flush_every=4)
    res_pop, res_lb = run_resumable(key2, pop2, tb2, NGEN,
                                    ckpt_path=root / "cut.ckpt",
                                    telemetry=tel_res, **kw)

    np.testing.assert_array_equal(np.asarray(ref_pop.genome),
                                  np.asarray(res_pop.genome))
    np.testing.assert_array_equal(np.asarray(ref_pop.fitness.values),
                                  np.asarray(res_pop.fitness.values))
    assert ref_lb.select("nevals") == res_lb.select("nevals"), \
        "resumed logbook diverged"
    for d_ref, d_res in ((tel_ref.state.counters, tel_res.state.counters),
                         (tel_ref.state.gauges, tel_res.state.gauges)):
        for k in d_ref:
            assert (np.asarray(d_ref[k]).tobytes()
                    == np.asarray(d_res[k]).tobytes()), \
                f"telemetry {k!r} diverged across resume"


def _drill_retry_flaky_writes(root: Path):
    """Checkpoint writes failing twice must succeed on the third try
    without real sleeping and leave a loadable checkpoint."""
    from deap_tpu.resilience import run_resumable, FaultPlan, FaultInjector
    from deap_tpu.utils.checkpoint import load_checkpoint

    tb, pop, key = _setup()
    inj = FaultInjector(FaultPlan(ckpt_fail_times=2))
    run_resumable(key, pop, tb, 4, ckpt_path=root / "flaky.ckpt",
                  checkpoint_every=4, loop_kwargs=dict(cxpb=0.6, mutpb=0.3),
                  faults=inj, io_retries=3,
                  io_sleep=inj.clock.sleep, io_clock=inj.clock.time)
    assert inj.saves_failed == 2, "fault never fired"
    assert load_checkpoint(root / "flaky.ckpt")["gen"] == 4


def _drill_quarantine(root: Path):
    """A NaN evaluation mid-run must not poison selection under either
    recovery policy, and must abort loudly under 'raise'."""
    import jax
    from deap_tpu.resilience import (run_resumable, Quarantine, FaultPlan,
                                     FaultInjector, NonFiniteFitnessError)
    from deap_tpu.algorithms import evaluate_population

    for policy in ("penalize", "resample"):
        tb, pop, key = _setup()
        tb.quarantine = Quarantine(policy)
        inj = FaultInjector(FaultPlan(nan_at_gen=3, nan_rows=(0, 1)))
        out, lb = run_resumable(key, pop, tb, 6,
                                ckpt_path=root / f"q_{policy}.ckpt",
                                checkpoint_every=3,
                                loop_kwargs=dict(cxpb=0.6, mutpb=0.3),
                                faults=inj)
        assert inj.gens_poisoned == [3], "fault never fired"
        assert np.isfinite(np.asarray(out.fitness.values)).all(), \
            f"{policy}: non-finite fitness leaked through"

    tb, pop, key = _setup()
    tb.quarantine = Quarantine("raise")
    tb.register("evaluate",
                lambda g: (jax.numpy.sum(g) / 0.0,))     # all rows +inf
    try:
        evaluate_population(tb, pop)
        raise AssertionError("'raise' policy did not raise")
    except NonFiniteFitnessError:
        pass


def _drill_sharded_restore(root: Path):
    """Sharded save must restore bit-identically onto a single-device
    (smaller) mesh — the post-preemption degraded topology."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from deap_tpu.utils.checkpoint import (save_sharded_checkpoint,
                                           load_sharded_checkpoint)

    devs = jax.devices()
    mesh = Mesh(np.array(devs), ("pop",))
    x = jnp.arange(len(devs) * 16, dtype=jnp.float32).reshape(-1, 2)
    xs = jax.device_put(x, NamedSharding(mesh, P("pop")))
    save_sharded_checkpoint(root / "shard", {"x": xs, "gen": 3})

    small = Mesh(np.array(devs[:1]), ("pop",))
    like = {"x": jax.ShapeDtypeStruct(x.shape, x.dtype,
                                      sharding=NamedSharding(small, P("pop"))),
            "gen": 0}
    r = load_sharded_checkpoint(root / "shard", like)
    np.testing.assert_array_equal(np.asarray(r["x"]), np.asarray(x))
    assert r["gen"] == 3


def main() -> int:
    import jax

    print(f"backend={jax.default_backend()} devices={len(jax.devices())} "
          f"pop={POP} ngen={NGEN}")
    failures: list[str] = []
    with tempfile.TemporaryDirectory(prefix="deap_tpu_faultdrill_") as td:
        root = Path(td)
        _check("preempt mid-run -> resume bitwise-exact",
               lambda: _drill_preempt_resume(root), failures)
        _check("checkpoint writes fail twice -> retry",
               lambda: _drill_retry_flaky_writes(root), failures)
        _check("NaN quarantine (penalize/resample/raise)",
               lambda: _drill_quarantine(root), failures)
        _check("sharded restore onto smaller mesh",
               lambda: _drill_sharded_restore(root), failures)
    if failures:
        print(f"FAILED: {len(failures)} recovery path(s) broken on this "
              f"backend: {failures}")
        return 1
    print("all recovery paths intact on this backend")
    return 0


if __name__ == "__main__":
    sys.exit(main())
