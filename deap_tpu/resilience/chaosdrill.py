"""Fleet chaos drill: the canonical plan against a live 3-instance fleet.

``deap-tpu-chaosdrill`` stands up three :class:`NetServer` instances,
each behind a :class:`~deap_tpu.serve.net.faultwire.FaultWire` proxy,
fronted by one :class:`RouterServer`, and runs scripted traffic through
:func:`~deap_tpu.resilience.chaos.canonical_plan`'s storm:

* **b0** survives corrupt/truncated/delayed request frames (typed
  ``ProtocolError`` + latency, blind-retried — request-direction faults
  provably never executed);
* **b1** is fully partitioned: the health loop latches it sick, the
  failover drain finds it unreachable, its sessions are LOST (the one
  failover shape that loses state);
* **b2** is the gray failure: healthz answers, the data path wedges —
  only its circuit breaker protects the fleet (opens, jittered half-open
  probes, typed ``CircuitOpen`` short-circuits).

The drill demands, and the committed ``BENCH_CHAOS.json`` records:

* surviving sessions **bitwise equal** to an undisturbed single-instance
  reference (no retried fault ever double-executed);
* goodput under storm and seconds-to-recovery after heal;
* breaker opens/probes, router+instance deadline sheds, and an
  in-process priority-brownout segment (``brownout_sheds``) all visible
  in metrics;
* every planned leg FIRED (a fault that never fired tested nothing);
* the injector's decision log REPLAYS to the identical fault sequence
  (the determinism oracle ``tests/test_chaos.py`` also pins).

    deap-tpu-chaosdrill                       # writes BENCH_CHAOS.json
    CHAOSDRILL_OUT=- deap-tpu-chaosdrill      # report to stdout only
    python -m deap_tpu.resilience.chaosdrill  # equivalent module form
"""

import json
import os
import sys
import threading
import time

import numpy as np

POP = int(os.environ.get("CHAOSDRILL_POP", 40))
NGEN = int(os.environ.get("CHAOSDRILL_NGEN", 8))
WARM = 2                                 # clean-wire generations
SEED = int(os.environ.get("CHAOSDRILL_SEED", 20))
OUT = os.environ.get("CHAOSDRILL_OUT", "BENCH_CHAOS.json")

#: six sessions over three bucket classes — cold placement spreads the
#: classes across the fleet, warm affinity pairs them up, so every
#: backend (the partitioned one included) hosts real traffic
SHAPES = ((POP, 8), (POP, 16), (POP, 32)) * 2


def _toolbox():
    import jax.numpy as jnp
    from deap_tpu import base
    from deap_tpu.ops import crossover, mutation, selection

    tb = base.Toolbox()
    tb.register("evaluate", lambda g: (jnp.sum(g),))
    tb.register("mate", crossover.cx_two_point)
    tb.register("mutate", mutation.mut_flip_bit, indpb=0.05)
    tb.register("select", selection.sel_tournament, tournsize=3)
    return tb


def _pop(key, n, d):
    import jax
    import jax.numpy as jnp
    from deap_tpu import base

    g = jax.random.bernoulli(key, 0.5, (n, d)).astype(jnp.float32)
    return base.Population(genome=g,
                           fitness=base.Fitness.empty(n, (1.0,)))


def _keys():
    import jax
    return list(jax.random.split(jax.random.PRNGKey(SEED), len(SHAPES)))


def _final(pop):
    return (np.asarray(pop.genome), np.asarray(pop.fitness.values),
            np.asarray(pop.fitness.valid))


def _reference():
    """Undisturbed single-instance trajectories — the bitwise oracle."""
    from deap_tpu.serve import EvolutionService

    tb = _toolbox()
    finals = []
    with EvolutionService(max_batch=4) as svc:
        for i, (k, (n, d)) in enumerate(zip(_keys(), SHAPES)):
            s = svc.open_session(k, _pop(k, n, d), tb, cxpb=0.6,
                                 mutpb=0.3, name=f"chaos-{i}")
            for f in s.step(NGEN):
                f.result(timeout=600)
            finals.append(_final(s.population()))
    return finals


def _retryable(exc) -> bool:
    """True when the failed op PROVABLY never executed.  Typed
    pre-execution rejections always qualify; a generic mid-request
    ``ServeError`` qualifies here ONLY because every fault of the
    canonical plan that can kill an exchange (partition, wedge, drop)
    acts on the request leg at the proxy — the instance never saw the
    op, so a blind retry cannot double-execute anything."""
    from deap_tpu.serve.dispatcher import (CircuitOpen, ServeError,
                                           ServiceBrownout,
                                           ServiceOverloaded)
    from deap_tpu.serve.net.protocol import ProtocolError

    return isinstance(exc, (ProtocolError, CircuitOpen, ServiceBrownout,
                            ServiceOverloaded, ServeError))


def _step_once(sess, counters):
    """One storm step attempt: 'ok' | 'retry' | 'lost'."""
    from deap_tpu.serve.dispatcher import SessionUnknown

    counters["attempts"] += 1
    try:
        [f] = sess.step(1)
        f.result(timeout=120)
    except SessionUnknown:
        return "lost"
    except Exception as e:  # noqa: BLE001 — typed check below
        if _retryable(e):
            return "retry"
        raise
    counters["successes"] += 1
    return "ok"


def _eval_once(sess, genomes, counters):
    counters["attempts"] += 1
    try:
        sess.evaluate(genomes).result(timeout=120)
    except Exception as e:  # noqa: BLE001 — typed check below
        if _retryable(e):
            return False
        raise
    counters["successes"] += 1
    return True


def _brownout_segment():
    """In-process priority-shedding proof: queue pressure of priority-2
    work; a priority-1 admission sheds typed, an equal-priority one is
    admitted (uniform-priority fleets degrade exactly as before)."""
    from deap_tpu.serve.dispatcher import (BatchDispatcher, Request,
                                           ServiceBrownout)
    from deap_tpu.serve.metrics import ServeMetrics

    hold = threading.Event()

    def execute(kind, program_key, requests):
        hold.wait(30)
        return [None] * len(requests)

    def req(priority):
        return Request(kind="noop", program_key=("k",), payload={},
                       priority=priority)

    m = ServeMetrics()
    d = BatchDispatcher(execute, metrics=m, max_pending=8,
                        brownout_watermark=0.25, brownout_grace_s=0.0)
    shed_typed = equal_admitted = False
    try:
        d.submit(req(2))                    # the worker picks this up
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:  # wait until it's in-flight
            with d._cv:
                if d._busy and not d._pending:
                    break
        for _ in range(3):                  # sustained pressure: 3 >= 2
            d.submit(req(2))
        try:
            d.submit(req(1))
        except ServiceBrownout:
            shed_typed = True
        d.submit(req(2))                    # equal priority: admitted
        equal_admitted = True
    finally:
        hold.set()
        d.close()
    return {"brownout_sheds": m.counter("brownout_sheds"),
            "shed_typed": shed_typed, "equal_admitted": equal_admitted}


def main() -> int:  # noqa: PLR0915 — one scripted drill, linear acts
    import jax

    from deap_tpu.resilience.chaos import ChaosInjector, canonical_plan
    from deap_tpu.serve import DeadlineExceeded, EvolutionService
    from deap_tpu.serve.net import NetServer, RemoteService
    from deap_tpu.serve.net.faultwire import FaultWire
    from deap_tpu.serve.router import (Backend, FleetRouter, HealthPolicy,
                                       RouterServer)

    print(f"backend={jax.default_backend()} pop={POP} ngen={NGEN} "
          f"seed={SEED} sessions={len(SHAPES)}")
    t_all = time.monotonic()
    print("[reference] undisturbed single-instance trajectories ...")
    want = _reference()

    plan = canonical_plan(seed=SEED)
    injector = ChaosInjector(plan)
    tb = _toolbox()
    svcs = [EvolutionService(max_batch=4) for _ in range(3)]
    srvs = [NetServer(s, {"onemax": tb}).start() for s in svcs]
    proxies = [FaultWire(srv.address, f"b{i}", injector).start()
               for i, srv in enumerate(srvs)]
    # generous forward timeout (first-step compiles must not read as
    # faults); wedges close the wire themselves after their hold
    backends = [Backend(f"b{i}", p.address, timeout=30.0,
                        control_timeout=2.0)
                for i, p in enumerate(proxies)]
    # health latches ONLY on unreachability (the partition): error spans
    # and failed counters are expected storm noise on surviving backends
    router = FleetRouter(
        backends,
        health=HealthPolicy(interval_s=0.2, fail_after=2,
                            max_failed_delta=10**9,
                            max_error_spans=10**9, stall_s=3600.0),
        breaker_policy={"fail_threshold": 1, "reset_s": 0.5},
        drain_timeout=5.0)
    front = RouterServer(router, failover_wait=5.0).start()
    cli = RemoteService(front.url, timeout=120)
    counters = {"attempts": 0, "successes": 0}
    report = {"bench": "chaos", "pop": POP, "ngen": NGEN, "seed": SEED,
              "plan_legs": len(plan.legs), "sessions": len(SHAPES)}
    failures = []
    try:
        # -- act 1: warmup (clean wire) ---------------------------------
        injector.set_phase("warmup")
        sessions = [cli.open_session(k, _pop(k, n, d), "onemax",
                                     cxpb=0.6, mutpb=0.3,
                                     name=f"chaos-{i}")
                    for i, (k, (n, d)) in enumerate(zip(_keys(), SHAPES))]
        for s in sessions:
            for f in s.step(WARM):
                f.result(timeout=600)
        homes = {s.name: router.route_of(s.name).name for s in sessions}
        print(f"[warmup] {WARM} gens clean; placement: {homes}")

        # -- act 2: storm -----------------------------------------------
        injector.set_phase("storm")
        t_storm = time.monotonic()
        remaining = {s.name: NGEN - 1 - WARM for s in sessions}
        lost = set()
        storm_deadline = t_storm + 240
        while time.monotonic() < storm_deadline:
            pending = [s for s in sessions
                       if s.name not in lost and remaining[s.name] > 0]
            if not pending:
                break
            for s in pending:
                out = _step_once(s, counters)
                if out == "ok":
                    remaining[s.name] -= 1
                elif out == "lost":
                    lost.add(s.name)
                    print(f"[storm] session {s.name} lost "
                          f"(was on {homes[s.name]})")
                else:
                    time.sleep(0.1)     # back off before the blind retry
        if any(remaining[s.name] > 0 for s in sessions
               if s.name not in lost):
            failures.append("storm generations did not complete in time")
        # keep storming with trajectory-neutral evaluates until every
        # leg aimed at a REACHABLE backend has fired — a planned fault
        # that never fired means the drill tested nothing
        survivors = [s for s in sessions if s.name not in lost]
        by_target = {}
        for s in survivors:
            by_target.setdefault(router.route_of(s.name).name, s)
        probe_g = {s.name: np.asarray(_pop(k, 8, d).genome)
                   for s, (k, (n, d)) in zip(sessions,
                                             zip(_keys(), SHAPES))}
        pad_deadline = time.monotonic() + 120
        while time.monotonic() < pad_deadline:
            unfired = [leg for leg in injector.unfired_legs()
                       if leg.target in by_target]
            if not unfired:
                break
            for leg in unfired:
                s = by_target[leg.target]
                if not _eval_once(s, probe_g[s.name], counters):
                    time.sleep(0.1)
        storm_s = time.monotonic() - t_storm
        goodput = (counters["successes"] / counters["attempts"]
                   if counters["attempts"] else 0.0)
        print(f"[storm] {storm_s:.1f}s: {counters['successes']}/"
              f"{counters['attempts']} ops succeeded "
              f"(goodput {goodput:.2f}), lost={sorted(lost)}")

        # -- act 3: heal ------------------------------------------------
        injector.set_phase("heal")
        t_heal = time.monotonic()
        heal_counters = {"attempts": 0, "successes": 0}
        for s in survivors:             # the reserved final generation
            out = "retry"
            while out == "retry" and time.monotonic() < t_heal + 60:
                out = _step_once(s, heal_counters)
                if out == "retry":
                    time.sleep(0.1)
            if out != "ok":
                failures.append(f"{s.name} never completed its final "
                                f"generation after the heal ({out})")
        # recovery is complete when every reachable backend's breaker
        # reads closed again — drive half-open probes with trajectory-
        # neutral evaluates until the probes succeed
        close_deadline = time.monotonic() + 60
        while time.monotonic() < close_deadline:
            open_b = [n for n, b in router.backends.items()
                      if not router.health.is_sick(n)
                      and b.breaker is not None
                      and b.breaker.state() != "closed"]
            if not open_b:
                break
            for n in open_b:
                s = by_target.get(n)
                if s is not None:
                    _eval_once(s, probe_g[s.name], heal_counters)
            time.sleep(0.05)
        else:
            failures.append("a circuit breaker never closed after heal")
        recovery_s = time.monotonic() - t_heal
        print(f"[heal] recovered in {recovery_s:.2f}s "
              f"({heal_counters['successes']} clean ops)")

        # -- act 4: verdicts --------------------------------------------
        bitwise = True
        for i, s in enumerate(sessions):
            if s.name in lost:
                continue
            got = _final(s.population())
            for g, w in zip(got, want[i]):
                if not np.array_equal(g, w):
                    bitwise = False
                    failures.append(f"{s.name} diverged from the "
                                    "undisturbed reference")
                    break
        unfired = [f"{leg.target}:{leg.kind}"
                   for leg in injector.unfired_legs()]
        if unfired:
            failures.append(f"planned legs never fired: {unfired}")
        replayed = ChaosInjector.replay(plan, injector.decision_log())
        replay_ok = replayed.fired() == injector.fired()
        if not replay_ok:
            failures.append("decision-log replay diverged (determinism "
                            "broken)")

        # deadline-budget sheds on the clean wire: the router hop sheds
        # a spent budget pre-forward; the instance sheds pre-dispatch
        probe = survivors[0]
        r0 = router.stats().counters["router_deadline_shed"]
        try:
            probe.step(1, deadline=1e-9)[0].result(timeout=60)
            failures.append("router accepted a spent deadline budget")
        except DeadlineExceeded:
            pass
        router_shed = router.stats().counters["router_deadline_shed"] - r0
        home_i = int(router.route_of(probe.name).name[1:])
        direct = RemoteService(srvs[home_i].url, timeout=60)
        ph = direct.attach(probe.name)
        try:
            ph.step(1, deadline=0.0)[0].result(timeout=60)
            failures.append("instance accepted a spent deadline budget")
        except DeadlineExceeded:
            pass
        direct.close()
        inst_shed = svcs[home_i].metrics.counter("deadline_shed")
        brown = _brownout_segment()
        if not brown["shed_typed"] or brown["brownout_sheds"] < 1:
            failures.append("brownout segment shed nothing")
        rc = router.stats().counters
        if rc["router_breaker_opens"] < 1 or rc["router_breaker_probes"] < 1:
            failures.append("breaker never opened/probed under the wedge")
        if router_shed < 1 or inst_shed < 1:
            failures.append("deadline sheds not visible in metrics")

        report.update({
            "goodput_frac": round(goodput, 4),
            "recovery_s": round(recovery_s, 3),
            "bitwise_identical": bitwise,
            "survivors": len(survivors), "lost": sorted(lost),
            "storm_s": round(storm_s, 2),
            "storm_attempts": counters["attempts"],
            "storm_successes": counters["successes"],
            "faults_injected": injector.fired_counts(),
            "unfired_legs": unfired,
            "determinism_replay_ok": replay_ok,
            "breaker": {"opens": rc["router_breaker_opens"],
                        "probes": rc["router_breaker_probes"],
                        "rejections": rc["router_breaker_rejections"]},
            "sheds": {"router_deadline_shed": router_shed,
                      "instance_deadline_shed": inst_shed,
                      "brownout_sheds": brown["brownout_sheds"]},
            "wall_s": round(time.monotonic() - t_all, 2),
        })
    finally:
        cli.close()
        front.close()               # closes the router too
        for p in proxies:
            p.close()
        for srv in srvs:
            srv.close()
        for svc in svcs:
            svc.close()

    text = json.dumps(report, indent=2, sort_keys=True)
    if OUT == "-":
        print(text)
    else:
        with open(OUT, "w", encoding="utf-8") as fh:
            fh.write(text + "\n")
        print(f"[report] wrote {OUT}")
    if failures:
        print(f"FAILED: {failures}")
        return 1
    print(f"chaos drill clean: goodput {report['goodput_frac']:.2f}, "
          f"recovery {report['recovery_s']:.2f}s, survivors bitwise "
          "identical")
    return 0


if __name__ == "__main__":
    sys.exit(main())
