"""Declarative, seed-deterministic chaos plans for fleet drills.

PR 1's :class:`~deap_tpu.resilience.faultinject.FaultPlan` injects
faults *inside one process* (an evaluation raises on schedule).  This
module is its wire-level sibling: a :class:`ChaosPlan` declares **which
network faults** hit **which backend** during **which drill phase**, and
a :class:`ChaosInjector` turns the plan into per-exchange decisions that
:class:`~deap_tpu.serve.net.faultwire.FaultWire` proxies execute on the
actual DTF1 socket path — drop, delay, bandwidth throttle, frame
truncation/corruption, wedge-after-headers, asymmetric partition, and
slow-drip responses.

Determinism is the whole point: every decision is a pure function of
``(plan.seed, target, leg index, per-target exchange index)`` through
SHA-256, **never** of wall time or thread interleaving.  Two runs that
present the same per-target exchange sequences draw the identical fault
sequence, so a chaos drill's failures reproduce from its seed (pinned by
``tests/test_chaos.py``).  Every fired fault is recorded; a leg that
never fired is detectable (:meth:`ChaosInjector.unfired_legs`) — a drill
whose fault never fired is a broken drill, not a passing one.

Phases are script-driven, not timer-driven: the drill calls
:meth:`ChaosInjector.set_phase` at its own act boundaries (``"warmup"``
→ ``"storm"`` → ``"heal"``), and a leg with ``phase=""`` applies in
every act.
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Dict, List, Optional, Tuple

from .. import sanitize

__all__ = ["ChaosLeg", "ChaosPlan", "ChaosFault", "ChaosInjector",
           "CHAOS_KINDS", "canonical_plan"]

#: Wire-fault vocabulary a :class:`~deap_tpu.serve.net.faultwire.FaultWire`
#: proxy knows how to execute (see that module for exact semantics).
CHAOS_KINDS = ("drop", "delay", "throttle", "truncate", "corrupt",
               "wedge", "partition", "drip")

_DIRECTIONS = ("request", "response", "both")

#: Exchange classes a leg may be scoped to: ``"data"`` is the session
#: plane (``/v1/sessions...``), ``"control"`` is everything else
#: (healthz/metrics/trace/admin), ``"any"`` hits both.  Scoping a leg to
#: ``"data"`` builds a GRAY failure — the instance's control plane keeps
#: answering politely while its data path misbehaves, exactly the
#: condition circuit breakers exist for and health polling alone misses.
_SCOPES = ("any", "data", "control")


@dataclasses.dataclass(frozen=True)
class ChaosLeg:
    """One scheduled fault stream against one target.

    ``target`` names the proxied backend; ``kind`` is one of
    :data:`CHAOS_KINDS`.  ``phase`` restricts the leg to one drill act
    (``""`` = all acts); ``start``/``stop`` bound the affected
    per-target exchange indices (``stop=None`` = unbounded);
    ``probability`` is the per-exchange firing chance drawn from the
    plan's seeded hash stream; ``direction`` selects which half of the
    exchange the fault mangles (``"request"`` faults provably never
    execute upstream — the only kind a drill may blindly retry);
    ``scope`` restricts the leg to one exchange class (see
    :data:`_SCOPES` — ``"data"`` legs build gray failures the control
    plane can't see); ``params`` are kind-specific knobs (``seconds``
    for delay,
    ``bytes_per_s`` for throttle, ``frac`` for truncate, ``xor`` for
    corrupt, ``chunk``/``seconds`` for drip) as a hashable item tuple.
    """

    target: str
    kind: str
    phase: str = ""
    start: int = 0
    stop: Optional[int] = None
    probability: float = 1.0
    direction: str = "both"
    scope: str = "any"
    params: Tuple[Tuple[str, float], ...] = ()

    def __post_init__(self):
        if self.kind not in CHAOS_KINDS:
            raise ValueError(f"unknown chaos kind {self.kind!r} "
                             f"(one of {CHAOS_KINDS})")
        if self.direction not in _DIRECTIONS:
            raise ValueError(f"direction must be one of {_DIRECTIONS}, "
                             f"got {self.direction!r}")
        if self.scope not in _SCOPES:
            raise ValueError(f"scope must be one of {_SCOPES}, "
                             f"got {self.scope!r}")
        if not 0.0 <= self.probability <= 1.0:
            raise ValueError("probability must be in [0, 1]")
        if self.start < 0:
            raise ValueError("start must be >= 0")
        if self.stop is not None and self.stop <= self.start:
            raise ValueError("stop must be > start")
        # normalize params to a sorted item tuple so two equal-by-value
        # legs hash and compare equal regardless of construction order
        object.__setattr__(self, "params",
                           tuple(sorted(dict(self.params).items())))

    def param(self, name: str, default=None):
        return dict(self.params).get(name, default)

    def active(self, phase: str, exchange: int) -> bool:
        if self.phase and phase != self.phase:
            return False
        if exchange < self.start:
            return False
        return self.stop is None or exchange < self.stop


@dataclasses.dataclass(frozen=True)
class ChaosPlan:
    """A seed plus the full leg schedule — everything a drill needs to
    reproduce its fault sequence bit-for-bit."""

    seed: int
    legs: Tuple[ChaosLeg, ...]

    def __post_init__(self):
        object.__setattr__(self, "legs", tuple(self.legs))
        for leg in self.legs:
            if not isinstance(leg, ChaosLeg):
                raise TypeError(f"plan legs must be ChaosLeg, got {leg!r}")

    def for_target(self, target: str) -> Tuple[Tuple[int, ChaosLeg], ...]:
        """(leg index, leg) pairs aimed at ``target`` — the index is the
        leg's identity in the hash stream, so reordering OTHER targets'
        legs never changes this target's draws."""
        return tuple((i, leg) for i, leg in enumerate(self.legs)
                     if leg.target == target)

    def describe(self) -> List[dict]:
        return [dataclasses.asdict(leg) for leg in self.legs]


@dataclasses.dataclass(frozen=True)
class ChaosFault:
    """One fired fault — what a FaultWire proxy executes on one
    exchange."""

    leg_index: int
    leg: ChaosLeg
    exchange: int
    phase: str


def _u01(seed: int, target: str, leg_index: int, exchange: int) -> float:
    """Deterministic uniform draw in [0, 1) — SHA-256 of the identifying
    tuple, so the stream is independent of thread interleaving, wall
    time and Python hash randomization."""
    h = hashlib.sha256(
        f"{seed}:{target}:{leg_index}:{exchange}".encode()).digest()
    return int.from_bytes(h[:8], "big") / float(1 << 64)


class ChaosInjector:
    """Turns a :class:`ChaosPlan` into per-exchange fault decisions and
    records every firing (see module docstring).

    One injector serves every proxy of a drill: each proxy calls
    :meth:`decide(target)` exactly once per HTTP exchange it relays, and
    the injector advances that target's private exchange counter.  The
    drill script moves acts with :meth:`set_phase`."""

    #: lock-guarded shared state: per-target exchange counters, the
    #: current phase and the fired-fault record are written by every
    #: proxy relay thread — writes only under ``self._lock``
    _GUARDED_BY = {"_lock": ("_counts", "_phase", "_fired", "_log")}

    def __init__(self, plan: ChaosPlan):
        self.plan = plan
        self._lock = sanitize.lock()
        self._counts: Dict[str, int] = {}
        self._phase = ""
        self._fired: List[ChaosFault] = []
        #: replayable decision log: (target, phase, klass) per decide()
        #: call, in call order — feeding it to :meth:`replay` on a fresh
        #: injector must reproduce the identical fired sequence
        self._log: List[Tuple[str, str, str]] = []

    # -- drill script surface ------------------------------------------------

    def set_phase(self, phase: str) -> None:
        with self._lock:
            self._phase = str(phase)

    def phase(self) -> str:
        with self._lock:
            return self._phase

    def decide(self, target: str,
               klass: str = "data") -> List[ChaosFault]:
        """The faults that hit ``target``'s next exchange (possibly
        empty).  ``klass`` is the exchange class (``"data"`` /
        ``"control"``) matched against each leg's ``scope``.  Pure in
        ``(seed, target, leg, exchange)`` — the lock only orders the
        per-target counter, it never feeds the draw."""
        with self._lock:
            exchange = self._counts.get(target, 0)
            self._counts[target] = exchange + 1
            phase = self._phase
            self._log.append((target, phase, klass))
            out: List[ChaosFault] = []
            for i, leg in self.plan.for_target(target):
                if not leg.active(phase, exchange):
                    continue
                if leg.scope != "any" and leg.scope != klass:
                    continue
                if _u01(self.plan.seed, target, i, exchange) \
                        < leg.probability:
                    fault = ChaosFault(leg_index=i, leg=leg,
                                       exchange=exchange, phase=phase)
                    out.append(fault)
                    self._fired.append(fault)
            return out

    # -- accounting ----------------------------------------------------------

    def fired(self) -> List[ChaosFault]:
        with self._lock:
            return list(self._fired)

    def fired_counts(self) -> Dict[str, int]:
        """Fired-fault tally by kind (the drill report's
        ``faults_injected`` table)."""
        out: Dict[str, int] = {}
        for f in self.fired():
            out[f.leg.kind] = out.get(f.leg.kind, 0) + 1
        return out

    def unfired_legs(self) -> List[ChaosLeg]:
        """Legs that never fired — a drill that planned a fault which
        never happened tested nothing and must FAIL, not pass."""
        hit = {f.leg_index for f in self.fired()}
        return [leg for i, leg in enumerate(self.plan.legs) if i not in hit]

    def decision_log(self) -> List[Tuple[str, str, str]]:
        with self._lock:
            return list(self._log)

    @classmethod
    def replay(cls, plan: ChaosPlan,
               log: List[Tuple[str, str, str]]) -> "ChaosInjector":
        """Re-run a recorded decision sequence against a fresh injector
        — the determinism oracle: ``replay(plan, inj.decision_log())``
        fires the identical fault sequence as ``inj`` did."""
        fresh = cls(plan)
        for target, phase, klass in log:
            fresh.set_phase(phase)
            fresh.decide(target, klass)
        return fresh


def canonical_plan(targets=("b0", "b1", "b2"), *, seed: int = 20,
                   storm: str = "storm") -> ChaosPlan:
    """The committed drill plan ``deap-tpu-chaosdrill`` runs (and
    ``BENCH_CHAOS.json`` reports): against a three-instance fleet, the
    storm act combines

    * a **delay** drag plus occasional request-frame **corruption** and
      **truncation** on the first backend (it stays up — typed 400s and
      latency, never lost state),
    * a full **asymmetric partition** of the second backend — control
      plane included, so the health loop latches it sick and the
      failover drain finds it unreachable (its sessions are LOST, the
      hard half of the drill), and
    * **wedge-after-headers** on the third backend's data plane only —
      the gray failure: healthz keeps answering, so only the circuit
      breaker (fed by forward outcomes) protects the fleet, and
    * a **slow-drip** response leg on the first backend (bandwidth
      starvation without failure).

    Request-direction-only mangling on the surviving backends is load-
    bearing: a request fault provably never executed upstream, so the
    drill may retry it blindly and still demand bitwise-identical
    surviving trajectories."""
    t0, t1, t2 = tuple(targets)[:3]
    return ChaosPlan(seed=seed, legs=(
        ChaosLeg(target=t0, kind="delay", phase=storm, probability=0.5,
                 direction="request", scope="data",
                 params=(("seconds", 0.02),)),
        ChaosLeg(target=t0, kind="corrupt", phase=storm, probability=0.15,
                 direction="request", scope="data",
                 params=(("xor", 0xA5),)),
        ChaosLeg(target=t0, kind="truncate", phase=storm, probability=0.15,
                 direction="request", scope="data",
                 params=(("frac", 0.5),)),
        ChaosLeg(target=t0, kind="drip", phase=storm, probability=0.1,
                 direction="response", scope="data",
                 params=(("chunk", 512), ("seconds", 0.005))),
        ChaosLeg(target=t1, kind="partition", phase=storm,
                 probability=1.0, direction="both", scope="any"),
        ChaosLeg(target=t2, kind="wedge", phase=storm, probability=0.45,
                 direction="request", scope="data",
                 params=(("seconds", 1.0),)),
    ))
