"""Preemption-safe resumable evolution driver.

``ea_simple``-family loops compile the whole run into ``lax.scan``
dispatches — fast, but a preempted TPU pod loses everything since the
last manual checkpoint, and the reference's answer is a copy-paste
pattern ("pickle a dict every FREQ generations",
doc/tutorials/advanced/checkpoint.rst).  :func:`run_resumable` makes the
pattern a driver:

* the run is segmented into ``checkpoint_every``-generation scans (the
  documented FREQ pattern — each segment reuses the compiled program);
* after each boundary the full run state — population, PRNG key,
  generation, hall-of-fame archive, logbook records — is checkpointed
  through :mod:`deap_tpu.utils.checkpoint` with bounded retries
  (:func:`~deap_tpu.resilience.retry.with_retries`) against flaky
  filesystems;
* SIGTERM (the preemption notice on TPU pods) trips a flag that is
  **agreed across hosts** at the next segment boundary: every process
  then checkpoints the same generation and the driver raises
  :class:`Preempted` — the scheduler restarts the job, and the same
  ``run_resumable`` call finds the checkpoint and resumes bit-exactly;
* with ``sharded=True`` the state goes through the per-shard tier, so a
  restart may come back on a *smaller* mesh (fewer hosts after
  preemption): pass the template population on the new mesh and restore
  reassembles every shard from the saved chunks.

Resume is exact: a run killed at any boundary and resumed produces the
bitwise-identical trajectory (population, fitness, logbook) of the same
driver left uninterrupted, because the per-segment key-split schedule is
a pure function of the generation number (tests/test_resilience.py).

Fault paths are tested by injection, not by hoping:
``run_resumable(..., faults=FaultInjector(plan))`` deterministically
poisons an evaluation, fails checkpoint writes, or delivers a simulated
preemption — see :mod:`deap_tpu.resilience.faultinject`.
"""

from __future__ import annotations

import contextlib
import pickle
import signal as _signal
import threading
import time
import warnings
from pathlib import Path

import numpy as np
import jax
import jax.numpy as jnp

from ..algorithms import ea_simple
from ..utils.checkpoint import (save_checkpoint, load_checkpoint,
                                save_sharded_checkpoint,
                                load_sharded_checkpoint, _read_commit)
from ..utils.support import Logbook
from .retry import with_retries

__all__ = ["run_resumable", "Preempted", "save_session_states",
           "load_session_states"]


class Preempted(RuntimeError):
    """The run was interrupted (SIGTERM or injected preemption) and its
    state was checkpointed at generation ``gen``; re-running the same
    :func:`run_resumable` call resumes from there."""

    def __init__(self, gen: int, path):
        super().__init__(
            f"preempted at generation {gen}; state checkpointed to {path} "
            "— re-run to resume")
        self.gen = gen
        self.path = path


class _PreemptFlag:
    def __init__(self):
        self.tripped = False

    def trip(self, *_args) -> None:
        self.tripped = True


@contextlib.contextmanager
def _trap_signals(signals, flag: _PreemptFlag):
    """Install flag-tripping handlers (main thread only — signal.signal
    raises elsewhere); always restore the previous handlers."""
    installed = []
    if threading.current_thread() is threading.main_thread():
        for s in signals:
            try:
                installed.append((s, _signal.signal(s, flag.trip)))
            except (ValueError, OSError):
                pass
    try:
        yield
    finally:
        for s, old in installed:
            _signal.signal(s, old)


def _global_any(flag: bool) -> bool:
    """Cross-host OR — a preemption notice lands on ONE host; every
    process must agree to take the checkpoint-and-exit path together."""
    if jax.process_count() == 1:
        return bool(flag)
    from jax.experimental import multihost_utils
    return bool(multihost_utils.process_allgather(
        np.asarray(flag, np.int32)).any())


def _global_agree(value: int) -> int:
    """Process 0's value, everywhere — resume decisions must not rest on
    every process re-reading a cached shared filesystem."""
    if jax.process_count() == 1:
        return int(value)
    from jax.experimental import multihost_utils
    return int(multihost_utils.broadcast_one_to_all(np.int32(value)))


def _nested_record(lb: Logbook, i: int) -> dict:
    """Re-nest entry ``i`` of a segment logbook (chapters back inside the
    record) so it can be re-``record()``-ed into the master logbook."""
    rec = dict(lb[i])
    for name, ch in lb.chapters.items():
        rec[name] = _nested_record(ch, i)
    return rec


def _has_checkpoint(path, sharded: bool) -> bool:
    p = Path(path)
    if not sharded:
        return p.exists()
    try:
        return _read_commit(p) is not None
    except ValueError:
        return True     # corrupt marker: surface the load error, not a
                        # silent fresh start over a half-dead checkpoint


def _pack_key(key):
    """Typed PRNG keys can't go through the plain pickle tier
    (``np.asarray`` on a key-dtype array raises); store their raw data +
    impl and rewrap on restore.  Legacy uint32 keys pass through."""
    if isinstance(key, jax.Array) and jax.dtypes.issubdtype(
            key.dtype, jax.dtypes.prng_key):
        return {"__prng_impl": str(jax.random.key_impl(key)),
                "data": jax.random.key_data(key)}
    return key


def _unpack_key(packed):
    if isinstance(packed, dict) and "__prng_impl" in packed:
        return jax.random.wrap_key_data(jnp.asarray(packed["data"]),
                                        impl=packed["__prng_impl"])
    return jnp.asarray(packed)


def _uncommit(tree):
    """Round-trip small replicated leaves (PRNG key, archive state)
    through the host so they come back *uncommitted*: the sharded loader
    pins every restored leaf to explicit devices, and a key committed to
    device 0 next to a population committed to the mesh makes ``lax.scan``
    reject the carry as mixed placement."""
    def f(x):
        if not isinstance(x, jax.Array):
            return x
        if jax.dtypes.issubdtype(x.dtype, jax.dtypes.prng_key):
            return jax.random.wrap_key_data(
                jnp.asarray(np.asarray(jax.random.key_data(x))),
                impl=str(jax.random.key_impl(x)))
        return jnp.asarray(np.asarray(x))
    return jax.tree_util.tree_map(f, tree)


def _device_like(template, value):
    """Loaded host arrays -> device arrays placed like ``template`` (the
    caller's live population/key carry the target sharding)."""
    def put(t, v):
        if isinstance(t, jax.Array):
            return jax.device_put(jnp.asarray(v), t.sharding)
        return v
    return jax.tree_util.tree_map(put, template, value)


_SESSION_FORMAT = 1


def save_session_states(ckpt_path, sessions: dict, *, io_retries: int = 3,
                        io_backoff: float = 0.5, io_sleep=time.sleep,
                        io_clock=time.monotonic) -> None:
    """Checkpoint the live-session snapshot of a
    :class:`deap_tpu.serve.EvolutionService` (the dict its
    ``snapshot_sessions()`` returns: per-session host state + run
    metadata) through the same retried single-pickle tier
    :func:`run_resumable` uses — a flaky filesystem costs retries, not the
    service.  Process-0-only on multihost, like the driver's checkpoints.

    The on-disk payload wraps the snapshot in a versioned envelope so a
    future layout change can migrate instead of corrupting restores."""
    state = {"format": _SESSION_FORMAT,
             "sessions": {name: dict(snap, key=_pack_key(snap["key"]))
                          for name, snap in sessions.items()}}

    def _save():
        if jax.process_count() == 1 or jax.process_index() == 0:
            save_checkpoint(ckpt_path, state)
    with_retries(_save, retries=io_retries, backoff=io_backoff,
                 sleep=io_sleep, clock=io_clock,
                 retry_on=(OSError, TimeoutError))()


def load_session_states(ckpt_path, *, io_retries: int = 3,
                        io_backoff: float = 0.5, io_sleep=time.sleep,
                        io_clock=time.monotonic) -> dict:
    """Load a :func:`save_session_states` checkpoint back into the
    snapshot dict ``EvolutionService.restore_sessions`` consumes."""
    loader = with_retries(load_checkpoint, retries=io_retries,
                          backoff=io_backoff, sleep=io_sleep, clock=io_clock,
                          retry_on=(OSError, TimeoutError))
    state = loader(ckpt_path)
    fmt = state.get("format")
    if fmt != _SESSION_FORMAT:
        raise ValueError(f"unsupported session checkpoint format {fmt!r} "
                         f"(this build reads format {_SESSION_FORMAT})")
    return {name: dict(snap, key=_unpack_key(snap["key"]))
            for name, snap in state["sessions"].items()}


def run_resumable(key, population, toolbox, ngen: int, *, ckpt_path,
                  checkpoint_every: int = 10, loop=ea_simple,
                  loop_kwargs: dict | None = None, stats=None,
                  halloffame=None, telemetry=None, sharded: bool = False,
                  io_retries: int = 3, io_backoff: float = 0.5,
                  io_sleep=time.sleep, io_clock=time.monotonic,
                  signals=(_signal.SIGTERM,), faults=None,
                  resume: str = "auto", verbose: bool = False):
    """Drive ``loop`` for ``ngen`` generations with periodic +
    preemption-triggered checkpointing and exact resume.

    ``loop`` is any ``ea_simple``-family callable — signature
    ``loop(key, population, toolbox, ngen=..., stats=..., halloffame=...,
    **loop_kwargs) -> (population, logbook)`` — e.g.
    :func:`~deap_tpu.algorithms.ea_simple` with
    ``loop_kwargs=dict(cxpb=0.5, mutpb=0.2)``, or
    :func:`~deap_tpu.algorithms.ea_mu_plus_lambda` with ``mu``/``lambda_``
    in ``loop_kwargs``.

    ``ckpt_path`` is a file for the single-pickle tier or a directory
    when ``sharded=True`` (per-shard fragments; required for populations
    not fully addressable by one process, and what makes restoring onto a
    smaller mesh possible).  ``resume`` is ``"auto"`` (resume iff a
    checkpoint exists), ``"never"`` or ``"require"``.

    Checkpoint I/O runs under :func:`with_retries` (``io_retries`` /
    ``io_backoff``; ``io_sleep``/``io_clock`` are injectable for tests).
    On preemption the state is saved and :class:`Preempted` is raised so
    schedulers observe a non-zero exit.  Returns
    ``(population, logbook)`` with the logbook covering generation 0
    through ``ngen`` regardless of how many restarts happened.

    ``telemetry`` (a :class:`deap_tpu.observability.Telemetry`) survives
    preemption: its :class:`~deap_tpu.observability.metrics.MetricBuffer`
    is part of every checkpoint and restored bit-exactly on resume, so
    cumulative counters span restarts.  In-scan flushing is suppressed
    under this driver (the loop numbers generations per segment, which
    would mislabel flush records); instead the buffer is drained to the
    sinks at every checkpoint boundary with the GLOBAL generation number.
    """
    if checkpoint_every < 1:
        raise ValueError("checkpoint_every must be >= 1")
    if resume not in ("auto", "never", "require"):
        raise ValueError(f"resume {resume!r}: expected 'auto', 'never' "
                         "or 'require'")
    loop_kwargs = dict(loop_kwargs or {})
    plan = faults.plan if faults is not None else None
    pid = jax.process_index()

    def _save_state(state) -> None:
        if sharded:
            save_sharded_checkpoint(ckpt_path, state)
        elif jax.process_count() == 1 or pid == 0:
            save_checkpoint(ckpt_path, state)

    saver = faults.wrap_save(_save_state) if faults is not None else _save_state
    if not (sharded and jax.process_count() > 1):
        # Per-host retry of a MULTI-PROCESS sharded save is unsafe: the
        # save contains cross-host collectives (version broadcast,
        # barriers), and one host re-entering from the top after a local
        # OSError would pair its collectives against the other hosts'
        # mid-save ones.  A flaky write there must fail the step for every
        # host together; retry wrapping applies everywhere else.
        saver = with_retries(saver, retries=io_retries, backoff=io_backoff,
                             sleep=io_sleep, clock=io_clock,
                             retry_on=(OSError, TimeoutError))
    # loads are collective-free (pure local reads), so retrying them is
    # safe on any topology
    loader = with_retries(
        load_sharded_checkpoint if sharded else load_checkpoint,
        retries=io_retries, backoff=io_backoff, sleep=io_sleep,
        clock=io_clock, retry_on=(OSError, TimeoutError))

    def _hof_template():
        if halloffame is None:
            return None
        return (halloffame.state if halloffame.state is not None
                else halloffame.init_state(population))

    def _tel_template():
        if telemetry is None:
            return None
        if telemetry.state is not None:
            return telemetry.state
        from ..observability.metrics import buffer_init
        return buffer_init(telemetry.counter_names, telemetry.gauge_names)

    # -- resume --------------------------------------------------------------
    gen = 0
    records: list[dict] = []
    found = _global_agree(_has_checkpoint(ckpt_path, sharded))
    if resume == "require" and not found:
        raise FileNotFoundError(
            f"resume='require' but no checkpoint at {ckpt_path}")
    if resume != "never" and found:
        if sharded:
            like = {"population": population, "key": key,
                    "hof": _hof_template(), "telemetry": _tel_template(),
                    "gen": 0, "records": b"",
                    "meta": {"checkpoint_every": 0, "ngen": 0}}
            state = loader(ckpt_path, like)
            population = state["population"]
            key = _uncommit(state["key"])
            hof_state = (None if state["hof"] is None
                         else _uncommit(state["hof"]))
            tel_state = (None if state.get("telemetry") is None
                         else _uncommit(state["telemetry"]))
        else:
            state = loader(ckpt_path)
            population = _device_like(population, state["population"])
            key = _unpack_key(state["key"])
            hof_state = (None if state["hof"] is None else
                         jax.tree_util.tree_map(jnp.asarray, state["hof"]))
            tel_state = (None if state.get("telemetry") is None else
                         jax.tree_util.tree_map(jnp.asarray,
                                                state["telemetry"]))
        gen = int(state["gen"])
        records = pickle.loads(state["records"])
        if halloffame is not None and hof_state is not None:
            halloffame.state = hof_state
        if telemetry is not None:
            # the checkpoint's buffer — INCLUDING None (a checkpoint
            # written without telemetry) — replaces any leftover host
            # state: continuation comes from the checkpoint, never from a
            # previously-used Telemetry object
            telemetry.state = tel_state
        saved_every = int(state["meta"]["checkpoint_every"])
        if saved_every != checkpoint_every:
            warnings.warn(
                f"resuming with checkpoint_every={checkpoint_every} but the "
                f"checkpoint was written with {saved_every}: the continued "
                "trajectory will not match an uninterrupted run (segment "
                "key-split schedule differs)")
        if verbose:
            from ..observability.sinks import emit_text
            emit_text(f"[run_resumable] resumed at generation {gen} "
                      f"from {ckpt_path}")
    else:
        # a fresh run starts fresh accumulators; continuation comes from
        # the checkpoint, never from leftover host state on the objects
        if halloffame is not None:
            halloffame.clear()
        if telemetry is not None:
            telemetry.clear()

    flag = _PreemptFlag()

    def _checkpoint(at_gen: int) -> None:
        state = {"population": population,
                 "key": key if sharded else _pack_key(key),
                 "hof": halloffame.state if halloffame is not None else None,
                 "telemetry": (telemetry.state if telemetry is not None
                               else None),
                 "gen": int(at_gen), "records": pickle.dumps(records),
                 "meta": {"checkpoint_every": int(checkpoint_every),
                          "ngen": int(ngen)}}
        saver(state)

    loop_tel = {"telemetry": telemetry} if telemetry is not None else {}

    # -- drive ---------------------------------------------------------------
    # in-scan flushes would carry SEGMENT-local generation numbers; the
    # driver drains at checkpoint boundaries with global numbers instead.
    # The mutation sits INSIDE the restoring try/finally so an exception
    # anywhere past this point cannot leak "accumulate" onto the caller's
    # Telemetry (resume errors above this line never touch it).
    tel_saved_mode = None
    if telemetry is not None:
        tel_saved_mode = telemetry.flush_mode
    try:
        if telemetry is not None:
            telemetry.flush_mode = "accumulate"
        with _trap_signals(signals, flag):
            while gen < ngen:
                boundary = min(ngen, (gen // checkpoint_every + 1)
                               * checkpoint_every)
                seg_toolbox = toolbox
                seg_end = boundary
                if faults is not None and plan.nan_at_gen is not None \
                        and gen < plan.nan_at_gen <= boundary:
                    if plan.nan_at_gen - 1 > gen:
                        seg_end = plan.nan_at_gen - 1  # stop short of it
                    else:
                        seg_end = gen + 1              # the poisoned gen
                        seg_toolbox = faults.poison_toolbox(toolbox, seg_end)

                key, k_seg = jax.random.split(key)
                population, seg_lb = loop(
                    k_seg, population, seg_toolbox, ngen=seg_end - gen,
                    stats=stats, halloffame=halloffame, **loop_tel,
                    **loop_kwargs)
                for i in range(len(seg_lb)):
                    rec = _nested_record(seg_lb, i)
                    local = rec.get("gen", i)
                    if local == 0 and (gen > 0 or records):
                        continue      # segment-start record duplicates the
                                      # previous segment's final state
                    rec["gen"] = gen + local
                    records.append(rec)
                gen = seg_end

                if faults is not None:
                    faults.maybe_preempt(gen, flag.trip)
                preempt = _global_any(flag.tripped)
                if preempt or gen >= ngen or gen % checkpoint_every == 0:
                    _checkpoint(gen)
                    if telemetry is not None and telemetry.state is not None:
                        # drain with the GLOBAL generation number (see
                        # docstring: in-scan flushing is suppressed here)
                        telemetry.host_drain(telemetry.state, gen)
                if preempt:
                    raise Preempted(gen, ckpt_path)
    finally:
        if telemetry is not None:
            telemetry.flush_mode = tel_saved_mode

    logbook = Logbook()
    logbook.header = ["gen", "nevals"] + (stats.fields if stats else [])
    for rec in records:
        logbook.record(**rec)
    return population, logbook
