"""Non-finite fitness quarantine.

A user evaluator that divides by zero or overflows returns NaN/Inf rows,
and NaN is *poisonous* to selection: every comparison against NaN is
false, so masked-wvalue sorts and tournament rank arithmetic return
arbitrary winners — the run keeps going and silently optimizes garbage
(the same silent-failure class as the round-3 miscompile,
deap_tpu/selftest.py).  A :class:`Quarantine` attached to the toolbox
(``toolbox.quarantine = Quarantine("penalize")``) is honored by
:func:`deap_tpu.algorithms.evaluate_population` — and therefore by every
canned loop, the islands driver and HARM-GP — immediately after each
evaluation:

* ``"penalize"`` — non-finite rows get a worst-case sentinel fitness
  (finite, so comparisons stay total); they remain valid and simply lose
  every selection.
* ``"resample"`` — as ``penalize``, plus the offending genome row is
  replaced by a clone of the current lexicographically-best finite row
  and its fitness is invalidated, so the clone is re-evaluated (after
  variation) next generation — the bad genome is discarded from the gene
  pool.
* ``"raise"`` — abort with the offending row indices.  Outside a trace
  this raises :class:`NonFiniteFitnessError` synchronously; inside a
  scanned loop the check runs as a host callback, so the error surfaces
  when the dispatch is consumed (``jax.effects_barrier()`` forces it).

All three policies are pure array transforms (safe under ``jit`` /
``lax.scan``); ``raise`` is the only one that needs a host hop.
"""

from __future__ import annotations

import dataclasses

import numpy as np
import jax
import jax.numpy as jnp

from ..base import Population, lex_argmax

__all__ = ["Quarantine", "NonFiniteFitnessError", "nonfinite_rows"]


class NonFiniteFitnessError(RuntimeError):
    """Raised by the ``"raise"`` policy; ``rows`` holds the offending
    population indices."""

    def __init__(self, rows):
        rows = np.asarray(rows).tolist()
        super().__init__(
            f"evaluator returned non-finite fitness for row(s) {rows}")
        self.rows = rows


def nonfinite_rows(values: jax.Array) -> jax.Array:
    """Bool ``(pop,)`` mask of rows with any NaN/Inf objective."""
    return ~jnp.all(jnp.isfinite(values), axis=-1)


def _raise_rows(bad) -> None:
    bad = np.asarray(bad)
    if bad.any():
        raise NonFiniteFitnessError(np.nonzero(bad)[0])


@dataclasses.dataclass(frozen=True)
class Quarantine:
    """Policy for non-finite evaluator output.

    ``sentinel`` is the worst-case magnitude in *weighted* space: a
    quarantined row's wvalue becomes ``-sentinel`` on every objective, so
    it loses every maximizing comparison yet stays finite.  The default
    (``None``) uses ``finfo(dtype).max / 16`` — far beyond any real
    fitness, far from overflow.
    """

    policy: str = "penalize"              # penalize | resample | raise
    sentinel: float | None = None

    def __post_init__(self):
        if self.policy not in ("penalize", "resample", "raise"):
            raise ValueError(
                f"unknown quarantine policy {self.policy!r}: expected "
                "'penalize', 'resample' or 'raise'")

    def _sentinel_values(self, weights, dtype) -> jax.Array:
        big = (jnp.finfo(dtype).max / 16 if self.sentinel is None
               else self.sentinel)
        w = jnp.asarray(weights, dtype)
        # raw value whose weighted form is -big — but both the raw value
        # and its weighted form must stay FINITE for any weight magnitude:
        # cap the raw magnitude at big, so |w| < 1 yields wvalue -big*|w|
        # (still astronomically worse than any real fitness) instead of
        # -big/|w| overflowing to inf.  A zero-weight objective is ignored
        # by every comparison, so 0 is as good as anything there.
        absw = jnp.where(w != 0, jnp.abs(w), 1.0)
        mag = jnp.minimum(big / absw, big)
        return jnp.where(w != 0, -jnp.sign(w) * mag, jnp.zeros_like(w))

    def apply(self, population: Population,
              newly: jax.Array | None = None) -> Population:
        """Quarantine the non-finite rows of ``population``.

        ``newly`` restricts the scan to rows just assigned by the current
        evaluation (rows the policy has already penalized carry a finite
        sentinel and must not be re-processed).
        """
        fit = population.fitness
        bad = nonfinite_rows(fit.values) & fit.valid
        if newly is not None:
            bad = bad & jnp.asarray(newly, bool)

        from ..observability import events as _events
        if _events.active():          # telemetry tap; inert when closed
            _events.emit("quarantined", jnp.sum(bad, dtype=jnp.int32))

        if self.policy == "raise":
            if isinstance(bad, jax.core.Tracer):
                jax.debug.callback(_raise_rows, bad)
            else:
                _raise_rows(bad)
            return population

        sent = self._sentinel_values(fit.weights, fit.values.dtype)
        values = jnp.where(bad[:, None], sent[None, :], fit.values)
        fit = dataclasses.replace(fit, values=values)
        if self.policy == "penalize":
            return Population(genome=population.genome, fitness=fit)

        # resample: clone the best finite row over each quarantined genome
        # and invalidate, so the clone re-enters variation + evaluation
        # next generation.  If NO row is finite the donor index is
        # arbitrary — every row already carries the sentinel, so the swap
        # is a no-op in fitness space.
        healthy_w = jnp.where((fit.valid & ~bad)[:, None],
                              fit.wvalues, -jnp.inf)
        donor = lex_argmax(healthy_w, axis=0)
        genome = jax.tree_util.tree_map(
            lambda g: jnp.where(
                bad.reshape(bad.shape + (1,) * (g.ndim - 1)),
                g[donor][None], g),
            population.genome)
        return Population(genome=genome, fitness=fit.invalidate(bad))
