"""Runtime spec factory — the TPU-native stand-in for ``deap.creator``.

The reference's ``creator.create(name, base, **attrs)`` manufactures Python
classes at runtime (reference creator.py:96-171): individuals are lists/arrays
with a ``fitness`` attribute, and class-valued kwargs become per-instance
attributes.  In an array-native framework an "individual type" is not a class
but a *population schema*: the fitness weights plus the pytree structure of
the genome (per-leaf dtype / trailing shape, and extra per-individual leaves
like PSO's ``speed``/``best``).

``create`` keeps the reference's ergonomics: it installs the produced spec
into this module's namespace under ``name`` and warns when overwriting an
existing name (reference creator.py:137-141).  Class-valued kwargs become
per-individual leaves of the genome pytree (the analogue of per-instance
attributes, reference creator.py:143-149,160-167); other kwargs become static
metadata on the spec (the analogue of class attributes).
"""

from __future__ import annotations

import sys
import warnings
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp

from .base import Fitness, Population

__all__ = ["create", "FitnessSpec", "IndividualSpec"]


class FitnessSpec:
    """Schema for a fitness: just the weights tuple (sign = min/max,
    reference base.py:148-161).  Instantiating the reference's Fitness class
    corresponds here to allocating an empty ``(pop, nobj)`` array."""

    def __init__(self, weights: Sequence[float]):
        self.weights = tuple(float(w) for w in weights)

    @property
    def nobj(self) -> int:
        return len(self.weights)

    def empty(self, pop_size: int, dtype=jnp.float32) -> Fitness:
        return Fitness.empty(pop_size, self.weights, dtype)

    def __repr__(self):
        return f"FitnessSpec(weights={self.weights})"


class IndividualSpec:
    """Schema for individuals: fitness spec + named per-individual leaves.

    ``leaves`` maps attribute names to initializer callables
    ``f(key, n) -> (n, ...) array`` (or to ``None`` for the primary genome,
    which the user supplies).  ``static`` holds schema-level constants (the
    reference's class attributes).
    """

    def __init__(self, fitness: FitnessSpec, leaves: dict | None = None,
                 static: dict | None = None):
        self.fitness = fitness
        self.leaves = dict(leaves or {})
        self.static = dict(static or {})

    @property
    def weights(self):
        return self.fitness.weights

    def population(self, genome: Any, **extra_leaves) -> Population:
        """Wrap an initialized genome (pytree with leading pop axis) into a
        :class:`Population` with empty fitness.  Extra per-individual leaves
        (``speed=...``) are grouped into a dict genome."""
        if extra_leaves:
            genome = dict(genome=genome, **extra_leaves)
        n = jax.tree_util.tree_leaves(genome)[0].shape[0]
        return Population(genome=genome, fitness=self.fitness.empty(n))

    def init_population(self, key: jax.Array, n: int, attr: Callable,
                        storage_dtype: str | None = None,
                        storage_bound: float = 0.0,
                        **extra_leaves) -> Population:
        """Initialize ``n`` individuals by vmapping the per-individual
        initializer ``attr(key) -> genome`` — the array-native
        ``tools.initRepeat(list, toolbox.individual, n)`` (reference
        init.py:3-25).

        ``storage_dtype`` opts the primary genome into the
        mixed-precision storage tier (``"bfloat16"`` / ``"int8"``, see
        :class:`deap_tpu.ops.generation_pallas.GenomeStorage`): the
        initializer draws in f32 — the PRNG stream is unchanged — and
        the drawn values are narrowed once here, so the population's
        on-device residency is narrow from generation zero.
        ``storage_bound`` is int8's symmetric quantization range."""
        keys = jax.random.split(key, n)
        genome = jax.vmap(attr)(keys)
        if storage_dtype is not None and storage_dtype != "float32":
            from .ops.generation_pallas import GenomeStorage
            storage = GenomeStorage(storage_dtype, storage_bound)

            def narrow(x):
                return (storage.to_storage(x)
                        if jnp.issubdtype(x.dtype, jnp.floating) else x)
            genome = jax.tree_util.tree_map(narrow, genome)
        # retire `key` before drawing extra leaves: it was just consumed
        # by the split above, and split(key, 2) is a prefix of
        # split(key, n) — re-splitting it would hand the first extra leaf
        # the SAME stream as individual 1's genome initializer (the
        # rng-key-reuse lint pass pins this)
        key = jax.random.fold_in(key, n)
        extras = {}
        for name, fn in self.leaves.items():
            if name in extra_leaves or fn is None:
                continue
            key, sub = jax.random.split(key)
            extras[name] = fn(sub, n)
        extras.update(extra_leaves)
        return self.population(genome, **extras)

    def __repr__(self):
        return (f"IndividualSpec(weights={self.fitness.weights}, "
                f"leaves={list(self.leaves)}, static={self.static})")


def create(name: str, base: Any = None, **kargs) -> Any:
    """Create a named spec and install it as ``deap_tpu.creator.<name>``.

    * ``create("FitnessMax", weights=(1.0,))`` (or with ``base=Fitness``)
      → :class:`FitnessSpec`.
    * ``create("Individual", fitness=creator.FitnessMax, speed=init_fn)``
      → :class:`IndividualSpec`; callable kwargs become per-individual
      leaves, everything else static metadata.

    Mirrors the redefinition warning of reference creator.py:137-141.
    """
    module = sys.modules[__name__]
    if hasattr(module, name):
        warnings.warn(
            f"A class named '{name}' has already been created and it will be "
            "overwritten. Consider deleting previous creation of that class "
            "or rename it.", RuntimeWarning)

    if "weights" in kargs and "fitness" not in kargs:
        spec = FitnessSpec(kargs.pop("weights"))
        spec.static = kargs
    else:
        fitness = kargs.pop("fitness", None)
        if fitness is None:
            raise TypeError(
                "create() needs either weights=... (fitness spec) or "
                "fitness=<FitnessSpec> (individual spec)")
        if isinstance(fitness, Sequence):
            fitness = FitnessSpec(fitness)
        leaves = {k: v for k, v in kargs.items() if callable(v) or v is None}
        static = {k: v for k, v in kargs.items() if k not in leaves}
        spec = IndividualSpec(fitness, leaves=leaves, static=static)

    setattr(module, name, spec)
    return spec
