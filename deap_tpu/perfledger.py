"""Perf-regression ledger: the committed ``BENCH_*.json`` trajectory as
an enforced contract (``deap-tpu-perfgate``).

The repo carries a dozen committed benchmark artifacts — the GA
gens/sec series (``BENCH_r*.json``), serving throughput and loopback
latency (``BENCH_SERVE``/``BENCH_NET``), the tracing/sanitizer/profiler
overhead records, weak-scaling overheads, memory footprints, the fleet
drill — but until this module their trajectory lived as prose in
``CHANGES.md``: nothing machine-readable said what the tracked metrics
ARE, what their last known-good values were, or how much noise each
measurement carries.  ``PERF_LEDGER.json`` is that record, and
``deap-tpu-perfgate`` is its gate:

* each **tracked metric** names the artifact (glob — series like
  ``BENCH_r*.json`` track their latest file), the JSON path of the
  value inside it, the regression **direction** (``higher`` = bigger is
  better, ``lower`` = smaller is better), a relative noise **band**
  (``0 < band <= 1`` — the measured spread of that benchmark on the
  timeshared hosts the repo benches on), and a human **provenance**
  line recording how the number was measured (min-of-k interleaved
  legs, marginal timing, deterministic compiler output, ...);
* the **baseline** is the last value ``--update`` blessed, and
  ``history`` keeps one entry per artifact file so the whole committed
  series stays diffable after old artifacts are pruned;
* the **gate** re-extracts every tracked value from the working tree
  and fails (rc=1) when a value regresses past its tolerance: beyond
  ``baseline*(1±band)`` in the bad direction — or past the metric's
  absolute ``max_value``/``min_value`` bar when one is declared (the
  overhead metrics use absolute bars: a 1%→3% tracing-overhead change
  is inside measurement noise of a ≤5% budget, and a relative band
  around a near-zero baseline would reject it).

Workflow: commit a new bench artifact → ``deap-tpu-perfgate`` compares
it against the ledger in tier-1 (and at pre-push) → a regression beyond
band fails the commit; an intentional change (or a real improvement)
is blessed with ``deap-tpu-perfgate --update``, which rewrites
baselines + history from the current tree.

This module is **jax-free** (stdlib only, <1s on the whole artifact
set) so the gate runs beside the AST lint on any box; the ledger's
schema is additionally enforced by the ``bench-json`` lint pass (via
:func:`ledger_schema_errors` — one schema, two gates).  Its stdout is
its interface (sanctioned print site, like ``lint/cli.py``).
"""

from __future__ import annotations

import argparse
import json
import math
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["DEFAULT_LEDGER", "ledger_schema_errors", "resolve_path",
           "artifact_series", "evaluate_ledger", "update_ledger", "main"]

DEFAULT_LEDGER = "PERF_LEDGER.json"

_DIRECTIONS = ("higher", "lower")


# ---------------------------------------------------------------------------
# schema (shared with the bench-json lint pass)
# ---------------------------------------------------------------------------


def _is_finite_number(v) -> bool:
    return (not isinstance(v, bool) and isinstance(v, (int, float))
            and math.isfinite(float(v)))


def ledger_schema_errors(doc: Any) -> List[str]:
    """Schema violations of one parsed ``PERF_LEDGER.json`` document —
    the single source of truth for both ``deap-tpu-perfgate`` (rc=2 on
    a malformed ledger) and the ``bench-json`` lint pass (a malformed
    ledger commit fails tier-1)."""
    errors: List[str] = []
    if not isinstance(doc, dict):
        return [f"top level must be a JSON object, got "
                f"{type(doc).__name__}"]
    if not isinstance(doc.get("version"), int) \
            or isinstance(doc.get("version"), bool):
        errors.append("key 'version' must be an integer")
    metrics = doc.get("metrics")
    if not isinstance(metrics, dict) or not metrics:
        errors.append("key 'metrics' must be a non-empty object "
                      "{name: spec}")
        return errors
    for name, spec in metrics.items():
        where = f"metrics[{name!r}]"
        if not isinstance(spec, dict):
            errors.append(f"{where} must be an object")
            continue
        for key in ("artifact", "path", "provenance"):
            v = spec.get(key)
            if not isinstance(v, str) or not v.strip():
                errors.append(f"{where}.{key} must be a non-empty string "
                              "(provenance records HOW the number was "
                              "measured)")
        if spec.get("direction") not in _DIRECTIONS:
            errors.append(f"{where}.direction must be one of "
                          f"{_DIRECTIONS}")
        band = spec.get("band")
        if not _is_finite_number(band) or not (0.0 < float(band) <= 1.0):
            errors.append(f"{where}.band must be a number in (0, 1] "
                          "(the metric's relative noise tolerance)")
        for key in ("max_value", "min_value"):
            if key in spec and not _is_finite_number(spec[key]):
                errors.append(f"{where}.{key} must be a finite number")
        base = spec.get("baseline")
        if not isinstance(base, dict) \
                or not isinstance(base.get("artifact"), str) \
                or not _is_finite_number(base.get("value")):
            errors.append(f"{where}.baseline must be "
                          "{'artifact': str, 'value': finite number}")
        hist = spec.get("history")
        if not isinstance(hist, list):
            errors.append(f"{where}.history must be a list")
        else:
            for i, row in enumerate(hist):
                if not isinstance(row, dict) \
                        or not isinstance(row.get("artifact"), str) \
                        or not _is_finite_number(row.get("value")):
                    errors.append(
                        f"{where}.history[{i}] must be "
                        "{'artifact': str, 'value': finite number}")
    return errors


# ---------------------------------------------------------------------------
# extraction
# ---------------------------------------------------------------------------


def resolve_path(doc: Any, dotted: str):
    """Walk ``a.b.0.c`` through dicts and lists; raises ``KeyError``
    with the failing segment."""
    node = doc
    for seg in dotted.split("."):
        if isinstance(node, list):
            try:
                node = node[int(seg)]
                continue
            except (ValueError, IndexError):
                raise KeyError(f"segment {seg!r} of {dotted!r} does not "
                               "index the list")
        if not isinstance(node, dict) or seg not in node:
            raise KeyError(f"segment {seg!r} of {dotted!r} missing")
        node = node[seg]
    return node


def artifact_series(repo: Path, pattern: str, path: str
                    ) -> List[Tuple[str, Optional[float], Optional[str]]]:
    """``(artifact name, value, error)`` for every file matching
    ``pattern`` (sorted by name — the rXX series' natural order).  A
    file whose JSON or path fails contributes an error string instead
    of a value; the caller decides whether that file is load-bearing
    (the latest is; historical files are best-effort)."""
    out: List[Tuple[str, Optional[float], Optional[str]]] = []
    for p in sorted(repo.glob(pattern)):
        try:
            doc = json.loads(p.read_text())
            value = resolve_path(doc, path)
        except (ValueError, KeyError) as e:
            out.append((p.name, None, str(e)))
            continue
        if not _is_finite_number(value):
            out.append((p.name, None,
                        f"value at {path!r} is not a finite number: "
                        f"{value!r}"))
            continue
        out.append((p.name, float(value), None))
    return out


# ---------------------------------------------------------------------------
# the gate
# ---------------------------------------------------------------------------


def _tolerance(spec: Dict[str, Any]) -> Tuple[float, str]:
    """(limit, description) of the metric's regression bar.  An absolute
    ``max_value``/``min_value`` bar — when declared — replaces the
    relative band: overhead-percentage metrics sit near zero, where a
    relative band would reject changes far inside their real budget."""
    direction = spec["direction"]
    base = float(spec["baseline"]["value"])
    band = float(spec["band"])
    if direction == "lower":
        if "max_value" in spec:
            return float(spec["max_value"]), \
                f"absolute bar {spec['max_value']}"
        limit = base * (1.0 + band)
        return limit, f"baseline {base:g} * (1+{band:g})"
    if "min_value" in spec:
        return float(spec["min_value"]), f"absolute bar {spec['min_value']}"
    limit = base * (1.0 - band)
    return limit, f"baseline {base:g} * (1-{band:g})"


def evaluate_ledger(repo: Path, doc: Dict[str, Any]) -> List[Dict[str, Any]]:
    """One result row per tracked metric: ``status`` is ``ok`` /
    ``improved`` (beyond band in the GOOD direction — informational) /
    ``regressed`` / ``error`` (artifact missing or unreadable)."""
    results: List[Dict[str, Any]] = []
    for name in sorted(doc["metrics"]):
        spec = doc["metrics"][name]
        series = artifact_series(repo, spec["artifact"], spec["path"])
        row: Dict[str, Any] = {"metric": name,
                               "direction": spec["direction"],
                               "baseline": float(spec["baseline"]["value"])}
        if not series:
            row.update(status="error",
                       detail=f"no artifact matches {spec['artifact']!r}")
            results.append(row)
            continue
        artifact, value, err = series[-1]
        row["artifact"] = artifact
        if err is not None:
            row.update(status="error", detail=err)
            results.append(row)
            continue
        row["value"] = value
        limit, how = _tolerance(spec)
        row["limit"] = limit
        base = row["baseline"]
        band = float(spec["band"])
        if spec["direction"] == "lower":
            regressed = value > limit
            improved = value < base * (1.0 - band)
        else:
            regressed = value < limit
            improved = value > base * (1.0 + band)
        if regressed:
            row.update(status="regressed",
                       detail=f"{value:g} is past {how} = {limit:g}")
        elif improved:
            row.update(status="improved",
                       detail=f"{value:g} beats baseline {base:g} beyond "
                              f"the {band:g} band — bless it with "
                              "--update")
        else:
            row.update(status="ok")
        results.append(row)
    return results


def update_ledger(repo: Path, doc: Dict[str, Any]) -> Dict[str, Any]:
    """Rebless: baseline := the latest artifact's current value, and
    ``history`` merged with one row per artifact file present in the
    tree (rows for artifacts since deleted are preserved — the ledger
    is the durable record).  The latest artifact must extract cleanly;
    a broken historical file is skipped."""
    out = json.loads(json.dumps(doc))    # deep copy, JSON-clean
    for name, spec in out["metrics"].items():
        series = artifact_series(repo, spec["artifact"], spec["path"])
        live = [(a, v) for a, v, err in series if err is None]
        if not series:
            raise FileNotFoundError(
                f"metric {name!r}: no artifact matches "
                f"{spec['artifact']!r}")
        latest_name, latest_value, latest_err = series[-1]
        if latest_err is not None:
            raise ValueError(f"metric {name!r}: latest artifact "
                             f"{latest_name} unreadable: {latest_err}")
        spec["baseline"] = {"artifact": latest_name, "value": latest_value}
        merged = {row["artifact"]: row["value"]
                  for row in spec.get("history", ())}
        merged.update(dict(live))
        spec["history"] = [{"artifact": a, "value": merged[a]}
                           for a in sorted(merged)]
    return out


def render_text(results: List[Dict[str, Any]]) -> str:
    lines = []
    width = max((len(r["metric"]) for r in results), default=10)
    for r in results:
        val = f"{r['value']:g}" if "value" in r else "-"
        mark = {"ok": "ok", "improved": "OK+", "regressed": "FAIL",
                "error": "ERR"}[r["status"]]
        line = (f"{mark:4s} {r['metric']:{width}s} {val:>12s} "
                f"({r['direction']}, baseline {r['baseline']:g})")
        if r.get("detail"):
            line += f" -- {r['detail']}"
        lines.append(line)
    bad = sum(1 for r in results if r["status"] in ("regressed", "error"))
    lines.append(f"{len(results)} tracked metrics, {bad} failing")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="deap-tpu-perfgate",
        description="Perf-regression gate over the committed BENCH_*.json "
                    "artifacts: every PERF_LEDGER.json metric must sit "
                    "inside its noise band (or absolute bar) relative to "
                    "its blessed baseline.")
    ap.add_argument("--ledger", default=None,
                    help=f"ledger path (default: <repo>/{DEFAULT_LEDGER})")
    ap.add_argument("--repo", default=".",
                    help="repo root the artifact globs resolve against "
                         "(default: cwd)")
    ap.add_argument("--update", action="store_true",
                    help="rebless: rewrite baselines + history from the "
                         "current artifact tree, then exit 0")
    ap.add_argument("--json", action="store_true", dest="json_out",
                    help="machine output on stdout")
    args = ap.parse_args(argv)

    repo = Path(args.repo).resolve()
    ledger_path = (Path(args.ledger) if args.ledger
                   else repo / DEFAULT_LEDGER)
    try:
        doc = json.loads(ledger_path.read_text())
    except FileNotFoundError:
        print(f"deap-tpu-perfgate: no ledger at {ledger_path}")
        return 2
    except ValueError as e:
        print(f"deap-tpu-perfgate: ledger is not valid JSON: {e}")
        return 2
    errors = ledger_schema_errors(doc)
    if errors:
        for e in errors:
            print(f"deap-tpu-perfgate: schema: {e}")
        return 2

    if args.update:
        try:
            doc = update_ledger(repo, doc)
        except (FileNotFoundError, ValueError) as e:
            print(f"deap-tpu-perfgate: {e}")
            return 2
        ledger_path.write_text(json.dumps(doc, indent=1, sort_keys=True)
                               + "\n")
        print(f"deap-tpu-perfgate: reblessed {len(doc['metrics'])} "
              f"baselines into {ledger_path}")
        return 0

    results = evaluate_ledger(repo, doc)
    if args.json_out:
        bad = sum(1 for r in results
                  if r["status"] in ("regressed", "error"))
        print(json.dumps({"ledger": str(ledger_path),
                          "results": results, "failing": bad},
                         indent=2, sort_keys=True))
    else:
        print(render_text(results))
    return 1 if any(r["status"] in ("regressed", "error")
                    for r in results) else 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
