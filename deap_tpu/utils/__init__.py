"""Support utilities: statistics, logbook, archives, genealogy, checkpoint."""

from .support import (Statistics, MultiStatistics, Logbook, HallOfFame,
                      ParetoFront, History, hof_init, hof_update,
                      pareto_init, pareto_update)  # noqa: F401
from .checkpoint import save_checkpoint, load_checkpoint  # noqa: F401
