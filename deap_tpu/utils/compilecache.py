"""Opt-in persistent XLA compilation cache.

Cold-start compiles dominate short runs and service restarts: the flagship
``bench.py`` program compiles in seconds-to-minutes depending on backend,
and a restarted :class:`~deap_tpu.serve.service.EvolutionService` pays one
compile per bucket before reaching steady state.  JAX can persist compiled
executables to disk and reload them across *processes* — this module is
the one switch that turns it on with sane settings:

    from deap_tpu.utils.compilecache import enable_compile_cache
    enable_compile_cache("~/.cache/deap_tpu_xla")

Entry points wire it to flags/environment: ``bench.py`` honors
``DEAP_TPU_COMPILE_CACHE=<dir>`` and ``deap-tpu-serve`` takes
``--compile-cache <dir>`` (see docs/performance.md).  Off by default —
the cache trades disk for startup latency and is a deployment decision.
"""

from __future__ import annotations

import os
import warnings
from pathlib import Path
from typing import Optional

__all__ = ["enable_compile_cache", "cache_dir_from_env", "ENV_VAR"]

#: Environment variable the entry points honor.
ENV_VAR = "DEAP_TPU_COMPILE_CACHE"


def cache_dir_from_env() -> Optional[str]:
    """The opt-in directory from ``DEAP_TPU_COMPILE_CACHE`` (None = off)."""
    path = os.environ.get(ENV_VAR, "").strip()
    return path or None


def enable_compile_cache(path, *, min_compile_time_secs: float = 0.0,
                         min_entry_size_bytes: int = 0) -> Optional[Path]:
    """Persist XLA compilations under ``path`` (created if missing) and
    reuse them across processes.

    By default every compilation is cached (``min_compile_time_secs=0`` /
    ``min_entry_size_bytes=0``) — the serving layer's bucket programs are
    individually cheap but numerous, which is exactly the cold-start cost
    the cache exists to amortize.  Returns the resolved cache directory,
    or ``None`` (with a warning) when this jax build has no persistent
    cache support — callers never have to gate on jax versions."""
    path = Path(path).expanduser()
    try:
        path.mkdir(parents=True, exist_ok=True)
    except OSError as e:
        warnings.warn(f"compile cache disabled: cannot create {path}: {e}")
        return None
    import jax
    try:
        jax.config.update("jax_compilation_cache_dir", str(path))
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          float(min_compile_time_secs))
        jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                          int(min_entry_size_bytes))
    except (AttributeError, ValueError) as e:
        warnings.warn(f"compile cache disabled: this jax build does not "
                      f"support the persistent compilation cache ({e})")
        return None
    return path
