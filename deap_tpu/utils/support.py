"""Observability & archives — array-native equivalent of ``deap/tools/support.py``.

* :class:`Statistics` / :class:`MultiStatistics` — reducer registries whose
  ``compile`` works on device arrays *inside jit* (reference
  support.py:154-259): in a scanned generation loop the per-generation stat
  dicts come out as stacked arrays, which :meth:`Logbook.record_stacked`
  unpacks into chronological records host-side.
* :class:`Logbook` — host-side chronological records with nested chapters
  and the column-aligned ASCII ``stream`` (reference support.py:261-487).
* :class:`HallOfFame` / :class:`ParetoFront` — fixed-capacity *device*
  archives (functional update kernels threaded through the scan carry) with
  thin host wrappers (reference support.py:490-640).  Fixed capacity +
  masking replaces the reference's dynamically-growing sorted lists.
* :class:`History` — host-side genealogy recorder (reference support.py:21-152).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from operator import eq
from typing import Any, Callable, Dict, List

import numpy as np
import jax
import jax.numpy as jnp

from ..base import Fitness, Population, dominates, lex_sort_indices

__all__ = [
    "Statistics", "MultiStatistics", "Logbook",
    "HallOfFame", "ParetoFront", "History",
    "hof_init", "hof_update", "pareto_init", "pareto_update",
]


class Statistics:
    """Reducer registry (reference Statistics, support.py:154-210).

    ``key`` extracts the data from what ``compile`` receives — e.g.
    ``Statistics(key=lambda pop: pop.fitness.values[:, 0])``.  Registered
    functions should be jnp reducers so ``compile`` can run under jit.
    """

    def __init__(self, key: Callable = lambda x: x):
        self.key = key
        self.functions: Dict[str, Callable] = {}
        self.fields: List[str] = []

    def register(self, name: str, function: Callable, *args, **kargs):
        self.functions[name] = partial(function, *args, **kargs)
        self.fields.append(name)

    def compile(self, data) -> Dict[str, Any]:
        values = self.key(data)
        return {name: func(values) for name, func in self.functions.items()}


class MultiStatistics(dict):
    """Dict of named :class:`Statistics` compiled together into nested
    chapters (reference MultiStatistics, support.py:212-259)."""

    def __init__(self, **kargs):
        super().__init__(**kargs)
        self.fields = sorted(kargs.keys())

    def register(self, name: str, function: Callable, *args, **kargs):
        for stats in self.values():
            stats.register(name, function, *args, **kargs)

    def compile(self, data) -> Dict[str, Dict[str, Any]]:
        return {name: stats.compile(data) for name, stats in self.items()}


class Logbook(list):
    """Chronological list of dict records with nested chapters and aligned
    ASCII streaming (reference Logbook, support.py:261-487)."""

    def __init__(self):
        super().__init__()
        self.buffindex = 0
        self.chapters: Dict[str, "Logbook"] = {}
        self.columns_len = None
        self.header = None
        self.log_header = True

    def record(self, **infos):
        apply_to_all = {k: v for k, v in infos.items() if not isinstance(v, dict)}
        for key, value in list(infos.items()):
            if isinstance(value, dict):
                chapter_infos = dict(value)
                chapter_infos.update(apply_to_all)
                if key not in self.chapters:
                    self.chapters[key] = Logbook()
                self.chapters[key].record(**chapter_infos)
                del infos[key]
        self.append(infos)

    def record_stacked(self, **stacked):
        """Unpack per-generation stacked arrays (as produced by a scanned
        loop) into one ``record`` call per generation.

        Each leaf is converted to host numpy ONCE up front: ``np.asarray``
        on a device array is a device->host transfer, and doing it inside
        the per-generation loop repeated the full-column transfer O(ngen)
        times per leaf."""
        def to_host(v):
            if isinstance(v, dict):
                return {k: to_host(x) for k, x in v.items()}
            return np.asarray(v)

        def length(v):
            if isinstance(v, dict):
                return length(next(iter(v.values())))
            return len(v)

        def slice_i(v, i):
            if isinstance(v, dict):
                return {k: slice_i(x, i) for k, x in v.items()}
            x = v[i]
            return x.item() if np.ndim(x) == 0 else x

        stacked = {k: to_host(v) for k, v in stacked.items()}
        ngen = length(next(iter(stacked.values())))
        for i in range(ngen):
            self.record(**{k: slice_i(v, i) for k, v in stacked.items()})

    def select(self, *names):
        if len(names) == 1:
            return [entry.get(names[0], None) for entry in self]
        return tuple([entry.get(name, None) for entry in self] for name in names)

    def pop(self, index=0):
        """Retrieve and delete element ``index``, also from the chapters
        (reference support.py:322-333)."""
        if index < self.buffindex:
            self.buffindex -= 1
        for chapter in self.chapters.values():
            chapter.pop(index)
        return super().pop(index)

    def __delitem__(self, key):
        for chapter in self.chapters.values():
            chapter.__delitem__(key)
        super().__delitem__(key)

    @property
    def stream(self) -> str:
        startindex, self.buffindex = self.buffindex, len(self)
        return self.__str__(startindex)

    def __txt__(self, startindex):
        """Render records ``startindex:`` as aligned text lines.

        Column-major pipeline: each column independently yields a *header
        block* (possibly several lines — chapters carry a centered title, a
        dash rule, and their own nested header) and a *body block* (one cell
        per record, chapters contributing their pre-rendered lines).  Blocks
        are then bottom-aligned and zipped into rows.  Column widths live in
        ``self.columns_len`` and only ever grow, so successive ``stream``
        chunks stay aligned with earlier output.
        """
        columns = self.header
        if not columns:
            columns = sorted(self[0].keys()) + sorted(self.chapters.keys())
        if not self.columns_len or len(self.columns_len) != len(columns):
            self.columns_len = [len(str(c)) for c in columns]

        show_header = startindex == 0 and self.log_header
        n_body = len(self) - startindex

        heads: list[list[str]] = []     # per-column header block
        bodies: list[list[str]] = []    # per-column body cells
        for j, name in enumerate(columns):
            chapter = self.chapters.get(name)
            if chapter is not None:
                sub = chapter.__txt__(startindex)
                split = len(sub) - n_body
                width = max((len(s.expandtabs()) for s in sub),
                            default=len(str(name)))
                head = [str(name).center(width), "-" * width] + sub[:split]
                body = sub[split:]
            else:
                body = []
                for rec in self[startindex:]:
                    v = rec.get(name, "")
                    body.append(f"{v:g}" if isinstance(v, float) else str(v))
                width = max(len(s) for s in body) if body else 0
                head = [str(name)]
            self.columns_len[j] = max(self.columns_len[j], width)
            heads.append(head)
            bodies.append(body)

        rows: list[list[str]] = []
        if show_header:
            depth = max(len(h) for h in heads)
            padded = [[""] * (depth - len(h)) + h for h in heads]
            rows.extend(list(r) for r in zip(*padded))
        if n_body:
            rows.extend(list(r) for r in zip(*bodies))

        return ["\t".join(cell.ljust(w)
                          for cell, w in zip(row, self.columns_len))
                for row in rows]

    def __str__(self, startindex=0):
        text = self.__txt__(startindex)
        return "\n".join(text)


# ---------------------------------------------------------------------------
# Device archives
# ---------------------------------------------------------------------------


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class _ArchiveState:
    genome: Any                  # pytree, leaves (maxsize, ...)
    values: jax.Array            # (maxsize, nobj) raw objective values
    filled: jax.Array            # (maxsize,) bool
    weights: tuple = dataclasses.field(metadata=dict(static=True))

    @property
    def wvalues(self):
        w = self.values * jnp.asarray(self.weights, self.values.dtype)
        return jnp.where(self.filled[:, None], w, -jnp.inf)


def _flat_genome(genome):
    """Flatten each individual's genome leaves into one (n, D) float row for
    equality tests."""
    leaves = jax.tree_util.tree_leaves(genome)
    return jnp.concatenate(
        [l.reshape(l.shape[0], -1).astype(jnp.float32) for l in leaves], axis=1)


def hof_init(maxsize: int, population: Population) -> _ArchiveState:
    """Empty hall-of-fame archive shaped like ``population``'s individuals
    (reference HallOfFame, support.py:490-588)."""
    genome = jax.tree_util.tree_map(
        lambda g: jnp.zeros((maxsize,) + g.shape[1:], g.dtype), population.genome)
    return _ArchiveState(
        genome=genome,
        values=jnp.zeros((maxsize, population.fitness.nobj),
                         population.fitness.values.dtype),
        filled=jnp.zeros((maxsize,), bool),
        weights=population.fitness.weights,
    )


def hof_update(state: _ArchiveState, population: Population,
               dedup: bool = True) -> _ArchiveState:
    """Functional HOF update: keep the lexicographically best ``maxsize``
    individuals of archive ∪ population (reference HallOfFame.update,
    support.py:517-540).  With ``dedup`` (the reference's ``similar=eq``),
    exact-duplicate genomes are inserted only once.

    Cost note: to stay O(pop · log pop), duplicates are eliminated among the
    top ``4·maxsize`` candidates only — beyond that margin duplicates cannot
    displace distinct elites in practice.
    """
    maxsize = state.filled.shape[0]
    cand_n = min(4 * maxsize, population.size) if dedup else maxsize

    pop_w = population.fitness.masked_wvalues()
    top = lex_sort_indices(pop_w, descending=True)[:cand_n]
    cand = population.take(top)

    all_genome = jax.tree_util.tree_map(
        lambda a, b: jnp.concatenate([a, b], 0), state.genome, cand.genome)
    all_values = jnp.concatenate([state.values, cand.fitness.values], 0)
    all_filled = jnp.concatenate([state.filled, cand.fitness.valid[:cand_n]], 0)
    w = all_values * jnp.asarray(state.weights, all_values.dtype)
    w = jnp.where(all_filled[:, None], w, -jnp.inf)

    order = lex_sort_indices(w, descending=True)
    sorted_genome = jax.tree_util.tree_map(lambda g: g[order], all_genome)
    sorted_values = all_values[order]
    sorted_filled = all_filled[order]

    if dedup:
        flat = _flat_genome(sorted_genome)
        m = flat.shape[0]
        same = jnp.all(flat[:, None, :] == flat[None, :, :], -1)
        earlier = jnp.tril(jnp.ones((m, m), bool), k=-1)
        is_dup = jnp.any(same & earlier & sorted_filled[None, :], axis=1)
        keep = sorted_filled & ~is_dup
        reorder = jnp.argsort(~keep, stable=True)
        sorted_genome = jax.tree_util.tree_map(lambda g: g[reorder], sorted_genome)
        sorted_values = sorted_values[reorder]
        sorted_filled = keep[reorder]

    return _ArchiveState(
        genome=jax.tree_util.tree_map(lambda g: g[:maxsize], sorted_genome),
        values=sorted_values[:maxsize],
        filled=sorted_filled[:maxsize],
        weights=state.weights,
    )


def pareto_init(maxsize: int, population: Population) -> _ArchiveState:
    """Empty Pareto archive (reference ParetoFront, support.py:591-640; the
    reference grows without bound — here capacity is static, pruned by
    crowding distance when full)."""
    return hof_init(maxsize, population)


def pareto_update(state: _ArchiveState, population: Population) -> _ArchiveState:
    """Keep the non-dominated subset of archive ∪ population, dropping
    crowding-poorest points when over capacity."""
    from ..ops.emo import nondominated_ranks, assign_crowding_dist

    maxsize = state.filled.shape[0]
    pop_w = population.fitness.masked_wvalues()
    # preselect the population's own nondominated subset, capped at maxsize
    ranks_p, _ = nondominated_ranks(pop_w)
    dist_p = assign_crowding_dist(population.fitness.values, ranks_p)
    order_p = jnp.lexsort((-dist_p, ranks_p))[:maxsize]
    cand = population.take(order_p)
    cand_first = ranks_p[order_p] == 0
    cand_valid = cand.fitness.valid & cand_first

    all_genome = jax.tree_util.tree_map(
        lambda a, b: jnp.concatenate([a, b], 0), state.genome, cand.genome)
    all_values = jnp.concatenate([state.values, cand.fitness.values], 0)
    all_filled = jnp.concatenate([state.filled, cand_valid], 0)
    w = all_values * jnp.asarray(state.weights, all_values.dtype)
    w = jnp.where(all_filled[:, None], w, -jnp.inf)

    # nondominated among the union; exact-duplicate wvalue rows keep one copy
    dom = dominates(w[:, None, :], w[None, :, :])
    dominated = jnp.any(dom & all_filled[:, None], axis=0)
    m = w.shape[0]
    same = jnp.all(w[:, None, :] == w[None, :, :], -1)
    earlier = jnp.tril(jnp.ones((m, m), bool), k=-1)
    is_dup = jnp.any(same & earlier & all_filled[None, :], axis=1)
    keep = all_filled & ~dominated & ~is_dup

    ranks = jnp.where(keep, 0, 1).astype(jnp.int32)
    dist = assign_crowding_dist(all_values, ranks)
    order = jnp.lexsort((-jnp.where(keep, dist, -jnp.inf), ~keep))
    return _ArchiveState(
        genome=jax.tree_util.tree_map(lambda g: g[order][:maxsize], all_genome),
        values=all_values[order][:maxsize],
        filled=keep[order][:maxsize],
        weights=state.weights,
    )


class HallOfFame:
    """Host wrapper over the device HOF kernels, API-compatible with the
    reference (support.py:490-588): ``update``, ``insert``-free iteration,
    ``__getitem__`` returning ``(genome, values)`` pairs, ``clear``."""

    _update_fn = staticmethod(hof_update)
    _init_fn = staticmethod(hof_init)

    def __init__(self, maxsize: int, similar: Callable | None = eq):
        self.maxsize = maxsize
        self.similar = similar
        self.state: _ArchiveState | None = None

    def init_state(self, population: Population) -> _ArchiveState:
        self.state = self._init_fn(self.maxsize, population)
        return self.state

    def update(self, population: Population):
        if self.state is None:
            self.init_state(population)
        if type(self)._update_fn is hof_update:
            self.state = hof_update(self.state, population,
                                    dedup=self.similar is not None)
        else:
            self.state = type(self)._update_fn(self.state, population)
        return self.state

    def clear(self):
        self.state = None

    def __len__(self):
        if self.state is None:
            return 0
        return int(np.sum(np.asarray(self.state.filled)))

    def __getitem__(self, i):
        genome = jax.tree_util.tree_map(lambda g: np.asarray(g)[i], self.state.genome)
        return genome, np.asarray(self.state.values)[i]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    @property
    def keys(self):
        return np.asarray(self.state.values)[: len(self)]


class ParetoFront(HallOfFame):
    """Host wrapper over the Pareto archive kernels (reference ParetoFront,
    support.py:591-640)."""

    _update_fn = staticmethod(pareto_update)
    _init_fn = staticmethod(pareto_init)

    def __init__(self, maxsize: int = 1024, similar: Callable | None = eq):
        super().__init__(maxsize, similar)


class History:
    """Genealogy recorder (reference History, support.py:21-152).  Host-side:
    snapshots flow through ``update`` with explicit parent indices (array
    programs know lineage by index, not object identity).  Produces the same
    ``genealogy_tree``/``genealogy_history`` structures, consumable by
    NetworkX."""

    def __init__(self):
        self.genealogy_index = 0
        self.genealogy_history: Dict[int, Any] = {}
        self.genealogy_tree: Dict[int, tuple] = {}
        self._latest: np.ndarray | None = None   # per-slot history index

    def update(self, genomes, parent_slots=None):
        """Record a population snapshot.  ``genomes``: pytree with leading
        pop axis (host or device).  ``parent_slots``: optional (pop, nparents)
        slot indices into the *previous* snapshot."""
        flat = jax.tree_util.tree_leaves(genomes)[0]
        n = np.asarray(flat).shape[0]
        new_idx = np.zeros(n, dtype=np.int64)
        for i in range(n):
            self.genealogy_index += 1
            new_idx[i] = self.genealogy_index
            self.genealogy_history[self.genealogy_index] = (
                jax.tree_util.tree_map(lambda g: np.asarray(g)[i], genomes))
            if parent_slots is None or self._latest is None:
                self.genealogy_tree[self.genealogy_index] = tuple()
            else:
                ps = np.atleast_1d(np.asarray(parent_slots)[i])
                self.genealogy_tree[self.genealogy_index] = tuple(
                    int(self._latest[p]) for p in ps)
        self._latest = new_idx

    def getGenealogy(self, index: int, max_depth: float = float("inf")):
        """Ancestor subtree of history entry ``index`` (reference
        support.py:123-152)."""
        gtree = {}
        visited = set()

        def walk(idx, depth):
            if depth > max_depth or idx in visited:
                return
            visited.add(idx)
            parents = self.genealogy_tree.get(idx, ())
            gtree[idx] = list(parents)
            for p in parents:
                walk(p, depth + 1)

        walk(index, 0)
        return gtree
