"""Checkpoint / resume.

The reference documents checkpointing as a pattern — pickle a dict of
{population, generation, halloffame, logbook, random.getstate()} every FREQ
generations (doc/tutorials/advanced/checkpoint.rst:21-72).  Here it is a
first-class API over arbitrary pytrees: device arrays are pulled to host
numpy, everything else pickles as-is, and the PRNG **key** replaces
``random.getstate()`` for exact resumption.

Two tiers:

* :func:`save_checkpoint` / :func:`load_checkpoint` — the reference's
  single-host pattern: the whole pytree gathered to one pickle.  Wrong for
  sharded populations: ``np.asarray`` on a non-fully-addressable array
  fails outright, and on a single-process sharded array it gathers every
  shard to the host.
* :func:`save_sharded_checkpoint` / :func:`load_sharded_checkpoint` — the
  orbax-style per-shard tier: every process writes only the addressable
  shards it owns (replica 0 of each, so nothing is written twice), and
  restore reassembles each *new* addressable shard from whichever saved
  chunks overlap it — the saving and restoring meshes may differ in
  layout, axis names, and process count (shared filesystem assumed,
  as orbax assumes).
"""

from __future__ import annotations

import pickle
import re
import shutil
import threading
from pathlib import Path
from typing import Any

import numpy as np
import jax
import jax.numpy as jnp

__all__ = ["save_checkpoint", "load_checkpoint", "async_save_checkpoint",
           "save_sharded_checkpoint", "load_sharded_checkpoint"]


def _to_host(tree):
    return jax.tree_util.tree_map(
        lambda x: np.asarray(x) if isinstance(x, jax.Array) else x, tree)


def save_checkpoint(path, state: Any) -> None:
    """Atomically pickle a state pytree (population, PRNG key, strategy
    state, logbook, ...) to ``path``."""
    path = Path(path)
    tmp = path.with_suffix(path.suffix + ".tmp")
    host_state = _to_host(state)
    with open(tmp, "wb") as f:
        pickle.dump(host_state, f, protocol=pickle.HIGHEST_PROTOCOL)
    tmp.replace(path)


class _AsyncSave(threading.Thread):
    """Writer thread that keeps its exception instead of losing it to the
    default thread excepthook.  ``result()`` joins and re-raises — the
    orbax ``AsyncCheckpointer.wait_until_finished`` contract."""

    def __init__(self, write_fn):
        super().__init__(daemon=True, name="deap-tpu-async-ckpt")
        self._write_fn = write_fn
        self.exc: BaseException | None = None

    def run(self):
        try:
            self._write_fn()
        except BaseException as e:          # noqa: BLE001 — must not vanish
            # traceback frames pin the write closure (and with it the full
            # host-side state copy); keep the exception, drop the frames
            self.exc = e.with_traceback(None)
        finally:
            # the closure holds the full host-side state copy; a finished
            # writer must not keep a checkpoint-sized buffer alive via the
            # module-global handle
            self._write_fn = None

    def result(self, timeout: float | None = None) -> None:
        self.join(timeout)
        if self.is_alive():
            raise TimeoutError(
                f"async checkpoint write still running after {timeout}s")
        if self.exc is not None:
            exc, self.exc = self.exc, None      # consume: report once —
            raise exc                           # not again from the next
                                                # async_save_checkpoint call


_async_registry_lock = threading.Lock()
# per-path serialization cells: {"lock": Lock, "handle": previous writer}.
# Entries are never removed — the registry grows by one small cell per
# DISTINCT checkpoint path (handles drop their payloads when done), and
# not deleting them is what makes the per-path locking race-free.
_async_saves: dict[str, dict] = {}


def async_save_checkpoint(path, state: Any) -> _AsyncSave:
    """Device→host transfer happens synchronously (cheap), serialization in
    a background thread — the orbax-style async pattern, so the training
    loop never blocks on disk.

    Overlapping saves **to the same path** are serialized: a new call
    first joins that path's previous writer (two concurrent writers would
    race on the ``.tmp`` file and could commit a stale state over a newer
    one).  A failure in the writer thread is never silently lost — it
    re-raises either from the returned handle's ``result()`` or, if
    nobody joined, from the *next* ``async_save_checkpoint`` call for
    that path (before the new write starts, so the caller can react while
    the previous checkpoint on disk is still intact).  Independent
    checkpoint streams to different paths neither block nor poison each
    other."""
    host_state = _to_host(state)
    # canonical key: two spellings of one file (relative vs absolute,
    # symlinked dirs) must land in the same serialization cell
    key = str(Path(path).expanduser().resolve())

    def _write():
        path_ = Path(path)
        tmp = path_.with_suffix(path_.suffix + ".tmp")
        with open(tmp, "wb") as f:
            pickle.dump(host_state, f, protocol=pickle.HIGHEST_PROTOCOL)
        tmp.replace(path_)

    # The registry lock only guards the dict; the PER-PATH lock covers the
    # whole pop-join-register sequence, so two callers racing on one path
    # cannot both see no predecessor and spawn concurrent writers on the
    # same .tmp, while saves to other paths proceed without waiting on
    # this stream's disk.  (Writer threads never take either lock, so
    # joining under the path lock cannot deadlock.)
    with _async_registry_lock:
        cell = _async_saves.setdefault(
            key, {"lock": threading.Lock(), "handle": None})
    with cell["lock"]:
        prev, cell["handle"] = cell["handle"], None
        if prev is not None:
            prev.join()
            if prev.exc is not None:
                exc, prev.exc = prev.exc, None      # report once
                raise RuntimeError(
                    f"previous async_save_checkpoint to {key} failed; the "
                    "new save was not started") from exc
        t = _AsyncSave(_write)
        cell["handle"] = t
        t.start()
    return t


def load_checkpoint(path) -> Any:
    with open(path, "rb") as f:
        return pickle.load(f)


# ---------------------------------------------------------------------------
# sharded (per-shard, mesh-agnostic) tier
# ---------------------------------------------------------------------------


def _leaf_key(path) -> str:
    return jax.tree_util.keystr(path)


def _is_prng_key(x) -> bool:
    return isinstance(x, jax.Array) and jax.dtypes.issubdtype(
        x.dtype, jax.dtypes.prng_key)


def _barrier(tag: str) -> None:
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(tag)


def _read_commit(d: Path):
    """Parse ``COMMIT`` → ``(version, nproc)``, ``(None, nproc)`` for the
    legacy flat layout, or ``None`` if absent.  Raises on corrupt content —
    a half-written marker must refuse, not silently skip validation."""
    try:
        txt = (d / "COMMIT").read_text().strip()
    except FileNotFoundError:
        return None
    toks = txt.split()
    if len(toks) == 2 and toks[0].startswith("v") and toks[0][1:].isdigit() \
            and toks[1].isdigit():
        return int(toks[0][1:]), int(toks[1])
    if len(toks) == 1 and toks[0].isdigit():      # legacy flat layout
        return None, int(toks[0])
    raise ValueError(
        f"{d}: corrupt COMMIT marker {txt!r} — refusing to load")


def _prune_versions(d: Path, keep: Path | None) -> None:
    """Remove every ``v<digits>`` checkpoint subdirectory except ``keep``.
    Anchored to the exact version-dir name shape so sibling user
    directories that merely start with 'v' are never touched."""
    for sub in d.glob("v*"):
        if sub != keep and sub.is_dir() and re.fullmatch(r"v\d+", sub.name):
            shutil.rmtree(sub, ignore_errors=True)


def _next_version(d: Path) -> int:
    """One past the highest existing ``v<digits>`` subdirectory — so a new
    save can never alias a directory holding committed *or* crashed-attempt
    fragments, whatever state the COMMIT marker is in."""
    vers = [int(m.group(1)) for sub in d.glob("v*")
            if sub.is_dir() and (m := re.fullmatch(r"v(\d+)", sub.name))]
    return max(vers, default=-1) + 1


def save_sharded_checkpoint(dirpath, state: Any) -> None:
    """Write ``state`` under directory ``dirpath``, one ``.npz`` of shard
    chunks plus one manifest fragment per process.

    Each process stores the replica-0 addressable shards of every
    ``jax.Array`` leaf (so a fully-replicated leaf is written exactly once,
    by the process owning its replica 0) tagged with the shard's global
    index box; non-array leaves pickle into process 0's manifest.

    Saves are *versioned* (the orbax step-directory pattern): fragments go
    into a fresh ``v{N}/`` subdirectory, and only after a cross-process
    barrier does process 0 atomically swing the ``COMMIT`` marker — which
    records the active version and the writing process count — onto the new
    version, then delete superseded ones.  A crash at ANY point before the
    marker swing leaves the previous checkpoint fully loadable; a crash
    after it leaves the new one loadable.  There is no window in which the
    directory mixes shards from different saves or holds no restorable
    state (advisor round-4 finding).  :func:`load_sharded_checkpoint`
    refuses a directory without a marker, with a corrupt marker, or whose
    fragment count disagrees with the recorded process count."""
    d = Path(dirpath)
    d.mkdir(parents=True, exist_ok=True)
    pid = jax.process_index()
    # Process 0 decides the next version (one past any existing version
    # dir, committed or crashed — a corrupt COMMIT marker therefore never
    # blocks saving, and NOTHING is deleted before the new marker lands,
    # so even a manually-recoverable wreck stays recoverable) and
    # broadcasts it: agreement must not rest on every process re-reading
    # the shared filesystem, whose caches can serve stale COMMIT content.
    version = _next_version(d) if pid == 0 else 0
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        version = int(multihost_utils.broadcast_one_to_all(
            np.int32(version)))
    vd = d / f"v{version}"
    vd.mkdir(parents=True, exist_ok=True)
    chunks: dict[str, np.ndarray] = {}
    meta: dict[str, Any] = {"leaves": {}, "chunks": []}
    flat, _ = jax.tree_util.tree_flatten_with_path(state)
    other: dict[str, Any] = {}
    for path, leaf in flat:
        key = _leaf_key(path)
        if isinstance(leaf, jax.Array):
            impl = None
            if _is_prng_key(leaf):
                impl = str(jax.random.key_impl(leaf))
                leaf = jax.random.key_data(leaf)
            meta["leaves"][key] = {
                "shape": tuple(leaf.shape), "dtype": str(leaf.dtype),
                "prng_impl": impl,
            }
            for i, shard in enumerate(leaf.addressable_shards):
                if shard.replica_id != 0:
                    continue
                box = tuple(
                    (0 if s.start is None else int(s.start),
                     dim if s.stop is None else int(s.stop))
                    for s, dim in zip(shard.index, leaf.shape))
                ck = f"c{len(chunks)}"
                chunks[ck] = np.asarray(shard.data)
                meta["chunks"].append({"leaf": key, "box": box, "key": ck})
        else:
            other[key] = leaf
    meta["other"] = other

    np_tmp = vd / f"shards_p{pid}.npz.tmp"
    with open(np_tmp, "wb") as f:       # handle, not path: savez would
        np.savez(f, **chunks)           # append .npz to the tmp name
    np_tmp.replace(vd / f"shards_p{pid}.npz")
    mf_tmp = vd / f"manifest_p{pid}.pkl.tmp"
    with open(mf_tmp, "wb") as f:
        pickle.dump(meta, f, protocol=pickle.HIGHEST_PROTOCOL)
    mf_tmp.replace(vd / f"manifest_p{pid}.pkl")

    _barrier("deap_tpu_ckpt_save")
    if pid == 0:
        # atomic marker swing: the old checkpoint stays loadable until
        # this single rename, the new one is loadable right after it
        c_tmp = d / "COMMIT.tmp"
        c_tmp.write_text(f"v{version} {jax.process_count()}")
        c_tmp.replace(d / "COMMIT")
        _prune_versions(d, keep=vd)
        for stale in (*d.glob("shards_p*"), *d.glob("manifest_p*")):
            stale.unlink(missing_ok=True)  # superseded legacy flat layout
    # no process may start the NEXT save (and re-read COMMIT) before the
    # marker swing lands
    _barrier("deap_tpu_ckpt_commit")


def load_sharded_checkpoint(dirpath, like: Any) -> Any:
    """Rebuild a checkpoint written by :func:`save_sharded_checkpoint`.

    ``like`` is a pytree matching the saved structure whose array leaves
    carry the *target* sharding (live arrays or ``ShapeDtypeStruct`` with a
    ``.sharding``); each new addressable shard is assembled from the saved
    chunks overlapping its index box, so restoring onto a different mesh —
    more processes, fewer devices, a different partition axis — is just a
    different overlap pattern.  Non-array leaves come from the manifest.
    Returns the restored pytree; array contents are bit-identical to what
    was saved."""
    d = Path(dirpath)
    commit = _read_commit(d)             # raises ValueError if corrupt
    if commit is None:
        raise FileNotFoundError(
            f"{d} has no COMMIT marker: incomplete or not a sharded "
            "checkpoint")
    version, nproc = commit
    frag_dir = d if version is None else d / f"v{version}"
    frags = sorted(frag_dir.glob("manifest_p*.pkl"))
    if len(frags) != nproc:
        raise ValueError(
            f"{frag_dir}: COMMIT records {nproc} writer process(es) but "
            f"{len(frags)} manifest fragment(s) present — mixed or "
            "partially-cleaned checkpoint")
    leaves_meta: dict[str, Any] = {}
    chunk_index: dict[str, list] = {}
    other: dict[str, Any] = {}
    files: dict[Path, Any] = {}
    for frag in frags:
        with open(frag, "rb") as f:
            meta = pickle.load(f)
        leaves_meta.update(meta["leaves"])
        other.update(meta.get("other", {}))
        npz = frag.with_name(frag.name.replace("manifest_", "shards_"
                                               ).replace(".pkl", ".npz"))
        for c in meta["chunks"]:
            chunk_index.setdefault(c["leaf"], []).append((npz, c))

    def get_file(p):
        if p not in files:
            files[p] = np.load(p)
        return files[p]

    def assemble(key, box):
        """Fill the [start, stop) box of leaf ``key`` from saved chunks."""
        m = leaves_meta[key]
        out = np.empty([hi - lo for lo, hi in box], dtype=m["dtype"])
        filled = 0
        for npz, c in chunk_index.get(key, ()):
            inter = [(max(lo, clo), min(hi, chi))
                     for (lo, hi), (clo, chi) in zip(box, c["box"])]
            if any(lo >= hi for lo, hi in inter):
                continue
            src = get_file(npz)[c["key"]]
            src_sl = tuple(slice(lo - clo, hi - clo) for (lo, hi), (clo, _)
                           in zip(inter, c["box"]))
            dst_sl = tuple(slice(lo - blo, hi - blo) for (lo, hi), (blo, _)
                           in zip(inter, box))
            out[dst_sl] = src[src_sl]
            filled += int(np.prod([hi - lo for lo, hi in inter]))
        if filled != out.size:
            raise ValueError(
                f"leaf {key}: only {filled}/{out.size} elements covered by "
                "saved chunks — checkpoint written by a partial process set?")
        return out

    flat, treedef = jax.tree_util.tree_flatten_with_path(like)
    out_leaves = []
    for path, leaf in flat:
        key = _leaf_key(path)
        if key in leaves_meta:
            m = leaves_meta[key]
            shape, dtype = tuple(m["shape"]), np.dtype(m["dtype"])
            sharding = getattr(leaf, "sharding", None)
            if sharding is None:
                val = jnp.asarray(assemble(key, tuple((0, s)
                                                      for s in shape)), dtype)
            else:
                def cb(index, key=key, shape=shape):
                    box = tuple(
                        (0 if s.start is None else int(s.start),
                         dim if s.stop is None else int(s.stop))
                        for s, dim in zip(index, shape))
                    return assemble(key, box)
                val = jax.make_array_from_callback(shape, sharding, cb)
            if m.get("prng_impl"):
                val = jax.random.wrap_key_data(val, impl=m["prng_impl"])
            out_leaves.append(val)
        elif key in other:
            out_leaves.append(other[key])
        else:
            raise KeyError(f"leaf {key} not present in checkpoint {d}")
    return jax.tree_util.tree_unflatten(treedef, out_leaves)
