"""Checkpoint / resume.

The reference documents checkpointing as a pattern — pickle a dict of
{population, generation, halloffame, logbook, random.getstate()} every FREQ
generations (doc/tutorials/advanced/checkpoint.rst:21-72).  Here it is a
first-class API over arbitrary pytrees: device arrays are pulled to host
numpy, everything else pickles as-is, and the PRNG **key** replaces
``random.getstate()`` for exact resumption.
"""

from __future__ import annotations

import pickle
import threading
from pathlib import Path
from typing import Any

import numpy as np
import jax

__all__ = ["save_checkpoint", "load_checkpoint", "async_save_checkpoint"]


def _to_host(tree):
    return jax.tree_util.tree_map(
        lambda x: np.asarray(x) if isinstance(x, jax.Array) else x, tree)


def save_checkpoint(path, state: Any) -> None:
    """Atomically pickle a state pytree (population, PRNG key, strategy
    state, logbook, ...) to ``path``."""
    path = Path(path)
    tmp = path.with_suffix(path.suffix + ".tmp")
    host_state = _to_host(state)
    with open(tmp, "wb") as f:
        pickle.dump(host_state, f, protocol=pickle.HIGHEST_PROTOCOL)
    tmp.replace(path)


def async_save_checkpoint(path, state: Any) -> threading.Thread:
    """Device→host transfer happens synchronously (cheap), serialization in
    a background thread — the orbax-style async pattern, so the training
    loop never blocks on disk."""
    host_state = _to_host(state)

    def _write():
        path_ = Path(path)
        tmp = path_.with_suffix(path_.suffix + ".tmp")
        with open(tmp, "wb") as f:
            pickle.dump(host_state, f, protocol=pickle.HIGHEST_PROTOCOL)
        tmp.replace(path_)

    t = threading.Thread(target=_write, daemon=True)
    t.start()
    return t


def load_checkpoint(path) -> Any:
    with open(path, "rb") as f:
        return pickle.load(f)
