"""Checkpoint / resume.

The reference documents checkpointing as a pattern — pickle a dict of
{population, generation, halloffame, logbook, random.getstate()} every FREQ
generations (doc/tutorials/advanced/checkpoint.rst:21-72).  Here it is a
first-class API over arbitrary pytrees: device arrays are pulled to host
numpy, everything else pickles as-is, and the PRNG **key** replaces
``random.getstate()`` for exact resumption.

Two tiers:

* :func:`save_checkpoint` / :func:`load_checkpoint` — the reference's
  single-host pattern: the whole pytree gathered to one pickle.  Wrong for
  sharded populations: ``np.asarray`` on a non-fully-addressable array
  fails outright, and on a single-process sharded array it gathers every
  shard to the host.
* :func:`save_sharded_checkpoint` / :func:`load_sharded_checkpoint` — the
  orbax-style per-shard tier: every process writes only the addressable
  shards it owns (replica 0 of each, so nothing is written twice), and
  restore reassembles each *new* addressable shard from whichever saved
  chunks overlap it — the saving and restoring meshes may differ in
  layout, axis names, and process count (shared filesystem assumed,
  as orbax assumes).
"""

from __future__ import annotations

import pickle
import threading
from pathlib import Path
from typing import Any

import numpy as np
import jax
import jax.numpy as jnp

__all__ = ["save_checkpoint", "load_checkpoint", "async_save_checkpoint",
           "save_sharded_checkpoint", "load_sharded_checkpoint"]


def _to_host(tree):
    return jax.tree_util.tree_map(
        lambda x: np.asarray(x) if isinstance(x, jax.Array) else x, tree)


def save_checkpoint(path, state: Any) -> None:
    """Atomically pickle a state pytree (population, PRNG key, strategy
    state, logbook, ...) to ``path``."""
    path = Path(path)
    tmp = path.with_suffix(path.suffix + ".tmp")
    host_state = _to_host(state)
    with open(tmp, "wb") as f:
        pickle.dump(host_state, f, protocol=pickle.HIGHEST_PROTOCOL)
    tmp.replace(path)


def async_save_checkpoint(path, state: Any) -> threading.Thread:
    """Device→host transfer happens synchronously (cheap), serialization in
    a background thread — the orbax-style async pattern, so the training
    loop never blocks on disk."""
    host_state = _to_host(state)

    def _write():
        path_ = Path(path)
        tmp = path_.with_suffix(path_.suffix + ".tmp")
        with open(tmp, "wb") as f:
            pickle.dump(host_state, f, protocol=pickle.HIGHEST_PROTOCOL)
        tmp.replace(path_)

    t = threading.Thread(target=_write, daemon=True)
    t.start()
    return t


def load_checkpoint(path) -> Any:
    with open(path, "rb") as f:
        return pickle.load(f)


# ---------------------------------------------------------------------------
# sharded (per-shard, mesh-agnostic) tier
# ---------------------------------------------------------------------------


def _leaf_key(path) -> str:
    return jax.tree_util.keystr(path)


def _is_prng_key(x) -> bool:
    return isinstance(x, jax.Array) and jax.dtypes.issubdtype(
        x.dtype, jax.dtypes.prng_key)


def save_sharded_checkpoint(dirpath, state: Any) -> None:
    """Write ``state`` under directory ``dirpath``, one ``.npz`` of shard
    chunks plus one manifest fragment per process.

    Each process stores the replica-0 addressable shards of every
    ``jax.Array`` leaf (so a fully-replicated leaf is written exactly once,
    by the process owning its replica 0) tagged with the shard's global
    index box; non-array leaves pickle into process 0's manifest.  The
    write is atomic per process (tmp + rename); a ``COMMIT`` marker by
    process 0 — after a cross-process barrier when distributed — marks the
    checkpoint complete, and :func:`load_sharded_checkpoint` refuses a
    directory without it."""
    d = Path(dirpath)
    d.mkdir(parents=True, exist_ok=True)
    pid = jax.process_index()
    chunks: dict[str, np.ndarray] = {}
    meta: dict[str, Any] = {"leaves": {}, "chunks": []}
    flat, _ = jax.tree_util.tree_flatten_with_path(state)
    other: dict[str, Any] = {}
    for path, leaf in flat:
        key = _leaf_key(path)
        if isinstance(leaf, jax.Array):
            impl = None
            if _is_prng_key(leaf):
                impl = str(jax.random.key_impl(leaf))
                leaf = jax.random.key_data(leaf)
            meta["leaves"][key] = {
                "shape": tuple(leaf.shape), "dtype": str(leaf.dtype),
                "prng_impl": impl,
            }
            for i, shard in enumerate(leaf.addressable_shards):
                if shard.replica_id != 0:
                    continue
                box = tuple(
                    (0 if s.start is None else int(s.start),
                     dim if s.stop is None else int(s.stop))
                    for s, dim in zip(shard.index, leaf.shape))
                ck = f"c{len(chunks)}"
                chunks[ck] = np.asarray(shard.data)
                meta["chunks"].append({"leaf": key, "box": box, "key": ck})
        else:
            other[key] = leaf
    meta["other"] = other

    np_tmp = d / f"shards_p{pid}.npz.tmp"
    with open(np_tmp, "wb") as f:       # handle, not path: savez would
        np.savez(f, **chunks)           # append .npz to the tmp name
    np_tmp.replace(d / f"shards_p{pid}.npz")
    mf_tmp = d / f"manifest_p{pid}.pkl.tmp"
    with open(mf_tmp, "wb") as f:
        pickle.dump(meta, f, protocol=pickle.HIGHEST_PROTOCOL)
    mf_tmp.replace(d / f"manifest_p{pid}.pkl")

    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices("deap_tpu_ckpt_save")
    if pid == 0:
        (d / "COMMIT").write_text(str(jax.process_count()))


def load_sharded_checkpoint(dirpath, like: Any) -> Any:
    """Rebuild a checkpoint written by :func:`save_sharded_checkpoint`.

    ``like`` is a pytree matching the saved structure whose array leaves
    carry the *target* sharding (live arrays or ``ShapeDtypeStruct`` with a
    ``.sharding``); each new addressable shard is assembled from the saved
    chunks overlapping its index box, so restoring onto a different mesh —
    more processes, fewer devices, a different partition axis — is just a
    different overlap pattern.  Non-array leaves come from the manifest.
    Returns the restored pytree; array contents are bit-identical to what
    was saved."""
    d = Path(dirpath)
    if not (d / "COMMIT").exists():
        raise FileNotFoundError(
            f"{d} has no COMMIT marker: incomplete or not a sharded "
            "checkpoint")
    frags = sorted(d.glob("manifest_p*.pkl"))
    leaves_meta: dict[str, Any] = {}
    chunk_index: dict[str, list] = {}
    other: dict[str, Any] = {}
    files: dict[Path, Any] = {}
    for frag in frags:
        with open(frag, "rb") as f:
            meta = pickle.load(f)
        leaves_meta.update(meta["leaves"])
        other.update(meta.get("other", {}))
        npz = d / frag.name.replace("manifest_", "shards_"
                                    ).replace(".pkl", ".npz")
        for c in meta["chunks"]:
            chunk_index.setdefault(c["leaf"], []).append((npz, c))

    def get_file(p):
        if p not in files:
            files[p] = np.load(p)
        return files[p]

    def assemble(key, box):
        """Fill the [start, stop) box of leaf ``key`` from saved chunks."""
        m = leaves_meta[key]
        out = np.empty([hi - lo for lo, hi in box], dtype=m["dtype"])
        filled = 0
        for npz, c in chunk_index.get(key, ()):
            inter = [(max(lo, clo), min(hi, chi))
                     for (lo, hi), (clo, chi) in zip(box, c["box"])]
            if any(lo >= hi for lo, hi in inter):
                continue
            src = get_file(npz)[c["key"]]
            src_sl = tuple(slice(lo - clo, hi - clo) for (lo, hi), (clo, _)
                           in zip(inter, c["box"]))
            dst_sl = tuple(slice(lo - blo, hi - blo) for (lo, hi), (blo, _)
                           in zip(inter, box))
            out[dst_sl] = src[src_sl]
            filled += int(np.prod([hi - lo for lo, hi in inter]))
        if filled != out.size:
            raise ValueError(
                f"leaf {key}: only {filled}/{out.size} elements covered by "
                "saved chunks — checkpoint written by a partial process set?")
        return out

    flat, treedef = jax.tree_util.tree_flatten_with_path(like)
    out_leaves = []
    for path, leaf in flat:
        key = _leaf_key(path)
        if key in leaves_meta:
            m = leaves_meta[key]
            shape, dtype = tuple(m["shape"]), np.dtype(m["dtype"])
            sharding = getattr(leaf, "sharding", None)
            if sharding is None:
                val = jnp.asarray(assemble(key, tuple((0, s)
                                                      for s in shape)), dtype)
            else:
                def cb(index, key=key, shape=shape):
                    box = tuple(
                        (0 if s.start is None else int(s.start),
                         dim if s.stop is None else int(s.stop))
                        for s, dim in zip(index, shape))
                    return assemble(key, box)
                val = jax.make_array_from_callback(shape, sharding, cb)
            if m.get("prng_impl"):
                val = jax.random.wrap_key_data(val, impl=m["prng_impl"])
            out_leaves.append(val)
        elif key in other:
            out_leaves.append(other[key])
        else:
            raise KeyError(f"leaf {key} not present in checkpoint {d}")
    return jax.tree_util.tree_unflatten(treedef, out_leaves)
