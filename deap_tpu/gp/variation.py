"""GP variation operators — array-native equivalents of the reference's
subtree crossover/mutations (gp.py:640-882) and the ``staticLimit`` bloat
decorator (gp.py:885-926).

The reference's ``searchSubtree`` slice finder (gp.py:172-182) becomes pure
index arithmetic: for prefix arrays the subtree rooted at ``i`` ends at the
first ``j >= i`` where ``cumsum(1 - arity)`` exceeds its value before ``i``
by exactly one.  Crossover/mutation are then masked three-segment gathers
(head + donor subtree + tail) over the fixed-capacity buffers; a child that
would overflow capacity leaves its parent unchanged (the array-native
counterpart of rejecting oversized offspring)."""

from __future__ import annotations

import inspect
from functools import partial
from typing import Callable

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .pset import PrimitiveSetTyped, freeze_pset as _frozen

__all__ = ["subtree_bounds", "node_depths", "tree_height",
           "cx_one_point", "cx_one_point_leaf_biased",
           "mut_uniform", "mut_node_replacement", "mut_ephemeral",
           "mut_insert", "mut_shrink", "static_limit",
           "cx_semantic", "mut_semantic"]


# Gather-free indexing.  On the bench TPU backend a vmapped per-row gather
# (take_along_axis / x[idx]) costs ~80x an elementwise op of the same shape
# (measured 2.6 ms vs 0.03 ms at (4096, 64)) and dominated the whole
# variation phase; the one-hot/where contractions below are value-exact
# (exactly one index matches, sums of a single term) and run as plain
# elementwise+reduce kernels.


def _take1(x, i):
    """``x[i]`` for a traced scalar index, without a gather.

    Precondition: ``0 <= i < x.shape[0]``.  Out-of-range indices return 0
    (no term matches the one-hot), NOT the clamped-edge element that plain
    jnp indexing would give — callers must clip or guard first, as every
    call site here does."""
    idx = jnp.arange(x.shape[0])
    shape = (x.shape[0],) + (1,) * (x.ndim - 1)
    return jnp.sum(jnp.where((idx == i).reshape(shape), x, 0), axis=0)


def _tbl(table, idx):
    """``table[idx]`` for a small static table and any-shape traced ``idx``,
    without a gather (one-hot contraction over the table axis).

    Precondition: ``0 <= idx < table.shape[0]`` elementwise; out-of-range
    entries yield 0, not jnp's clamp — clip or guard at the call site."""
    m = table.shape[0]
    oh = idx[..., None] == jnp.arange(m).reshape((1,) * idx.ndim + (m,))
    return jnp.sum(jnp.where(oh, table.reshape((1,) * idx.ndim + (m,)), 0),
                   axis=-1)


def _vgather(x, idx):
    """``x[idx]`` for same-length 1-D ``x`` and traced index vector, without
    a gather: (cap, cap) one-hot contraction.

    Precondition: ``0 <= idx < x.shape[0]`` elementwise; out-of-range
    entries yield 0, not jnp's clamp — clip or guard at the call site."""
    oh = idx[:, None] == jnp.arange(x.shape[0])[None, :]
    return jnp.sum(jnp.where(oh, x[None, :], 0), axis=1)


def _surplus(codes, length, arity):
    """cumsum(1 - arity) over valid tokens; the prefix-structure invariant:
    the subtree from i ends where the surplus relative to i reaches 1."""
    contrib = jnp.where(jnp.arange(codes.shape[0]) < length,
                        1 - _tbl(arity, codes), 0)
    return jnp.cumsum(contrib)


def subtree_bounds(codes, length, i, arity):
    """(start, end) of the subtree rooted at ``i`` (reference searchSubtree,
    gp.py:172-182)."""
    cap = codes.shape[0]
    s = _surplus(codes, length, arity)
    base = jnp.where(i > 0, _take1(s, jnp.maximum(i - 1, 0)), 0)
    k = jnp.arange(cap)
    hit = (k >= i) & (s - base == 1)
    end = jnp.argmax(hit) + 1
    return i, jnp.where(jnp.any(hit), end, length)


def _all_subtree_ends(codes, length, arity):
    """end[j] for every root j — O(cap²) masked argmax (cap is small)."""
    cap = codes.shape[0]
    s = _surplus(codes, length, arity)
    base = jnp.concatenate([jnp.zeros(1, s.dtype), s[:-1]])
    k = jnp.arange(cap)
    hit = (k[None, :] >= k[:, None]) & (s[None, :] - base[:, None] == 1)
    ends = jnp.argmax(hit, axis=1) + 1
    return jnp.where(jnp.any(hit, axis=1), ends, length)


def node_depths(codes, length, arity):
    """depth[i] = #ancestors of node i = #{j < i : end_j > i}."""
    cap = codes.shape[0]
    ends = _all_subtree_ends(codes, length, arity)
    k = jnp.arange(cap)
    anc = (k[:, None] > k[None, :]) & (ends[None, :] > k[:, None])
    return jnp.sum(anc, axis=1)


def tree_height(codes, length, arity):
    """Height of the tree (reference PrimitiveTree.height, gp.py:153-164)."""
    d = node_depths(codes, length, arity)
    return jnp.max(jnp.where(jnp.arange(codes.shape[0]) < length, d, 0))


def _splice(dst, dst_consts, l_dst, i, j, src, src_consts, a, b):
    """Replace dst[i:j] with src[a:b]; returns (codes, consts, new_len,
    fits).  When the result would overflow capacity, returns dst unchanged
    with fits=False."""
    cap = dst.shape[0]
    seg = b - a
    new_len = i + seg + (l_dst - j)
    fits = new_len <= cap
    p = jnp.arange(cap)
    src_idx = jnp.clip(a + (p - i), 0, cap - 1)
    tail_idx = jnp.clip(j + (p - i - seg), 0, cap - 1)
    out = jnp.where(p < i, dst,
                    jnp.where(p < i + seg, _vgather(src, src_idx),
                              _vgather(dst, tail_idx)))
    out_c = jnp.where(p < i, dst_consts,
                      jnp.where(p < i + seg, _vgather(src_consts, src_idx),
                                _vgather(dst_consts, tail_idx)))
    out = jnp.where(p < new_len, out, 0)
    out_c = jnp.where(p < new_len, out_c, 0.0)
    return (jnp.where(fits, out, dst),
            jnp.where(fits, out_c, dst_consts),
            jnp.where(fits, new_len, l_dst),
            fits)


def _expr_takes_type(expr: Callable) -> bool:
    """Whether ``expr`` accepts a second (return-type) argument.  Inspected
    via the signature rather than a trial call, so TypeErrors raised *inside*
    a two-argument expr (including tracer ConcretizationTypeError) propagate
    instead of silently downgrading to the untyped call."""
    try:
        sig = inspect.signature(expr)
    except (TypeError, ValueError):
        return True
    n = 0
    for p in sig.parameters.values():
        if p.kind in (p.VAR_POSITIONAL,):
            return True
        if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD):
            n += 1
    return n >= 2


def _masked_choice(key, mask, fallback=0):
    """Uniform index among True entries of mask (fallback if none)."""
    u = jax.random.uniform(key, mask.shape)
    any_ = jnp.any(mask)
    return jnp.where(any_, jnp.argmax(jnp.where(mask, u, -1.0)), fallback)


def _make_cx(pset, leaf_bias: float | None):
    f = _frozen(pset)
    arity = jnp.asarray(f.arity)
    rtype = jnp.asarray(f.ret_type)

    def cx(key, t1, t2, termpb=0.1):
        c1, k1cst, l1 = t1
        c2, k2cst, l2 = t2
        cap = c1.shape[0]
        k_i1, k_i2, k_b1, k_b2 = jax.random.split(key, 4)
        p = jnp.arange(cap)

        # type availability in the partner (reference builds the
        # types1/types2 dicts and intersects, gp.py:653-670)
        rt1 = _tbl(rtype, c1)
        rt2 = _tbl(rtype, c2)
        # exclude roots when trees have >1 node (reference gp.py:648-651)
        valid1 = (p < l1) & ((p >= 1) | (l1 <= 1))
        valid2 = (p < l2) & ((p >= 1) | (l2 <= 1))
        # present2[t] = any valid node of type t in the partner; queried at
        # rt1 — fused into one (cap, cap) type-equality reduction so neither
        # a scatter-max nor a gather is needed
        elig1 = valid1 & jnp.any((rt1[:, None] == rt2[None, :])
                                 & valid2[None, :], axis=1)
        if leaf_bias is not None:
            k_i1, k_lb = jax.random.split(k_i1)
            pick_term = jax.random.bernoulli(k_lb, termpb)
            is_term1 = _tbl(arity, c1) == 0
            bias1 = elig1 & (is_term1 == pick_term)
            elig1 = jnp.where(jnp.any(bias1), bias1, elig1)
        i1 = _masked_choice(k_b1, elig1)
        want_t = _take1(rt1, i1)
        elig2 = valid2 & (rt2 == want_t)
        if leaf_bias is not None:
            k_i2, k_lb2 = jax.random.split(k_i2)
            pick_term2 = jax.random.bernoulli(k_lb2, termpb)
            is_term2 = _tbl(arity, c2) == 0
            bias2 = elig2 & (is_term2 == pick_term2)
            elig2 = jnp.where(jnp.any(bias2), bias2, elig2)
        i2 = _masked_choice(k_b2, elig2)
        ok = jnp.any(elig1) & jnp.any(elig2)

        s1, e1 = subtree_bounds(c1, l1, i1, arity)
        s2, e2 = subtree_bounds(c2, l2, i2, arity)
        n1, n1c, nl1, fit1 = _splice(c1, k1cst, l1, s1, e1, c2, k2cst, s2, e2)
        n2, n2c, nl2, fit2 = _splice(c2, k2cst, l2, s2, e2, c1, k1cst, s1, e1)
        keep = ok & fit1 & fit2

        def sel(new, old):
            return jnp.where(keep, new, old)
        return ((sel(n1, c1), sel(n1c, k1cst), sel(nl1, l1)),
                (sel(n2, c2), sel(n2c, k2cst), sel(nl2, l2)))

    return cx


def cx_one_point(key, tree1, tree2, pset):
    """Typed one-point subtree crossover (reference gp.cxOnePoint,
    gp.py:640-677)."""
    return _make_cx(pset, None)(key, tree1, tree2)


def cx_one_point_leaf_biased(key, tree1, tree2, pset, termpb=0.1):
    """Koza 90/10 leaf-biased crossover (reference cxOnePointLeafBiased,
    gp.py:680-732): each tree independently picks a terminal point with
    probability ``termpb``, an internal point otherwise (the reference also
    draws one coin per tree)."""
    return _make_cx(pset, termpb)(key, tree1, tree2, termpb)


def mut_uniform(key, tree, expr: Callable, pset):
    """Replace a random subtree with a generated one of the *same return
    type* (reference mutUniform, gp.py:738-752, which passes
    ``type_=individual[index].ret``).  ``expr(key, ret_type) ->
    (codes, consts, length)`` — e.g. a
    :func:`deap_tpu.gp.generate.make_generator` closure, whose generators
    accept the traced type id; a single-type expr may ignore the second
    argument."""
    f = _frozen(pset)
    arity = jnp.asarray(f.arity)
    rtype = jnp.asarray(f.ret_type)
    codes, consts, length = tree
    k_i, k_gen = jax.random.split(key)
    i = jax.random.randint(k_i, (), 0, jnp.maximum(length, 1))
    s, e = subtree_bounds(codes, length, i, arity)
    if _expr_takes_type(expr):
        g_codes, g_consts, g_len = expr(k_gen, _tbl(rtype, _take1(codes, i)))
    else:
        g_codes, g_consts, g_len = expr(k_gen)
    n, nc, nl, fits = _splice(codes, consts, length, s, e,
                              g_codes, g_consts, 0, g_len)
    return n, nc, nl


def mut_node_replacement(key, tree, pset):
    """Replace a random node with another of identical signature (reference
    mutNodeReplacement, gp.py:755-778): primitives swap with same-arity,
    same-type primitives; terminals with same-type terminals."""
    f = _frozen(pset)
    arity = jnp.asarray(f.arity)
    rtype = jnp.asarray(f.ret_type)
    in_types = jnp.asarray(f.in_types)
    is_eph = jnp.asarray(f.is_ephemeral)
    codes, consts, length = tree
    k_i, k_pick, k_const = jax.random.split(key, 3)
    i = jax.random.randint(k_i, (), 0, jnp.maximum(length, 1))
    c = codes[i]
    same_sig = ((rtype == rtype[c]) & (arity == arity[c])
                & jnp.all(in_types == in_types[c], axis=1))
    new_c = _masked_choice(k_pick, same_sig, fallback=c)
    # new ephemerals need a fresh constant; plain terminals their value
    const = lax.switch(new_c, f.const_fns, k_const)
    codes = codes.at[i].set(new_c.astype(codes.dtype))
    consts = consts.at[i].set(jnp.where(is_eph[new_c] | (arity[new_c] == 0),
                                        const, consts[i]))
    return codes, consts, length


def mut_ephemeral(key, tree, pset, mode: str = "one"):
    """Re-draw ephemeral constants (reference mutEphemeral, gp.py:781-806):
    mode "one" re-samples a single random ephemeral node, "all" every one."""
    f = _frozen(pset)
    is_eph = jnp.asarray(f.is_ephemeral)
    codes, consts, length = tree
    cap = codes.shape[0]
    k_pick, k_new = jax.random.split(key)
    mask = is_eph[codes] & (jnp.arange(cap) < length)
    if mode == "one":
        i = _masked_choice(k_pick, mask)
        sel = (jnp.arange(cap) == i) & jnp.any(mask)
    else:
        sel = mask
    fns = f.const_fns
    keys = jax.random.split(k_new, cap)
    new_consts = jax.vmap(lambda c, k: lax.switch(c, fns, k))(codes, keys)
    return codes, jnp.where(sel, new_consts, consts), length


def mut_insert(key, tree, pset):
    """Insert a primitive above a random subtree (reference mutInsert,
    gp.py:809-846): the old subtree becomes one argument; the other
    arguments are filled with new terminals."""
    f = _frozen(pset)
    arity_np = f.arity
    cap = tree[0].shape[0]
    arity = jnp.asarray(arity_np)
    rtype = jnp.asarray(f.ret_type)
    in_types = jnp.asarray(f.in_types)
    term_arr, term_cnt = (jnp.asarray(f.term_by_type[0]),
                          jnp.asarray(f.term_by_type[1]))
    max_arity = max(f.max_arity, 1)
    codes, consts, length = tree
    k_i, k_p, k_slot, k_terms, k_consts = jax.random.split(key, 5)
    i = jax.random.randint(k_i, (), 0, jnp.maximum(length, 1))
    t = rtype[codes[i]]
    # primitives returning t that accept t somewhere
    accepts = jnp.any((in_types == t[None]) &
                      (jnp.arange(max_arity)[None, :] < arity[:, None]), axis=1)
    # only primitives whose every argument type has terminals available —
    # the padded candidate table would otherwise yield code 0 for an empty
    # bucket and corrupt the prefix structure
    fillable = jnp.asarray(f.args_have_terminals)
    cand = (rtype == t) & (arity > 0) & accepts & fillable
    p_code = _masked_choice(k_p, cand)
    ok = jnp.any(cand)
    a = arity[p_code]
    # choose which slot receives the old subtree, among type-matching slots
    slot_ok = (in_types[p_code] == t) & (jnp.arange(max_arity) < a)
    slot = _masked_choice(k_slot, slot_ok)

    s, e = subtree_bounds(codes, length, i, arity)
    sub_len = e - s
    # build the insertion segment: primitive, terminals, subtree at `slot`
    seg_len = 1 + (a - 1) + sub_len
    p_arange = jnp.arange(cap)
    # terminal fill codes for each slot
    tk = jax.random.split(k_terms, max_arity)
    fill = jnp.stack([
        term_arr[in_types[p_code, j],
                 jax.random.randint(tk[j], (), 0,
                                    jnp.maximum(term_cnt[in_types[p_code, j]], 1))]
        for j in range(max_arity)])
    fns = f.const_fns
    ck = jax.random.split(k_consts, max_arity)
    fill_consts = jnp.stack([lax.switch(fill[j], fns, ck[j])
                             for j in range(max_arity)])

    # segment layout: position 0 = primitive; then for each slot j<a either
    # the subtree (at j == slot, occupying sub_len tokens) or one terminal
    # offset of slot j in the segment:
    j_idx = jnp.arange(max_arity)
    # width of each slot: 1 except `slot` which is sub_len
    widths = jnp.where(j_idx == slot, sub_len, 1) * (j_idx < a)
    offsets = 1 + jnp.concatenate([jnp.zeros(1, jnp.int32),
                                   jnp.cumsum(widths)[:-1]])
    seg = jnp.zeros(cap, codes.dtype).at[0].set(p_code)
    seg_c = jnp.zeros(cap, consts.dtype)
    # place terminals
    for j in range(max_arity):
        real = (j < a) & (j != slot)
        seg = seg.at[jnp.where(real, offsets[j], cap - 1)].set(
            jnp.where(real, fill[j], seg[cap - 1]))
        seg_c = seg_c.at[jnp.where(real, offsets[j], cap - 1)].set(
            jnp.where(real, fill_consts[j], seg_c[cap - 1]))
    # place the subtree
    sub_src = jnp.clip(s + (p_arange - offsets[slot]), 0, cap - 1)
    in_sub = (p_arange >= offsets[slot]) & (p_arange < offsets[slot] + sub_len)
    seg = jnp.where(in_sub, codes[sub_src], seg)
    seg_c = jnp.where(in_sub, consts[sub_src], seg_c)

    n, nc, nl, fits = _splice(codes, consts, length, s, e, seg, seg_c,
                              0, seg_len)
    keep = ok & fits
    return (jnp.where(keep, n, codes), jnp.where(keep, nc, consts),
            jnp.where(keep, nl, length))


def mut_shrink(key, tree, pset):
    """Replace a random primitive by one of its (type-matching) argument
    subtrees (reference mutShrink, gp.py:849-882)."""
    f = _frozen(pset)
    arity = jnp.asarray(f.arity)
    rtype = jnp.asarray(f.ret_type)
    codes, consts, length = tree
    cap = codes.shape[0]
    k_i, k_arg = jax.random.split(key)
    p = jnp.arange(cap)
    is_prim = (arity[codes] > 0) & (p < length)
    i = _masked_choice(k_i, is_prim)
    ok = jnp.any(is_prim)
    s, e = subtree_bounds(codes, length, i, arity)
    # children roots: walk via subtree ends
    ends = _all_subtree_ends(codes, length, arity)
    # child starts: first child at i+1, next at end of previous
    max_a = max(f.max_arity, 1)
    child_starts = [i + 1]
    for _ in range(max_a - 1):
        child_starts.append(ends[jnp.clip(child_starts[-1], 0, cap - 1)])
    child_starts = jnp.stack(child_starts)
    a = arity[codes[i]]
    match = (jnp.arange(max_a) < a) & (
        rtype[codes[jnp.clip(child_starts, 0, cap - 1)]] == rtype[codes[i]])
    which = _masked_choice(k_arg, match)
    ok = ok & jnp.any(match)
    cs = child_starts[which]
    ce = ends[jnp.clip(cs, 0, cap - 1)]
    n, nc, nl, fits = _splice(codes, consts, length, s, e,
                              codes, consts, cs, ce)
    keep = ok & fits
    return (jnp.where(keep, n, codes), jnp.where(keep, nc, consts),
            jnp.where(keep, nl, length))


def _append(codes, consts, length, src, src_consts, a, b):
    """Append ``src[a:b]`` at the end of the tree buffer (``_splice`` with an
    empty target window at ``length``)."""
    return _splice(codes, consts, length, length, length, src, src_consts,
                   a, b)


def _scalar_code(f):
    """A terminal/ephemeral code whose interpreter op reads the per-node
    constant — used to inject literal scalars (the semantic operators'
    mutation step and the constant 1.0).  Arguments read from X, so they
    don't qualify.  A plain Terminal is preferred over an Ephemeral: a later
    ``mut_ephemeral`` resamples nodes carrying ephemeral codes, which would
    silently rewrite the injected literal and break the semantic operators'
    convex-combination property (the reference embeds a Terminal that
    mutEphemeral never touches, gp.py:1210-1324)."""
    fallback = None
    for i in range(f.n_nodes):
        if not f.is_primitive[i] and not f.is_argument[i]:
            if not f.is_ephemeral[i]:
                return i
            if fallback is None:
                fallback = i
    if fallback is not None:
        return fallback
    raise AssertionError(
        "Semantic operators need at least one constant terminal or "
        "ephemeral in the primitive set to encode literal scalars.")


def _semantic_codes(f):
    """Codes of the lf/mul/add/sub primitives the GSGP operators compose
    with (the reference asserts the same four names, gp.py:1239-1240)."""
    codes = {}
    for name in ("lf", "mul", "add", "sub"):
        assert name in f.pset.mapping, (
            f"A '{name}' function is required in order to perform semantic "
            "variation")
        codes[name] = f.code_of(name)
    return codes


def mut_semantic(key, tree, pset, expr: Callable | None = None,
                 ms=None, min_=2, max_=6):
    """Geometric semantic mutation (Moraglio 2012; reference mutSemantic,
    gp.py:1210-1263): ``child = ind + ms * (lf(tr1) - lf(tr2))`` built
    *structurally* — prefix layout ``[add] ind [mul ms sub lf] tr1 [lf]
    tr2``.  ``expr(key) -> tree`` generates the random trees (defaults to a
    grow-method generator); ``ms`` is the mutation step (defaults to
    U(0, 2), matching the reference).  A child that would overflow the
    fixed capacity leaves the parent unchanged."""
    from .generate import make_generator
    f = _frozen(pset)
    codes, consts, length = tree
    cap = codes.shape[0]
    sem = _semantic_codes(f)
    ms_code = _scalar_code(f)
    if expr is None:
        expr = lambda k: make_generator(pset, cap, "grow")(k, min_, max_)
    k_t1, k_t2, k_ms = jax.random.split(key, 3)
    t1c, t1k, t1l = expr(k_t1)
    t2c, t2k, t2l = expr(k_t2)
    if ms is None:
        ms = jax.random.uniform(k_ms, (), minval=0.0, maxval=2.0)
    ms = jnp.asarray(ms, consts.dtype)

    glue = jnp.array([sem["mul"], ms_code, sem["sub"], sem["lf"]],
                     codes.dtype)
    glue_c = jnp.array([0.0, 1.0, 0.0, 0.0], consts.dtype).at[1].set(ms)
    head = jnp.array([sem["add"]], codes.dtype)
    zero1 = jnp.zeros(1, consts.dtype)
    lf1 = jnp.array([sem["lf"]], codes.dtype)

    out = (jnp.zeros_like(codes), jnp.zeros_like(consts),
           jnp.asarray(0, length.dtype), jnp.asarray(True))

    def push(state, src, src_c, a, b):
        c, k, l, ok = state
        c, k, l, fit = _append(c, k, l, src, src_c, a, b)
        return c, k, l, ok & fit

    out = push(out, head, zero1, 0, 1)
    out = push(out, codes, consts, 0, length)
    out = push(out, glue, glue_c, 0, 4)
    out = push(out, t1c, t1k, 0, t1l)
    out = push(out, lf1, zero1, 0, 1)
    out = push(out, t2c, t2k, 0, t2l)
    nc, nk, nl, ok = out
    return (jnp.where(ok, nc, codes), jnp.where(ok, nk, consts),
            jnp.where(ok, nl, length))


def cx_semantic(key, tree1, tree2, pset, expr: Callable | None = None,
                min_=2, max_=6):
    """Geometric semantic crossover (Moraglio 2012; reference cxSemantic,
    gp.py:1266-1324): ``child1 = lf(tr)*ind1 + (1-lf(tr))*ind2`` and the
    symmetric child2, built structurally with prefix layout ``[add mul lf]
    tr ind1 [mul sub 1.0 lf] tr ind2``.  Children that would overflow the
    capacity fall back to their parent (the array-native bloat bound)."""
    from .generate import make_generator
    f = _frozen(pset)
    c1, k1, l1 = tree1
    c2, k2, l2 = tree2
    cap = c1.shape[0]
    sem = _semantic_codes(f)
    one_code = _scalar_code(f)
    if expr is None:
        expr = lambda k: make_generator(pset, cap, "grow")(k, min_, max_)
    trc, trk, trl = expr(key)

    head = jnp.array([sem["add"], sem["mul"], sem["lf"]], c1.dtype)
    zero3 = jnp.zeros(3, k1.dtype)
    mid = jnp.array([sem["mul"], sem["sub"], one_code, sem["lf"]], c1.dtype)
    mid_c = jnp.array([0.0, 0.0, 1.0, 0.0], k1.dtype)

    def build(pa, pa_c, pl, pb, pb_c, plb):
        out = (jnp.zeros_like(pa), jnp.zeros_like(pa_c),
               jnp.asarray(0, pl.dtype), jnp.asarray(True))

        def push(state, src, src_c, a, b):
            c, k, l, ok = state
            c, k, l, fit = _append(c, k, l, src, src_c, a, b)
            return c, k, l, ok & fit

        out = push(out, head, zero3, 0, 3)
        out = push(out, trc, trk, 0, trl)
        out = push(out, pa, pa_c, 0, pl)
        out = push(out, mid, mid_c, 0, 4)
        out = push(out, trc, trk, 0, trl)
        out = push(out, pb, pb_c, 0, plb)
        nc, nk, nl, ok = out
        return (jnp.where(ok, nc, pa), jnp.where(ok, nk, pa_c),
                jnp.where(ok, nl, pl))

    return build(c1, k1, l1, c2, k2, l2), build(c2, k2, l2, c1, k1, l1)


def static_limit(key_fn: Callable, max_value: int, pset):
    """Bloat-control decorator (reference staticLimit, gp.py:885-926): if an
    offspring exceeds ``max_value`` under ``key_fn`` (height or length), one
    of its parents replaces it.

    Wraps tree operators of signature ``op(key, tree, ...)-> tree`` or
    ``op(key, t1, t2, ...) -> (t1', t2')``."""
    f_check = key_fn

    def decorator(op):
        def wrapper(key, *trees_and_args):
            trees = [t for t in trees_and_args if isinstance(t, tuple)
                     and len(t) == 3]
            out = op(key, *trees_and_args)
            if isinstance(out, tuple) and isinstance(out[0], tuple):
                new_trees = list(out)
            else:
                new_trees = [out]
            result = []
            for parent, child in zip(trees, new_trees):
                over = f_check(child) > max_value
                result.append(tuple(
                    jnp.where(over, pa, ch)
                    for pa, ch in zip(parent, child)))
            return tuple(result) if len(result) > 1 else result[0]
        return wrapper
    return decorator
