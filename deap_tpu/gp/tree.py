"""Host-side GP tree utilities: string round-trip and graph export —
equivalents of the reference's ``PrimitiveTree.__str__`` (gp.py:88-102),
``from_string`` (gp.py:104-151) and ``graph`` (gp.py:1133-1203).

Device code never needs these; they serve logging, debugging, tests and
visualization of ``(codes, consts, length)`` prefix arrays."""

from __future__ import annotations

import re
from typing import Sequence

import numpy as np

from .pset import (FrozenPSet, Primitive, Terminal, Ephemeral, Argument,
                   PrimitiveSetTyped, freeze_pset as _f)

__all__ = ["to_string", "from_string", "graph"]


def to_string(tree, pset) -> str:
    """Prefix array -> readable expression (reference __str__,
    gp.py:88-102, same stack algorithm)."""
    f = _f(pset)
    codes, consts, length = tree
    codes = np.asarray(codes)
    consts = np.asarray(consts)
    length = int(length)
    string = ""
    stack = []
    for i in range(length):
        c = int(codes[i])
        node = f.pset.nodes[c]
        stack.append((c, i, []))
        while len(stack[-1][2]) == int(f.arity[stack[-1][0]]):
            c2, pos, args = stack.pop()
            n2 = f.pset.nodes[c2]
            if isinstance(n2, Primitive):
                string = n2.format(*args)
            elif isinstance(n2, Ephemeral):
                string = repr(float(consts[pos]))
            elif isinstance(n2, Terminal):
                string = n2.format()
            else:
                string = n2.name
            if len(stack) == 0:
                break
            stack[-1][2].append(string)
    return string


def from_string(string: str, pset, cap: int = 64):
    """Expression string -> prefix arrays (reference from_string,
    gp.py:104-151).  Accepts primitive/terminal/argument names and numeric
    literals (which become per-node constants on the first ephemeral code,
    or anonymous constants when the set has none)."""
    f = _f(pset)
    tokens = re.split(r"[ \t\n\r\f\v(),]", string)
    codes, consts = [], []
    name_to_code = {n: i for i, n in enumerate(f.names)}
    eph_codes = [i for i in range(f.n_nodes) if f.is_ephemeral[i]]
    for tok in tokens:
        if tok == "":
            continue
        if tok in name_to_code:
            c = name_to_code[tok]
            codes.append(c)
            consts.append(float(f.const_value[c]))
        else:
            try:
                val = float(tok)
            except ValueError:
                raise TypeError(
                    f"Unable to find symbol {tok!r} in {f.pset.name}.")
            if not eph_codes:
                raise TypeError(
                    f"Numeric literal {tok} requires an ephemeral constant "
                    "in the primitive set.")
            codes.append(eph_codes[0])
            consts.append(val)
    length = len(codes)
    if length > cap:
        raise ValueError(f"expression has {length} nodes > capacity {cap}")
    codes_arr = np.zeros(cap, np.int32)
    consts_arr = np.zeros(cap, np.float32)
    codes_arr[:length] = codes
    consts_arr[:length] = consts
    return codes_arr, consts_arr, np.int32(length)


def graph(tree, pset):
    """(nodes, edges, labels) for NetworkX/pygraphviz rendering (reference
    graph, gp.py:1133-1203)."""
    f = _f(pset)
    codes, consts, length = tree
    codes = np.asarray(codes)
    consts = np.asarray(consts)
    length = int(length)
    nodes = list(range(length))
    edges = []
    labels = {}
    stack = []
    for i in range(length):
        c = int(codes[i])
        node = f.pset.nodes[c]
        if stack:
            edges.append((stack[-1][0], i))
            stack[-1][1] -= 1
        if isinstance(node, Ephemeral):
            labels[i] = round(float(consts[i]), 4)
        else:
            labels[i] = node.name
        a = int(f.arity[c])
        if a > 0:
            stack.append([i, a])
        else:
            while stack and stack[-1][1] == 0:
                stack.pop()
    return nodes, edges, labels
