"""Automatically Defined Functions — array-native equivalent of the
reference's ADF support (``PrimitiveSetTyped.addADF`` gp.py:412-427,
``compileADF`` gp.py:488-511, example examples/gp/adf_symbreg.py).

Reference model: an individual is a *list* of trees — the main tree plus one
tree per ADF — and ``compileADF`` compiles them innermost-first, injecting
each compiled ADF into the enclosing pset's eval context.

Array-native model: an individual is a tuple of ``(codes, consts, length)``
triples aligned with the pset list (main first, matching compileADF's
ordering contract).  :func:`make_adf_evaluator` builds a *nested* stack
machine: when the interpreter for pset ``i`` hits an ADF call node, the
``lax.switch`` branch runs the interpreter for the callee pset with the
popped argument rows as its input matrix.  ADF references are acyclic by
construction (a set can only reference previously constructed sets), so the
nesting is finite and the whole multi-tree program still compiles to one XLA
computation.
"""

from __future__ import annotations

from typing import Callable, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .pset import Primitive, Argument, PrimitiveSetTyped, freeze_pset
from .interp import run_stack_machine

__all__ = ["make_adf_evaluator", "make_adf_population_evaluator",
           "compile_adf"]


def make_adf_evaluator(psets: Sequence[PrimitiveSetTyped], cap: int) -> Callable:
    """Build ``evaluate(trees, X) -> (n_points,)`` for ADF programs.

    ``psets``: the main set first, then the ADF sets it (transitively)
    references — the same ordering ``compileADF`` expects (gp.py:490-492).
    ``trees``: a matching sequence of ``(codes, consts, length)`` triples.
    ``X``: ``(n_main_args, n_points)``.
    """
    fs = [freeze_pset(p) for p in psets]
    name_to_idx = {f.pset.name: j for j, f in enumerate(fs)}
    cache: dict = {}
    building: list = []

    def build(i: int) -> Callable:
        if i in cache:
            return cache[i]
        if i in building:
            chain = " -> ".join(fs[j].pset.name for j in building + [i])
            raise ValueError(
                f"cyclic ADF reference: {chain}; ADF references must be "
                "acyclic")
        building.append(i)
        f = fs[i]
        arity_arr = jnp.asarray(f.arity)
        max_arity = max(f.max_arity, 1)
        nodes = f.pset.nodes

        # resolve sub-evaluators eagerly so cycles surface as the ValueError
        # above (lazy resolution inside the branch closures would recurse at
        # trace time instead)
        subs = {}
        for node in nodes:
            if isinstance(node, Primitive) and node.name in name_to_idx:
                j = name_to_idx[node.name]
                subs[node.name] = (build(j), len(fs[j].pset.ins))

        def evaluate_i(trees, X):
            codes, consts, length = trees[i]

            def make_branch(node):
                if isinstance(node, Primitive) and node.name in subs:
                    sub, n_args = subs[node.name]
                    return lambda args, const: sub(trees, args[:n_args])
                if isinstance(node, Primitive):
                    k, fn = node.arity, node.func
                    return lambda args, const: fn(*(args[t] for t in range(k)))
                if isinstance(node, Argument):
                    idx = node.index
                    return lambda args, const: X[idx]
                return lambda args, const: jnp.broadcast_to(const,
                                                            X.shape[1:])
            branches = tuple(make_branch(n) for n in nodes)
            return run_stack_machine(codes, consts, length, X, branches,
                                     arity_arr, max_arity, cap)

        building.pop()
        cache[i] = evaluate_i
        return evaluate_i

    return build(0)


def make_adf_population_evaluator(psets, cap: int) -> Callable:
    """``evaluate_pop(stacked_trees, X) -> (pop, n_points)`` — vmap of
    :func:`make_adf_evaluator` over individuals whose tree triples carry a
    leading population axis."""
    ev = make_adf_evaluator(psets, cap)
    return jax.vmap(ev, in_axes=(0, None))


def compile_adf(trees, psets, cap: int | None = None) -> Callable:
    """Host-facing parity with reference ``compileADF`` (gp.py:488-511):
    returns a Python callable over the main pset's arguments."""
    cap = cap or np.asarray(trees[0][0]).shape[-1]
    ev = jax.jit(make_adf_evaluator(psets, cap))
    trees = tuple((jnp.asarray(c), jnp.asarray(k), jnp.asarray(l))
                  for c, k, l in trees)

    def func(*args):
        if args:
            ndims = {np.ndim(a) for a in args}
            if len(ndims) > 1:
                raise TypeError(
                    "compile_adf arguments must be all scalars or all "
                    f"equal-length 1-D arrays, got ndims {sorted(ndims)}")
            scalar = ndims == {0}
            cols = [jnp.atleast_1d(jnp.asarray(a, jnp.float32))
                    for a in args]
            lengths = {c.shape[0] for c in cols}
            if len(lengths) > 1:
                raise TypeError(
                    "compile_adf array arguments must share one length, "
                    f"got lengths {sorted(lengths)}")
            X = jnp.stack(cols)
        else:
            scalar = False
            X = jnp.zeros((1, 1), jnp.float32)
        out = ev(trees, X)
        return float(out[0]) if scalar else out

    return func
