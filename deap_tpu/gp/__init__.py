"""Genetic Programming engine — TPU-native equivalent of ``deap/gp.py``.

The reference represents programs as Python object trees compiled through
``eval`` (gp.py:460-485).  Here a program is a fixed-capacity prefix token
array ``(codes, consts, length)`` evaluated by a vmapped stack machine
(:mod:`.interp`), generated (:mod:`.generate`) and varied (:mod:`.variation`)
by jitted index arithmetic — the whole GP generation loop compiles to one
XLA program.

Standard protected primitives (the ones every reference example registers,
e.g. examples/gp/symbreg.py) are provided in :data:`safe_ops`.
"""

import jax
import jax.numpy as jnp

from .pset import (Primitive, Terminal, Ephemeral, Argument,
                   PrimitiveSetTyped, PrimitiveSet, FrozenPSet)  # noqa: F401
from .interp import (make_evaluator, make_population_evaluator,
                     compile_tree)  # noqa: F401
from .generate import (make_generator, gen_full, gen_grow,
                       gen_half_and_half)  # noqa: F401
from .variation import (cx_one_point, cx_one_point_leaf_biased, mut_uniform,
                        mut_node_replacement, mut_ephemeral, mut_insert,
                        mut_shrink, static_limit, subtree_bounds,
                        node_depths, tree_height, cx_semantic,
                        mut_semantic)  # noqa: F401
from .tree import to_string, from_string, graph  # noqa: F401
from .harm import harm  # noqa: F401
from .adf import (make_adf_evaluator, make_adf_population_evaluator,
                  compile_adf)  # noqa: F401
from .routine import make_routine_interpreter  # noqa: F401
compileADF = compile_adf

# camelCase aliases (reference API names)
compile = compile_tree
genFull = gen_full
genGrow = gen_grow
genHalfAndHalf = gen_half_and_half
cxOnePoint = cx_one_point
cxOnePointLeafBiased = cx_one_point_leaf_biased
mutUniform = mut_uniform
mutNodeReplacement = mut_node_replacement
mutEphemeral = mut_ephemeral
mutInsert = mut_insert
mutShrink = mut_shrink
staticLimit = static_limit
cxSemantic = cx_semantic
mutSemantic = mut_semantic


def protected_div(left, right):
    """Protected division -> 1 on |denominator| ~ 0 (the convention of the
    reference's symbreg examples)."""
    return jnp.where(jnp.abs(right) > 1e-9, left / jnp.where(
        jnp.abs(right) > 1e-9, right, 1.0), 1.0)


def protected_log(x):
    return jnp.log(jnp.maximum(jnp.abs(x), 1e-9))


def protected_sqrt(x):
    return jnp.sqrt(jnp.abs(x))


def logistic(x):
    """The ``lf`` wrapper the semantic operators require (reference
    gp.py:1227: ``1 / (1 + exp(-x))``)."""
    return jax.nn.sigmoid(x)


def _b(x):
    return x != 0


def b_and(a, b):
    return (_b(a) & _b(b)).astype(a.dtype)


def b_or(a, b):
    return (_b(a) | _b(b)).astype(a.dtype)


def b_xor(a, b):
    return (_b(a) ^ _b(b)).astype(a.dtype)


def b_not(a):
    return (~_b(a)).astype(a.dtype)


def b_if_then_else(c, a, b):
    return jnp.where(_b(c), a, b)


#: Boolean primitives encoded on the float stack (0.0 = false) — the
#: interpreter requires every op to return the stack dtype, so logical ops
#: cast back (used by the multiplexer/parity examples, reference
#: examples/gp/multiplexer.py, parity.py).
bool_ops = {
    "and_": (b_and, 2),
    "or_": (b_or, 2),
    "xor_": (b_xor, 2),
    "not_": (b_not, 1),
    "if_then_else": (b_if_then_else, 3),
}

safe_ops = {
    "add": (jnp.add, 2),
    "sub": (jnp.subtract, 2),
    "mul": (jnp.multiply, 2),
    "div": (protected_div, 2),
    "neg": (jnp.negative, 1),
    "cos": (jnp.cos, 1),
    "sin": (jnp.sin, 1),
    "log": (protected_log, 1),
    "sqrt": (protected_sqrt, 1),
    "lf": (logistic, 1),
}
