"""Pallas TPU kernel for GP tree evaluation — the hot-op fast path behind
:func:`deap_tpu.gp.interp.make_population_evaluator`.

Why a kernel: the XLA interpreter vmaps a stack machine over the
population, and under ``vmap`` every ``lax.switch`` computes **every**
primitive for **every** tree and selects per lane — cost factor =
#primitives — while the ``(pop, cap+1, n_points)`` stack lives in HBM, so
each of the ``cap`` scan steps pays full-population gather/scatter
bandwidth.  But a tree's opcode at a given step is *uniform across its
points*: inside a Pallas kernel the dispatch is **scalar** control flow
(``lax.switch`` on an SMEM value — only the one live branch executes), the
stack is a VMEM scratch buffer that never touches HBM, and the token loop
runs ``length`` steps instead of ``cap``.  Per tree the work drops from
``cap × n_prims`` full-width lane-selected ops with HBM round-trips to
``length`` single VPU ops on resident data.

The contract matches :func:`deap_tpu.gp.interp.run_stack_machine` exactly
(same prefix encoding, same result), pinned by
``tests/test_gp_pallas.py``; CPU CI runs the kernel in interpreter mode.

Trees MUST be *valid* prefix programs — this is the kernel's input
contract, and everything the generators and variation operators produce
satisfies it.  A valid program keeps the stack pointer in (0, #terminals]
throughout the right-to-left walk, so the ``cap + 1``-row scratch bounds
every access.  A *malformed* program (e.g. a primitive token with too few
operands below it) would drive ``sp`` negative and index out of bounds —
unchecked in compiled Mosaic — so callers feeding hand-built token arrays
must validate them first (the XLA interpreter clamps instead and is the
safer path for untrusted trees).

Reference parity: replaces ``gp.compile`` + per-point Python arithmetic
(/root/reference/deap/gp.py:460-485), the reference's hottest path
(SURVEY §3.4).
"""

from __future__ import annotations

import functools
from typing import Callable

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pset import Argument, Ephemeral, Primitive, Terminal, freeze_pset

__all__ = ["make_population_evaluator_pallas"]

_LANE = 128


def _round_up(n: int, m: int) -> int:
    return (n + m - 1) // m * m


def make_population_evaluator_pallas(pset, cap: int, *,
                                     block_trees: int = 8,
                                     interpret: bool | None = None
                                     ) -> Callable:
    """Build ``evaluate_pop(codes (pop, cap), consts (pop, cap), lengths
    (pop,), X (n_args, n_points)) -> (pop, n_points)`` running the prefix
    stack machine as one Pallas kernel.

    ``block_trees`` trees are handled per grid step (rounded up to a
    multiple of 8 for Mosaic's SMEM sublane tiling).  Measured at
    pop=4096/cap=64/1024 pts: 32 is ~4× faster than 8 for *standalone*
    back-to-back evaluation (0.04 vs 0.18 ms/eval), while the full
    scanned symbreg bench is statistically indistinguishable between the
    two (run variance from bloat dynamics dominates) — the default stays
    8; tune upward for standalone-evaluation workloads.
    ``interpret=None`` auto-selects interpreter mode off-TPU so the same
    evaluator runs in CPU tests.  Only float-valued, non-ADF
    primitive sets are supported — callers fall back to the XLA
    interpreter otherwise (``make_population_evaluator`` does this
    automatically).
    """
    f = freeze_pset(pset)
    if any(isinstance(n, Primitive) and n.func is None for n in f.pset.nodes):
        raise ValueError("ADF placeholder primitives have no kernel form; "
                         "use the XLA interpreter")
    if not f.pset.arguments:
        # a 0-argument pset would give X a zero-sized (0, pts_pad) block —
        # rejected here (a build-time ValueError) so backend="auto" falls
        # back to the XLA interpreter instead of crashing at call time
        raise ValueError("0-argument primitive sets have no kernel form "
                         "(zero-sized X block); use the XLA interpreter")
    nodes = list(f.pset.nodes)
    if block_trees < 1:
        raise ValueError(f"block_trees must be >= 1, got {block_trees}")
    # Mosaic SMEM blocks need the sublane dim divisible by 8
    tb = _round_up(block_trees, 8)

    def step_branch(node):
        """Per-opcode branch with the stack TOP carried as a loop value
        (measured round 4: the naive all-through-VMEM form spends most of
        its ~60 cycles/token on stack row loads/stores; keeping
        ``stack[sp-1]`` in the carry makes binary ops one row-read, unary
        ops zero, pushes one row-write).  Invariant: ``top`` holds
        ``stack[sp-1]``; VMEM rows ``0..sp-2`` hold the rest.  All shapes
        static inside a branch — only ``sp``/row indices are dynamic
        scalars."""
        # ``top`` is carried as a (1, pts_pad) rank-2 value: Mosaic's
        # layout inference rejects a rank-1 fori_loop carry at some
        # widths ("arr.size() >= layout_rank" check abort, seen at
        # pts_pad=128 — found by tools/tpu_selftest.py, not the bench)
        if isinstance(node, Primitive):
            k, fn = node.arity, node.func

            def branch(sp, top, const, stack_ref, x_ref):
                args = [top] + [stack_ref[sp - 2 - j, :][None, :]
                                for j in range(k - 1)]
                return sp - k + 1, fn(*args)
        elif isinstance(node, Argument):
            ai = node.index

            def branch(sp, top, const, stack_ref, x_ref):
                # push: spill the old top.  At sp == 0 the clamped row-0
                # write stores an uninitialized top, but every read of a
                # row happens only after the push that brought sp past it
                # rewrote it — see the invariant above.
                stack_ref[jnp.maximum(sp - 1, 0), :] = top[0, :]
                return sp + 1, x_ref[ai, :][None, :]
        else:                       # Terminal / Ephemeral: stored constant

            def branch(sp, top, const, stack_ref, x_ref):
                stack_ref[jnp.maximum(sp - 1, 0), :] = top[0, :]
                return sp + 1, jnp.full((1, stack_ref.shape[1]), const,
                                        stack_ref.dtype)
        return branch

    branches = [step_branch(n) for n in nodes]

    def kernel(codes_ref, consts_ref, lengths_ref, x_ref, out_ref,
               stack_ref):
        def tree_body(i, _):
            length = lengths_ref[i, 0]

            def step(t_rev, carry):
                sp, top = carry
                t = length - 1 - t_rev
                c = codes_ref[i, t]
                const = consts_ref[i, t]
                return lax.switch(
                    c, [functools.partial(b, stack_ref=stack_ref,
                                          x_ref=x_ref) for b in branches],
                    sp, top, const)

            top0 = jnp.zeros((1, stack_ref.shape[1]), stack_ref.dtype)
            # no explicit unroll: jax 0.4.x rejects ANY unroll argument
            # (even False) when the trip count is dynamic, and rolled is
            # the default everywhere
            _, top = lax.fori_loop(0, length, step, (0, top0))
            out_ref[i, :] = top[0, :]
            return 0

        lax.fori_loop(0, tb, tree_body, 0)

    # VMEM is ~16 MB/core; the kernel never blocks over the points axis,
    # so its live buffers scale with pts_pad.  Checked per call (shapes are
    # static at trace time) with a descriptive error instead of the opaque
    # Mosaic allocation failure the advisor flagged.
    _VMEM_BUDGET = 12 * 1024 * 1024

    @jax.jit
    def evaluate_pop(codes, consts, lengths, X):
        pop = codes.shape[0]
        n_args, n_points = X.shape
        dtype = X.dtype
        pop_pad = _round_up(max(pop, tb), tb)
        pts_pad = _round_up(n_points, _LANE)
        itemsize = jnp.dtype(dtype).itemsize
        # stack scratch + resident X + double-buffered out blocks
        vmem = (cap + 1 + n_args + 2 * tb) * pts_pad * itemsize
        if vmem > _VMEM_BUDGET:
            raise ValueError(
                f"Pallas GP evaluator needs ~{vmem / 2**20:.0f} MiB of VMEM "
                f"(cap={cap}, n_args={n_args}, n_points={n_points} padded "
                f"to {pts_pad}) but only ~{_VMEM_BUDGET / 2**20:.0f} MiB is "
                "available: the kernel keeps the whole points axis "
                "resident.  Evaluate in point chunks, or build the "
                'evaluator with backend="xla".')
        if pop_pad != pop:
            pad = pop_pad - pop
            codes = jnp.concatenate(
                [codes, jnp.zeros((pad, cap), codes.dtype)], 0)
            consts = jnp.concatenate(
                [consts, jnp.zeros((pad, cap), consts.dtype)], 0)
            # padded trees get length 0: the token loop runs zero steps,
            # so no stack access happens (code 0 is a primitive, which at
            # sp=0 would read/write negative rows); their out_ref row is
            # stale scratch and is sliced off below
            lengths = jnp.concatenate(
                [lengths, jnp.zeros((pad,), lengths.dtype)], 0)
        if pts_pad != n_points:
            X = jnp.concatenate(
                [X, jnp.zeros((n_args, pts_pad - n_points), dtype)], 1)

        run = pl.pallas_call(
            kernel,
            grid=(pop_pad // tb,),
            in_specs=[
                pl.BlockSpec((tb, cap), lambda g: (g, 0),
                             memory_space=pltpu.SMEM),
                pl.BlockSpec((tb, cap), lambda g: (g, 0),
                             memory_space=pltpu.SMEM),
                pl.BlockSpec((tb, 1), lambda g: (g, 0),
                             memory_space=pltpu.SMEM),
                pl.BlockSpec((n_args, pts_pad), lambda g: (0, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((tb, pts_pad), lambda g: (g, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((pop_pad, pts_pad), dtype),
            scratch_shapes=[pltpu.VMEM((cap + 1, pts_pad), dtype)],
            interpret=(jax.default_backend() != "tpu"
                       if interpret is None else interpret),
        )
        out = run(codes.astype(jnp.int32), consts.astype(dtype),
                  lengths.astype(jnp.int32)[:, None], X)
        return out[:pop, :n_points]

    return evaluate_pop
