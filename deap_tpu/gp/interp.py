"""GP tree evaluation — the TPU replacement for the reference's
``gp.compile`` (string-build + Python ``eval``, gp.py:460-485, flagged in
SURVEY §3.4 as the hottest Python-bound path in the library).

A tree is ``(codes, consts, length)`` — prefix order, fixed capacity.
Evaluation is a *stack machine*: scan the token array right-to-left; push
terminal values; for a primitive of arity ``a`` pop ``a`` children (in
left-to-right order) and apply the op via ``lax.switch``.  All sample
points evaluate simultaneously — the stack holds ``(cap+1, n_points)``
values — and the whole population vmaps over trees, so one jitted program
evaluates every tree of every individual on every point with no Python in
the loop.

Under vmap, ``lax.switch`` computes every op and selects per lane — the
standard SIMD trade for interpreters (cost factor = #primitives, each a
cheap elementwise kernel).
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .pset import FrozenPSet, PrimitiveSetTyped, freeze_pset

__all__ = ["make_evaluator", "make_population_evaluator", "compile_tree",
           "run_stack_machine"]


def run_stack_machine(codes, consts, length, X, branches, arity, max_arity,
                      cap):
    """The shared scan core: run the prefix program right-to-left, pushing
    terminal values and applying primitives via ``lax.switch`` over
    ``branches`` (one callable per node code, signature
    ``(args (max_arity, n_points), const) -> (n_points,)``).  Returns the
    value at the top of the stack."""
    n_points = X.shape[1]
    stack0 = jnp.zeros((cap + 1, n_points), X.dtype)

    def step(carry, tok):
        stack, sp = carry
        c, const, pos = tok
        active = pos < length
        a = arity[c]
        arg_rows = jnp.clip(sp - 1 - jnp.arange(max_arity), 0, cap)
        args = stack[arg_rows]                      # (max_arity, n_points)
        res = lax.switch(c, branches, args, const)
        new_sp = jnp.where(active, sp - a + 1, sp)
        row = jnp.where(active, jnp.clip(new_sp - 1, 0, cap - 1), cap)
        # dynamic_update_slice, NOT ``stack.at[row].set``: the batched
        # scatter that ``.at[].set`` lowers to under vmap miscompiles on
        # the axon TPU backend at batch >= 1024 (wrong rows written —
        # found round 3; tests/test_gp_pallas.py::test_batch_size_invariance
        # is the chunked-vs-full oracle, decisive when run on TPU).  DUS
        # lowers to an in-place update and is correct at every batch size.
        stack = lax.dynamic_update_slice(stack, res[None, :], (row, 0))
        return (stack, new_sp), None

    toks = (codes[::-1], consts[::-1], jnp.arange(cap)[::-1])
    (stack, sp), _ = lax.scan(step, (stack0, jnp.int32(0)), toks)
    return stack[jnp.clip(sp - 1, 0, cap - 1)]


def make_evaluator(pset, cap: int) -> Callable:
    """Build ``evaluate(codes, consts, length, X) -> (n_points,)`` for trees
    of capacity ``cap``.  ``X`` is ``(n_args, n_points)``."""
    f = freeze_pset(pset)
    arity = jnp.asarray(f.arity)
    max_arity = max(f.max_arity, 1)
    ops = f.ops

    def evaluate(codes, consts, length, X):
        branches = tuple(
            (lambda args, const, op=op: op(args, const, X)) for op in ops)
        return run_stack_machine(codes, consts, length, X, branches, arity,
                                 max_arity, cap)

    return evaluate


def make_population_evaluator(pset, cap: int, *,
                              backend: str = "auto",
                              block_trees: int = 8) -> Callable:
    """``evaluate_pop(codes (pop,cap), consts (pop,cap), lengths (pop,), X
    (n_args, n_points)) -> (pop, n_points)``.

    ``backend="auto"`` uses the Pallas kernel
    (:mod:`deap_tpu.gp.interp_pallas`) — scalar opcode dispatch with the
    stack in VMEM instead of vmapped compute-every-primitive-and-select —
    when running on TPU and the pset has a kernel form (no ADF
    placeholders); off-TPU (where the kernel would run in slow interpret
    mode) and for ADF psets it uses the vmapped XLA interpreter.
    ``backend="xla"`` / ``"pallas"`` force a path.  ``block_trees`` is
    the Pallas kernel's trees-per-grid-step (rounded up to a multiple of
    8; see :func:`make_population_evaluator_pallas` for tuning — ignored
    on the XLA path)."""
    if backend not in ("auto", "xla", "pallas"):
        raise ValueError(f"unknown backend {backend!r}")
    if block_trees < 1:
        # validated HERE, not inside the pallas builder: auto's
        # ValueError fallback would silently demote a misconfiguration
        # to the ~3x-slower XLA interpreter
        raise ValueError(f"block_trees must be >= 1, got {block_trees}")
    use_pallas = (backend == "pallas" or
                  (backend == "auto" and jax.default_backend() == "tpu"))
    if use_pallas:
        try:
            from .interp_pallas import make_population_evaluator_pallas
            return make_population_evaluator_pallas(pset, cap,
                                                    block_trees=block_trees)
        except ValueError:
            if backend == "pallas":
                raise
    ev = make_evaluator(pset, cap)
    return jax.vmap(ev, in_axes=(0, 0, 0, None))


def compile_tree(tree, pset, cap: int | None = None) -> Callable:
    """Host-facing parity with reference ``gp.compile`` (gp.py:460-485):
    returns a Python callable ``f(*args)`` evaluating the tree.  Scalars or
    arrays accepted; args follow the pset's argument order."""
    codes, consts, length = tree
    cap = cap or codes.shape[-1]
    ev = jax.jit(make_evaluator(pset, cap))

    def func(*args):
        if args:
            scalar = np.ndim(args[0]) == 0
            X = jnp.stack([jnp.atleast_1d(jnp.asarray(a, jnp.float32))
                           for a in args])
        else:
            scalar = False
            X = jnp.zeros((1, 1), jnp.float32)
        out = ev(jnp.asarray(codes), jnp.asarray(consts),
                 jnp.asarray(length), X)
        return float(out[0]) if scalar else out

    return func
