"""Control-flow GP: routines over an explicit world state — the TPU-native
equivalent of the reference's side-effectful program trees (the artificial
ant, examples/gp/ant.py:75-156, where primitives are closures mutating an
``AntSimulator`` and ``run`` re-executes the routine until the move budget
is spent).

The reference's model cannot compile: its nodes *are* Python side effects.
Here a routine is the usual prefix array and the interpreter is a
``lax.while_loop`` over an explicit traversal stack:

* **action terminals** apply ``state -> state`` transformers;
* **sequence primitives** (``prog2``/``prog3``-style) push their children;
* **conditional primitives** evaluate a ``state -> bool`` predicate and push
  exactly one child — true data-dependent branching, not speculative
  execution, because only the chosen subtree's *indices* are pushed;
* when the stack empties the routine restarts from the root (the
  reference's ``while moves < max: routine()``), until ``continue_fn``
  says stop.

Everything vmaps over a population of routines: each lane runs its own
while loop; XLA masks finished lanes.
"""

from __future__ import annotations

from typing import Any, Callable, Mapping

import jax
import jax.numpy as jnp
from jax import lax

from .pset import Primitive, freeze_pset
from .variation import _all_subtree_ends, _take1, _tbl

__all__ = ["make_routine_interpreter"]


def make_routine_interpreter(pset, cap: int, actions: Mapping[str, Callable],
                             conds: Mapping[str, Callable],
                             continue_fn: Callable,
                             max_steps: int | None = None) -> Callable:
    """Build ``run(tree, state) -> state``.

    :param actions: terminal name -> ``state -> state``.
    :param conds: conditional-primitive name -> ``state -> bool`` (arity
        must be 2: the true and false subtree).  All other primitives are
        sequencers executing their children left to right.
    :param continue_fn: ``state -> bool`` — the loop runs while true (the
        move budget of the reference's ``run``, ant.py:120-123).
    :param max_steps: hard cap on interpreter steps (defaults to
        ``64 * cap``) guarding against action-free routines that would
        otherwise spin forever.
    """
    f = freeze_pset(pset)
    arity_np = f.arity
    n_nodes = f.n_nodes
    max_steps = max_steps or 64 * cap

    # per-code dispatch tables
    kind_seq, kind_cond, kind_act = 0, 1, 2
    kinds = []
    act_fns, cond_fns = [], []
    identity = lambda s: s
    false_fn = lambda s: jnp.asarray(False)
    for i in range(n_nodes):
        node = f.pset.nodes[i]
        name = getattr(node, "name", None)
        if name in conds:
            if not (isinstance(node, Primitive) and node.arity == 2):
                raise ValueError(
                    f"conditional {name!r} must be a binary primitive")
            kinds.append(kind_cond)
            act_fns.append(identity)
            cond_fns.append(conds[name])
        elif name in actions:
            kinds.append(kind_act)
            act_fns.append(actions[name])
            cond_fns.append(false_fn)
        elif isinstance(node, Primitive):
            kinds.append(kind_seq)
            act_fns.append(identity)
            cond_fns.append(false_fn)
        else:
            raise ValueError(
                f"terminal {name!r} has no action; every routine terminal "
                "needs an entry in `actions`")
    kinds = jnp.asarray(kinds, jnp.int32)
    arity = jnp.asarray(arity_np)
    act_fns = tuple(act_fns)
    cond_fns = tuple(cond_fns)
    max_arity = max(f.max_arity, 1)

    def run(tree, state):
        codes, consts, length = tree
        ends = _all_subtree_ends(codes, length, arity)

        # traversal stack of node indices
        stack0 = jnp.zeros((cap,), jnp.int32)
        rows_all = jnp.arange(cap)

        def child_starts(i):
            """Start index of each child of node i (prefix layout).  All
            indexing is gather/scatter-free (``_take1``-style one-hot
            contractions): vmapped per-row gathers and scalar scatters are
            ~80x an elementwise op on the bench TPU backend, and the
            ``.at[].set`` scatter pattern miscompiles there at batch >=
            1024 (see deap_tpu/gp/interp.py)."""
            starts = [i + 1]
            for _ in range(max_arity - 1):
                starts.append(_take1(ends, jnp.clip(starts[-1], 0,
                                                    cap - 1)))
            return jnp.stack(starts)

        def cond(carry):
            state, stack, sp, steps = carry
            return continue_fn(state) & (steps < max_steps)

        def body(carry):
            state, stack, sp, steps = carry
            # empty stack -> restart the routine from the root
            restart = sp == 0
            stack = jnp.where(restart & (rows_all == 0), 0, stack)
            sp = jnp.where(restart, 1, sp)

            i = _take1(stack, sp - 1)
            sp = sp - 1
            c = _take1(codes, i)
            kind = _tbl(kinds, c)

            # action: apply the state transformer
            state_act = lax.switch(c, act_fns, state)
            state = jax.tree_util.tree_map(
                lambda a, b: jnp.where(kind == kind_act, a, b),
                state_act, state)

            starts = child_starts(i)
            a = _tbl(arity, c)
            # conditional: push exactly one child by predicate
            pred = lax.switch(c, cond_fns, state)
            chosen = jnp.where(pred, starts[0],
                               starts[jnp.minimum(1, max_arity - 1)])
            push_cond = jnp.where(rows_all == jnp.clip(sp, 0, cap - 1),
                                  chosen, stack)
            sp_cond = sp + 1
            # sequencer: push children right-to-left so leftmost pops
            # first: row sp+j receives starts[a-1-j] for j < a
            j = jnp.arange(max_arity)
            rev = _tbl(starts, jnp.clip(a - 1 - j, 0, max_arity - 1))
            write = ((rows_all[:, None] == (sp + j)[None, :])
                     & (j < a)[None, :])                   # (cap, ma)
            push_seq = jnp.where(jnp.any(write, axis=1),
                                 jnp.sum(jnp.where(write, rev[None, :], 0),
                                         axis=1),
                                 stack)
            sp_seq = sp + a

            is_cond = kind == kind_cond
            is_seq = kind == kind_seq
            stack = jnp.where(is_cond, push_cond,
                              jnp.where(is_seq, push_seq, stack))
            sp = jnp.where(is_cond, sp_cond, jnp.where(is_seq, sp_seq, sp))
            return state, stack, sp, steps + 1

        state, _, _, _ = lax.while_loop(
            cond, body, (state, stack0, jnp.int32(0), jnp.int32(0)))
        return state

    return run
