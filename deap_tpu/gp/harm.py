"""HARM-GP — bloat control by dynamically shaping the genotype size
distribution (Gardner, Gagné & Parizeau 2015; reference ``gp.harm``,
gp.py:933-1130).

The reference's generation body (1) samples a large "natural" offspring
population to model the size distribution, (2) KDE-smooths a size histogram,
(3) picks a cutoff size from the best-fitness tail, (4) builds a target
exponential-decay histogram above the cutoff, and (5) accepts offspring with
probability target/natural per size bin — all with Python loops and
variable-length lists.

Array-native redesign: tree sizes are bounded by the fixed capacity ``cap``,
so the size histogram is a *fixed-shape* ``(cap + 3,)`` array built with
scatter-adds; the natural population is one :func:`~deap_tpu.algorithms.var_or`
batch; acceptance is a masked gather that recycles accepted individuals when
too few pass (the reference instead loops generating more).  The whole run
compiles to one ``lax.scan``.

The population genome must be the GP triple ``(codes, consts, lengths)``
with leaves ``(pop, cap) (pop, cap) (pop,)`` — individual size is
``lengths`` exactly as the reference uses ``len(individual)``.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax

from ..base import Population, lex_sort_indices
from ..algorithms import (var_or, evaluate_population, _hof_setup, _record,
                          _finish)

__all__ = ["harm"]

_KDE = ((-2, 0.1), (-1, 0.2), (0, 0.4), (1, 0.2), (2, 0.1))


def harm(key, population: Population, toolbox, cxpb: float, mutpb: float,
         ngen: int, alpha: float = 0.05, beta: float = 10.0,
         gamma: float = 0.25, rho: float = 0.9, nbrindsmodel: int = -1,
         mincutoff: int = 20, stats=None, halloffame=None, verbose=False):
    """Evolve ``population`` for ``ngen`` generations under HARM-GP size
    control.  Same toolbox protocol as :func:`~deap_tpu.algorithms.ea_simple`
    (``evaluate``/``mate``/``mutate``/``select``); recommended parameters
    follow the paper: alpha=0.05, beta=10, gamma=0.25, rho=0.9 (reference
    gp.py:975-981).  Returns ``(population, logbook)``."""
    n = population.size
    if nbrindsmodel == -1:
        nbrindsmodel = max(2000, n)
    m = nbrindsmodel
    cap = jax.tree_util.tree_leaves(population.genome)[0].shape[-1]
    nbins = cap + 3
    ln2 = math.log(2.0)

    population, nevals0 = evaluate_population(toolbox, population)
    hof_state, hof_upd = _hof_setup(halloffame, population)
    if hof_state is not None:
        hof_state = hof_upd(hof_state, population)
    rec0 = _record(stats, population, nevals0)

    def halflife(x):
        return x * alpha + beta

    def gen_step(carry, _):
        key, pop, hof = carry
        key, k_sel, k_nat, k_acc = jax.random.split(key, 4)

        # 1. natural distribution (reference _genpop with default
        #    acceptance, gp.py:989-1038).  The reference draws every child's
        #    parents through ``toolbox.select``; here one m-wide selection
        #    builds the parent pool (each pick is an independent tournament,
        #    so uniform draws from the pool in var_or reproduce the same
        #    per-child selection pressure), then one varOr batch varies it.
        #    Reproduced children keep their parent's valid fitness; cx/mut
        #    children are invalid — exactly the mix the reference sorts
        #    below.
        parents = pop.take(toolbox.select(k_sel, pop.fitness, m))
        natural = var_or(k_nat, parents, toolbox, m, cxpb, mutpb)
        sizes = natural.genome[2].astype(jnp.int32)            # (m,)

        # 2. KDE-smoothed size histogram (reference gp.py:1074-1084),
        #    normalized to the population scale.
        hist = jnp.zeros((nbins,), jnp.float32)
        for off, w in _KDE:
            b = sizes + off
            ok = (b >= 0) & (b < nbins)
            hist = hist.at[jnp.where(ok, b, nbins - 1)].add(
                jnp.where(ok, w, 0.0))
        natural_hist = hist * (n / m)

        # 3. cutoff size: among the best-fitness tail of the natural pop
        #    (invalid fitness sorts worst, like the reference's empty
        #    wvalues), the smallest individual — floored at mincutoff
        #    (reference gp.py:1087-1092).
        order = lex_sort_indices(natural.fitness.masked_wvalues(),
                                 descending=False)
        cand_sizes = sizes[order[int(n * rho) - 1:]]
        cutoff = jnp.maximum(mincutoff, jnp.min(cand_sizes))

        # 4. target histogram: natural below the cutoff, exponential decay
        #    with size-dependent half-life above it (reference gp.py:1095-1103).
        bins = jnp.arange(nbins, dtype=jnp.float32)
        hl = halflife(bins)
        target_fn = (gamma * n * ln2 / hl) * jnp.exp(
            -ln2 * (bins - cutoff.astype(jnp.float32)) / hl)
        target_hist = jnp.where(bins <= cutoff, natural_hist, target_fn)

        # 5. per-size acceptance probability (reference gp.py:1106-1112)
        prob_hist = jnp.where(natural_hist > 0,
                              target_hist / jnp.maximum(natural_hist, 1e-30),
                              target_hist)

        # accept each natural individual with its size's probability, then
        # take the first n accepted (recycling accepted ones if fewer than n
        # pass — the reference loops generating more instead,
        # gp.py:1115-1117)
        u = jax.random.uniform(k_acc, (m,))
        accept = u <= prob_hist[jnp.clip(sizes, 0, nbins - 1)]
        rank = jnp.where(accept, jnp.arange(m), m + jnp.arange(m))
        by_accept = jnp.argsort(rank)
        n_acc = jnp.sum(accept)
        slots = jnp.arange(n) % jnp.maximum(n_acc, 1)
        # degenerate case n_acc == 0 (possible only at extreme cutoffs):
        # keep the first n natural individuals instead of replicating one
        # rejected individual n times (the reference would keep generating
        # until n are accepted, gp.py:1115-1117)
        chosen = jnp.where(n_acc > 0, by_accept[slots], jnp.arange(n))
        offspring = natural.take(chosen)

        offspring, nevals = evaluate_population(toolbox, offspring)
        if hof is not None:
            hof = hof_upd(hof, offspring)
        return (key, offspring, hof), _record(stats, offspring, nevals)

    (key, population, hof_state), stacked = lax.scan(
        gen_step, (key, population, hof_state), None, length=ngen)
    logbook = _finish(key, population, hof_state, halloffame, stats, rec0,
                      stacked, ngen, verbose)
    return population, logbook
