"""Primitive sets — array-native equivalent of the reference's
``PrimitiveSetTyped``/``PrimitiveSet`` (gp.py:258-454).

The reference registers primitives/terminals into dicts and compiles trees
by building Python source and ``eval``-ing it (gp.py:460-485).  Here the
registry is *compiled to static tables* when frozen:

* a node table (one integer code per primitive/terminal/ephemeral/argument),
* arity / return-type / argument-type arrays,
* per-type candidate lists for generation,
* a tuple of jax op callables, one per node, dispatched by ``lax.switch``
  inside the stack-machine interpreter (:mod:`deap_tpu.gp.interp`).

Trees are then triples ``(codes, consts, length)`` of fixed-capacity arrays
(prefix/depth-first order, exactly the reference's flat-list layout,
gp.py:61-86) and every GP operation is index arithmetic.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Sequence

import numpy as np
import jax
import jax.numpy as jnp

__all__ = ["Primitive", "Terminal", "Ephemeral", "Argument",
           "PrimitiveSetTyped", "PrimitiveSet", "freeze_pset"]


def freeze_pset(pset):
    """Coerce a (possibly already frozen) primitive set to a FrozenPSet."""
    return pset.freeze() if isinstance(pset, PrimitiveSetTyped) else pset


@dataclasses.dataclass
class Primitive:
    """An operator node (reference Primitive, gp.py:185-211)."""
    name: str
    arity: int
    func: Callable                       # (args: (max_arity, n), const) -> (n,)
    ret_type: int
    in_types: tuple
    fmt: str | None = None               # e.g. "({0} + {1})"

    def format(self, *args):
        if self.fmt is not None:
            return self.fmt.format(*args)
        return f"{self.name}({', '.join(args)})"


@dataclasses.dataclass
class Terminal:
    """A constant-valued leaf (reference Terminal, gp.py:214-238)."""
    name: str
    value: float
    ret_type: int

    def format(self):
        return self.name


@dataclasses.dataclass
class Ephemeral:
    """A random-constant leaf: the value is drawn per occurrence at
    generation time and then mutated in place (reference Ephemeral,
    gp.py:241-255).  ``sampler(key) -> float`` replaces the reference's
    zero-arg ``random`` lambdas."""
    name: str
    sampler: Callable
    ret_type: int


@dataclasses.dataclass
class Argument:
    """An input-variable leaf (the reference's ARGx terminals,
    gp.py:286-294)."""
    name: str
    index: int
    ret_type: int


class PrimitiveSetTyped:
    """Typed primitive registry (reference PrimitiveSetTyped, gp.py:258-427).

    Types are arbitrary hashables mapped to small ints internally.  After
    all registrations, :meth:`freeze` compiles the static tables; the
    interpreter and generators take the frozen set.
    """

    def __init__(self, name: str, in_types: Sequence[Any], ret_type: Any,
                 prefix: str = "ARG"):
        self.name = name
        self._type_ids: dict = {}
        self.ret = self._type_id(ret_type)
        self.ins = [self._type_id(t) for t in in_types]
        self.prefix = prefix
        self.primitives: list[Primitive] = []
        self.terminals: list[Terminal] = []
        self.ephemerals: list[Ephemeral] = []
        self.arguments: list[Argument] = []
        self.mapping: dict[str, Any] = {}
        for i, t in enumerate(self.ins):
            arg = Argument(f"{prefix}{i}", i, t)
            self.arguments.append(arg)
            self.mapping[arg.name] = arg
        self._frozen = None

    # -- type bookkeeping ---------------------------------------------------
    def _type_id(self, t) -> int:
        if t not in self._type_ids:
            self._type_ids[t] = len(self._type_ids)
        return self._type_ids[t]

    @property
    def n_types(self) -> int:
        return len(self._type_ids)

    # -- registration (reference addPrimitive/addTerminal/addEphemeralConstant,
    #    gp.py:297-383) ------------------------------------------------------
    def _check_name(self, name):
        if name in self.mapping:
            raise ValueError(
                f"Primitives are required to have a unique name. "
                f"Consider using the argument 'name' to rename your "
                f"second '{name}' primitive.")

    def add_primitive(self, func: Callable, in_types: Sequence[Any],
                      ret_type: Any, name: str | None = None,
                      fmt: str | None = None):
        """``func`` is a natural jnp function of ``arity`` array arguments,
        each ``(n_points,)``, returning ``(n_points,)`` — e.g.
        ``jnp.add`` or ``lambda a, b: jnp.where(jnp.abs(b) > 1e-9, a / b,
        1.0)`` for protected division."""
        name = name or getattr(func, "__name__", f"prim{len(self.primitives)}")
        self._check_name(name)
        prim = Primitive(name, len(in_types), func,
                         self._type_id(ret_type),
                         tuple(self._type_id(t) for t in in_types), fmt)
        self.primitives.append(prim)
        self.mapping[name] = prim
        self._frozen = None
        return prim

    def add_terminal(self, value: float, ret_type: Any, name: str | None = None):
        name = name or str(value)
        self._check_name(name)
        term = Terminal(name, float(value), self._type_id(ret_type))
        self.terminals.append(term)
        self.mapping[name] = term
        self._frozen = None
        return term

    def add_ephemeral_constant(self, name: str, sampler: Callable, ret_type: Any):
        """``sampler(key) -> scalar`` (jax); reference gp.py:348-383."""
        self._check_name(name)
        eph = Ephemeral(name, sampler, self._type_id(ret_type))
        self.ephemerals.append(eph)
        self.mapping[name] = eph
        self._frozen = None
        return eph

    def add_adf(self, adfset: "PrimitiveSetTyped"):
        """Register another primitive set as a callable ADF node (reference
        addADF, gp.py:412-427).  The node's signature mirrors the ADF set's
        argument/return types; its behavior is supplied per-individual by
        :func:`deap_tpu.gp.adf.make_adf_evaluator` (the plain evaluator
        yields NaN for ADF nodes)."""
        inv = {v: k for k, v in adfset._type_ids.items()}
        # call the typed base implementation explicitly: the untyped facade
        # overrides add_primitive with an (func, arity) signature
        return PrimitiveSetTyped.add_primitive(
            self, None, [inv[i] for i in adfset.ins], inv[adfset.ret],
            name=adfset.name)

    def rename_arguments(self, **kargs):
        """Rename input arguments, e.g. ``rename_arguments(ARG0="x")``
        (reference renameArguments, gp.py:396-410)."""
        for old_name, new_name in kargs.items():
            arg = self.mapping.get(old_name)
            if not isinstance(arg, Argument):
                raise ValueError(f"{old_name!r} is not an argument of "
                                 f"primitive set {self.name!r}")
            self._check_name(new_name)
            del self.mapping[old_name]
            arg.name = new_name
            self.mapping[new_name] = arg
        self._frozen = None

    # camelCase aliases matching the reference API
    addPrimitive = add_primitive
    addTerminal = add_terminal
    addEphemeralConstant = add_ephemeral_constant
    addADF = add_adf
    renameArguments = rename_arguments

    # -- freezing -----------------------------------------------------------
    @property
    def nodes(self) -> list:
        """Node table: primitives, then terminals, ephemerals, arguments —
        a node's position is its integer code."""
        return (list(self.primitives) + list(self.terminals)
                + list(self.ephemerals) + list(self.arguments))

    def freeze(self) -> "FrozenPSet":
        if self._frozen is None:
            self._frozen = FrozenPSet(self)
        return self._frozen


class PrimitiveSet(PrimitiveSetTyped):
    """Untyped facade: every type is ``object`` (reference PrimitiveSet,
    gp.py:430-454)."""

    def __init__(self, name: str, arity: int, prefix: str = "ARG"):
        super().__init__(name, [object] * arity, object, prefix)

    def add_primitive(self, func, arity: int | Sequence, name=None,
                      fmt=None):
        if isinstance(arity, int):
            in_types = [object] * arity
        elif arity is None:
            raise TypeError("add_primitive() requires an arity (int) or an "
                            "explicit sequence of argument types")
        else:
            in_types = arity
        return super().add_primitive(func, in_types, object, name, fmt)

    def add_terminal(self, value, name=None):
        return super().add_terminal(value, object, name)

    def add_ephemeral_constant(self, name, sampler):
        return super().add_ephemeral_constant(name, sampler, object)

    addPrimitive = add_primitive
    addTerminal = add_terminal
    addEphemeralConstant = add_ephemeral_constant


class FrozenPSet:
    """Static tables compiled from a PrimitiveSet — everything the jitted
    interpreter/generators need, as numpy constants baked into the trace."""

    def __init__(self, pset: PrimitiveSetTyped):
        self.pset = pset
        nodes = pset.nodes
        self.n_nodes = len(nodes)
        self.names = [getattr(n, "name") for n in nodes]
        self.arity = np.array(
            [n.arity if isinstance(n, Primitive) else 0 for n in nodes],
            np.int32)
        self.max_arity = int(self.arity.max()) if len(nodes) else 0
        self.ret_type = np.array([n.ret_type for n in nodes], np.int32)
        self.is_primitive = np.array(
            [isinstance(n, Primitive) for n in nodes], bool)
        self.is_terminal = ~self.is_primitive
        self.is_ephemeral = np.array(
            [isinstance(n, Ephemeral) for n in nodes], bool)
        self.is_argument = np.array(
            [isinstance(n, Argument) for n in nodes], bool)
        self.arg_index = np.array(
            [n.index if isinstance(n, Argument) else 0 for n in nodes],
            np.int32)
        self.const_value = np.array(
            [n.value if isinstance(n, Terminal) else 0.0 for n in nodes],
            np.float32)
        # child types padded to max_arity
        self.in_types = np.zeros((self.n_nodes, max(self.max_arity, 1)),
                                 np.int32)
        for i, n in enumerate(nodes):
            if isinstance(n, Primitive):
                self.in_types[i, :n.arity] = n.in_types

        # per-type candidate lists (for generation): padded code arrays
        nt = pset.n_types
        self.prim_by_type = _candidates(
            nt, [(i, n.ret_type) for i, n in enumerate(nodes)
                 if isinstance(n, Primitive)])
        self.term_by_type = _candidates(
            nt, [(i, n.ret_type) for i, n in enumerate(nodes)
                 if not isinstance(n, Primitive)])
        # terminal ratio (reference pset.terminalRatio, gp.py:420-426)
        n_term = int(self.is_terminal.sum())
        self.terminal_ratio = n_term / max(1, self.n_nodes)

        # ephemeral samplers table aligned with codes
        self.eph_samplers = [
            n.sampler if isinstance(n, Ephemeral) else None for n in nodes]

        # which primitives have terminals available for every argument type
        # (guards the padded candidate tables: gathering from an empty
        # bucket would silently return code 0)
        term_cnt = self.term_by_type[1]
        self.args_have_terminals = np.array([
            all(term_cnt[t] > 0 for t in n.in_types)
            if isinstance(n, Primitive) else True
            for n in nodes])
        self._const_fns = None
        self._device_tables = None

        # jax ops for the interpreter: one callable per node code
        def make_op(i, n):
            if isinstance(n, Primitive):
                if n.func is None:
                    # ADF placeholder: only meaningful through the nested
                    # interpreter (deap_tpu.gp.adf); NaN flags misuse here
                    return lambda args, const, X: jnp.full(
                        X.shape[1:], jnp.nan, X.dtype)
                k = n.arity
                fn = n.func
                return lambda args, const, X: fn(*(args[j] for j in range(k)))
            if isinstance(n, Argument):
                k = n.index
                return lambda args, const, X: X[k]
            # Terminal / Ephemeral: the per-node stored constant
            return lambda args, const, X: jnp.broadcast_to(const, X.shape[1:])
        self.ops = tuple(make_op(i, n) for i, n in enumerate(nodes))

    def code_of(self, name: str) -> int:
        return self.names.index(name)

    @property
    def const_fns(self):
        """Cached per-code constant samplers for ``lax.switch`` (ephemerals
        draw from their sampler; other nodes return their static value).
        Cached so repeated operator calls reuse the same callables and jit
        traces hit the cache."""
        if self._const_fns is None:
            fns = []
            for i in range(self.n_nodes):
                if self.eph_samplers[i] is not None:
                    sampler = self.eph_samplers[i]
                    fns.append(lambda key, s=sampler:
                               jnp.asarray(s(key), jnp.float32))
                else:
                    v = float(self.const_value[i])
                    fns.append(lambda key, v=v: jnp.asarray(v, jnp.float32))
            self._const_fns = tuple(fns)
        return self._const_fns


def _candidates(n_types: int, pairs):
    """pairs: (code, type) -> padded (n_types, max_count) array + counts."""
    buckets = [[] for _ in range(max(n_types, 1))]
    for code, t in pairs:
        buckets[t].append(code)
    width = max((len(b) for b in buckets), default=0)
    width = max(width, 1)
    arr = np.zeros((max(n_types, 1), width), np.int32)
    cnt = np.zeros(max(n_types, 1), np.int32)
    for t, b in enumerate(buckets):
        cnt[t] = len(b)
        for j, c in enumerate(b):
            arr[t, j] = c
    return arr, cnt
