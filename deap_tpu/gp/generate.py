"""GP tree generation — array-native equivalents of ``genFull``/``genGrow``/
``genHalfAndHalf`` (reference gp.py:517-633).

The reference generates trees with a Python loop over a typed stack
(``generate``, gp.py:587-633).  Here the same typed-stack algorithm runs as
a ``lax.while_loop`` emitting prefix tokens into a fixed-capacity buffer, so
*whole populations* of random trees generate inside one jitted program
(initialization, and crucially ``mutUniform``'s random subtrees inside the
evolution loop).

Capacity safety: when the emitted length plus outstanding slots approaches
``cap``, the generator forces terminals — trees always fit the buffer (the
reference instead grows unbounded Python lists)."""

from __future__ import annotations

from functools import partial
from typing import Callable

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .pset import PrimitiveSetTyped, freeze_pset

__all__ = ["make_generator", "gen_full", "gen_grow", "gen_half_and_half"]


def make_generator(pset, cap: int, kind: str = "half_and_half") -> Callable:
    """Build ``gen(key, min_depth, max_depth, ret_type=None) ->
    (codes, consts, length)``.

    ``kind``: "full" (terminals only at max depth, reference gp.py:517-535),
    "grow" (terminals allowed from min depth per terminal ratio, reference
    gp.py:537-558), or "half_and_half" (coin flip per tree, gp.py:560-575).
    min/max depth must be static ints; ``ret_type`` may be a traced type id
    (typed ``mutUniform`` passes the replaced subtree's type, reference
    gp.py:750).

    Raises at construction if any reachable argument type has no terminal —
    such a set cannot bound tree depth (the reference raises IndexError at
    generation time instead, gp.py:612-617)."""
    f = freeze_pset(pset)
    term_cnt_np = f.term_by_type[1]
    reachable = {f.pset.ret}
    for i in range(f.n_nodes):
        if f.is_primitive[i]:
            reachable.update(int(t) for t in f.in_types[i, :f.arity[i]])
    missing = [t for t in reachable if term_cnt_np[t] == 0]
    if missing:
        raise ValueError(
            f"The primitive set has no terminal for type id(s) {missing}; "
            "tree generation cannot terminate. Add a terminal of that type "
            "(reference gp.generate raises IndexError for this, "
            "gp.py:612-617).")

    prim_arr, prim_cnt = (jnp.asarray(f.prim_by_type[0]),
                          jnp.asarray(f.prim_by_type[1]))
    term_arr, term_cnt = (jnp.asarray(f.term_by_type[0]),
                          jnp.asarray(f.term_by_type[1]))
    arity = jnp.asarray(f.arity)
    in_types = jnp.asarray(f.in_types)
    const_fns = f.const_fns
    ret_type = f.pset.ret
    max_arity = max(f.max_arity, 1)
    terminal_ratio = f.terminal_ratio

    def gen_one(key, min_depth: int, max_depth: int, ret_type=ret_type,
                force_grow=None):
        k_height, k_kind, key = jax.random.split(key, 3)
        height = jax.random.randint(k_height, (), min_depth, max_depth + 1)
        if kind == "full":
            grow = jnp.asarray(False)
        elif kind == "grow":
            grow = jnp.asarray(True)
        else:
            grow = jax.random.bernoulli(k_kind, 0.5)
        if force_grow is not None:
            grow = force_grow

        codes0 = jnp.zeros((cap,), jnp.int32)
        consts0 = jnp.zeros((cap,), jnp.float32)
        # typed stack of required (type, depth)
        st_type0 = jnp.zeros((cap + max_arity,), jnp.int32).at[0].set(ret_type)
        st_depth0 = jnp.zeros((cap + max_arity,), jnp.int32)

        def cond(state):
            _, _, pos, _, _, sp, _ = state
            return (sp > 0) & (pos < cap)

        # gather/scatter-free body: on the bench TPU backend a vmapped
        # per-row gather or scatter costs ~80x an elementwise op, so every
        # stack/table access below is a where/one-hot contraction over the
        # small axis instead (helpers shared with the variation operators)
        from .variation import _take1 as at_, _tbl as tbl_
        st_rows = jnp.arange(cap + max_arity)
        buf_rows = jnp.arange(cap)

        def body(state):
            codes, consts, pos, st_type, st_depth, sp, key = state
            key, k_term, k_pick, k_const = jax.random.split(key, 4)
            t = at_(st_type, sp - 1)
            d = at_(st_depth, sp - 1)
            sp = sp - 1

            t_term_cnt = tbl_(term_cnt, t)
            t_prim_cnt = tbl_(prim_cnt, t)
            has_prim = t_prim_cnt > 0
            has_term = t_term_cnt > 0
            # reference genFull: terminal iff depth == height;
            # genGrow: depth == height or (depth >= min and u < ratio)
            at_bottom = d >= height
            grow_term = (d >= min_depth) & (
                jax.random.uniform(k_term) < terminal_ratio)
            want_term = at_bottom | (grow & grow_term)
            # capacity guard: outstanding slots must still fit
            must_term = (pos + sp + max_arity) >= cap
            choose_term = (want_term & has_term) | must_term | ~has_prim

            tpick = jax.random.randint(k_pick, (), 0,
                                       jnp.maximum(t_term_cnt, 1))
            # deliberate shared key: exactly ONE of tpick/ppick is
            # consumed (choose_term selects), and the committed GP trees
            # / bench streams pin these bits
            ppick = jax.random.randint(k_pick, (), 0,  # lint: disable=rng-key-reuse -- only one draw is consumed; stream pinned by committed GP benches
                                       jnp.maximum(t_prim_cnt, 1))
            hot_t = ((jnp.arange(term_arr.shape[0])[:, None] == t)
                     & (jnp.arange(term_arr.shape[1])[None, :] == tpick))
            hot_p = ((jnp.arange(prim_arr.shape[0])[:, None] == t)
                     & (jnp.arange(prim_arr.shape[1])[None, :] == ppick))
            code = jnp.where(choose_term,
                             jnp.sum(jnp.where(hot_t, term_arr, 0)),
                             jnp.sum(jnp.where(hot_p, prim_arr, 0)))
            const = lax.switch(code, const_fns, k_const)
            codes = jnp.where(buf_rows == pos, code, codes)
            consts = jnp.where(buf_rows == pos, const, consts)

            # push chosen primitive's argument types, right-to-left so the
            # leftmost child pops first (prefix order): reversed args occupy
            # rows sp .. sp+a-1 with types in_types[code, a-1-j]
            a = tbl_(arity, code)
            j = jnp.arange(max_arity)
            real = j < a
            # in_types row for `code`, then reversed into push order
            ty_row = jnp.sum(
                jnp.where(jnp.arange(in_types.shape[0])[:, None] == code,
                          in_types, 0), axis=0)               # (max_arity,)
            rev_ty = jnp.sum(
                jnp.where(j[:, None] == jnp.clip(a - 1 - j, 0,
                                                 max_arity - 1)[None, :],
                          ty_row[:, None], 0), axis=0)
            slot = st_rows[:, None] == (sp + j)[None, :]      # (cap+ma, ma)
            write = slot & real[None, :]
            st_type = jnp.sum(jnp.where(write, rev_ty[None, :], 0), axis=1) \
                + jnp.where(jnp.any(write, axis=1), 0, st_type)
            st_depth = jnp.where(jnp.any(write, axis=1), d + 1, st_depth)
            sp = sp + a
            return codes, consts, pos + 1, st_type, st_depth, sp, key

        codes, consts, pos, _, _, _, _ = lax.while_loop(
            cond, body,
            (codes0, consts0, jnp.int32(0), st_type0, st_depth0,
             jnp.int32(1), key))
        return codes, consts, pos

    return gen_one


def gen_full(key, pset, min_, max_, cap: int = 64):
    """One full-method tree (reference genFull, gp.py:517-535)."""
    return make_generator(pset, cap, "full")(key, min_, max_)


def gen_grow(key, pset, min_, max_, cap: int = 64):
    """One grow-method tree (reference genGrow, gp.py:537-558)."""
    return make_generator(pset, cap, "grow")(key, min_, max_)


def gen_half_and_half(key, pset, min_, max_, cap: int = 64):
    """Ramped half-and-half (reference genHalfAndHalf, gp.py:560-575)."""
    return make_generator(pset, cap, "half_and_half")(key, min_, max_)
