"""``deap-tpu-lint`` — console entry of the static-analysis framework.

::

    deap-tpu-lint                        # all default passes, whole repo
    deap-tpu-lint deap_tpu/serve        # restrict the scanned paths
    deap-tpu-lint --select rng-key-reuse,tracer-leak
    deap-tpu-lint --select collective-budget   # the heavy opt-in gate
    deap-tpu-lint --format json|sarif   # machine output on stdout
    deap-tpu-lint --update-baseline     # grandfather the current findings
    deap-tpu-lint --list-rules

Exit codes: 0 clean (baselined/suppressed findings don't fail), 1 live
findings, 2 usage or internal error.  The tier-1 gate
(``tests/test_tooling.py``) runs the default pass set over the whole
repo and asserts 0.

This module is the one sanctioned ``print`` site of the lint package
(its stdout IS its interface — same contract the no-bare-print pass
enforces everywhere else).  It never imports jax: linting must work,
fast, on a box with no accelerator stack.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from .core import REPO, iter_rules, run_lint
from .baseline import (DEFAULT_BASELINE, load_baseline, write_baseline)
from .reporters import render_text, render_json, render_sarif


def _split_rules(value):
    return [v.strip() for v in value.split(",") if v.strip()]


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="deap-tpu-lint",
        description="Unified static analysis for deap_tpu: JAX "
                    "trace-safety, RNG discipline, lock discipline, "
                    "output routing, benchmark-artifact schemas.")
    ap.add_argument("paths", nargs="*", type=Path,
                    help="files/directories to scan (default: the repo)")
    ap.add_argument("--repo", type=Path, default=REPO,
                    help="repository root (default: auto-detected)")
    ap.add_argument("--select", type=_split_rules, default=None,
                    metavar="RULE[,RULE...]",
                    help="run ONLY these rules (also the way to run "
                         "default-off heavy rules like collective-budget)")
    ap.add_argument("--ignore", type=_split_rules, default=None,
                    metavar="RULE[,RULE...]", help="skip these rules")
    ap.add_argument("--format", choices=("text", "json", "sarif"),
                    default="text")
    ap.add_argument("--baseline", type=Path, default=None,
                    help=f"baseline file (default: {DEFAULT_BASELINE})")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline (report everything live)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline to grandfather exactly the "
                         "current findings, then exit 0")
    ap.add_argument("--changed", action="store_true",
                    help="scan only git-touched .py files (diff vs HEAD, "
                         "staged, and untracked) -- the sub-second "
                         "pre-commit loop; exits 0 immediately when "
                         "nothing changed")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("-v", "--verbose", action="store_true",
                    help="text format: also list baselined findings")
    return ap


def changed_py_files(repo: Path):
    """Repo-relative ``.py`` paths git considers touched: working-tree +
    staged changes vs HEAD, plus untracked files.  Deleted files are
    excluded (nothing to lint).  Raises ``RuntimeError`` when git is
    unavailable or ``repo`` is not a work tree — the caller surfaces
    that as a usage error rather than silently linting nothing."""
    import subprocess
    cmds = (["git", "diff", "--name-only", "HEAD", "--"],
            ["git", "ls-files", "--others", "--exclude-standard"])
    names = []
    for cmd in cmds:
        try:
            proc = subprocess.run(cmd, cwd=repo, capture_output=True,
                                  text=True)
        except OSError as e:
            raise RuntimeError(f"{' '.join(cmd)} failed: {e}") from None
        if proc.returncode != 0:
            raise RuntimeError(
                f"{' '.join(cmd)} failed: "
                f"{proc.stderr.strip() or 'not a git work tree?'}")
        names.extend(proc.stdout.splitlines())
    out = []
    seen = set()
    for name in names:
        if not name.endswith(".py") or name in seen:
            continue
        seen.add(name)
        path = Path(repo) / name
        if path.is_file():
            out.append(path)
    return sorted(out)


def main(argv=None) -> int:
    try:
        return _main(argv)
    except BrokenPipeError:
        # stdout went away mid-report (`deap-tpu-lint | head`): exit
        # quietly instead of spraying a traceback onto stderr
        try:
            sys.stdout.close()
        except BrokenPipeError:
            pass
        return 0


def _main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    if args.list_rules:
        for r in iter_rules():
            tag = "" if r.default else "  [opt-in: --select]"
            print(f"{r.name:20s} {r.severity:7s} {r.doc}{tag}")
        return 0

    if args.update_baseline and (args.select or args.ignore or args.paths
                                 or args.changed):
        # a partial run sees a subset of findings; rewriting the whole
        # baseline from it would silently drop every other rule's/path's
        # grandfathered entries
        print("deap-tpu-lint: --update-baseline requires a full run "
              "(no --select/--ignore/--changed/paths)", file=sys.stderr)
        return 2

    if args.changed:
        if args.paths:
            print("deap-tpu-lint: --changed and explicit paths are "
                  "mutually exclusive", file=sys.stderr)
            return 2
        try:
            changed = changed_py_files(Path(args.repo))
        except RuntimeError as e:
            print(f"deap-tpu-lint: --changed: {e}", file=sys.stderr)
            return 2
        if not changed:
            # nothing to scan: emit a format-faithful empty report (a
            # JSON/SARIF consumer must still receive its document)
            from .core import LintResult
            empty = LintResult(findings=[], suppressed=[], baselined=[],
                               expired=[], rules_run=[], files_scanned=0)
            if args.format == "json":
                print(json.dumps(render_json(empty), indent=2,
                                 sort_keys=True))
            elif args.format == "sarif":
                print(json.dumps(render_sarif(empty), indent=2,
                                 sort_keys=True))
            else:
                print("0 finding(s) in 0 files "
                      "(no git-touched .py files)")
            return 0
        # a path-restricted run: whole-repo coverage pins don't apply,
        # which is exactly right for a per-commit loop
        args.paths = changed

    baseline_path = args.baseline
    if baseline_path is None:
        baseline_path = Path(args.repo) / "tools" / "lint_baseline.json"

    try:
        baseline = {} if (args.no_baseline or args.update_baseline) \
            else load_baseline(baseline_path)
    except ValueError as e:
        print(f"deap-tpu-lint: {e}", file=sys.stderr)
        return 2

    try:
        result = run_lint(repo=args.repo, paths=args.paths or None,
                          select=args.select, ignore=args.ignore,
                          baseline=baseline)
    except KeyError as e:   # unknown rule name from --select/--ignore
        print(f"deap-tpu-lint: {e.args[0]}", file=sys.stderr)
        return 2

    if args.update_baseline:
        doc = write_baseline(result.findings, baseline_path)
        print(f"baseline updated: {baseline_path} "
              f"({len(doc['entries'])} entries)")
        return 0

    if args.format == "json":
        print(json.dumps(render_json(result), indent=2, sort_keys=True))
    elif args.format == "sarif":
        print(json.dumps(render_sarif(result), indent=2, sort_keys=True))
    else:
        print(render_text(result, verbose=args.verbose))
    return result.exit_code


if __name__ == "__main__":
    sys.exit(main())
