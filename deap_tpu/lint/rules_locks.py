"""``lock-order`` — static deadlock lint over the serve fleet's locks.

The ``lock-discipline`` pass (:mod:`deap_tpu.lint.rules_repo`) proves
each guarded *write* holds its lock; it says nothing about the ORDER
locks nest in.  With several lock-bearing objects on one request path —
the dispatcher's ``_cv``, the service's ``_lock``, a session's
``_phase_lock``, the tracer's ``_lock`` — an inverted nesting is a real
deadlock no type checker sees: ``serve/service.py`` documents exactly
this hazard ("NEVER held across a submit — the dispatcher takes its own
lock first on some failure paths, and the reverse order would
deadlock").

This pass builds the static acquisition graph per class:

* **nodes** are the class's lock attributes — ``_GUARDED_BY`` keys plus
  every ``self.<attr> = threading.Lock()/RLock()/Condition()`` binding;
* **edges** ``A → B`` whenever ``with self.B:`` is entered while ``A``
  is held — directly nested, through a local alias (``cv = self._cv``,
  the dispatcher idiom, resolved by the same prescan lock-discipline
  uses), or via a ``self.<method>()`` call whose body (transitively,
  through further self-calls) acquires ``B``.

A **cycle** in the graph is two code paths that can interleave into a
deadlock and fails the gate.  Re-entrant self-edges (``with self._lock``
inside a ``*_locked`` helper called under the same lock) are excluded —
re-entry is an RLock legality question, not an ordering one, and the
repo's ``*_locked`` convention already marks those helpers.
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, Iterable, List, Set, Tuple

from .core import Finding, LintContext, rule
from .rules_repo import _lock_aliases, _own_expressions, _self_attr

__all__ = ["lock_attributes", "acquisition_graph", "graph_cycles",
           "lock_order_findings"]

#: constructor names whose result bound to ``self.<attr>`` makes the
#: attribute a lock node (``threading.Lock()`` / bare ``Lock()`` after a
#: from-import both count)
_LOCK_FACTORIES = {"Lock", "RLock", "Condition"}


def lock_attributes(cls: ast.ClassDef) -> Set[str]:
    """The class's lock nodes: ``_GUARDED_BY`` keys (string-literal
    dict, same contract as lock-discipline) plus every attribute
    assigned a ``Lock()``/``RLock()``/``Condition()`` anywhere in the
    class body."""
    locks: Set[str] = set()
    for stmt in cls.body:
        if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and stmt.targets[0].id == "_GUARDED_BY"
                and isinstance(stmt.value, ast.Dict)):
            for k in stmt.value.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    locks.add(k.value)
    for node in ast.walk(cls):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
            continue
        attr = _self_attr(node.targets[0])
        if attr is None or not isinstance(node.value, ast.Call):
            continue
        f = node.value.func
        name = (f.attr if isinstance(f, ast.Attribute)
                else f.id if isinstance(f, ast.Name) else None)
        if name in _LOCK_FACTORIES:
            locks.add(attr)
    return locks


def _method_scan(meth, locks: Set[str]
                 ) -> Tuple[Set[Tuple[str, str]], Set[str],
                            List[Tuple[FrozenSet[str], str]]]:
    """One method's direct evidence: nesting ``edges``, the set of locks
    it ``acquires`` directly, and its self-method ``calls`` with the
    lock set held at each call site."""
    aliases = _lock_aliases(meth, dict.fromkeys(locks))
    edges: Set[Tuple[str, str]] = set()
    acquires: Set[str] = set()
    calls: List[Tuple[FrozenSet[str], str]] = []

    def resolve(expr) -> str:
        a = _self_attr(expr)
        if a is None and isinstance(expr, ast.Name):
            a = aliases.get(expr.id)
        return a if a in locks else None

    def scan_calls(root, held: Set[str]) -> None:
        for node in ast.walk(root):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and _self_attr(node.func) is not None):
                calls.append((frozenset(held), node.func.attr))

    def walk(stmts, held: Set[str]) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue        # a nested def's body runs later, unlocked
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                now = set(held)
                for item in stmt.items:
                    scan_calls(item.context_expr, now)
                    lk = resolve(item.context_expr)
                    if lk is not None:
                        acquires.add(lk)
                        edges.update((h, lk) for h in now if h != lk)
                        now.add(lk)
                walk(stmt.body, now)
                continue
            for expr in _own_expressions(stmt):
                scan_calls(expr, held)
            for body in (getattr(stmt, "body", None),
                         getattr(stmt, "orelse", None),
                         getattr(stmt, "finalbody", None)):
                if body:
                    walk(body, held)
            for h in getattr(stmt, "handlers", []):
                walk(h.body, held)

    walk(meth.body, set())
    return edges, acquires, calls


def acquisition_graph(cls: ast.ClassDef) -> Set[Tuple[str, str]]:
    """The class's lock acquisition edges: direct ``with`` nesting plus
    one-class interprocedural propagation — a ``self.m()`` call under a
    held lock contributes an edge to every lock ``m`` may (transitively,
    through further self-calls) acquire."""
    locks = lock_attributes(cls)
    if len(locks) < 2:
        return set()
    methods = [m for m in cls.body
               if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))]
    edges: Set[Tuple[str, str]] = set()
    direct: Dict[str, Set[str]] = {}
    calls: Dict[str, List[Tuple[FrozenSet[str], str]]] = {}
    for meth in methods:
        e, acq, c = _method_scan(meth, locks)
        edges |= e
        direct[meth.name] = acq
        calls[meth.name] = c
    # transitive may-acquire closure over the class-local call graph
    may: Dict[str, Set[str]] = {m: set(a) for m, a in direct.items()}
    changed = True
    while changed:
        changed = False
        for m, sites in calls.items():
            for _held, callee in sites:
                gain = may.get(callee, set()) - may[m]
                if gain:
                    may[m] |= gain
                    changed = True
    for m, sites in calls.items():
        for held, callee in sites:
            for lk in may.get(callee, ()):
                edges.update((h, lk) for h in held if h != lk)
    return edges


def graph_cycles(edges: Set[Tuple[str, str]]) -> List[List[str]]:
    """Elementary cycles of the (small) acquisition graph, each
    normalized to start at its lexicographically smallest node and
    deduplicated — stable output for stable finding messages."""
    adj: Dict[str, Set[str]] = {}
    for a, b in edges:
        adj.setdefault(a, set()).add(b)
    seen: Set[Tuple[str, ...]] = set()
    cycles: List[List[str]] = []

    def dfs(start: str, node: str, path: List[str]) -> None:
        for nxt in sorted(adj.get(node, ())):
            if nxt == start:
                rot = min(range(len(path)), key=lambda i: path[i])
                key = tuple(path[rot:] + path[:rot])
                if key not in seen:
                    seen.add(key)
                    cycles.append(list(key))
            elif nxt not in path and nxt > start:
                # only visit nodes above the start so each cycle is
                # discovered exactly once, from its smallest node
                dfs(start, nxt, path + [nxt])

    for start in sorted(adj):
        dfs(start, start, [start])
    return cycles


def lock_order_findings(tree: ast.AST, path: str) -> List[Finding]:
    """Every acquisition-order cycle in every class of ``tree``."""
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        for cyc in graph_cycles(acquisition_graph(node)):
            order = " -> ".join(cyc + [cyc[0]])
            findings.append(Finding(
                rule="lock-order", path=path, line=node.lineno,
                message=(f"{node.name}: lock acquisition cycle {order} "
                         "-- two threads taking these locks in opposite "
                         "orders deadlock; pick ONE order and hold it on "
                         "every path (or collapse to a single lock)")))
    return findings


@rule("lock-order",
      "nested 'with self.<lock>:' acquisitions (direct, aliased, or via "
      "self-method calls) must form a consistent acyclic order per class "
      "-- the static deadlock lint for the serve fleet's lock trio")
def _check_lock_order(ctx: LintContext) -> Iterable[Finding]:
    for pf in ctx.py_files:
        if pf.tree is None:
            continue
        yield from lock_order_findings(pf.tree, pf.rel)
