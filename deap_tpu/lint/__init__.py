"""``deap_tpu.lint`` — the repo's unified static-analysis framework.

The toolbox boundary gates all parallelism behind ``jit``/``scan``
programs, which moves the dominant correctness hazards out of ordinary
Python semantics and into *trace time*: a host side effect baked into a
compiled body runs once at trace instead of per step, a reused PRNG key
silently correlates whole populations (the dominant user-facing bug
class EvoJAX and evosax both document), and the serving fleet's shared
state races when written off-lock.  Each of those invariants used to be
policed by a one-off script under ``tools/``; this package replaces them
with one framework:

* **one AST parse per file** shared across every pass
  (:class:`~deap_tpu.lint.core.PyFile`);
* a uniform :class:`~deap_tpu.lint.core.Finding` record (rule id,
  severity, ``file:line``, stable message) and a rule registry;
* inline ``# lint: disable=<rule> -- reason`` suppressions;
* a committed baseline (``tools/lint_baseline.json``) for grandfathered
  findings, refreshed with ``deap-tpu-lint --update-baseline``;
* text / JSON / SARIF reporters and a ``deap-tpu-lint`` console entry;
* a single tier-1 gate test (``tests/test_tooling.py``).

**No JAX import is required to lint**: every pass here is pure
``ast``/``json`` analysis (``deap_tpu``'s package init is lazy, so
``import deap_tpu.lint`` does not pull the array stack in), and the one
pass that does need a lowering — ``collective-budget`` — is default-off
and shells out to ``tools/check_collective_budget.py``.

Rule catalog (see ``docs/static_analysis.md`` for bad/good examples):

================== ========================================================
``no-bare-print``    library output must route through observability sinks
``no-blocking-sleep`` no ``time.sleep`` / polled ``asyncio.sleep`` in serve/
``lock-discipline``  ``_GUARDED_BY`` attrs written only under their lock
                     (reads too, in return/condition position)
``lock-order``       nested lock acquisitions form a consistent acyclic
                     order per class (static deadlock lint)
``sanitizer-factory`` serve-fleet locks built via ``deap_tpu.sanitize``
                     so the runtime sanitizer can instrument them
``guardedby-coverage`` factory-locked classes declare ``_GUARDED_BY``
``trace-impurity``   host side effects reachable inside traced functions
``rng-key-reuse``    a PRNG key consumed twice without split/fold_in
``tracer-leak``      ``int()``/``bool()``/``if`` on traced values
``bench-json``       committed BENCH/MULTICHIP/budget JSONs match schema
``metric-discipline`` serve metric names snake_case + in the registry
``collective-budget`` HLO collective counts within budget (heavy, opt-in)
``program-contract`` compiled-program contracts: donation, recompile
                     hazards, callbacks under a mesh, per-program
                     budgets via ``deap-tpu-analyze`` (heavy, opt-in)
================== ========================================================
"""

from .core import (Finding, PyFile, Rule, LintContext, LintResult,
                   iter_rules, get_rule, run_lint, rule)
from .baseline import (load_baseline, write_baseline, apply_baseline,
                       DEFAULT_BASELINE)
from .reporters import render_text, render_json, render_sarif

# importing the rule modules registers their passes
from . import rules_repo, rules_jax, rules_data, rules_locks, \
    rules_sanitize  # noqa: F401  (registration)

__all__ = [
    "Finding", "PyFile", "Rule", "LintContext", "LintResult",
    "iter_rules", "get_rule", "run_lint", "rule",
    "load_baseline", "write_baseline", "apply_baseline", "DEFAULT_BASELINE",
    "render_text", "render_json", "render_sarif",
]
