"""Data passes over committed artifacts (not Python source).

``bench-json`` validates the committed benchmark/trajectory JSONs —
``BENCH_*.json`` / ``MULTICHIP_*.json`` / ``tools/collective_budget.json``
— against a small schema, so a malformed benchmark commit fails tier-1
instead of silently breaking the trajectory tooling that diffs them.

``collective-budget`` and ``program-contract`` are the framework
registrations of the two HLO-level gates: both are **default-off**
(select explicitly) because they lower real programs on an
8-virtual-device mesh — the passes that need JAX.  Both shell out in a
subprocess (``tools/check_collective_budget.py`` for the three
weak-scaling layouts; ``deap-tpu-analyze`` for the program-contract
inventory of :mod:`deap_tpu.analysis`), so even selecting them never
imports jax into the linting process.
"""

from __future__ import annotations

import json
import math
import subprocess
import sys
from pathlib import Path
from typing import Iterable, List, Tuple

from .core import Finding, LintContext, rule

__all__ = ["bench_json_targets", "bench_json_findings"]

#: string values that smuggle a non-finite float past JSON (trajectory
#: tooling would coerce them to NaN or crash)
_NAN_STRINGS = {"nan", "-nan", "inf", "-inf", "infinity", "-infinity"}


def bench_json_targets(repo: Path) -> List[Tuple[str, Path]]:
    """(schema kind, path) for every committed artifact the pass owns.
    ``BENCH_TRACE.json`` (the tracing-overhead artifact from
    ``tools/bench_serve.py --net --trace``) gets its own stricter
    schema."""
    out: List[Tuple[str, Path]] = []
    _SPECIAL = {"BENCH_TRACE.json": "trace", "BENCH_MEMORY.json": "memory",
                "BENCH_FLEET.json": "fleet", "BENCH_TSAN.json": "tsan",
                "BENCH_CHAOS.json": "chaos",
                "BENCH_PROFILE.json": "profile",
                "BENCH_MEGAKERNEL.json": "megakernel",
                "BENCH_OOC.json": "ooc",
                "BENCH_PROBE_GA.json": "probe_ga"}
    for p in sorted(repo.glob("BENCH_*.json")):
        if p.name.startswith("BENCH_WEAKSCALING"):
            out.append(("weakscaling", p))
        else:
            out.append((_SPECIAL.get(p.name, "bench"), p))
    for p in sorted(repo.glob("MULTICHIP_*.json")):
        out.append(("multichip", p))
    budget = repo / "tools" / "collective_budget.json"
    if budget.exists():
        out.append(("budget", budget))
    ledger = repo / "PERF_LEDGER.json"
    if ledger.exists():
        out.append(("perf_ledger", ledger))
    return out


def _reject_constant(value: str):
    raise ValueError(f"non-finite JSON constant {value!r}")


def _walk_values(doc, path: str = "$"):
    yield path, doc
    if isinstance(doc, dict):
        for k, v in doc.items():
            yield from _walk_values(v, f"{path}.{k}")
    elif isinstance(doc, list):
        for i, v in enumerate(doc):
            yield from _walk_values(v, f"{path}[{i}]")


def _schema_errors(kind: str, doc) -> List[str]:
    """Schema violations for one parsed document (strings, no lines —
    JSON line numbers are formatting noise)."""
    errors: List[str] = []
    if not isinstance(doc, dict):
        return [f"top level must be a JSON object, got "
                f"{type(doc).__name__}"]

    def require(key, types, typename):
        if key not in doc:
            errors.append(f"required key '{key}' missing")
            return None
        v = doc[key]
        if isinstance(v, bool) or not isinstance(v, types):
            errors.append(f"key '{key}' must be {typename}, got "
                          f"{type(v).__name__}")
            return None
        return v

    if kind == "bench":
        # three committed shapes: a metric record (bench.py JSON line), a
        # raw runner log (n/cmd/rc/tail), or an annotated result document
        # (cmd + result object, e.g. BENCH_WEAKSCALING_*)
        if "metric" in doc:
            require("metric", str, "a string")
            value = require("value", (int, float), "a number")
            require("unit", str, "a string")
            if isinstance(value, float) and not math.isfinite(value):
                errors.append("key 'value' must be finite")
        elif "rc" in doc:
            if not isinstance(doc["rc"], int) or isinstance(doc["rc"], bool):
                errors.append("key 'rc' must be an integer")
            require("tail", str, "a string")
        elif "result" in doc:
            require("cmd", str, "a string")
            if not isinstance(doc["result"], dict):
                errors.append("key 'result' must be an object")
        else:
            errors.append("bench record needs a 'metric'/'value'/'unit' "
                          "triple, an 'rc'/'tail' runner log, or a "
                          "'cmd'/'result' document")
    elif kind == "trace":
        # BENCH_TRACE.json: the tracing-overhead record — a metric triple
        # plus the two loopback latency legs it was computed from, so a
        # malformed commit (missing leg, NaN overhead) fails tier-1
        require("metric", str, "a string")
        value = require("value", (int, float), "a number")
        require("unit", str, "a string")
        if isinstance(value, float) and not math.isfinite(value):
            errors.append("key 'value' must be finite")
        for leg in ("traced", "untraced"):
            sub = doc.get(leg)
            if not isinstance(sub, dict):
                errors.append(f"key '{leg}' must be an object with the "
                              "leg's latency quantiles")
                continue
            p50 = sub.get("roundtrip_p50_ms")
            if isinstance(p50, bool) or not isinstance(p50, (int, float)) \
                    or not math.isfinite(float(p50)):
                errors.append(f"key '{leg}.roundtrip_p50_ms' must be a "
                              "finite number")
    elif kind == "tsan":
        # BENCH_TSAN.json: the concurrency-sanitizer overhead record
        # from ``tools/bench_serve.py --net --tsan`` — a metric triple
        # plus the two interleaved loopback legs (sanitizer armed /
        # off), and the armed leg's violation count, which MUST be zero:
        # the committed artifact doubles as the proof that the real
        # serving drill runs clean under the lockset detector
        require("metric", str, "a string")
        value = require("value", (int, float), "a number")
        require("unit", str, "a string")
        if isinstance(value, float) and not math.isfinite(value):
            errors.append("key 'value' must be finite")
        for leg in ("tsan_on", "tsan_off"):
            sub = doc.get(leg)
            if not isinstance(sub, dict):
                errors.append(f"key '{leg}' must be an object with the "
                              "leg's latency quantiles")
                continue
            p50 = sub.get("roundtrip_p50_ms")
            if isinstance(p50, bool) or not isinstance(p50, (int, float)) \
                    or not math.isfinite(float(p50)):
                errors.append(f"key '{leg}.roundtrip_p50_ms' must be a "
                              "finite number")
        violations = doc.get("violations")
        if isinstance(violations, bool) or not isinstance(violations, int):
            errors.append("key 'violations' must be an integer (the armed "
                          "leg's sanitizer finding count)")
        elif violations != 0:
            errors.append("key 'violations' must be 0 -- the committed "
                          "artifact is the clean-drill proof; a nonzero "
                          "count means the serving fleet raced under the "
                          "sanitizer and must not be committed")
    elif kind == "chaos":
        # BENCH_CHAOS.json: the fleet chaos-drill report from
        # ``deap-tpu-chaosdrill`` — goodput under the canonical fault
        # plan, recovery wall after heal, and the bitwise-survivor
        # verdict, which MUST be true: the committed artifact doubles as
        # the proof that blind retry under the request-leg-only fault
        # plan never double-executed a generation
        goodput = doc.get("goodput_frac")
        if isinstance(goodput, bool) or not isinstance(goodput,
                                                       (int, float)) \
                or not math.isfinite(float(goodput)) \
                or not (0.0 <= float(goodput) <= 1.0):
            errors.append("key 'goodput_frac' must be a finite number in "
                          "[0, 1] (storm successes / attempts)")
        recovery = doc.get("recovery_s")
        if isinstance(recovery, bool) or not isinstance(recovery,
                                                        (int, float)) \
                or not math.isfinite(float(recovery)) or recovery < 0:
            errors.append("key 'recovery_s' must be a finite non-negative "
                          "number (heal-act wall until breakers closed)")
        if doc.get("bitwise_identical") is not True:
            errors.append("key 'bitwise_identical' must be true -- the "
                          "committed artifact is the no-divergence proof; "
                          "anything else means a survivor's trajectory "
                          "diverged from the single-instance reference "
                          "and must not be committed")
        fired = doc.get("faults_injected")
        if not isinstance(fired, dict) or not fired:
            errors.append("key 'faults_injected' must be a non-empty "
                          "object {target: {kind: count}} -- a chaos "
                          "drill that injected nothing proves nothing")
    elif kind == "profile":
        # BENCH_PROFILE.json: the device-phase profiler overhead record
        # from ``tools/bench_serve.py --net --profile`` — a metric
        # triple plus the two interleaved loopback legs (profiler
        # on/off), mirroring the trace/tsan schemas so a malformed
        # commit fails tier-1
        require("metric", str, "a string")
        value = require("value", (int, float), "a number")
        require("unit", str, "a string")
        if isinstance(value, float) and not math.isfinite(value):
            errors.append("key 'value' must be finite")
        for leg in ("profiled", "unprofiled"):
            sub = doc.get(leg)
            if not isinstance(sub, dict):
                errors.append(f"key '{leg}' must be an object with the "
                              "leg's latency quantiles")
                continue
            p50 = sub.get("roundtrip_p50_ms")
            if isinstance(p50, bool) or not isinstance(p50, (int, float)) \
                    or not math.isfinite(float(p50)):
                errors.append(f"key '{leg}.roundtrip_p50_ms' must be a "
                              "finite number")
        programs = doc.get("programs_profiled")
        if isinstance(programs, bool) or not isinstance(programs, int) \
                or programs < 1:
            errors.append("key 'programs_profiled' must be a positive "
                          "integer (the profiled legs must actually have "
                          "profiled something)")
    elif kind == "megakernel":
        # BENCH_MEGAKERNEL.json: the fused-generation before/after from
        # tools/bench_megakernel.py — interleaved XLA-vs-Pallas legs
        # plus the mixed-precision traffic fractions; a malformed
        # commit (missing leg, non-finite wall, savings outside [0,1])
        # fails tier-1 before the perf ledger reads it
        require("cmd", str, "a string")
        res = doc.get("result")
        if not isinstance(res, dict):
            errors.append("key 'result' must be an object")
        else:
            for leg in ("xla_f32", "mega_f32", "mega_bf16",
                        "sharded_f32", "mupl_xla_f32", "mupl_f32"):
                sub = res.get(leg)
                if not isinstance(sub, dict):
                    errors.append(f"result.{leg} must be an object with "
                                  "the leg's per-generation wall")
                    continue
                pg = sub.get("per_gen_ms")
                if isinstance(pg, bool) or not isinstance(pg, (int, float)) \
                        or not math.isfinite(float(pg)) or pg <= 0:
                    errors.append(f"result.{leg}.per_gen_ms must be a "
                                  "finite positive number")
            # the sharded leg doubles as the cross-device proof: its
            # committed run re-verifies winner indices + genome bits
            # against the single-device fused path in-process, so
            # anything but true means the sharded generation diverged
            # and must not be committed (the bench-ooc discipline)
            sharded = res.get("sharded_f32")
            if isinstance(sharded, dict):
                if sharded.get("bitwise_identical") is not True:
                    errors.append("result.sharded_f32.bitwise_identical "
                                  "must be true -- the committed sharded "
                                  "leg is the device-count-invariance "
                                  "proof; anything else means the "
                                  "sharded generation diverged and must "
                                  "not be committed")
                nd = sharded.get("n_devices")
                if isinstance(nd, bool) or not isinstance(nd, int) \
                        or nd < 2:
                    errors.append("result.sharded_f32.n_devices must be "
                                  "an integer >= 2 (a sharded leg timed "
                                  "on one device is not a sharded leg)")
            for key in ("speedup_mega_f32", "bf16_traffic_savings_frac",
                        "speedup_sharded_f32", "speedup_mupl_f32"):
                v = res.get(key)
                if isinstance(v, bool) or not isinstance(v, (int, float)) \
                        or not math.isfinite(float(v)):
                    errors.append(f"result.{key} must be a finite number")
            frac = res.get("bf16_traffic_savings_frac")
            if isinstance(frac, (int, float)) and not isinstance(frac, bool) \
                    and math.isfinite(float(frac)) \
                    and not (0.0 <= float(frac) <= 1.0):
                errors.append("result.bf16_traffic_savings_frac must lie "
                              "in [0, 1] (a fraction of argument traffic)")
    elif kind == "ooc":
        # BENCH_OOC.json: the out-of-core crossover study from
        # tools/bench_ooc.py — resident-vs-streamed gens/sec across a
        # population sweep.  The committed artifact doubles as the
        # bitwise proof: a streamed generation at pop=N must equal the
        # resident generation at pop=N bit for bit, so
        # ``bitwise_identical`` anything but true must not be committed
        require("cmd", str, "a string")
        res = doc.get("result")
        if not isinstance(res, dict):
            errors.append("key 'result' must be an object")
        else:
            legs = res.get("legs")
            if not isinstance(legs, list) or not legs:
                errors.append("result.legs must be a non-empty list of "
                              "per-population legs")
            else:
                for i, leg in enumerate(legs):
                    if not isinstance(leg, dict):
                        errors.append(f"result.legs[{i}] must be an object")
                        continue
                    pop = leg.get("pop")
                    if isinstance(pop, bool) or not isinstance(pop, int) \
                            or pop < 1:
                        errors.append(f"result.legs[{i}].pop must be a "
                                      "positive integer")
                    sg = leg.get("streamed_gens_per_sec")
                    if isinstance(sg, bool) \
                            or not isinstance(sg, (int, float)) \
                            or not math.isfinite(float(sg)) or sg <= 0:
                        errors.append(f"result.legs[{i}]."
                                      "streamed_gens_per_sec must be a "
                                      "finite positive number")
                    rg = leg.get("resident_gens_per_sec")
                    if rg is not None and (
                            isinstance(rg, bool)
                            or not isinstance(rg, (int, float))
                            or not math.isfinite(float(rg)) or rg <= 0):
                        errors.append(f"result.legs[{i}]."
                                      "resident_gens_per_sec must be a "
                                      "finite positive number, or null "
                                      "when the resident run does not "
                                      "fit device memory")
            if res.get("bitwise_identical") is not True:
                errors.append("result.bitwise_identical must be true -- "
                              "the committed artifact is the "
                              "streamed==resident proof; anything else "
                              "means a streamed generation diverged and "
                              "must not be committed")
            xover = res.get("crossover_pop")
            if xover is not None and (isinstance(xover, bool)
                                      or not isinstance(xover, int)
                                      or xover < 1):
                errors.append("result.crossover_pop must be a positive "
                              "integer (smallest benched pop where "
                              "streamed beats resident) or null when "
                              "streamed never wins on this host")
    elif kind == "probe_ga":
        # BENCH_PROBE_GA.json: the committed stage-budget report from
        # tools/pallas_probe_ga.py --json — per-probe marginal walls +
        # linearity witnesses; probes the backend cannot run must land
        # in 'errors' (never as fabricated rows)
        require("cmd", str, "a string")
        res = doc.get("result")
        if not isinstance(res, dict):
            errors.append("key 'result' must be an object")
        else:
            for key in ("pop", "dim"):
                v = res.get(key)
                if isinstance(v, bool) or not isinstance(v, int) or v < 1:
                    errors.append(f"result.{key} must be a positive "
                                  "integer")
            probes = res.get("probes")
            if not isinstance(probes, list) or not probes:
                errors.append("result.probes must be a non-empty list of "
                              "probe records")
            else:
                for i, row in enumerate(probes):
                    if not isinstance(row, dict) \
                            or not isinstance(row.get("probe"), str):
                        errors.append(f"result.probes[{i}] must be an "
                                      "object with a 'probe' name")
                        continue
                    for key in ("ms", "linearity_t2k_over_tk"):
                        v = row.get(key)
                        if isinstance(v, bool) \
                                or not isinstance(v, (int, float)) \
                                or not math.isfinite(float(v)):
                            errors.append(
                                f"result.probes[{i}].{key} must be a "
                                "finite number")
            errs = res.get("errors")
            if not isinstance(errs, list):
                errors.append("result.errors must be a list (probes the "
                              "backend could not run)")
            else:
                for i, row in enumerate(errs):
                    if not isinstance(row, dict) \
                            or not isinstance(row.get("probe"), str) \
                            or not isinstance(row.get("error"), str):
                        errors.append(f"result.errors[{i}] must be "
                                      "{'probe': str, 'error': str}")
    elif kind == "weakscaling":
        # BENCH_WEAKSCALING_r*.json: the partition-overhead study from
        # bench_weakscaling.py — per-layout same-total-size walls on a
        # 1- vs N-device mesh plus the compiled collective inventory.
        # The perfgate rows mo_weak_scaling_overhead / mo_grid_overhead /
        # hypervolume_pts_per_sec read the LATEST artifact by glob, so a
        # malformed commit breaks the perf gate; -1 is the harness
        # convention for a failed linearity gate (never fabricate a
        # number), everything else must be finite and positive
        require("cmd", str, "a string")
        res = doc.get("result")
        if not isinstance(res, dict):
            errors.append("key 'result' must be an object")
        else:
            layouts = res.get("layouts")
            if not isinstance(layouts, dict) or not layouts:
                errors.append("result.layouts must be a non-empty object "
                              "{layout: row}")
                layouts = {}
            for name, row in layouts.items():
                if not isinstance(row, dict):
                    errors.append(f"result.layouts[{name!r}] must be an "
                                  "object")
                    continue
                for k, v in row.items():
                    if not (k.endswith("_per_gen_ms")
                            or k in ("overhead_factor", "pts_per_sec")):
                        continue
                    bad = (isinstance(v, bool)
                           or not isinstance(v, (int, float))
                           or not math.isfinite(float(v)))
                    if not bad and k.endswith("_per_gen_ms"):
                        bad = v <= 0
                    elif not bad:
                        bad = v <= 0 and v != -1
                    if bad:
                        errors.append(
                            f"result.layouts[{name!r}].{k} must be a "
                            "finite positive number (or the harness "
                            "convention -1 for a failed linearity gate "
                            "on derived metrics)")
                for ck in ("collectives_in_hlo", "collective_ops_in_hlo"):
                    ops = row.get(ck)
                    if ops is None:
                        continue
                    if not isinstance(ops, dict):
                        errors.append(f"result.layouts[{name!r}].{ck} "
                                      "must be an object "
                                      "{collective: count}")
                        continue
                    for op, count in ops.items():
                        if isinstance(count, bool) \
                                or not isinstance(count, int) or count < 0:
                            errors.append(
                                f"result.layouts[{name!r}].{ck}[{op!r}] "
                                "must be a non-negative integer")
                if name == "mo_grid" \
                        and row.get("bitwise_identical") is not True:
                    errors.append(
                        "result.layouts['mo_grid'].bitwise_identical "
                        "must be true -- the committed grid leg doubles "
                        "as the sharded==single-chip index proof; "
                        "anything else means the sharded grid selection "
                        "diverged and must not be committed")
    elif kind == "perf_ledger":
        # PERF_LEDGER.json: the perf-regression ledger deap-tpu-perfgate
        # enforces — one schema, two gates (deap_tpu.perfledger is the
        # shared jax-free validator): finite metrics, band in (0, 1],
        # provenance required, baseline/history well-formed
        from ..perfledger import ledger_schema_errors
        errors.extend(ledger_schema_errors(doc))
    elif kind == "memory":
        # BENCH_MEMORY.json: the footprint-trajectory record from
        # tools/bench_memory.py — runner status (int rc / bool ok) plus
        # entry-keyed rows of finite non-negative byte counts, so a
        # malformed commit fails tier-1 before the trajectory tooling
        # (or the memory-budget gate's cross-check) chokes on it
        if not isinstance(doc.get("rc"), int) or isinstance(doc.get("rc"),
                                                            bool):
            errors.append("key 'rc' must be an integer")
        if not isinstance(doc.get("ok"), bool):
            errors.append("key 'ok' must be a boolean")
        rows = doc.get("entries")
        if not isinstance(rows, dict) or not rows:
            errors.append("key 'entries' must be a non-empty object "
                          "{program name: {metric: bytes}}")
        else:
            for name, row in rows.items():
                if not isinstance(row, dict):
                    errors.append(f"entries[{name!r}] must be an object")
                    continue
                for k, v in row.items():
                    if k.endswith("_bytes"):
                        if isinstance(v, bool) or not isinstance(v, int) \
                                or v < 0:
                            errors.append(
                                f"entries[{name!r}][{k!r}] must be a "
                                "non-negative integer byte count")
                    elif isinstance(v, float) and not math.isfinite(v):
                        errors.append(
                            f"entries[{name!r}][{k!r}] must be finite")
    elif kind == "fleet":
        # BENCH_FLEET.json: the router-tier scale proof from
        # tools/bench_fleet.py — runner status plus the three fleet
        # metrics the drill claims (per-instance throughput, failover
        # recovery, tenant fairness), each pinned finite so a malformed
        # commit fails tier-1 before the trajectory tooling reads it
        if not isinstance(doc.get("rc"), int) or isinstance(doc.get("rc"),
                                                            bool):
            errors.append("key 'rc' must be an integer")
        if not isinstance(doc.get("ok"), bool):
            errors.append("key 'ok' must be a boolean")
        sessions = doc.get("sessions")
        if isinstance(sessions, bool) or not isinstance(sessions, int) \
                or sessions < 1:
            errors.append("key 'sessions' must be a positive integer "
                          "(the remote-session count the loadgen drove)")
        per = doc.get("per_instance_throughput")
        if not isinstance(per, dict) or not per:
            errors.append("key 'per_instance_throughput' must be a "
                          "non-empty object {instance: steps_per_s}")
        else:
            for inst, v in per.items():
                if isinstance(v, bool) or not isinstance(v, (int, float)) \
                        or not math.isfinite(float(v)) or v < 0:
                    errors.append(
                        f"per_instance_throughput[{inst!r}] must be a "
                        "finite non-negative number")
        for key in ("failover_recovery_s", "tenant_fairness_ratio"):
            v = doc.get(key)
            if isinstance(v, bool) or not isinstance(v, (int, float)) \
                    or not math.isfinite(float(v)) or v < 0:
                errors.append(f"key '{key}' must be a finite non-negative "
                              "number")
        # the elastic leg (tools/bench_fleet.py --elastic): autoscaler
        # scale-out/in walls, live-migration downtime and the
        # fleet-rebalance wall — the perfgate rows fleet_migration_s /
        # fleet_rebalance_s read these paths, so they must be present
        # and finite in every committed artifact
        elastic = doc.get("elastic")
        if not isinstance(elastic, dict):
            errors.append("key 'elastic' must be an object (run "
                          "tools/bench_fleet.py with --elastic)")
        else:
            for key in ("scale_out_s", "migration_downtime_s",
                        "rebalance_s", "scale_in_s"):
                v = elastic.get(key)
                if isinstance(v, bool) or not isinstance(v, (int, float)) \
                        or not math.isfinite(float(v)) or v < 0:
                    errors.append(f"elastic[{key!r}] must be a finite "
                                  "non-negative number")
            m = elastic.get("migrations")
            if isinstance(m, bool) or not isinstance(m, int) or m < 1:
                errors.append("elastic['migrations'] must be a positive "
                              "integer")
    elif kind == "multichip":
        if not isinstance(doc.get("rc"), int) or isinstance(doc.get("rc"),
                                                            bool):
            errors.append("key 'rc' must be an integer")
        if not isinstance(doc.get("ok"), bool):
            errors.append("key 'ok' must be a boolean")
    elif kind == "budget":
        n_dev = doc.get("n_devices")
        if not isinstance(n_dev, int) or isinstance(n_dev, bool):
            errors.append("key 'n_devices' must be an integer")
        budget = doc.get("budget")
        if not isinstance(budget, dict):
            errors.append("key 'budget' must be an object "
                          "{layout: {collective: count}}")
        else:
            for layout, ops in budget.items():
                if not isinstance(ops, dict):
                    errors.append(f"budget[{layout!r}] must be an object")
                    continue
                for op, count in ops.items():
                    if not isinstance(count, int) or isinstance(count, bool) \
                            or count < 0:
                        errors.append(f"budget[{layout!r}][{op!r}] must be "
                                      "a non-negative integer")
        if not isinstance(doc.get("shapes"), dict):
            errors.append("key 'shapes' must be an object")

    # universal: no NaN smuggled as a string where a number belongs
    for vpath, v in _walk_values(doc):
        if isinstance(v, str) and v.strip().lower() in _NAN_STRINGS:
            errors.append(f"{vpath} is the string {v!r} -- a non-finite "
                          "number must not be committed as a string")
    return errors


def bench_json_findings(repo: Path) -> List[Finding]:
    findings: List[Finding] = []
    for kind, path in bench_json_targets(repo):
        rel = path.relative_to(repo).as_posix()
        try:
            doc = json.loads(path.read_text(),
                             parse_constant=_reject_constant)
        except ValueError as e:
            findings.append(Finding(
                rule="bench-json", path=rel, line=1,
                message=f"invalid JSON: {e}"))
            continue
        for err in _schema_errors(kind, doc):
            findings.append(Finding(
                rule="bench-json", path=rel, line=1,
                message=f"schema violation ({kind} record): {err}"))
    return findings


@rule("bench-json",
      "committed BENCH_*/MULTICHIP_*/collective_budget JSONs must parse "
      "(no NaN/Infinity constants) and match their record schema")
def _check_bench_json(ctx: LintContext) -> Iterable[Finding]:
    return bench_json_findings(ctx.repo)


@rule("program-contract",
      "program-level contracts of the compiled inventory (donation "
      "leaks, recompile hazards, callbacks under a mesh, per-program "
      "collective budgets) via deap-tpu-analyze (heavy: lowers the "
      "inventory on an 8-device virtual mesh; select explicitly)",
      default=False)
def _check_program_contract(ctx: LintContext) -> Iterable[Finding]:
    """Framework registration of :mod:`deap_tpu.analysis` — like
    ``collective-budget``, it shells out so that even selecting it never
    imports jax into the linting process.  The subprocess's JSON
    findings re-surface here with their sub-rule folded into the
    message, so they ride the same reporters/baseline machinery as
    every AST finding."""
    out = subprocess.run(
        [sys.executable, "-m", "deap_tpu.analysis.cli", "--format",
         "json"],
        capture_output=True, text=True, timeout=600, cwd=str(ctx.repo))
    try:
        report = json.loads(out.stdout)
    except ValueError:
        tail = (out.stderr or out.stdout).strip().splitlines()[-3:]
        yield Finding(rule="program-contract",
                      path="deap_tpu/analysis", line=1,
                      message=("program-contract analyzer failed (rc="
                               f"{out.returncode}): " + "; ".join(tail)))
        return
    for f in report.get("findings", []):
        yield Finding(rule="program-contract", path=f["path"],
                      line=int(f.get("line", 1)),
                      message=f"[{f['rule']}] {f['message']}")


@rule("collective-budget",
      "HLO collective instruction counts of the three weak-scaling "
      "layouts must stay within tools/collective_budget.json (heavy: "
      "lowers on an 8-device virtual mesh; select explicitly)",
      default=False)
def _check_collective_budget(ctx: LintContext) -> Iterable[Finding]:
    script = ctx.repo / "tools" / "check_collective_budget.py"
    if not script.exists():
        yield Finding(rule="collective-budget", path="tools", line=1,
                      message="tools/check_collective_budget.py missing -- "
                              "the collective-budget gate lost its "
                              "implementation")
        return
    out = subprocess.run([sys.executable, str(script)],
                         capture_output=True, text=True, timeout=600)
    if out.returncode == 0:
        return
    tail = (out.stderr or out.stdout).strip().splitlines()
    # the script prints one "COLLECTIVE BUDGET EXCEEDED — ..." line per
    # violation on stderr; surface each as its own finding
    breaches = [ln for ln in tail if "COLLECTIVE BUDGET" in ln]
    if breaches:
        for ln in breaches:
            yield Finding(rule="collective-budget",
                          path="tools/collective_budget.json", line=1,
                          message=ln.strip())
    else:
        yield Finding(rule="collective-budget",
                      path="tools/collective_budget.json", line=1,
                      message=("collective budget gate failed (rc="
                               f"{out.returncode}): "
                               + "; ".join(tail[-3:])))
