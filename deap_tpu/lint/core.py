"""Framework core: findings, parsed files, the rule registry, the engine.

Design contract (what every pass can rely on):

* a :class:`PyFile` is created once per source file per run; its
  ``tree`` property parses lazily and caches, so N passes over M files
  cost exactly M ``ast.parse`` calls;
* passes share per-file derived analysis through ``PyFile.cache`` (the
  JAX passes memoize their traced-function set there);
* suppression comments are resolved by the *engine*, not by passes —
  a pass only reports, and ``# lint: disable=<rule> -- reason`` on the
  finding's line retires it (counted, never silently dropped);
* pure stdlib: importing this module (or running any default pass) must
  never import jax — linting has to work on a box with no accelerator
  stack at all, and has to stay fast enough for tier-1.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import re
from pathlib import Path
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = ["REPO", "Finding", "PyFile", "Rule", "LintContext", "LintResult",
           "rule", "iter_rules", "get_rule", "run_lint"]

#: repository root (deap_tpu/lint/core.py -> repo)
REPO = Path(__file__).resolve().parents[2]

#: directories never collected (anywhere in the path)
EXCLUDED_DIRS = {"__pycache__", ".git", ".pytest_cache", ".ruff_cache",
                 "node_modules", ".venv", "venv", ".eggs", "build", "dist"}


@dataclasses.dataclass(frozen=True)
class Finding:
    """One diagnostic: ``rule`` id, repo-relative ``path``, 1-based
    ``line``, and a *stable* message (no line numbers inside the message
    — the baseline fingerprints ``rule + path + message``, and messages
    that drift with unrelated edits would churn the baseline)."""

    rule: str
    path: str
    line: int
    message: str
    severity: str = "error"
    col: int = 0

    @property
    def location(self) -> str:
        return f"{self.path}:{self.line}"

    def fingerprint(self) -> str:
        """Line-independent identity used by the baseline: findings move
        with their code, they don't expire because a neighbor edit
        shifted line numbers."""
        raw = f"{self.rule}::{self.path}::{self.message}"
        return hashlib.blake2b(raw.encode(), digest_size=8).hexdigest()

    def as_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "severity": self.severity,
                "message": self.message,
                "fingerprint": self.fingerprint()}


_SUPPRESS_RE = re.compile(
    r"#\s*lint:\s*disable=([A-Za-z0-9_,\-]+)(?:\s+--\s*(\S.*))?")


class PyFile:
    """One Python source file: text read once, AST parsed once (lazily,
    shared by every pass through this object), suppression comments
    mapped by line, and a free-form ``cache`` dict for passes to memoize
    derived per-file analysis into."""

    def __init__(self, path: Path, repo: Path = REPO):
        self.path = Path(path)
        self.repo = Path(repo)
        try:
            self.rel = self.path.resolve().relative_to(
                self.repo.resolve()).as_posix()
        except ValueError:
            # explicit path outside the repo root: lint it under its
            # absolute name (repo-scoped rules simply won't match it)
            self.rel = self.path.resolve().as_posix()
        self.text = self.path.read_text()
        self.lines = self.text.splitlines()
        self.cache: dict = {}
        self._tree: Optional[ast.AST] = None
        self._parse_error: Optional[SyntaxError] = None
        self._suppress: Optional[Dict[int, Tuple[frozenset, Optional[str]]]] \
            = None

    @property
    def tree(self) -> Optional[ast.Module]:
        """The module AST (parsed on first access, ``None`` if the file
        does not parse — the engine reports that as a ``parse-error``
        finding so passes can just skip it)."""
        if self._tree is None and self._parse_error is None:
            try:
                self._tree = ast.parse(self.text, filename=str(self.path))
            except SyntaxError as e:
                self._parse_error = e
        return self._tree

    @property
    def parse_error(self) -> Optional[SyntaxError]:
        self.tree  # force the parse attempt
        return self._parse_error

    def _suppressions(self) -> Dict[int, Tuple[frozenset, Optional[str]]]:
        if self._suppress is None:
            out: Dict[int, Tuple[frozenset, Optional[str]]] = {}
            for i, line in enumerate(self.lines, start=1):
                if "lint:" not in line:
                    continue
                m = _SUPPRESS_RE.search(line)
                if m:
                    rules = frozenset(
                        r.strip() for r in m.group(1).split(",") if r.strip())
                    out[i] = (rules, m.group(2))
            self._suppress = out
        return self._suppress

    def suppressed(self, line: int, rule_name: str) -> bool:
        """True iff ``line`` carries ``# lint: disable=`` naming
        ``rule_name`` (or ``all``)."""
        entry = self._suppressions().get(line)
        if entry is None:
            return False
        rules, _reason = entry
        return rule_name in rules or "all" in rules

    def suppression_reason(self, line: int) -> Optional[str]:
        entry = self._suppressions().get(line)
        return entry[1] if entry else None


@dataclasses.dataclass(frozen=True)
class Rule:
    """One registered pass.  ``check(ctx)`` yields :class:`Finding`\\ s;
    ``default=False`` marks heavy opt-in passes (run only via
    ``--select``, e.g. the HLO-lowering collective budget)."""

    name: str
    doc: str
    check: Callable[["LintContext"], Iterable[Finding]]
    severity: str = "error"
    default: bool = True


_REGISTRY: Dict[str, Rule] = {}


def rule(name: str, doc: str, *, severity: str = "error",
         default: bool = True):
    """Decorator registering a pass under ``name``."""
    def deco(fn):
        if name in _REGISTRY:
            raise ValueError(f"lint rule {name!r} registered twice")
        _REGISTRY[name] = Rule(name=name, doc=doc, check=fn,
                               severity=severity, default=default)
        return fn
    return deco


def iter_rules() -> List[Rule]:
    return [_REGISTRY[k] for k in sorted(_REGISTRY)]


def get_rule(name: str) -> Rule:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(f"unknown lint rule {name!r} "
                       f"(have: {', '.join(sorted(_REGISTRY))})") from None


class LintContext:
    """One run's shared state: the collected :class:`PyFile` set (built
    once, reused by every pass) and the repo root data passes resolve
    their committed files against."""

    def __init__(self, repo: Path = REPO,
                 paths: Optional[Sequence[Path]] = None):
        self.repo = Path(repo)
        #: True when the caller restricted the scanned paths — coverage
        #: pins (``serve/net must contribute files``) only apply to
        #: whole-repo runs
        self.path_restricted = bool(paths)
        self.py_files: List[PyFile] = []
        seen = set()
        for p in self._collect(paths):
            rp = p.resolve()
            if rp not in seen:
                seen.add(rp)
                self.py_files.append(PyFile(p, repo=self.repo))
        self.by_rel: Dict[str, PyFile] = {pf.rel: pf for pf in self.py_files}

    def _collect(self, paths: Optional[Sequence[Path]]) -> List[Path]:
        roots = [Path(p) for p in paths] if paths else [self.repo]
        out: List[Path] = []
        for root in roots:
            if root.is_file():
                out.append(root)
                continue
            for p in sorted(root.rglob("*.py")):
                if not any(part in EXCLUDED_DIRS or part.startswith(".")
                           for part in p.relative_to(root).parts):
                    out.append(p)
        return out

    def files_under(self, *prefixes: str) -> List[PyFile]:
        """The run's files whose repo-relative path starts with any of
        ``prefixes`` (all files when none given)."""
        if not prefixes:
            return list(self.py_files)
        return [pf for pf in self.py_files
                if any(pf.rel.startswith(pre) for pre in prefixes)]


@dataclasses.dataclass
class LintResult:
    """Outcome of one engine run.  ``findings`` are the live (non-
    suppressed, non-baselined) diagnostics the gate fails on;
    ``baselined``/``suppressed`` are retired-but-counted; ``expired``
    are baseline entries that no longer fire (clean them up with
    ``--update-baseline``)."""

    findings: List[Finding]
    suppressed: List[Finding]
    baselined: List[Finding]
    expired: List[dict]
    rules_run: List[str]
    files_scanned: int

    @property
    def exit_code(self) -> int:
        return 1 if self.findings else 0


def _select_rules(select: Optional[Sequence[str]],
                  ignore: Optional[Sequence[str]]) -> List[Rule]:
    if select:
        rules = [get_rule(n) for n in select]
    else:
        rules = [r for r in iter_rules() if r.default]
    if ignore:
        for n in ignore:
            get_rule(n)  # typo check
        rules = [r for r in rules if r.name not in set(ignore)]
    return rules


def run_lint(*, repo: Path = REPO, paths: Optional[Sequence[Path]] = None,
             select: Optional[Sequence[str]] = None,
             ignore: Optional[Sequence[str]] = None,
             baseline: Optional[dict] = None) -> LintResult:
    """Run the selected passes (default: every ``default=True`` rule)
    over ``paths`` (default: the whole repo) and partition the findings
    against ``baseline`` (a :func:`~deap_tpu.lint.baseline.load_baseline`
    dict; ``None`` = no baseline)."""
    ctx = LintContext(repo=repo, paths=paths)
    rules = _select_rules(select, ignore)

    raw: List[Finding] = []
    for pf in ctx.py_files:
        if pf.parse_error is not None:
            e = pf.parse_error
            raw.append(Finding(
                rule="parse-error", path=pf.rel, line=e.lineno or 1,
                message=f"file does not parse: {e.msg}"))
    for r in rules:
        for f in r.check(ctx):
            raw.append(f)

    findings: List[Finding] = []
    suppressed: List[Finding] = []
    for f in raw:
        pf = ctx.by_rel.get(f.path)
        if pf is not None and pf.suppressed(f.line, f.rule):
            suppressed.append(f)
        else:
            findings.append(f)

    baselined: List[Finding] = []
    expired: List[dict] = []
    if baseline:
        from .baseline import apply_baseline
        findings, baselined, expired = apply_baseline(findings, baseline)
        if ctx.path_restricted:
            # a partial scan (explicit paths / --changed) cannot tell
            # whether an entry in an UNSCANNED file still fires — only
            # entries whose file was actually scanned may be reported
            # expired, or every pre-commit run would nag to
            # --update-baseline over files it never looked at
            expired = [e for e in expired if e.get("path") in ctx.by_rel]

    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return LintResult(findings=findings, suppressed=suppressed,
                      baselined=baselined, expired=expired,
                      rules_run=[r.name for r in rules],
                      files_scanned=len(ctx.py_files))
