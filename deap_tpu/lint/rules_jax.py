"""JAX trace-safety passes: ``trace-impurity``, ``rng-key-reuse``,
``tracer-leak``.

All three share one per-file analysis (memoized in ``PyFile.cache``):
an import-alias map (which local names mean ``jax``, ``jax.random``,
``jax.lax``, ``numpy``, stdlib ``random``/``time``/``datetime``) and the
**traced-function set** — every function that JAX will retrace:

* decorated ``@jax.jit`` / ``@jit`` / ``@partial(jax.jit, ...)`` /
  ``@jax.vmap`` / ``@jax.pmap``;
* referenced by name at a tracing position of a call anywhere in the
  module: ``jit(f)``, ``vmap(f)``, ``pmap(f)``, ``lax.scan(f, ...)``,
  ``lax.map(f, ...)``, ``lax.fori_loop(_, _, f, ...)``,
  ``lax.while_loop(c, b, ...)``, ``lax.cond(_, t, f, ...)``,
  ``lax.switch(_, [f, ...])``, ``jax.checkpoint(f)`` (lambdas at those
  positions count too);
* lexically nested inside, or called by name from, a traced function
  (one-module transitive closure — a helper inlined into a trace
  inherits its constraints).

Functions referenced as **host callbacks** (``io_callback`` /
``pure_callback`` / ``jax.debug.callback`` positions) are explicitly
exempt: they run on the host by design, so host side effects there are
the point, not a hazard.

The analysis is purely lexical and module-local — it never imports the
linted code and never imports jax.  Cross-module tracing (a toolbox
registered callable traced by another module's scan) is out of scope;
the baseline/suppression machinery absorbs the residue.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .core import Finding, LintContext, PyFile, rule

__all__ = ["JaxNames", "jax_names", "traced_functions",
           "trace_impurity_findings", "rng_key_reuse_findings",
           "tracer_leak_findings", "JAX_RULE_EXCLUDED_PREFIXES"]

#: paths the three JAX passes skip by default: tests deliberately reuse
#: keys (determinism assertions: same key twice must give the same
#: bits), so running the RNG pass there would flag the test suite's
#: most legitimate pattern
JAX_RULE_EXCLUDED_PREFIXES = ("tests/",)


# ---------------------------------------------------------------------------
# shared per-file analysis

@dataclasses.dataclass
class JaxNames:
    """Local spellings of the modules the passes care about."""
    jax: Set[str]
    jax_random: Set[str]          # names aliasing the jax.random MODULE
    jax_random_funcs: Dict[str, str]  # local name -> jax.random function
    lax: Set[str]
    lax_funcs: Dict[str, str]     # local name -> lax function
    jit_like: Set[str]            # local names for jit/vmap/pmap/checkpoint
    numpy: Set[str]
    numpy_random: Set[str]        # names aliasing the np.random MODULE
    std_random: Set[str]          # names aliasing STDLIB random module
    std_random_funcs: Set[str]    # from random import randint, ...
    time: Set[str]
    time_funcs: Set[str]          # from time import time/perf_counter/...
    datetime_mod: Set[str]
    datetime_cls: Set[str]        # from datetime import datetime/date
    partial: Set[str]             # functools / partial spellings
    callback_funcs: Set[str]      # io_callback/pure_callback local names


_JIT_LIKE = {"jit", "vmap", "pmap", "checkpoint", "remat"}
_CALLBACKS = {"io_callback", "pure_callback", "callback"}
_TIME_FUNCS = {"time", "perf_counter", "monotonic", "process_time",
               "time_ns", "perf_counter_ns", "monotonic_ns", "sleep",
               "ctime", "localtime", "gmtime"}


def jax_names(pf: PyFile) -> JaxNames:
    """Import-alias map for ``pf`` (memoized in ``pf.cache``)."""
    if "jax_names" in pf.cache:
        return pf.cache["jax_names"]
    jn = JaxNames(jax=set(), jax_random=set(), jax_random_funcs={},
                  lax=set(), lax_funcs={}, jit_like=set(), numpy=set(),
                  numpy_random=set(), std_random=set(),
                  std_random_funcs=set(), time=set(), time_funcs=set(),
                  datetime_mod=set(), datetime_cls=set(), partial=set(),
                  callback_funcs=set())
    jn.partial.add("functools")
    tree = pf.tree
    if tree is None:
        pf.cache["jax_names"] = jn
        return jn
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                name = a.asname or a.name.split(".")[0]
                if a.name == "jax":
                    jn.jax.add(name)
                elif a.name == "jax.random":
                    if a.asname:
                        jn.jax_random.add(a.asname)
                    else:   # plain `import jax.random` binds `jax`
                        jn.jax.add("jax")
                elif a.name == "jax.numpy":
                    pass
                elif a.name == "jax.lax":
                    if a.asname:
                        jn.lax.add(a.asname)
                    else:
                        jn.jax.add("jax")
                elif a.name == "numpy":
                    jn.numpy.add(name)
                elif a.name == "numpy.random":
                    jn.numpy_random.add(a.asname or "numpy")
                elif a.name == "random":
                    jn.std_random.add(name)
                elif a.name == "time":
                    jn.time.add(name)
                elif a.name == "datetime":
                    jn.datetime_mod.add(name)
                elif a.name == "functools":
                    jn.partial.add(name)
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            for a in node.names:
                local = a.asname or a.name
                if mod == "jax":
                    if a.name == "random":
                        jn.jax_random.add(local)
                    elif a.name == "lax":
                        jn.lax.add(local)
                    elif a.name == "numpy":
                        pass
                    elif a.name in _JIT_LIKE:
                        jn.jit_like.add(local)
                elif mod == "jax.random":
                    jn.jax_random_funcs[local] = a.name
                elif mod in ("jax.lax", "jax.experimental"):
                    if a.name in _CALLBACKS:
                        jn.callback_funcs.add(local)
                    else:
                        jn.lax_funcs[local] = a.name
                elif mod == "jax.experimental.io_callback":
                    jn.callback_funcs.add(local)
                elif mod == "numpy":
                    if a.name == "random":
                        jn.numpy_random.add(local)
                elif mod == "random":
                    jn.std_random_funcs.add(local)
                elif mod == "time":
                    jn.time_funcs.add(local)
                elif mod == "datetime":
                    if a.name in ("datetime", "date"):
                        jn.datetime_cls.add(local)
                elif mod == "functools":
                    if a.name == "partial":
                        jn.partial.add(local)
    pf.cache["jax_names"] = jn
    return jn


def _attr_chain(node: ast.AST) -> Optional[List[str]]:
    """``a.b.c`` → ["a", "b", "c"], None for non-name-rooted chains."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        parts.reverse()
        return parts
    return None


def _lax_func_of(func: ast.AST, jn: JaxNames) -> Optional[str]:
    """The ``jax.lax`` function name a call target spells, if any."""
    chain = _attr_chain(func)
    if chain is None:
        return None
    if len(chain) == 1:
        return jn.lax_funcs.get(chain[0])
    if len(chain) == 2 and chain[0] in jn.lax:
        # `from jax import lax; lax.scan` OR `import jax.lax; jax.lax...`
        # (the latter lands here only as ["jax","lax"] root, 3 parts)
        return chain[1]
    if len(chain) == 3 and chain[0] in jn.jax and chain[1] == "lax":
        return chain[2]
    return None


def _jit_like_of(func: ast.AST, jn: JaxNames) -> Optional[str]:
    """"jit"/"vmap"/"pmap"/"checkpoint" when the call target spells one."""
    chain = _attr_chain(func)
    if chain is None:
        return None
    if len(chain) == 1 and chain[0] in jn.jit_like:
        return chain[0]
    if len(chain) == 2 and chain[0] in jn.jax and chain[1] in _JIT_LIKE:
        return chain[1]
    return None


def _callback_of(func: ast.AST, jn: JaxNames) -> bool:
    """True when the call target is a host-callback entry (io_callback /
    pure_callback / jax.debug.callback / jax.experimental.io_callback)."""
    chain = _attr_chain(func)
    if chain is None:
        return False
    if len(chain) == 1:
        return chain[0] in jn.callback_funcs
    if chain[0] in jn.jax:
        tail = chain[1:]
        if tail[-1] in _CALLBACKS:
            return True
    return False


def _static_params_of(call: ast.Call) -> Tuple[Set[str], Set[int]]:
    """``static_argnames``/``static_argnums`` literals from a jit-like
    call's keywords (``jax.jit(f, static_argnums=0)``,
    ``@partial(jax.jit, static_argnames=("method",))``) — those
    parameters are Python values at trace time, never tracers."""
    names: Set[str] = set()
    nums: Set[int] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            v = kw.value
            elts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            for el in elts:
                if isinstance(el, ast.Constant) and isinstance(el.value,
                                                               str):
                    names.add(el.value)
        elif kw.arg == "static_argnums":
            v = kw.value
            elts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            for el in elts:
                if isinstance(el, ast.Constant) and isinstance(el.value,
                                                               int):
                    nums.add(el.value)
    return names, nums


#: callable-argument positions per tracing entry: indices into the
#: positional args that are traced callables
_TRACING_ARG_POSITIONS = {
    "scan": (0,), "map": (0,), "fori_loop": (2,), "while_loop": (0, 1),
    "cond": (1, 2), "switch": (1,), "associative_scan": (0,),
    "reduce": (2,),
}


@dataclasses.dataclass
class _FnInfo:
    node: ast.AST                      # FunctionDef/AsyncFunctionDef/Lambda
    name: Optional[str]
    parent: Optional["_FnInfo"]
    traced: bool = False
    #: traced DIRECTLY (decorator / tracing argument position) — the
    #: tracer-leak pass only taints these: a helper merely *called* from
    #: traced code usually receives a mix of traced and static arguments
    #: the lexical analysis cannot apportion
    direct: bool = False
    host: bool = False                 # referenced as a host callback
    reason: str = ""
    static_names: Set[str] = dataclasses.field(default_factory=set)
    static_nums: Set[int] = dataclasses.field(default_factory=set)

    @property
    def display(self) -> str:
        return self.name or "<lambda>"

    def is_ancestor_or_self(self, other: "_FnInfo") -> bool:
        node: Optional[_FnInfo] = other
        while node is not None:
            if node is self:
                return True
            node = node.parent
        return False


def traced_functions(pf: PyFile) -> List[_FnInfo]:
    """Every function node of ``pf`` with its traced/host classification
    (memoized — the three passes share one computation)."""
    if "traced_fns" in pf.cache:
        return pf.cache["traced_fns"]
    jn = jax_names(pf)
    tree = pf.tree
    infos: List[_FnInfo] = []
    by_node: Dict[ast.AST, _FnInfo] = {}
    by_name: Dict[str, List[_FnInfo]] = {}
    if tree is None or not (jn.jax or jn.jit_like or jn.lax
                            or jn.lax_funcs):
        # no tracing entry point can be spelled without these imports
        pf.cache["traced_fns"] = infos
        return infos

    # 1. index every function node with lexical parent links
    def index(node: ast.AST, parent: Optional[_FnInfo]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                name = getattr(child, "name", None)
                info = _FnInfo(node=child, name=name, parent=parent)
                infos.append(info)
                by_node[child] = info
                if name:
                    by_name.setdefault(name, []).append(info)
                index(child, info)
            else:
                index(child, parent)

    index(tree, None)

    def mark(info: _FnInfo, reason: str, *, direct: bool = False,
             statics: Optional[ast.Call] = None) -> None:
        if not info.traced:
            info.traced = True
            info.reason = reason
        if direct:
            info.direct = True
        if statics is not None:
            names, nums = _static_params_of(statics)
            info.static_names |= names
            info.static_nums |= nums

    def mark_name(name: str, reason: str, *, direct: bool = False,
                  statics: Optional[ast.Call] = None) -> None:
        for info in by_name.get(name, []):
            mark(info, reason, direct=direct, statics=statics)

    # 2. decorators
    for info in infos:
        for dec in getattr(info.node, "decorator_list", []):
            kind = _jit_like_of(dec, jn)
            if kind:
                mark(info, f"@{kind}", direct=True)
                continue
            if isinstance(dec, ast.Call):
                kind = _jit_like_of(dec.func, jn)
                if kind:   # @jax.jit(...) decorator factory form
                    mark(info, f"@{kind}(...)", direct=True, statics=dec)
                    continue
                chain = _attr_chain(dec.func)
                if chain and chain[-1] == "partial":
                    for arg in dec.args:
                        kind = _jit_like_of(arg, jn)
                        if kind:
                            mark(info, f"@partial({kind}, ...)",
                                 direct=True, statics=dec)

    # 3. call-site tracing positions (+ host-callback positions)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        positions: Tuple[int, ...] = ()
        reason = ""
        kind = _jit_like_of(node.func, jn)
        if kind:
            positions, reason = (0,), f"passed to {kind}()"
        else:
            lax_fn = _lax_func_of(node.func, jn)
            if lax_fn in _TRACING_ARG_POSITIONS:
                positions = _TRACING_ARG_POSITIONS[lax_fn]
                reason = f"passed to lax.{lax_fn}()"
        if _callback_of(node.func, jn) and node.args:
            arg = node.args[0]
            if isinstance(arg, ast.Name):
                for info in by_name.get(arg.id, []):
                    info.host = True
            elif arg in by_node:
                by_node[arg].host = True
            continue
        if not positions:
            continue
        statics = node if kind else None   # jit(f, static_argnums=...)
        for i in positions:
            if i >= len(node.args):
                continue
            arg = node.args[i]
            if isinstance(arg, ast.Name):
                mark_name(arg.id, reason, direct=True, statics=statics)
            elif isinstance(arg, ast.Lambda):
                mark(by_node[arg], reason, direct=True, statics=statics)
            elif isinstance(arg, (ast.List, ast.Tuple)):  # lax.switch
                for el in arg.elts:
                    if isinstance(el, ast.Name):
                        mark_name(el.id, reason, direct=True)
                    elif isinstance(el, ast.Lambda):
                        mark(by_node[el], reason, direct=True)

    # 4. closure: nested defs + called-by-name helpers, to fixpoint
    changed = True
    while changed:
        changed = False
        for info in infos:
            if info.traced or info.host:
                continue
            if info.parent is not None and info.parent.traced \
                    and not info.parent.host:
                mark(info, f"defined inside traced "
                           f"'{info.parent.display}'")
                changed = True
        for info in infos:
            if not info.traced or info.host:
                continue
            for node in _own_body_walk(info.node):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Name)):
                    for callee in by_name.get(node.func.id, []):
                        # only functions lexically VISIBLE from the
                        # caller (module-level, or nested in one of the
                        # caller's ancestors) — a same-named def inside
                        # an unrelated function is a different object
                        visible = (callee.parent is None
                                   or callee.parent.is_ancestor_or_self(
                                       info))
                        if visible and not callee.traced \
                                and not callee.host:
                            mark(callee, f"called from traced "
                                         f"'{info.display}'")
                            changed = True

    pf.cache["traced_fns"] = infos
    return infos


def _own_body_walk(fn: ast.AST) -> Iterable[ast.AST]:
    """Walk a function's body WITHOUT descending into nested function
    definitions (those are separate scopes with their own classification)."""
    if isinstance(fn, ast.Lambda):
        stack: List[ast.AST] = [fn.body]
    else:
        stack = list(getattr(fn, "body", []) or [])
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            stack.append(child)


def _bound_names(fn: ast.AST) -> Set[str]:
    """Names bound inside the function (params + assignments + loop/with
    targets + comprehension targets) — the complement is its free
    variables."""
    bound: Set[str] = set()
    args = getattr(fn, "args", None)
    if args is not None:
        for a in (args.posonlyargs + args.args + args.kwonlyargs):
            bound.add(a.arg)
        if args.vararg:
            bound.add(args.vararg.arg)
        if args.kwarg:
            bound.add(args.kwarg.arg)
    for node in _own_body_walk(fn):
        if isinstance(node, ast.Name) and isinstance(node.ctx,
                                                     (ast.Store, ast.Del)):
            bound.add(node.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            bound.add(node.name)
        elif isinstance(node, ast.comprehension):
            for t in ast.walk(node.target):
                if isinstance(t, ast.Name):
                    bound.add(t.id)
    return bound


# ---------------------------------------------------------------------------
# trace-impurity

#: receiver-mutating method names (free-variable mutation from inside a
#: trace persists across retraces — the classic "accumulate into an
#: outer list from a jitted body" bug)
_MUTATING_METHODS = {"append", "appendleft", "extend", "insert", "update",
                     "setdefault", "add", "remove", "discard", "clear",
                     "pop", "popleft", "popitem", "write"}


def _impure_call(node: ast.Call, jn: JaxNames,
                 np_rng_names: Set[str]) -> Optional[str]:
    """A description of the host effect this call performs, or None."""
    chain = _attr_chain(node.func)
    if chain is None:
        return None
    head, tail = chain[0], chain[1:]
    if len(chain) == 1:
        if head in jn.time_funcs:
            return f"host clock call '{head}()'"
        if head in jn.std_random_funcs:
            return f"stdlib random call '{head}()'"
        if head in ("input",):
            return f"host I/O call '{head}()'"
        if head == "open":
            return "host I/O call 'open()'"
        if head == "print":
            return "host I/O call 'print()'"
        return None
    dotted = ".".join(chain)
    if head in jn.time:
        return f"host clock call '{dotted}()'"
    if head in jn.std_random and head not in jn.jax_random:
        return f"stdlib random call '{dotted}()'"
    if head in jn.numpy and len(chain) >= 3 and chain[1] == "random":
        return f"numpy RNG call '{dotted}()'"
    if head in jn.numpy_random:
        return f"numpy RNG call '{dotted}()'"
    if head in np_rng_names:
        return f"numpy RNG call '{dotted}()'"
    if head in jn.datetime_mod or head in jn.datetime_cls:
        if chain[-1] in ("now", "utcnow", "today"):
            return f"host clock call '{dotted}()'"
    return None


def _numpy_rng_bindings(pf: PyFile) -> Set[str]:
    """Names assigned from ``np.random.RandomState(...)`` /
    ``np.random.default_rng(...)`` anywhere in the module — calls on
    them inside traced code are host RNG draws."""
    jn = jax_names(pf)
    names: Set[str] = set()
    if pf.tree is None:
        return names
    for node in ast.walk(pf.tree):
        if not (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)):
            continue
        chain = _attr_chain(node.value.func)
        if not chain or chain[-1] not in ("RandomState", "default_rng",
                                          "Generator"):
            continue
        if (chain[0] in jn.numpy or chain[0] in jn.numpy_random):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
    return names


def trace_impurity_findings(pf: PyFile) -> List[Finding]:
    findings: List[Finding] = []
    jn = jax_names(pf)
    np_rngs = _numpy_rng_bindings(pf)
    for info in traced_functions(pf):
        if not info.traced or info.host:
            continue
        bound = _bound_names(info.node)
        for node in _own_body_walk(info.node):
            if isinstance(node, ast.Call):
                why = _impure_call(node, jn, np_rngs)
                if why is not None:
                    findings.append(Finding(
                        rule="trace-impurity", path=pf.rel,
                        line=node.lineno, col=node.col_offset,
                        message=(f"{why} inside traced function "
                                 f"'{info.display}' ({info.reason}): it "
                                 "runs once at trace time and its result "
                                 "is baked into the compiled program -- "
                                 "hoist it out of the traced code, or "
                                 "route through io_callback")))
                    continue
            elif isinstance(node, ast.Expr) and isinstance(node.value,
                                                           ast.Call):
                # statement-expression calls only: a mutator whose result
                # is USED (``state = strategy.update(state, pop)``) is
                # the functional-update idiom, not a mutation
                f = node.value.func
                if (isinstance(f, ast.Attribute)
                        and f.attr in _MUTATING_METHODS
                        and isinstance(f.value, ast.Name)
                        and f.value.id not in bound):
                    findings.append(Finding(
                        rule="trace-impurity", path=pf.rel,
                        line=node.lineno, col=node.col_offset,
                        message=(f"mutation '{f.value.id}.{f.attr}(...)' "
                                 "of a closed-over object inside traced "
                                 f"function '{info.display}' "
                                 f"({info.reason}): the mutation happens "
                                 "at trace time and repeats on every "
                                 "retrace -- return the value instead, "
                                 "or route through io_callback")))
            elif isinstance(node, ast.Global):
                findings.append(Finding(
                    rule="trace-impurity", path=pf.rel, line=node.lineno,
                    message=(f"'global' statement inside traced function "
                             f"'{info.display}' ({info.reason}): global "
                             "mutation is a trace-time side effect")))
    return findings


@rule("trace-impurity",
      "host side effects (clocks, host RNG, I/O, closure mutation) must "
      "not be reachable inside functions JAX traces -- they run once at "
      "trace time, not per call")
def _check_trace_impurity(ctx: LintContext) -> Iterable[Finding]:
    for pf in ctx.py_files:
        if pf.rel.startswith(JAX_RULE_EXCLUDED_PREFIXES):
            continue
        yield from trace_impurity_findings(pf)


# ---------------------------------------------------------------------------
# rng-key-reuse

#: jax.random functions that do NOT consume their key argument:
#: constructors, converters, and fold_in (deriving many streams from one
#: key with distinct data is the sanctioned pattern).  ``split`` is NOT
#: here: using a key after splitting it replays the split's bits.
_NONCONSUMING = {"fold_in", "PRNGKey", "key", "key_data", "wrap_key_data",
                 "clone", "key_impl"}


def _jax_random_func_of(func: ast.AST, jn: JaxNames) -> Optional[str]:
    """The jax.random function name a call target spells, or None."""
    chain = _attr_chain(func)
    if chain is None:
        return None
    if len(chain) == 1:
        return jn.jax_random_funcs.get(chain[0])
    if len(chain) == 2 and chain[0] in jn.jax_random:
        return chain[1]
    if len(chain) == 3 and chain[0] in jn.jax and chain[1] == "random":
        return chain[2]
    return None


def _key_arg(node: ast.Call) -> Optional[str]:
    """The key argument's name when it is a plain variable."""
    if node.args and isinstance(node.args[0], ast.Name):
        return node.args[0].id
    for kw in node.keywords:
        if kw.arg == "key" and isinstance(kw.value, ast.Name):
            return kw.value.id
    return None


def _assigned_names(stmt: ast.stmt) -> Set[str]:
    """Names (re)bound by this statement's own targets."""
    out: Set[str] = set()
    targets: Sequence[ast.AST] = ()
    if isinstance(stmt, ast.Assign):
        targets = stmt.targets
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        targets = [stmt.target]
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        targets = [i.optional_vars for i in stmt.items if i.optional_vars]
    for t in targets:
        for node in ast.walk(t):
            if isinstance(node, ast.Name):
                out.add(node.id)
    # comprehension targets leak no binding into the scope, but they DO
    # shadow the name for the consumption the comprehension performs —
    # treat them as rebindings so `[f(k) for k in keys]` clears `k`
    for node in ast.walk(stmt):
        if isinstance(node, ast.comprehension):
            for t in ast.walk(node.target):
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


def _walk_pruned(root: ast.AST) -> Iterable[ast.AST]:
    """Walk ``root``'s subtree WITHOUT descending into nested function
    definitions or lambdas (separate scopes, analyzed on their own)."""
    stack: List[ast.AST] = [root]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            stack.append(child)


def _rebound_in(stmts: Sequence[ast.stmt]) -> Set[str]:
    """Every name bound anywhere under ``stmts`` (nested defs excluded)."""
    out: Set[str] = set()
    for stmt in stmts:
        for node in _walk_pruned(stmt):
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
                out.add(node.id)
    return out


def _scope_bodies(pf: PyFile) -> List[Tuple[str, List[ast.stmt]]]:
    """(display name, statement list) per statement scope: the module
    and every def.  Nested defs and lambdas are pruned from the
    enclosing scope by the statement walker (lambdas are analyzed
    separately as single-expression scopes)."""
    out: List[Tuple[str, List[ast.stmt]]] = []
    tree = pf.tree
    if tree is None:
        return out
    out.append(("<module>", list(tree.body)))
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.append((node.name, list(node.body)))
    return out


def rng_key_reuse_findings(pf: PyFile) -> List[Finding]:
    """Per scope, in statement order: a name consumed by a jax.random
    sampler (or ``split``) and consumed AGAIN without an intervening
    rebinding is a finding — two draws from one key return identical
    bits, silently correlating whatever they feed.  ``fold_in`` and key
    constructors don't consume.  Branches are analyzed independently
    (an if/else that each consume the key once is fine); a consumption
    inside a loop whose key is never rebound per iteration fires the
    every-iteration form of the bug.  Rebinding is recognized both in
    the loop body (the ``key, sub = jax.random.split(key)`` tuple-unpack
    idiom consumes and retires the key in one statement) and in the loop
    statement's own targets (``for k in jax.random.split(key, n):``
    rebinds ``k`` every iteration)."""
    findings: List[Finding] = []
    jn = jax_names(pf)
    if not (jn.jax or jn.jax_random or jn.jax_random_funcs):
        return findings

    def calls_in(*roots: ast.AST) -> List[ast.Call]:
        calls = []
        for root in roots:
            for node in _walk_pruned(root):
                if isinstance(node, ast.Call):
                    calls.append(node)
        return sorted(calls, key=lambda c: (c.lineno, c.col_offset))

    def consume(call: ast.Call, consumed: Dict[str, str], scope: str,
                loop_ctx: Optional[ast.stmt]) -> None:
        fname = _jax_random_func_of(call.func, jn)
        if fname is None or fname in _NONCONSUMING:
            return
        keyname = _key_arg(call)
        if keyname is None:
            return
        if keyname in consumed:
            findings.append(Finding(
                rule="rng-key-reuse", path=pf.rel, line=call.lineno,
                col=call.col_offset,
                message=(f"PRNG key '{keyname}' passed to jax.random."
                         f"{fname} in '{scope}' was already consumed by "
                         f"jax.random.{consumed[keyname]} -- reusing a "
                         "key replays the same bits (split or fold_in "
                         "first)")))
        elif loop_ctx is not None:
            # a per-iteration rebinding retires the key: anything bound
            # in the loop BODY (the `key, sub = jax.random.split(key)`
            # tuple-unpack rebind idiom included) — and the loop
            # statement's OWN targets, which rebind on every iteration
            # too (`for k in jax.random.split(key, n): use(k)` is the
            # canonical iterate-over-subkeys idiom, not a reuse)
            rebound = (_rebound_in(loop_ctx.body)
                       | _assigned_names(loop_ctx))
            if keyname not in rebound:
                findings.append(Finding(
                    rule="rng-key-reuse", path=pf.rel, line=call.lineno,
                    col=call.col_offset,
                    message=(f"PRNG key '{keyname}' consumed by jax."
                             f"random.{fname} on every iteration of a "
                             f"loop in '{scope}' without being rebound "
                             "-- every iteration draws identical bits "
                             "(split per iteration, or fold_in the loop "
                             "index)")))
        consumed[keyname] = fname

    def walk(stmts: Sequence[ast.stmt], consumed: Dict[str, str],
             scope: str, loop_ctx: Optional[ast.stmt]) -> bool:
        """Analyze ``stmts`` in order, mutating ``consumed``.  Returns
        True when control cannot fall off the end (return/raise/break/
        continue) — a terminated branch's consumption never merges into
        the continuation, so early-return dispatch chains that consume
        the same key in each mutually-exclusive arm stay clean."""
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue   # separate scope
            if isinstance(stmt, ast.ClassDef):
                walk(stmt.body, consumed, scope, loop_ctx)
                continue
            if isinstance(stmt, ast.If):
                for call in calls_in(stmt.test):
                    consume(call, consumed, scope, loop_ctx)
                body_c = dict(consumed)
                t_body = walk(stmt.body, body_c, scope, loop_ctx)
                else_c = dict(consumed)
                t_else = walk(stmt.orelse, else_c, scope, loop_ctx) \
                    if stmt.orelse else False
                if not t_body:
                    consumed.update(body_c)
                if stmt.orelse and not t_else:
                    consumed.update(else_c)
                if t_body and t_else and stmt.orelse:
                    return True
                continue
            if isinstance(stmt, ast.Try):
                walk(stmt.body, consumed, scope, loop_ctx)
                for h in stmt.handlers:
                    walk(h.body, dict(consumed), scope, loop_ctx)
                walk(stmt.orelse, consumed, scope, loop_ctx)
                walk(stmt.finalbody, consumed, scope, loop_ctx)
                continue
            if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                headers = ([stmt.iter] if isinstance(stmt, (ast.For,
                                                            ast.AsyncFor))
                           else [stmt.test])
                for call in calls_in(*headers):
                    consume(call, consumed, scope, loop_ctx)
                inner = dict(consumed)
                for t in _assigned_names(stmt):
                    inner.pop(t, None)
                walk(stmt.body, inner, scope, stmt)
                walk(stmt.orelse, consumed, scope, loop_ctx)
                consumed.update(inner)
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                for call in calls_in(*(i.context_expr
                                       for i in stmt.items)):
                    consume(call, consumed, scope, loop_ctx)
                if walk(stmt.body, consumed, scope, loop_ctx):
                    return True
                continue
            # simple statement: consume calls in evaluation order, then
            # apply its bindings
            for call in calls_in(stmt):
                consume(call, consumed, scope, loop_ctx)
            for name in _assigned_names(stmt):
                consumed.pop(name, None)
            if isinstance(stmt, (ast.Return, ast.Raise, ast.Break,
                                 ast.Continue)):
                return True
        return False

    for scope_name, body in _scope_bodies(pf):
        walk(body, {}, scope_name, None)

    # every lambda is its own single-expression scope: consume its calls
    # in order with a fresh key set (its params shadow enclosing names)
    tree = pf.tree
    if tree is not None:
        for node in ast.walk(tree):
            if isinstance(node, ast.Lambda):
                consumed: Dict[str, str] = {}
                for call in calls_in(node.body):
                    consume(call, consumed, "<lambda>", None)
    return findings


@rule("rng-key-reuse",
      "a PRNG key consumed by a jax.random sampler (or split) must not "
      "be consumed again without an intervening split/fold_in -- reuse "
      "replays identical bits and silently correlates populations")
def _check_rng_key_reuse(ctx: LintContext) -> Iterable[Finding]:
    for pf in ctx.py_files:
        if pf.rel.startswith(JAX_RULE_EXCLUDED_PREFIXES):
            continue
        yield from rng_key_reuse_findings(pf)


# ---------------------------------------------------------------------------
# tracer-leak

#: attribute accesses that are STATIC on a traced array (never leak)
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "sharding", "aval",
                 "weak_type", "itemsize", "nbytes"}
_CAST_FUNCS = {"int", "float", "bool", "complex"}
_HOST_METHODS = {"item", "tolist", "__index__"}


def _tainted_names_in(expr: ast.AST, tainted: Set[str]) -> Set[str]:
    """Tainted names *loaded* by ``expr``, ignoring uses that stay
    static under tracing: ``x.shape``/``x.ndim``/``x.dtype`` chains,
    ``isinstance(x, ...)``, ``x is None`` comparisons, and nested
    function bodies (their own scope)."""
    hits: Set[str] = set()

    def visit(node: ast.AST) -> None:
        if isinstance(node, ast.Attribute) and node.attr in _STATIC_ATTRS:
            return
        if isinstance(node, ast.Call):
            chain = _attr_chain(node.func)
            if chain and chain[-1] in ("isinstance", "getattr", "hasattr",
                                       "len"):
                return
        if isinstance(node, ast.Compare):
            ops_static = all(isinstance(op, (ast.Is, ast.IsNot))
                             for op in node.ops)
            if ops_static:
                return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load) \
                and node.id in tainted:
            hits.add(node.id)
        for child in ast.iter_child_nodes(node):
            visit(child)

    visit(expr)
    return hits


def tracer_leak_findings(pf: PyFile) -> List[Finding]:
    """Inside traced functions, values derived from the traced
    parameters must never reach Python control flow or host casts:
    ``int()/float()/bool()`` / ``.item()`` / ``np.asarray`` calls and
    ``if``/``while``/``assert`` tests on them raise (or silently
    constant-fold) at trace time.  Taint = the function's parameters,
    propagated through assignments in statement order; ``.shape`` /
    ``.ndim`` / ``.dtype`` and ``is None`` checks are static and never
    taint."""
    findings: List[Finding] = []
    jn = jax_names(pf)
    for info in traced_functions(pf):
        # direct only: a helper merely CALLED from traced code receives a
        # mix of traced and static arguments this lexical analysis cannot
        # apportion — flagging all its params would drown real leaks
        if not info.direct or info.host:
            continue
        fn = info.node
        args = getattr(fn, "args", None)
        if args is None:
            continue
        tainted: Set[str] = set()
        positional = args.posonlyargs + args.args
        for i, a in enumerate(positional):
            if a.arg in ("self", "cls"):
                continue
            if a.arg in info.static_names or i in info.static_nums:
                continue   # python value at trace time, not a tracer
            tainted.add(a.arg)
        for a in args.kwonlyargs:
            if a.arg not in ("self", "cls") \
                    and a.arg not in info.static_names:
                tainted.add(a.arg)
        if not tainted:
            continue

        def flag(line: int, col: int, what: str, names: Set[str]) -> None:
            shown = ", ".join(sorted(names))
            findings.append(Finding(
                rule="tracer-leak", path=pf.rel, line=line, col=col,
                message=(f"{what} on traced value(s) [{shown}] inside "
                         f"traced function '{info.display}' "
                         f"({info.reason}): tracers have no concrete "
                         "value at trace time -- use lax.cond/jnp.where "
                         "for data-dependent control flow, or mark the "
                         "argument static")))

        def scan_expr_for_casts(expr: ast.AST) -> None:
            for node in ast.walk(expr):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.Lambda)):
                    continue
                if not isinstance(node, ast.Call):
                    continue
                chain = _attr_chain(node.func)
                if chain is None:
                    continue
                if (len(chain) == 1 and chain[0] in _CAST_FUNCS
                        and node.args):
                    names = _tainted_names_in(node.args[0], tainted)
                    if names:
                        flag(node.lineno, node.col_offset,
                             f"Python cast {chain[0]}()", names)
                elif chain[-1] in _HOST_METHODS:
                    names = _tainted_names_in(node.func, tainted)
                    if names:
                        flag(node.lineno, node.col_offset,
                             f".{chain[-1]}() host transfer", names)
                elif (len(chain) >= 2 and chain[0] in jn.numpy
                        and chain[-1] in ("asarray", "array", "float64",
                                          "float32", "int32", "int64")):
                    names = set()
                    for arg in node.args[:1]:
                        names |= _tainted_names_in(arg, tainted)
                    if names:
                        flag(node.lineno, node.col_offset,
                             f"numpy host conversion {'.'.join(chain)}()",
                             names)

        def walk(stmts: Sequence[ast.stmt]) -> None:
            for stmt in stmts:
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    continue   # separate scope
                if isinstance(stmt, (ast.If, ast.While)):
                    names = _tainted_names_in(stmt.test, tainted)
                    if names:
                        kind = "if" if isinstance(stmt, ast.If) else "while"
                        flag(stmt.lineno, stmt.col_offset,
                             f"Python '{kind}' branch", names)
                    scan_expr_for_casts(stmt.test)
                    walk(stmt.body)
                    walk(stmt.orelse)
                elif isinstance(stmt, ast.Assert):
                    names = _tainted_names_in(stmt.test, tainted)
                    if names:
                        flag(stmt.lineno, stmt.col_offset,
                             "Python 'assert'", names)
                elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                    scan_expr_for_casts(stmt.iter)
                    if _tainted_names_in(stmt.iter, tainted):
                        for name in _assigned_names(stmt):
                            tainted.add(name)
                    walk(stmt.body)
                    walk(stmt.orelse)
                elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                    for item in stmt.items:
                        scan_expr_for_casts(item.context_expr)
                    walk(stmt.body)
                elif isinstance(stmt, ast.Try):
                    walk(stmt.body)
                    for h in stmt.handlers:
                        walk(h.body)
                    walk(stmt.orelse)
                    walk(stmt.finalbody)
                else:
                    # simple statement: casts anywhere in it, then taint
                    # propagation through its bindings
                    scan_expr_for_casts(stmt)
                    if isinstance(stmt, ast.Assign):
                        rhs_tainted = bool(_tainted_names_in(stmt.value,
                                                             tainted))
                        for name in _assigned_names(stmt):
                            if rhs_tainted:
                                tainted.add(name)
                            else:
                                tainted.discard(name)
                    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
                        if stmt.value is not None and _tainted_names_in(
                                stmt.value, tainted):
                            for name in _assigned_names(stmt):
                                tainted.add(name)

        body = [fn.body] if isinstance(fn, ast.Lambda) else list(fn.body)
        if isinstance(fn, ast.Lambda):
            scan_expr_for_casts(fn.body)
        else:
            walk(body)
    return findings


@rule("tracer-leak",
      "int()/float()/bool()/.item()/if on values derived from a traced "
      "function's parameters -- tracers have no concrete value at trace "
      "time")
def _check_tracer_leak(ctx: LintContext) -> Iterable[Finding]:
    for pf in ctx.py_files:
        if pf.rel.startswith(JAX_RULE_EXCLUDED_PREFIXES):
            continue
        yield from tracer_leak_findings(pf)
