"""Repo-invariant passes: output routing, service wait discipline, and
the serve fleet's lock discipline.

``no-bare-print`` and ``no-blocking-sleep`` are the two original
standalone checkers (``tools/check_no_bare_print.py`` /
``check_no_blocking_sleep.py``) migrated into the framework — the
scripts remain as thin shims over the helpers exported here, so direct
invocations and their unit tests keep working.  ``lock-discipline`` is
new: a lightweight static race detector for the serving fleet's shared
state.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .core import Finding, LintContext, rule

__all__ = ["SANCTIONED_PRINT_MODULES", "REQUIRED_SLEEP_SUBPACKAGES",
           "bare_print_lines", "blocking_sleep_lines",
           "async_poll_sleep_lines", "guarded_declarations",
           "lock_discipline_findings",
           "METRIC_REGISTRY_MODULE", "METRIC_REGISTRY_TUPLES",
           "SANCTIONED_METRIC_PREFIXES", "metric_registry",
           "metric_discipline_findings"]


# ---------------------------------------------------------------------------
# no-bare-print

#: posix-relative paths (under deap_tpu/) allowed to call print(): the
#: sink layer itself plus console entries whose stdout IS their interface
SANCTIONED_PRINT_MODULES = {
    "observability/sinks.py",
    "observability/cli.py",
    "serve/cli.py",
    "serve/router/cli.py",
    "serve/top.py",
    "perfledger.py",
    "selftest.py",
    "resilience/faultdrill.py",
    "resilience/chaosdrill.py",
    "native/build.py",
    "lint/cli.py",
    "analysis/cli.py",
}


def bare_print_lines(tree: ast.AST) -> List[int]:
    """Line numbers of ``print(...)`` calls in ``tree``."""
    return sorted(node.lineno for node in ast.walk(tree)
                  if isinstance(node, ast.Call)
                  and isinstance(node.func, ast.Name)
                  and node.func.id == "print")


@rule("no-bare-print",
      "runtime output in library code must route through the "
      "observability sink layer, never a bare print()")
def _check_bare_print(ctx: LintContext) -> Iterable[Finding]:
    for pf in ctx.files_under("deap_tpu/"):
        if pf.tree is None:
            continue
        rel = pf.rel[len("deap_tpu/"):]
        if rel in SANCTIONED_PRINT_MODULES:
            continue
        for lineno in bare_print_lines(pf.tree):
            yield Finding(
                rule="no-bare-print", path=pf.rel, line=lineno,
                message=("bare print() in library code -- route through "
                         "deap_tpu.observability.sinks.emit_text, or add "
                         "the module to SANCTIONED_PRINT_MODULES if its "
                         "stdout is its interface"))


# ---------------------------------------------------------------------------
# no-blocking-sleep

#: subpackages of deap_tpu/serve/ the walk MUST find modules under — a
#: rename/move fails the gate instead of silently shrinking its scope
REQUIRED_SLEEP_SUBPACKAGES = ("net", "router", "autoscale")


def _time_sleep_spellings(tree: ast.AST) -> Tuple[Set[str], Set[str]]:
    """(module aliases of ``time``, local names bound to ``time.sleep``)."""
    time_aliases = {"time"}
    sleep_names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "time":
                    time_aliases.add(a.asname or "time")
        elif isinstance(node, ast.ImportFrom) and node.module == "time":
            for a in node.names:
                if a.name == "sleep":
                    sleep_names.add(a.asname or "sleep")
    return time_aliases, sleep_names


def blocking_sleep_lines(tree: ast.AST) -> List[int]:
    """Line numbers of blocking-sleep calls: ``time.sleep(...)`` through
    any module alias, and bare ``sleep(...)`` imported from ``time``."""
    time_aliases, sleep_names = _time_sleep_spellings(tree)
    lines = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if (isinstance(f, ast.Attribute) and f.attr == "sleep"
                and isinstance(f.value, ast.Name)
                and f.value.id in time_aliases):
            lines.append(node.lineno)
        elif isinstance(f, ast.Name) and f.id in sleep_names:
            lines.append(node.lineno)
    return sorted(lines)


def _asyncio_sleep_call(node: ast.Call, asyncio_aliases: Set[str],
                        sleep_names: Set[str]) -> bool:
    f = node.func
    if (isinstance(f, ast.Attribute) and f.attr == "sleep"
            and isinstance(f.value, ast.Name)
            and f.value.id in asyncio_aliases):
        return True
    return isinstance(f, ast.Name) and f.id in sleep_names


def async_poll_sleep_lines(tree: ast.AST) -> List[int]:
    """Line numbers of ``asyncio.sleep(...)`` calls lexically inside a
    ``while``/``for`` loop — the async spelling of a polling nap.  The
    serve invariant (PR 3/7) is that all waiting wakes on notify
    (Condition/Event/queue timeouts); a sleep-loop polls instead, adding
    its full period to every wakeup's latency."""
    asyncio_aliases = {"asyncio"}
    sleep_names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "asyncio":
                    asyncio_aliases.add(a.asname or "asyncio")
        elif isinstance(node, ast.ImportFrom) and node.module == "asyncio":
            for a in node.names:
                if a.name == "sleep":
                    sleep_names.add(a.asname or "sleep")

    lines: List[int] = []

    def walk(node: ast.AST, in_loop: bool) -> None:
        for child in ast.iter_child_nodes(node):
            loop_now = in_loop or isinstance(child, (ast.While, ast.For,
                                                     ast.AsyncFor))
            if (isinstance(child, ast.Call) and in_loop
                    and _asyncio_sleep_call(child, asyncio_aliases,
                                            sleep_names)):
                lines.append(child.lineno)
            walk(child, loop_now)

    walk(tree, False)
    return sorted(lines)


@rule("no-blocking-sleep",
      "no blocking time.sleep (or polled asyncio.sleep) on the serving "
      "layer's async paths -- waits must wake on notify")
def _check_blocking_sleep(ctx: LintContext) -> Iterable[Finding]:
    serve_files = ctx.files_under("deap_tpu/serve/")
    # coverage pin, for whole-repo runs over a real deap_tpu package: the
    # walk must see the serve tree AND every required subpackage — a
    # package rename must fail the gate, never silently shrink its scope.
    # (Path-restricted runs and fixture repos without a package init are
    # exempt: there is no coverage to lose there.)
    pin_applies = (not ctx.path_restricted
                   and (ctx.repo / "deap_tpu" / "__init__.py").exists())
    if pin_applies:
        missing = []
        if not serve_files:
            missing.append("deap_tpu/serve/")
        missing += [f"deap_tpu/serve/{sub}/"
                    for sub in REQUIRED_SLEEP_SUBPACKAGES
                    if not any(pf.rel.startswith(f"deap_tpu/serve/{sub}/")
                               for pf in serve_files)]
        for lost in missing:
            yield Finding(
                rule="no-blocking-sleep", path="deap_tpu/serve", line=1,
                message=(f"no modules found under {lost} -- the "
                         "no-blocking-sleep pass lost coverage of a "
                         "required package"))
    for pf in serve_files:
        if pf.tree is None:
            continue
        for lineno in blocking_sleep_lines(pf.tree):
            yield Finding(
                rule="no-blocking-sleep", path=pf.rel, line=lineno,
                message=("blocking time.sleep on a service async path -- "
                         "use threading.Condition/Event wait timeouts, "
                         "which wake on notify"))
        for lineno in async_poll_sleep_lines(pf.tree):
            yield Finding(
                rule="no-blocking-sleep", path=pf.rel, line=lineno,
                message=("asyncio.sleep polling loop on a service async "
                         "path -- wait on a Condition/Event (or an "
                         "asyncio.Event) that wakes on notify instead of "
                         "polling"))


# ---------------------------------------------------------------------------
# lock-discipline

#: method-call names that mutate their receiver (list/deque/dict/set/
#: OrderedDict surface) — a call ``self.<guarded>.<one of these>(...)``
#: is a write
_MUTATORS = {
    "append", "appendleft", "extend", "extendleft", "insert", "remove",
    "pop", "popleft", "popitem", "clear", "update", "setdefault", "add",
    "discard", "move_to_end", "rotate", "sort", "reverse",
}


def guarded_declarations(tree: ast.AST
                         ) -> List[Tuple[ast.ClassDef, Dict[str, Set[str]]]]:
    """Classes declaring ``_GUARDED_BY = {"<lock attr>": ("attr", ...)}``
    as a class-level *literal* dict — the in-code registration the pass
    enforces.  Non-literal declarations are ignored (the pass never
    executes code)."""
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        for stmt in node.body:
            if not (isinstance(stmt, ast.Assign)
                    and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and stmt.targets[0].id == "_GUARDED_BY"
                    and isinstance(stmt.value, ast.Dict)):
                continue
            decl: Dict[str, Set[str]] = {}
            for k, v in zip(stmt.value.keys, stmt.value.values):
                if not (isinstance(k, ast.Constant) and isinstance(k.value,
                                                                   str)):
                    continue
                attrs: Set[str] = set()
                if isinstance(v, (ast.Tuple, ast.List, ast.Set)):
                    for el in v.elts:
                        if isinstance(el, ast.Constant) and isinstance(
                                el.value, str):
                            attrs.add(el.value)
                elif isinstance(v, ast.Constant) and isinstance(v.value, str):
                    attrs.add(v.value)
                decl[k.value] = attrs
            if decl:
                out.append((node, decl))
    return out


def _self_attr(node: ast.AST) -> Optional[str]:
    """``self.<attr>`` → attr name, else None."""
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _own_expressions(stmt: ast.stmt) -> List[ast.AST]:
    """The nodes a compound statement owns DIRECTLY (its header), so the
    mutator scan never descends into nested statements — those are
    visited by the walker at their own (possibly lock-held) context."""
    if isinstance(stmt, (ast.If, ast.While)):
        return [stmt.test]
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return [stmt.target, stmt.iter]
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        return [i.context_expr for i in stmt.items]
    if isinstance(stmt, (ast.Try, ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef)):
        return []
    return [stmt]


def _written_guarded_attrs(stmt: ast.stmt, guarded: Set[str]
                           ) -> List[Tuple[int, str, str]]:
    """(line, attr, how) for every write this single statement makes to
    a guarded ``self.<attr>``: rebinding, augmented assignment, item or
    slice store/delete, and mutating method calls.  Compound statements
    contribute only their header expressions (bodies are the walker's
    job)."""
    hits: List[Tuple[int, str, str]] = []

    def targets_of(node):
        if isinstance(node, ast.Assign):
            return node.targets
        if isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            return [node.target]
        if isinstance(node, ast.Delete):
            return node.targets
        return []

    for t in targets_of(stmt):
        attr = _self_attr(t)
        if attr in guarded:
            hits.append((t.lineno, attr, "rebound"))
        # self._entries[k] = v / del self._entries[k]
        if isinstance(t, ast.Subscript):
            attr = _self_attr(t.value)
            if attr in guarded:
                hits.append((t.lineno, attr, "item-assigned"))
        # unpacking targets: (self._a, x) = ...
        if isinstance(t, (ast.Tuple, ast.List)):
            for el in t.elts:
                a = _self_attr(el)
                if a in guarded:
                    hits.append((el.lineno, a, "rebound"))

    # mutating method calls in the statement's own expressions
    for root in _own_expressions(stmt):
        for node in ast.walk(root):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if (isinstance(f, ast.Attribute) and f.attr in _MUTATORS):
                attr = _self_attr(f.value)
                if attr in guarded:
                    hits.append((node.lineno, attr, f"mutated (.{f.attr})"))
    return hits


def _read_guarded_attrs(stmt: ast.stmt, guarded: Set[str]
                        ) -> List[Tuple[int, str, str]]:
    """(line, attr, position) for guarded ``self.<attr>`` *reads* in the
    two decision positions worth flagging: a ``return`` value and an
    ``if``/``while`` condition.  A racy read that feeds a branch or a
    caller's decision is the read that matters (Eraser's insight: reads
    participate in races too); incidental reads elsewhere stay out of
    scope so the fleet's accepted opportunistic-gauge reads don't drown
    the signal."""
    if isinstance(stmt, ast.Return) and stmt.value is not None:
        roots = [(stmt.value, "return")]
    elif isinstance(stmt, (ast.If, ast.While)):
        roots = [(stmt.test, "condition")]
    else:
        return []
    hits: List[Tuple[int, str, str]] = []
    for root, where in roots:
        for node in ast.walk(root):
            if isinstance(node, ast.Attribute) \
                    and isinstance(node.ctx, ast.Load):
                attr = _self_attr(node)
                if attr in guarded:
                    hits.append((node.lineno, attr, where))
    return hits


def _lock_aliases(meth: ast.AST, decl: Dict[str, Set[str]]) -> Dict[str, str]:
    """Local names bound to a registered lock inside ``meth``:
    ``cv = self._cv`` makes ``with cv:`` hold ``_cv``.  The alias map is
    a per-method prescan (statement order is not tracked: aliasing a
    lock and then rebinding the name to something else in the same
    method is pathological, and treating the name as the lock errs on
    the quiet side only for that pathology)."""
    aliases: Dict[str, str] = {}
    for node in ast.walk(meth):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        lock = _self_attr(node.value)
        if lock in decl:
            aliases[node.targets[0].id] = lock
    return aliases


def lock_discipline_findings(tree: ast.AST, path: str) -> List[Finding]:
    """Enforce every ``_GUARDED_BY`` declaration in ``tree``: a write to
    a registered attribute outside a ``with self.<its lock>:`` block is
    a finding.  ``with`` context expressions are resolved through lock
    aliasing — ``cv = self._cv`` followed by ``with cv:`` holds ``_cv``
    (the dispatcher-style local-alias idiom).  Exemptions, by
    convention:

    * ``__init__`` — construction precedes publication to other threads;
    * methods whose name ends ``_locked`` — the caller holds the lock
      (the serve codebase's existing convention).

    Reads are checked only in *decision positions* — a ``return`` value
    or an ``if``/``while`` condition (:func:`_read_guarded_attrs`):
    those are the racy reads that feed control flow, while incidental
    opportunistic reads of gauges/flags stay out of scope so they don't
    drown the real races.  Pre-existing benign decision reads are
    grandfathered through the count-aware baseline."""
    findings: List[Finding] = []
    for cls, decl in guarded_declarations(tree):
        attr_lock = {a: lock for lock, attrs in decl.items() for a in attrs}
        guarded = set(attr_lock)
        for meth in cls.body:
            if not isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if meth.name == "__init__" or meth.name.endswith("_locked"):
                continue
            aliases = _lock_aliases(meth, decl)

            def walk(stmts: List[ast.stmt], held: Set[str]) -> None:
                for stmt in stmts:
                    for line, attr, how in _written_guarded_attrs(stmt,
                                                                  guarded):
                        if attr_lock[attr] not in held:
                            findings.append(Finding(
                                rule="lock-discipline", path=path,
                                line=line,
                                message=(f"{cls.name}.{attr} {how} in "
                                         f"'{meth.name}' outside 'with "
                                         f"self.{attr_lock[attr]}:' -- it "
                                         "is registered lock-guarded in "
                                         f"{cls.name}._GUARDED_BY (hold "
                                         "the lock, or rename the method "
                                         "*_locked if every caller "
                                         "already does)")))
                    for line, attr, where in _read_guarded_attrs(stmt,
                                                                 guarded):
                        if attr_lock[attr] not in held:
                            findings.append(Finding(
                                rule="lock-discipline", path=path,
                                line=line,
                                message=(f"{cls.name}.{attr} read in "
                                         f"{where} position in "
                                         f"'{meth.name}' outside 'with "
                                         f"self.{attr_lock[attr]}:' -- a "
                                         "racy read feeding a decision; "
                                         "hold the lock (or rename the "
                                         "method *_locked if every "
                                         "caller already does)")))
                    now = set(held)
                    if isinstance(stmt, (ast.With, ast.AsyncWith)):
                        for item in stmt.items:
                            a = _self_attr(item.context_expr)
                            if a is None and isinstance(item.context_expr,
                                                        ast.Name):
                                a = aliases.get(item.context_expr.id)
                            if a in decl:
                                now = now | {a}
                        walk(stmt.body, now)
                        continue
                    for body in (getattr(stmt, "body", None),
                                 getattr(stmt, "orelse", None),
                                 getattr(stmt, "finalbody", None)):
                        if body:
                            walk(body, held)
                    for h in getattr(stmt, "handlers", []):
                        walk(h.body, held)

            walk(meth.body, set())
    return findings


@rule("lock-discipline",
      "attributes registered in a class's _GUARDED_BY dict must only be "
      "written under 'with self.<lock>:' (static race detector for the "
      "serve fleet's shared state)")
def _check_lock_discipline(ctx: LintContext) -> Iterable[Finding]:
    for pf in ctx.py_files:
        if pf.tree is None:
            continue
        yield from lock_discipline_findings(pf.tree, pf.rel)


# ---------------------------------------------------------------------------
# metric-discipline

#: the module whose registry tuples are THE committed metric-name list —
#: every constant name at an inc()/set_gauge()/inc_tenant() site diffs
#: against it, so an inc-site typo ("cache_hit" for "cache_hits") fails
#: the gate instead of silently creating a parallel counter nobody reads
METRIC_REGISTRY_MODULE = "deap_tpu/serve/metrics.py"

#: registry tuple name -> the writer methods it governs
METRIC_REGISTRY_TUPLES = {
    "SERVE_COUNTERS": ("inc",),
    "NET_COUNTERS": ("inc",),
    "ROUTER_COUNTERS": ("inc",),
    "SERVE_GAUGES": ("set_gauge",),
    "ROUTER_GAUGES": ("set_gauge",),
    "AUTOSCALE_COUNTERS": ("inc",),
    "AUTOSCALE_GAUGES": ("set_gauge",),
    "TENANT_COUNTERS": ("inc_tenant",),
}

#: static f-string prefixes a *dynamic* metric name may carry: the
#: latency quantile family, the per-kind compile counters, and the
#: per-tenant namespace.  Any other f-string metric name is an
#: unreviewable cardinality/typo hazard and is flagged.
SANCTIONED_METRIC_PREFIXES = ("latency_", "compiles_", "tenant_")

#: writer method -> index of its metric-name argument
_METRIC_WRITERS = {"inc": 0, "set_gauge": 0, "inc_tenant": 1}

_SNAKE_RE = re.compile(r"[a-z][a-z0-9_]*\Z")


def metric_registry(tree: ast.AST) -> Dict[str, Set[str]]:
    """Parse the committed name registries out of the metrics module's
    AST: ``{writer method: allowed names}``.  Pure AST — the lint
    process never imports the serve package."""
    allowed: Dict[str, Set[str]] = {m: set()
                                    for ms in METRIC_REGISTRY_TUPLES.values()
                                    for m in ms}
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id in METRIC_REGISTRY_TUPLES
                and isinstance(node.value, (ast.Tuple, ast.List))):
            continue
        names = {el.value for el in node.value.elts
                 if isinstance(el, ast.Constant) and isinstance(el.value,
                                                                str)}
        for meth in METRIC_REGISTRY_TUPLES[node.targets[0].id]:
            allowed[meth] |= names
    return allowed


def _is_metrics_receiver(func: ast.Attribute) -> bool:
    """``<something>.metrics.inc(...)`` / ``self._metrics.inc(...)`` /
    bare ``metrics.inc(...)`` — the receiver's last segment must name a
    metrics object, so unrelated ``.inc()`` methods stay out of scope."""
    v = func.value
    if isinstance(v, ast.Name):
        return v.id in ("metrics", "_metrics")
    if isinstance(v, ast.Attribute):
        return v.attr in ("metrics", "_metrics")
    return False


def metric_discipline_findings(tree: ast.AST, path: str,
                               allowed: Dict[str, Set[str]]
                               ) -> List[Finding]:
    """Findings for one file's metric writer sites: non-snake_case
    constant names, constant names missing from the committed registry,
    and dynamic f-string names outside the sanctioned prefixes.
    Non-literal name expressions (a ``name`` variable forwarded by a
    helper) are out of scope — the registry diff catches their callers'
    constants instead."""
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _METRIC_WRITERS
                and _is_metrics_receiver(node.func)):
            continue
        idx = _METRIC_WRITERS[node.func.attr]
        if len(node.args) <= idx:
            continue
        arg = node.args[idx]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            name = arg.value
            if not _SNAKE_RE.match(name):
                findings.append(Finding(
                    rule="metric-discipline", path=path, line=node.lineno,
                    message=(f"metric name {name!r} is not snake_case -- "
                             "counter/gauge names must match "
                             "[a-z][a-z0-9_]*")))
            elif allowed.get(node.func.attr) and \
                    name not in allowed[node.func.attr]:
                findings.append(Finding(
                    rule="metric-discipline", path=path, line=node.lineno,
                    message=(f"metric name {name!r} is not in the "
                             "committed registry of "
                             f"{METRIC_REGISTRY_MODULE} -- an inc-site "
                             "typo creates a parallel series nobody "
                             "reads; fix the name or register it")))
        elif isinstance(arg, ast.JoinedStr):
            prefix = ""
            if arg.values and isinstance(arg.values[0], ast.Constant) \
                    and isinstance(arg.values[0].value, str):
                prefix = arg.values[0].value
            if not prefix.startswith(SANCTIONED_METRIC_PREFIXES):
                findings.append(Finding(
                    rule="metric-discipline", path=path, line=node.lineno,
                    message=(f"dynamic f-string metric name (prefix "
                             f"{prefix!r}) outside the sanctioned "
                             f"prefixes {SANCTIONED_METRIC_PREFIXES} -- "
                             "dynamic names defeat the registry diff and "
                             "can explode series cardinality; use a "
                             "static name, or a per-tenant/latency "
                             "prefix")))
    return findings


@rule("metric-discipline",
      "serve-layer metric names must be snake_case, match the committed "
      "registry in serve/metrics.py at constant inc/set_gauge sites, and "
      "never be dynamic f-strings outside the per-tenant/latency/compile "
      "prefixes")
def _check_metric_discipline(ctx: LintContext) -> Iterable[Finding]:
    reg_file = ctx.by_rel.get(METRIC_REGISTRY_MODULE)
    allowed: Dict[str, Set[str]] = {}
    if reg_file is not None and reg_file.tree is not None:
        allowed = metric_registry(reg_file.tree)
        if not any(allowed.values()):
            allowed = {}
    pin_applies = (not ctx.path_restricted
                   and (ctx.repo / "deap_tpu" / "__init__.py").exists())
    if not allowed and pin_applies:
        # whole-repo run over the real package with no parseable
        # registry: the diff lost its reference list — fail loudly
        # instead of silently checking nothing
        yield Finding(
            rule="metric-discipline", path=METRIC_REGISTRY_MODULE, line=1,
            message=("metric name registry (SERVE_COUNTERS/SERVE_GAUGES/"
                     "NET_COUNTERS/TENANT_COUNTERS tuples) not found -- "
                     "the metric-discipline pass lost its committed name "
                     "list"))
        return
    for pf in ctx.files_under("deap_tpu/serve/"):
        if pf.tree is None:
            continue
        yield from metric_discipline_findings(pf.tree, pf.rel, allowed)
