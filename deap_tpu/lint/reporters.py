"""Reporters: render a :class:`~deap_tpu.lint.core.LintResult` as
human text, machine JSON, or SARIF 2.1.0 (the interchange shape code
hosts ingest for inline review annotations).

All three render from the same result object; none of them prints —
the CLI owns stdout (and is the one sanctioned ``print`` site, the same
contract the no-bare-print pass enforces on the rest of the tree).
"""

from __future__ import annotations

from typing import List

from .core import LintResult, iter_rules

__all__ = ["render_text", "render_json", "render_sarif"]


def render_text(result: LintResult, *, verbose: bool = False) -> str:
    """One ``path:line: rule severity: message`` line per live finding
    plus a summary tail (files scanned, suppressed/baselined/expired
    counts) — grep-friendly, and the shape the gate's failure output
    surfaces in CI logs."""
    lines: List[str] = []
    for f in result.findings:
        lines.append(f"{f.path}:{f.line}: [{f.rule}] {f.severity}: "
                     f"{f.message}")
    if verbose:
        for f in result.baselined:
            lines.append(f"{f.path}:{f.line}: [{f.rule}] baselined: "
                         f"{f.message}")
    summary = (f"{len(result.findings)} finding(s) in "
               f"{result.files_scanned} files "
               f"({len(result.rules_run)} rules; "
               f"{len(result.suppressed)} suppressed, "
               f"{len(result.baselined)} baselined)")
    if result.expired:
        summary += (f"; {len(result.expired)} baseline entr"
                    f"{'y' if len(result.expired) == 1 else 'ies'} no "
                    "longer fire -- run --update-baseline to drop them")
    lines.append(summary)
    return "\n".join(lines)


def render_json(result: LintResult) -> dict:
    """Stable machine shape: finding dicts (with fingerprints, so a
    caller can build a baseline out-of-band) + the run summary."""
    return {
        "findings": [f.as_dict() for f in result.findings],
        "baselined": [f.as_dict() for f in result.baselined],
        "suppressed": [f.as_dict() for f in result.suppressed],
        "expired_baseline_entries": result.expired,
        "summary": {"files_scanned": result.files_scanned,
                    "rules_run": result.rules_run,
                    "findings": len(result.findings),
                    "exit_code": result.exit_code},
    }


_SARIF_LEVEL = {"error": "error", "warning": "warning"}


def render_sarif(result: LintResult) -> dict:
    """Minimal valid SARIF 2.1.0 log: one run, one driver
    (``deap-tpu-lint``), rule metadata from the registry, one result per
    live finding with a physical location."""
    known = {r.name: r for r in iter_rules()}
    rule_ids = sorted({f.rule for f in result.findings} | set(known))
    rules = []
    for rid in rule_ids:
        entry = {"id": rid}
        if rid in known:
            entry["shortDescription"] = {"text": known[rid].doc}
        rules.append(entry)
    index = {rid: i for i, rid in enumerate(rule_ids)}
    results = []
    for f in result.findings:
        results.append({
            "ruleId": f.rule,
            "ruleIndex": index[f.rule],
            "level": _SARIF_LEVEL.get(f.severity, "error"),
            "message": {"text": f.message},
            "fingerprints": {"deapTpuLint/v1": f.fingerprint()},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path},
                    "region": {"startLine": f.line,
                               "startColumn": max(1, f.col + 1)},
                },
            }],
        })
    return {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {"name": "deap-tpu-lint",
                                "informationUri":
                                    "docs/static_analysis.md",
                                "rules": rules}},
            "results": results,
        }],
    }
