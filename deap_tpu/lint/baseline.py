"""Committed-baseline handling: grandfather findings without losing them.

A new pass over a grown tree usually fires on pre-existing code that is
not worth fixing in the same PR.  Instead of weakening the rule or
littering suppressions, the gate consults ``tools/lint_baseline.json``:
findings whose fingerprint (rule + path + stable message — deliberately
line-independent, see :meth:`~deap_tpu.lint.core.Finding.fingerprint`)
appear there are *baselined* — reported in the summary, never failing
the gate.  The workflow:

* a finding fires on old code you can't fix now →
  ``deap-tpu-lint --update-baseline`` and commit the diff (the review
  sees exactly which findings were grandfathered);
* the code gets fixed later → the entry is *expired* (reported in the
  summary); ``--update-baseline`` drops it, so the baseline only ever
  shrinks back toward empty;
* a NEW finding (not in the baseline) always fails the gate — the
  baseline can never mask regressions, only history.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Dict, List, Sequence, Tuple

from .core import Finding, REPO

__all__ = ["DEFAULT_BASELINE", "load_baseline", "write_baseline",
           "apply_baseline", "occurrence_fingerprints"]

DEFAULT_BASELINE = REPO / "tools" / "lint_baseline.json"

_NOTE = ("grandfathered deap-tpu-lint findings, keyed by line-independent "
         "fingerprint (rule+path+message); regenerate with "
         "deap-tpu-lint --update-baseline and commit the diff -- a finding "
         "absent from this file always fails the gate")


def load_baseline(path: Path = DEFAULT_BASELINE) -> Dict[str, dict]:
    """Fingerprint → entry dict.  A missing file is an empty baseline
    (the committed default); a malformed one raises — a broken baseline
    must fail loudly, not silently un-grandfather the tree."""
    path = Path(path)
    if not path.exists():
        return {}
    doc = json.loads(path.read_text())
    entries = doc.get("entries", {})
    if not isinstance(entries, dict):
        raise ValueError(f"{path}: 'entries' must be an object")
    return entries


def occurrence_fingerprints(findings: Sequence[Finding]) -> List[str]:
    """One baseline key per finding: the line-independent fingerprint,
    suffixed ``#k`` for the k-th IDENTICAL finding (same rule + path +
    message) in line order.  The suffix makes the baseline count-aware:
    grandfathering N occurrences of a defect admits exactly N — a new
    (N+1)-th occurrence of the same defect in the same file gets a key
    absent from the baseline and fails the gate, and fixing one of the
    N expires the highest ordinal."""
    ordered = sorted(range(len(findings)),
                     key=lambda i: (findings[i].path, findings[i].line,
                                    findings[i].col, findings[i].rule))
    seen: Dict[str, int] = {}
    out = [""] * len(findings)
    for i in ordered:
        base = findings[i].fingerprint()
        k = seen.get(base, 0)
        seen[base] = k + 1
        out[i] = base if k == 0 else f"{base}#{k}"
    return out


def write_baseline(findings: Sequence[Finding],
                   path: Path = DEFAULT_BASELINE) -> dict:
    """Rewrite ``path`` to grandfather exactly ``findings`` (pass the
    current run's live findings: entries that stopped firing are thereby
    dropped — the expire half of the workflow)."""
    entries = {}
    for f, fp in zip(findings, occurrence_fingerprints(findings)):
        entries[fp] = {"rule": f.rule, "path": f.path,
                       "message": f.message}
    doc = {"_note": _NOTE, "version": 1, "entries": entries}
    Path(path).write_text(json.dumps(doc, indent=2, sort_keys=True) + "\n")
    return doc


def apply_baseline(findings: Sequence[Finding], baseline: Dict[str, dict]
                   ) -> Tuple[List[Finding], List[Finding], List[dict]]:
    """Partition ``findings`` into (live, baselined) and compute the
    baseline entries that no longer fire (*expired*).  Matching is
    count-aware (see :func:`occurrence_fingerprints`): a baselined
    defect with N grandfathered occurrences admits at most N."""
    live: List[Finding] = []
    baselined: List[Finding] = []
    hit = set()
    for f, fp in zip(findings, occurrence_fingerprints(findings)):
        if fp in baseline:
            hit.add(fp)
            baselined.append(f)
        else:
            live.append(f)
    expired = [dict(baseline[fp], fingerprint=fp)
               for fp in sorted(set(baseline) - hit)]
    return live, baselined, expired
