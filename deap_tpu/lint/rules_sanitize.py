"""Passes pinning the concurrency sanitizer's coverage.

``sanitizer-factory`` — the runtime sanitizer (:mod:`deap_tpu.sanitize`)
can only see locks built through its factory; a raw
``threading.Lock()``/``RLock()``/``Condition()`` constructor in the
serving fleet is a lock the lockset detector, order witness, and
watchdog are all blind to.  This pass pins that no raw constructor
survives under ``deap_tpu/serve/`` (net and router included) or in
``observability/fleettrace.py`` — the subpackages whose construction
sites were migrated — with the same lost-coverage pin the
``no-blocking-sleep`` pass carries: a package rename fails the gate
instead of silently shrinking the scope.

``guardedby-coverage`` — a class that constructs a lock *through the
factory* but declares no ``_GUARDED_BY`` map gets mutual exclusion with
no contract: neither the AST ``lock-discipline`` pass nor the runtime
lockset detector can check anything about it.  Declaring which
attributes the lock guards is one literal dict; this pass warns until it
exists (grandfathered for the pre-existing classes via the count-aware
baseline, so the warning gates only NEW undeclared locks)."""

from __future__ import annotations

import ast
from typing import Iterable, List, Set, Tuple

from .core import Finding, LintContext, rule

__all__ = ["FACTORY_SCOPE_PREFIXES", "FACTORY_SCOPE_MODULES",
           "raw_lock_constructions", "factory_locked_classes"]

#: repo-relative prefixes/modules whose lock construction must route
#: through deap_tpu.sanitize — the sanitizer's instrumented surface
FACTORY_SCOPE_PREFIXES = ("deap_tpu/serve/", "deap_tpu/bigpop/")
FACTORY_SCOPE_MODULES = ("deap_tpu/observability/fleettrace.py",)

#: serve subpackages the scope walk must find modules under (the same
#: lost-coverage contract as no-blocking-sleep's REQUIRED_SUBPACKAGES)
REQUIRED_FACTORY_SUBPACKAGES = ("net", "router", "autoscale")

#: threading constructors the factory replaces (Event carries no mutual
#: exclusion to check and stays stdlib)
_RAW_CTORS = ("Lock", "RLock", "Condition")

#: factory call names, on a ``sanitize`` receiver or from-imported
_FACTORY_NAMES = ("lock", "rlock", "condition")


def _threading_spellings(tree: ast.AST) -> Tuple[Set[str], Set[str]]:
    """(module aliases of ``threading``, local names bound to a raw
    constructor via ``from threading import Lock [as L]``)."""
    aliases = {"threading"}
    ctor_names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "threading":
                    aliases.add(a.asname or "threading")
        elif isinstance(node, ast.ImportFrom) and node.module == "threading":
            for a in node.names:
                if a.name in _RAW_CTORS:
                    ctor_names.add(a.asname or a.name)
    return aliases, ctor_names


def raw_lock_constructions(tree: ast.AST) -> List[Tuple[int, str]]:
    """(line, constructor) of every raw ``threading.Lock/RLock/
    Condition`` call — through any module alias or from-import."""
    aliases, ctor_names = _threading_spellings(tree)
    hits: List[Tuple[int, str]] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if (isinstance(f, ast.Attribute) and f.attr in _RAW_CTORS
                and isinstance(f.value, ast.Name)
                and f.value.id in aliases):
            hits.append((node.lineno, f.attr))
        elif isinstance(f, ast.Name) and f.id in ctor_names:
            hits.append((node.lineno, f.id))
    return sorted(hits)


@rule("sanitizer-factory",
      "the serving fleet (deap_tpu/serve/** and observability/"
      "fleettrace.py) must construct Lock/RLock/Condition through "
      "deap_tpu.sanitize -- a raw threading constructor is invisible to "
      "the runtime concurrency sanitizer")
def _check_sanitizer_factory(ctx: LintContext) -> Iterable[Finding]:
    scoped = [pf for pf in ctx.py_files
              if any(pf.rel.startswith(p) for p in FACTORY_SCOPE_PREFIXES)
              or pf.rel in FACTORY_SCOPE_MODULES]
    pin_applies = (not ctx.path_restricted
                   and (ctx.repo / "deap_tpu" / "__init__.py").exists())
    if pin_applies:
        # lost-coverage pin: the scope must actually contain the fleet
        missing = []
        if not any(pf.rel.startswith("deap_tpu/serve/") for pf in scoped):
            missing.append("deap_tpu/serve/")
        missing += [f"deap_tpu/serve/{sub}/"
                    for sub in REQUIRED_FACTORY_SUBPACKAGES
                    if not any(pf.rel.startswith(f"deap_tpu/serve/{sub}/")
                               for pf in scoped)]
        missing += [m for m in FACTORY_SCOPE_MODULES
                    if m not in ctx.by_rel]
        for lost in missing:
            yield Finding(
                rule="sanitizer-factory", path="deap_tpu/serve", line=1,
                message=(f"no modules found under {lost} -- the "
                         "sanitizer-factory pass lost coverage of a "
                         "required package"))
    for pf in scoped:
        if pf.tree is None:
            continue
        for lineno, ctor in raw_lock_constructions(pf.tree):
            yield Finding(
                rule="sanitizer-factory", path=pf.rel, line=lineno,
                message=(f"raw threading.{ctor}() in the serving fleet -- "
                         "construct it via deap_tpu.sanitize."
                         f"{ctor.lower()}() so "
                         "the runtime concurrency sanitizer can "
                         "instrument it under DEAP_TPU_TSAN=1"))


# ---------------------------------------------------------------------------
# guardedby-coverage


def _factory_call(node: ast.Call, imported: Set[str]) -> bool:
    """A ``sanitize.lock()``-style factory call: attribute access on a
    name ``sanitize`` (the migration idiom, ``from .. import sanitize``)
    or a bare name from-imported out of a ``sanitize`` module."""
    f = node.func
    if (isinstance(f, ast.Attribute) and f.attr in _FACTORY_NAMES
            and isinstance(f.value, ast.Name) and f.value.id == "sanitize"):
        return True
    return isinstance(f, ast.Name) and f.id in imported


def _factory_imports(tree: ast.AST) -> Set[str]:
    """Local names bound to factory functions via
    ``from deap_tpu.sanitize import lock [as L]`` (any relative
    spelling whose module path ends in ``sanitize``)."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module \
                and node.module.split(".")[-1] == "sanitize":
            for a in node.names:
                if a.name in _FACTORY_NAMES:
                    out.add(a.asname or a.name)
    return out


def _declares_guarded_by(cls: ast.ClassDef) -> bool:
    for stmt in cls.body:
        if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and stmt.targets[0].id == "_GUARDED_BY"
                and isinstance(stmt.value, ast.Dict)):
            return True
    return False


def factory_locked_classes(tree: ast.AST
                           ) -> List[Tuple[ast.ClassDef, int, bool]]:
    """(class, first factory-lock line, declares _GUARDED_BY) for every
    class that binds a factory-built lock to a ``self.`` attribute."""
    imported = _factory_imports(tree)
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        lines = []
        for sub in ast.walk(node):
            if (isinstance(sub, ast.Assign) and len(sub.targets) == 1
                    and isinstance(sub.targets[0], ast.Attribute)
                    and isinstance(sub.targets[0].value, ast.Name)
                    and sub.targets[0].value.id == "self"
                    and isinstance(sub.value, ast.Call)
                    and _factory_call(sub.value, imported)):
                lines.append(sub.lineno)
        if lines:
            out.append((node, min(lines), _declares_guarded_by(node)))
    return out


@rule("guardedby-coverage",
      "a class constructing a lock via the sanitize factory should "
      "declare a _GUARDED_BY map -- an undeclared lock is mutual "
      "exclusion with no checkable contract (neither the AST "
      "lock-discipline pass nor the runtime lockset detector can "
      "verify it)", severity="warning")
def _check_guardedby_coverage(ctx: LintContext) -> Iterable[Finding]:
    for pf in ctx.py_files:
        if pf.tree is None:
            continue
        for cls, line, declared in factory_locked_classes(pf.tree):
            if declared:
                continue
            yield Finding(
                rule="guardedby-coverage", path=pf.rel, line=line,
                severity="warning",
                message=(f"{cls.name} constructs a sanitize-factory lock "
                         "but declares no _GUARDED_BY map -- declare "
                         "which attributes the lock guards so "
                         "lock-discipline and the runtime sanitizer can "
                         "check them (grandfathered in the baseline for "
                         "pre-existing classes)"))
