"""deap_tpu — a TPU-native evolutionary-computation framework.

Same capabilities as DEAP (the reference at /root/reference: GA over
arbitrary representations, GP, ES/CMA-ES/MO-CMA-ES, PSO, DE, EDA,
NSGA-II/III, SPEA2, co-evolution, islands, archives, statistics,
checkpointing, benchmark library) — designed for JAX/XLA rather than ported:

* populations are ``jnp.ndarray`` pytrees, fitness a ``(pop, nobj)`` array;
* operators are pure vectorized kernels vmapped over whole populations;
* the generational loop is one ``lax.scan`` compiled once per run;
* distribution is ``jax.sharding`` over a device mesh — pop-axis sharding
  for fitness parallelism, island-axis sharding with ppermute migration —
  behind the same toolbox ``map``/``register`` plugin boundary the reference
  uses for multiprocessing/SCOOP.

The package init is **lazy** (PEP 562): ``import deap_tpu`` binds nothing
heavy, and each subpackage — or the ``Toolbox``/``Fitness``/``Population``
re-exports — imports on first attribute access.  This keeps jax entirely
out of lightweight consumers: ``deap_tpu.lint`` (the static-analysis
framework, which must run on boxes with no accelerator stack) imports in
milliseconds, and CLI/tooling startup no longer pays the array-stack
import for code paths that never touch a device.
"""

import importlib

__version__ = "0.1.0"
__revision__ = "0.1.0"

#: subpackages/submodules resolved on first attribute access
_SUBMODULES = (
    "base", "creator", "tools", "algorithms", "cma", "benchmarks", "ops",
    "utils", "parallel", "pso", "de", "eda", "coev", "gp", "resilience",
    "observability", "serve", "lint", "analysis", "sanitize", "selftest",
)
#: conveniences re-exported from deap_tpu.base on first access
_BASE_EXPORTS = ("Toolbox", "Fitness", "Population")

__all__ = list(_SUBMODULES) + list(_BASE_EXPORTS)


def __getattr__(name):
    if name in _SUBMODULES:
        module = importlib.import_module("." + name, __name__)
        globals()[name] = module
        return module
    if name in _BASE_EXPORTS:
        base = importlib.import_module(".base", __name__)
        value = getattr(base, name)
        globals()[name] = value
        return value
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(__all__))
