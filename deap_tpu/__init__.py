"""deap_tpu — a TPU-native evolutionary-computation framework.

Same capabilities as DEAP (the reference at /root/reference: GA over
arbitrary representations, GP, ES/CMA-ES/MO-CMA-ES, PSO, DE, EDA,
NSGA-II/III, SPEA2, co-evolution, islands, archives, statistics,
checkpointing, benchmark library) — designed for JAX/XLA rather than ported:

* populations are ``jnp.ndarray`` pytrees, fitness a ``(pop, nobj)`` array;
* operators are pure vectorized kernels vmapped over whole populations;
* the generational loop is one ``lax.scan`` compiled once per run;
* distribution is ``jax.sharding`` over a device mesh — pop-axis sharding
  for fitness parallelism, island-axis sharding with ppermute migration —
  behind the same toolbox ``map``/``register`` plugin boundary the reference
  uses for multiprocessing/SCOOP.
"""

__version__ = "0.1.0"
__revision__ = "0.1.0"

from . import base, creator, tools, algorithms, cma, benchmarks, ops, utils, parallel  # noqa: F401
from . import pso, de, eda, coev, resilience, observability, serve  # noqa: F401
from .base import Toolbox, Fitness, Population  # noqa: F401
